"""Numerical-failure resilience (ISSUE 14): the in-graph divergence
sentinel, bad-batch quarantine, automatic checkpoint rollback, and
checkpoint integrity verification.

The acceptance loop under test: a seeded `nan` fault taints one batch
through the REAL dispatch path -> the sentinel reads the in-graph
[loss, grad_norm] diagnostic, quarantines the batch (pre-step references
restored), rolls back to the last-good checkpoint, replays past the
quarantined batch, and the fit completes with a finite final loss —
bit-identically across two runs of the same plan. Plus: the integrity
half (per-entry SHA-256 manifests; a byte-flipped newest zip makes every
restore path fall back — loudly, counted — to the previous good
checkpoint), the unattached-hook overhead pin, and the unified
non-finite-score path shared with early stopping.
"""

import glob
import math
import os
import signal
import subprocess
import sys
import time
import zipfile

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"))

from deeplearning4j_tpu.cli import main as cli_main
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import sentinel as sentinel_mod
from deeplearning4j_tpu.train.checkpoint import (
    CheckpointListener,
    corrupt_zip_entry,
    scan_checkpoints,
)
from deeplearning4j_tpu.train.sentinel import (
    DivergenceSentinel,
    TrainingDivergedError,
)
from deeplearning4j_tpu.utils import faultpoints as fp
from deeplearning4j_tpu.utils.metrics import get_registry
from deeplearning4j_tpu.utils.model_serializer import (
    save_model,
    verify_checkpoint,
)

N_IN = 8


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Updater.SGD)
            .learning_rate(0.05).weight_init("xavier").list()
            .layer(DenseLayer(n_in=N_IN, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _iterator(n=128, seed=0):
    rng = np.random.default_rng(seed)
    full = DataSet(rng.standard_normal((n, N_IN)).astype(np.float32),
                   np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)])
    return ListDataSetIterator(full, 8)


class _ScoreTrail:
    """(iteration, score) per step — the replay-equality probe."""

    def __init__(self):
        self.trail = []

    def iteration_done(self, model, iteration, info):
        self.trail.append((iteration, float(np.asarray(info["score"]()))))

    def on_epoch_start(self, model, epoch):
        pass

    def on_epoch_end(self, model, epoch):
        pass


def _trails_equal(a, b):
    """Bit-identical, NaN-aware (the anomalous step's score IS NaN)."""
    if len(a) != len(b):
        return False
    for (ia, sa), (ib, sb) in zip(a, b):
        if ia != ib:
            return False
        if not (sa == sb or (math.isnan(sa) and math.isnan(sb))):
            return False
    return True


def _divergence_run(ckdir, nan_step=8, **sentinel_kw):
    net = _net()
    listener = CheckpointListener(ckdir, every_n_iterations=3,
                                  every_n_epochs=None, keep_last=5,
                                  async_save=False)
    kw = dict(rollback_after=1, max_rollbacks=2)
    kw.update(sentinel_kw)
    sent = DivergenceSentinel(**kw)
    trail = _ScoreTrail()
    net.set_listeners(listener, trail)
    net.set_sentinel(sent)
    plan = fp.FaultPlan(seed=1).add("train_step", "nan",
                                    between=(nan_step, nan_step))
    with fp.active(plan):
        net.fit(_iterator(), epochs=1, async_prefetch=False)
    return net, sent, trail.trail


# -- the acceptance loop ------------------------------------------------------


def test_nan_injection_quarantine_rollback_recovers(tmp_path):
    """Seeded NaN mid-fit -> the batch is quarantined, the run rolls
    back to the last-good checkpoint, the quarantined batch is skipped
    on replay, and the fit completes with a finite final loss — with
    every stage in the books (train_anomaly_total,
    quarantined_batches_total{quarantined,replay_skipped},
    train_rollback_total) and an SN001 finding on the sentinel."""
    reg = get_registry().scalar_values()
    base_anom = reg.get('train_anomaly_total{kind="nonfinite_loss"}', 0.0)
    net, sent, trail = _divergence_run(str(tmp_path / "ck"))
    assert math.isfinite(float(np.asarray(net._score)))
    assert sent.anomalies == 1
    assert sent.quarantined == 1
    assert sent.rollbacks == 1
    assert len(sent.records) == 1
    rec = sent.records[0]
    assert rec["anomaly"] == "nonfinite_loss"
    assert rec["digest"]  # content hash recorded alongside the position
    # exactly one NaN score in the trail (the anomalous step), then
    # recovery: the final scores are finite
    nans = [s for _, s in trail if math.isnan(s)]
    assert len(nans) == 1
    assert math.isfinite(trail[-1][1])
    sc = get_registry().scalar_values()
    assert sc['train_anomaly_total{kind="nonfinite_loss"}'] == base_anom + 1
    assert sc.get('quarantined_batches_total{action="quarantined"}', 0) >= 1
    assert sc.get('quarantined_batches_total{action="replay_skipped"}',
                  0) >= 1
    assert sc.get("train_rollback_total", 0) >= 1
    assert any(f.code == "SN001" for f in sent.findings)


def test_lr_backoff_survives_rollback_restore(tmp_path):
    """lr_backoff mutates the live config BETWEEN the save and the
    restore; the rollback restore must exempt the learning rate from
    its config-equality guard (regression: the backed-off config
    disqualified every checkpoint -> spurious TrainingDivergedError)
    and the backed-off rate must survive the restore."""
    net, sent, _ = _divergence_run(str(tmp_path / "ck"),
                                   lr_backoff=0.5)
    assert math.isfinite(float(np.asarray(net._score)))
    assert sent.rollbacks == 1
    assert net.net_conf.learning_rate == pytest.approx(0.025)


def test_checkpoint_saved_during_anomalous_step_is_rejected(tmp_path):
    """A CheckpointListener firing INSIDE the anomalous dispatch (before
    the sentinel judged it) saves the very update quarantine discards.
    With every_n_iterations=1 such a save always exists; rollback must
    reject it (tainted iteration) and restore the one before."""
    net = _net()
    ckdir = str(tmp_path / "ck")
    listener = CheckpointListener(ckdir, every_n_iterations=1,
                                  every_n_epochs=None, keep_last=0,
                                  async_save=False)
    sent = DivergenceSentinel(rollback_after=1, max_rollbacks=2)
    net.set_listeners(listener)
    net.set_sentinel(sent)
    base = get_registry().scalar_values().get(
        "checkpoint_integrity_failures_total", 0.0)
    plan = fp.FaultPlan(seed=1).add("train_step", "nan", between=(8, 8))
    with fp.active(plan):
        net.fit(_iterator(), epochs=1, async_prefetch=False)
    assert math.isfinite(float(np.asarray(net._score)))
    # the NaN hit step index 7; the discarded update is iteration 8 —
    # the checkpoint captured during that dispatch is tainted
    assert sent.tainted_iterations == {8}
    # the tainted candidate was rejected (counted on the same fallback
    # books as corruption) before an older good one restored
    sc = get_registry().scalar_values()
    assert sc["checkpoint_integrity_failures_total"] >= base + 1


def test_replay_bit_identical(tmp_path):
    """Two runs of the same seeded plan produce the SAME per-step score
    sequence — the whole detect/quarantine/rollback/replay loop is a
    pure function of the seed."""
    _, _, a = _divergence_run(str(tmp_path / "a"))
    _, _, b = _divergence_run(str(tmp_path / "b"))
    assert _trails_equal(a, b), (a, b)


def test_sentinel_attached_no_anomaly_is_equivalent(tmp_path):
    """A sentinel judging a healthy run changes NOTHING: per-step scores
    are bit-identical to a sentinel-off fit (the diagnostic is computed
    in-graph either way; judgment only reads it)."""
    def run(with_sentinel):
        net = _net()
        trail = _ScoreTrail()
        net.set_listeners(trail)
        if with_sentinel:
            net.set_sentinel(DivergenceSentinel())
        net.fit(_iterator(n=64), epochs=1, async_prefetch=False)
        return trail.trail

    assert _trails_equal(run(False), run(True))


def test_unattached_hook_under_10us():
    """The off-path contract: with no sentinel attached, the fit loop's
    pre-step hook is one attribute read (same pin as devprof/runledger)."""
    net = _net()
    assert net._sentinel is None
    t0 = time.perf_counter()
    for _ in range(10_000):
        sentinel_mod.pre_step(net)
    per_call = (time.perf_counter() - t0) / 10_000
    assert per_call < 10e-6, f"pre_step cost {per_call * 1e6:.2f}us"


def test_grad_norm_spike_classification():
    """The rolling-median spike detector, judged against a stub net —
    steady norms pass, a k x median outlier is anomalous, and the gauge
    tracks the last judged norm."""
    sent = DivergenceSentinel(grad_norm_factor=5.0, min_history=4)

    class Stub:
        iteration = 1
        _score = None
        _step_diag = None

    stub = Stub()
    for i in range(6):
        stub._step_diag = np.asarray([0.5, 1.0 + 0.01 * i], np.float32)
        stub.iteration += 1
        assert sent.judge(stub) == "ok"
    stub._step_diag = np.asarray([0.5, 50.0], np.float32)
    assert sent.judge(stub) == "grad_norm_spike"
    assert sent.streak == 1
    # a healthy step resets the streak (and the spike never entered the
    # rolling window — the median stays uncontaminated)
    stub._step_diag = np.asarray([0.5, 1.02], np.float32)
    assert sent.judge(stub) == "ok"
    assert sent.streak == 0


def test_no_checkpoint_dir_diverges_with_dump(tmp_path):
    """rollback_after consecutive anomalies with nowhere to roll back
    to: a diagnosable TrainingDivergedError carrying the dump path."""
    net = _net()
    net.set_sentinel(DivergenceSentinel(rollback_after=1))
    plan = fp.FaultPlan(seed=1).add("train_step", "nan", between=(3, 3))
    with fp.active(plan):
        with pytest.raises(TrainingDivergedError) as ei:
            net.fit(_iterator(n=64), epochs=1, async_prefetch=False)
    assert ei.value.dump_path and os.path.exists(ei.value.dump_path)


# -- checkpoint integrity -----------------------------------------------------


def _fit_with_checkpoints(ckdir, n=96):
    net = _net()
    listener = CheckpointListener(ckdir, every_n_iterations=3,
                                  every_n_epochs=None, keep_last=5,
                                  async_save=False)
    net.set_listeners(listener)
    net.fit(_iterator(n=n), epochs=1, async_prefetch=False)
    return net


def test_corrupt_newest_falls_back_and_is_visible(tmp_path, capsys):
    """Injected byte flip in the newest zip -> restore_latest verifies
    the manifest, skips it loudly (counter + checkpoint_corrupt event),
    and restores the PREVIOUS good checkpoint; the fallback renders in
    `cli blackbox` under "numerical resilience"."""
    ckdir = str(tmp_path / "ck")
    _fit_with_checkpoints(ckdir)
    cks = scan_checkpoints(ckdir)
    assert len(cks) >= 2
    corrupt_zip_entry(os.path.join(ckdir, cks[-1][1]))
    base = get_registry().scalar_values().get(
        "checkpoint_integrity_failures_total", 0.0)
    model, meta = CheckpointListener.restore_latest(ckdir)
    assert meta["file"] == cks[-2][1]
    sc = get_registry().scalar_values()
    assert sc["checkpoint_integrity_failures_total"] == base + 1
    # the event is in the flight recorder and the blackbox render
    from deeplearning4j_tpu.utils import blackbox

    dump = str(tmp_path / "dump.json")
    blackbox.get_recorder().dump(dump, reason="test")
    rc = cli_main(["blackbox", dump])
    out = capsys.readouterr().out
    assert rc == 0
    assert "numerical resilience" in out
    assert "corrupt checkpoint skipped" in out


def test_resume_from_corrupt_newest_uses_previous(tmp_path):
    """fit(resume_from=) over a directory whose newest zip is
    bit-flipped resumes from the previous good checkpoint and completes
    — the corruption costs one save interval, not the run."""
    ckdir = str(tmp_path / "ck")
    _fit_with_checkpoints(ckdir)
    cks = scan_checkpoints(ckdir)
    corrupt_zip_entry(os.path.join(ckdir, cks[-1][1]))
    net = _net()
    net.fit(_iterator(), epochs=1, resume_from=ckdir,
            async_prefetch=False)
    assert math.isfinite(float(np.asarray(net._score)))
    # it restored the PREVIOUS checkpoint's iteration, then continued
    # to the epoch end (16 batches total)
    assert net.iteration == 16


def test_resume_all_candidates_rejected_raises(tmp_path):
    """Checkpoints EXIST but every one is corrupt: fit(resume_from=)
    must raise (NoUsableCheckpointError), not silently restart from
    iteration 0 — a fresh run's saves would GC the corrupt zips,
    destroying both progress and evidence. An empty directory stays a
    fresh start."""
    from deeplearning4j_tpu.train.checkpoint import (
        NoUsableCheckpointError,
    )

    ckdir = str(tmp_path / "ck")
    _fit_with_checkpoints(ckdir)
    for _, name in scan_checkpoints(ckdir):
        corrupt_zip_entry(os.path.join(ckdir, name))
    net = _net()
    with pytest.raises(NoUsableCheckpointError):
        net.fit(_iterator(), epochs=1, resume_from=ckdir,
                async_prefetch=False)
    # restore_latest draws the same distinction: NOT FileNotFoundError
    # (the documented fresh-start signal) over a corrupted history
    with pytest.raises(NoUsableCheckpointError):
        CheckpointListener.restore_latest(ckdir)
    with pytest.raises(FileNotFoundError):
        CheckpointListener.restore_latest(str(tmp_path / "nothing"))
    # empty directory: unchanged contract — fresh start
    net2 = _net()
    net2.fit(_iterator(n=32), epochs=1,
             resume_from=str(tmp_path / "empty"), async_prefetch=False)
    assert net2.iteration == 4


def test_rebinding_sentinel_to_another_net_clears_run_state(tmp_path):
    """One sentinel reused on a DIFFERENT net must not position-match
    the old run's quarantine records against the new run's batches."""
    net, sent, _ = _divergence_run(str(tmp_path / "ck"))
    assert sent.records and sent.tainted_iterations
    other = _net(seed=11)
    other.set_sentinel(sent)
    other.fit(_iterator(n=64), epochs=1, async_prefetch=False)
    # the stale records were cleared at bind time: every batch of the
    # new net's run dispatched (8 batches -> 8 iterations)
    assert other.iteration == 8
    assert not sent.records


def test_verify_checkpoint_statuses(tmp_path):
    """Per-entry verdicts: ok on a clean zip; mismatch when an entry's
    bytes changed under a valid zip layer; unlisted for entries the
    manifest never digested; legacy for pre-digest zips."""
    net = _net()
    p = str(tmp_path / "m.zip")
    save_model(net, p)
    v = verify_checkpoint(p)
    assert v["ok"] and not v["legacy"]
    assert all(e["status"] == "ok" for e in v["entries"].values())

    # rewrite one entry with different (valid) bytes -> digest mismatch
    tampered = str(tmp_path / "tampered.zip")
    with zipfile.ZipFile(p) as zin, zipfile.ZipFile(tampered, "w") as zout:
        for name in zin.namelist():
            data = zin.read(name)
            if name == "trainState.json" or name == "meta.json":
                data = data + b" "
            zout.writestr(name, data)
        zout.writestr("extra.bin", b"not in the manifest")
    v = verify_checkpoint(tampered)
    assert not v["ok"]
    assert v["entries"]["meta.json"]["status"] == "mismatch"
    assert v["entries"]["extra.bin"]["status"] == "unlisted"

    # legacy: no manifest at all — graceful, nothing to verify
    legacy = str(tmp_path / "legacy.zip")
    with zipfile.ZipFile(p) as zin, zipfile.ZipFile(legacy, "w") as zout:
        for name in zin.namelist():
            if name != "manifest.json":
                zout.writestr(name, zin.read(name))
    v = verify_checkpoint(legacy)
    assert v["ok"] and v["legacy"]


def test_cli_resume_integrity_preflight(tmp_path, capsys):
    """`cli resume <dir>`: per-entry digest report, exit 1 on a
    corrupted newest checkpoint, exit 0 (with a note) on pre-digest
    legacy checkpoints."""
    ckdir = str(tmp_path / "ck")
    _fit_with_checkpoints(ckdir)
    assert cli_main(["resume", ckdir]) == 0
    out = capsys.readouterr().out
    assert "integrity: ok" in out

    cks = scan_checkpoints(ckdir)
    corrupt_zip_entry(os.path.join(ckdir, cks[-1][1]))
    assert cli_main(["resume", ckdir]) == 1
    out = capsys.readouterr().out
    assert "integrity: FAILED" in out
    assert "unreadable" in out or "mismatch" in out

    # legacy directory: manifest stripped from a good zip
    legacy_dir = str(tmp_path / "legacy")
    os.makedirs(legacy_dir)
    src = os.path.join(ckdir, cks[-2][1])
    dst = os.path.join(legacy_dir, cks[-2][1])
    with zipfile.ZipFile(src) as zin, zipfile.ZipFile(dst, "w") as zout:
        for name in zin.namelist():
            if name != "manifest.json":
                zout.writestr(name, zin.read(name))
    assert cli_main(["resume", legacy_dir]) == 0
    out = capsys.readouterr().out
    assert "no digest manifest" in out


def test_sigkill_mid_rollback_resumes_cleanly(tmp_path):
    """SIGKILL delivered WHILE the rollback restore is in flight (the
    child holds the rollback-event hook open for the kill window): the
    checkpoint directory stays consistent — atomic writes, read-only
    restore — and a fresh process `fit(resume_from=)` completes the run
    with a finite loss."""
    child = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "sentinel_child.py")
    ckdir = str(tmp_path / "ck")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(child))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    env.pop("T1_BLACKBOX_ARTIFACT", None)
    proc = subprocess.Popen(
        [sys.executable, child, "--ckpt-dir", ckdir,
         "--rollback-hold", "3.0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
    killed = False
    try:
        for line in proc.stdout:
            if line.startswith("EVENT train_rollback"):
                proc.send_signal(signal.SIGKILL)
                killed = True
                break
            if line.startswith("FIT DONE"):
                break
    finally:
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
    assert killed, "child finished before any rollback fired"
    assert proc.returncode == -signal.SIGKILL
    assert glob.glob(os.path.join(ckdir, "checkpoint_iter*.zip"))

    import sentinel_child

    net = sentinel_child.build_net()
    net.fit(sentinel_child.build_iterator(), epochs=1,
            resume_from=ckdir, async_prefetch=False)
    assert math.isfinite(float(np.asarray(net._score)))
    assert net.iteration == 16  # all 16 batches accounted for


# -- unified detection path / fault kinds / SLO precursor ---------------------


def test_earlystopping_invalid_score_counts_in_sentinel_books():
    """InvalidScoreIterationTerminationCondition routes through the ONE
    sentinel classification path: a NaN terminates AND lands in
    train_anomaly_total{kind="nonfinite_loss"}."""
    from deeplearning4j_tpu.train.earlystopping import (
        InvalidScoreIterationTerminationCondition,
    )

    cond = InvalidScoreIterationTerminationCondition()
    base = get_registry().scalar_values().get(
        'train_anomaly_total{kind="nonfinite_loss"}', 0.0)
    assert cond.terminate(3, 1.25) is False
    assert cond.terminate(4, float("nan")) is True
    assert cond.terminate(5, float("inf")) is True
    sc = get_registry().scalar_values()
    assert sc['train_anomaly_total{kind="nonfinite_loss"}'] == base + 2


def test_taint_nan_poisons_features():
    ds = DataSet(np.ones((4, 3), np.float32),
                 np.ones((4, 2), np.float32))
    fp.taint_nan(ds)
    assert np.isnan(ds.features).all()
    assert np.isfinite(ds.labels).all()


def test_fault_kind_serde_and_cooperative_return():
    """`nan`/`corrupt` round-trip through plan JSON and RETURN the kind
    from fault_point instead of raising."""
    plan = fp.FaultPlan(seed=3).add("train_step", "nan", between=(1, 1)) \
        .add("ckpt_write", "corrupt", every_nth=1, max_fires=1)
    plan2 = fp.FaultPlan.from_json(plan.to_json())
    assert [r.kind for r in plan2.rules] == ["nan", "corrupt"]
    with fp.active(plan2):
        assert fp.fault_point("train_step") == "nan"
        assert fp.fault_point("train_step") is None  # outside `between`
        assert fp.fault_point("ckpt_write") == "corrupt"
        assert fp.fault_point("ckpt_write") is None  # max_fires spent
    assert [e["kind"] for e in plan2.event_log()] == ["corrupt", "nan"]


def test_slo_default_pack_grad_norm_precursor():
    """The default pack carries a rate-of-change rule on the sentinel's
    train_grad_norm gauge; a fast ramp fires it (warning), absence of
    the series never alerts."""
    from deeplearning4j_tpu.analysis import slo

    rules = slo.default_rule_pack(sample_every=1.0)
    rule = next(r for r in rules
                if r.name == "grad_norm_divergence_precursor")
    assert rule.kind == "rate_of_change"
    assert rule.severity == "warning"
    rs = slo.SLORuleSet([rule])
    # no series -> never violated
    assert rs.evaluate(0.0, {}) == []
    # ramp at 100/s for > for_seconds -> fires
    transitions = []
    for i in range(6):
        transitions += rs.evaluate(
            float(i), {"train_grad_norm": 100.0 * i})
    assert any(t["to"] == "firing" for t in transitions)


@pytest.mark.slow
def test_chaos_divergence_preset_loop(tmp_path, capsys):
    """The chaos-loop gate: the divergence preset recovers (exit 0)
    across several seeds, and two runs of the same seed produce the
    same event log (replay determinism at the CLI surface)."""
    import json

    reports = []
    for seed in (0, 1):
        for rep in range(2):
            out = str(tmp_path / f"r{seed}_{rep}.json")
            rc = cli_main(["chaos", "--preset", "divergence",
                           "--steps", "16", "--seed", str(seed),
                           "--json", out])
            capsys.readouterr()
            assert rc == 0, f"divergence chaos seed={seed} failed"
            with open(out) as f:
                reports.append(json.load(f))
    assert reports[0]["events"] == reports[1]["events"]
    for rep in reports:
        assert rep["outcome"] == "recovered"
        assert rep["final_score_finite"] is True
        assert rep["loop_exercised"] is True
        assert rep["sentinel"]["quarantined"] >= 1
    # a vacuous plan (the NaN never fires) must FAIL the gate: a finite
    # final loss without an exercised loop is not a rehearsal
    plan_path = str(tmp_path / "vacuous.json")
    with open(plan_path, "w") as f:
        f.write(fp.FaultPlan(seed=0).add(
            "train_step", "nan", between=(999, 999)).to_json())
    rc = cli_main(["chaos", "--preset", "divergence", "--steps", "6",
                   "--plan", plan_path])
    capsys.readouterr()
    assert rc == 1
