"""Asynchronous parameter server for embedding training (DCN path).

Design (the written PS/embedding-async plan; reference:
ParameterServerTrainer.java:32-66 pushNDArray over Aeron,
SparkSequenceVectors.java:292-294 VoidParameterServer):

Why a PS at all, when gradient allreduce covers dense training? Embedding
workloads touch a SPARSE, tiny slice of an enormous table each step;
allreducing a dense table-sized gradient per step is absurd, and the
hot-word rows tolerate stale updates (async SGD is the reference's own
semantics — it documents the nondeterminism, DeepWalk.java:223). So:

  server:  row-sharded tables (syn0/syn1/syn1neg) in host memory, one
           process per DCN endpoint; applies row DELTAS in arrival order
           (Hogwild-style), serves row PULLS. HTTP here; the transport is
           the pluggable part (the reference swapped Aeron in the same
           slot) — gRPC/DCN drops into _Transport without touching
           trainer logic.
  client:  per-batch: PULL the rows the batch touches, run the jitted
           device skip-gram/CBOW step (nlp/learning.py — the
           AggregateSkipGram analog) on those rows only, PUSH back the
           row deltas fire-and-forget on a bounded queue.
  sharding: row id -> shard by modulo over server endpoints; each
           endpoint owns rows i with i % n_servers == k, so pushes from
           all workers for one row serialize at one owner (no
           cross-server coordination).

Staleness bound: one in-flight push window per worker (the queue), i.e.
a worker's pulls lag its own pushes by <= queue depth; convergence for
embedding objectives is unaffected in practice (the reference ships the
same tradeoff).
"""

from __future__ import annotations

import json
import queue
import threading
import urllib.request
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.utils.jsonhttp import JsonHttpServer, json_response


class EmbeddingParameterServer:
    """One shard-owner process. Tables are {name: [rows, dim]} float32."""

    def __init__(self, tables: Dict[str, np.ndarray], port: int = 0):
        self.tables = {k: np.asarray(v, np.float32) for k, v in tables.items()}
        self._locks = {k: threading.Lock() for k in self.tables}
        self._server = JsonHttpServer(post=self._post, port=port)
        self.pushes_applied = 0

    @property
    def port(self) -> int:
        return self._server.port

    # -- core ops ------------------------------------------------------------

    def pull(self, name: str, rows: List[int]) -> np.ndarray:
        with self._locks[name]:
            return self.tables[name][rows].copy()

    def push(self, name: str, rows: List[int], deltas: np.ndarray) -> None:
        """Apply row deltas in arrival order (async SGD)."""
        with self._locks[name]:
            np.add.at(self.tables[name], rows, deltas)
            self.pushes_applied += 1

    # -- http transport ------------------------------------------------------

    def _post(self, path, body, headers):
        req = json.loads(body)
        name = req["table"]
        rows = req["rows"]
        if path == "/pull":
            return json_response({"data": self.pull(name, rows).tolist()})
        if path == "/push":
            self.push(name, rows, np.asarray(req["deltas"], np.float32))
            return json_response({"status": "ok"})
        return None

    def start(self) -> int:
        return self._server.start()

    def stop(self):
        self._server.stop()


class EmbeddingPSClient:
    """Worker-side pull/push. Pushes ride a bounded background queue
    (fire-and-forget, the Aeron pushNDArray analog); pulls are
    synchronous (the step needs the rows)."""

    def __init__(self, urls: List[str], queue_size: int = 64,
                 timeout: float = 10.0):
        self.urls = [u.rstrip("/") for u in urls]
        self.timeout = timeout
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    def _owner(self, row: int) -> int:
        return row % len(self.urls)

    def _post(self, url: str, route: str, body: dict) -> dict:
        req = urllib.request.Request(
            f"{url}{route}", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def pull(self, table: str, rows: np.ndarray) -> np.ndarray:
        """Fetch rows (grouped per owning shard, order restored)."""
        rows = np.asarray(rows, np.int64)
        out: Optional[np.ndarray] = None
        for s, url in enumerate(self.urls):
            sel = np.nonzero(rows % len(self.urls) == s)[0]
            if sel.size == 0:
                continue
            got = np.asarray(self._post(url, "/pull", {
                "table": table, "rows": rows[sel].tolist()})["data"],
                np.float32)
            if out is None:
                out = np.zeros((rows.size, got.shape[1]), np.float32)
            out[sel] = got
        return out

    def push_async(self, table: str, rows: np.ndarray,
                   deltas: np.ndarray) -> None:
        try:
            self._q.put_nowait((table, np.asarray(rows, np.int64),
                                np.asarray(deltas, np.float32)))
        except queue.Full:
            # backpressure: block — dropping would lose gradient mass
            self._q.put((table, np.asarray(rows, np.int64),
                         np.asarray(deltas, np.float32)))

    def _drain(self):
        while True:
            table, rows, deltas = self._q.get()
            try:
                for s, url in enumerate(self.urls):
                    sel = np.nonzero(rows % len(self.urls) == s)[0]
                    if sel.size == 0:
                        continue
                    self._post(url, "/push", {
                        "table": table, "rows": rows[sel].tolist(),
                        "deltas": deltas[sel].tolist()})
            except OSError:
                pass  # endpoint down: drop this push, keep training
            finally:
                self._q.task_done()

    def flush(self, timeout: float = 30.0):
        import time

        deadline = time.monotonic() + timeout
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.02)
        self._q.join()
