"""Operator CLI (reference: ParallelWrapperMain.java:28-54 — train a
serialized model from flags; NearestNeighborsServer; PlayUIServer runnable).

    python -m deeplearning4j_tpu.cli train --model-path m.zip --data iris \
        --epochs 3 --batch-size 32 --output trained.zip --ui-port 9090
    python -m deeplearning4j_tpu.cli evaluate --model-path m.zip --data iris
    python -m deeplearning4j_tpu.cli knn-server --ndarray-path pts.npy
    python -m deeplearning4j_tpu.cli inference-server --model-path m.zip
    python -m deeplearning4j_tpu.cli ui-server --stats-file stats.bin

Data sources: mnist | cifar10 | iris | lfw | csv:<path>:<labelIndex>:<numClasses>
Model zips: this framework's format (utils/model_serializer), a DL4J
reference zip (modelimport/dl4j), or a Keras 1.x .h5 — sniffed by
ModelGuesser the way util/ModelGuesser.java does."""

from __future__ import annotations

import argparse
import sys
import zipfile


def guess_and_load_model(path: str):
    """ModelGuesser analog (reference: core util/ModelGuesser.java): sniff
    the container format and dispatch to the right loader."""
    if path.endswith((".h5", ".hdf5")):
        from deeplearning4j_tpu.modelimport.keras import (
            import_keras_model_and_weights,
        )

        return import_keras_model_and_weights(path)
    with zipfile.ZipFile(path) as zf:
        names = set(zf.namelist())
    # both formats carry configuration.json + coefficients.bin; only this
    # framework's zips have meta.json (utils/model_serializer)
    if "coefficients.bin" in names and "meta.json" not in names:
        import json

        from deeplearning4j_tpu.modelimport.dl4j import (
            import_dl4j_computation_graph,
            import_dl4j_multilayer,
        )

        with zipfile.ZipFile(path) as zf:
            conf = json.loads(zf.read("configuration.json"))
        if "networkInputs" in conf:  # ComputationGraphConfiguration
            return import_dl4j_computation_graph(path)
        return import_dl4j_multilayer(path)
    from deeplearning4j_tpu.utils.model_serializer import load_model

    return load_model(path)


def _data_iterator(spec: str, batch_size: int, train: bool = True,
                   num_examples: int = None):
    if spec == "mnist":
        from deeplearning4j_tpu.data.mnist import (
            MnistDataFetcher,
            MnistDataSetIterator,
        )

        return MnistDataSetIterator(
            batch_size, train=train, num_examples=num_examples,
            fetcher=MnistDataFetcher(allow_download=True))
    if spec == "cifar10":
        from deeplearning4j_tpu.data.fetchers import CifarDataSetIterator

        return CifarDataSetIterator(batch_size, train=train,
                                    num_examples=num_examples)
    if spec == "iris":
        from deeplearning4j_tpu.data.fetchers import IrisDataSetIterator

        return IrisDataSetIterator(batch_size)
    if spec == "lfw":
        from deeplearning4j_tpu.data.fetchers import LFWDataSetIterator

        return LFWDataSetIterator(batch_size, train=train,
                                  num_examples=num_examples)
    if spec.startswith("csv:"):
        _, path, label_idx, n_classes = spec.split(":")
        from deeplearning4j_tpu.data.records import (
            CSVRecordReader,
            RecordReaderDataSetIterator,
        )

        return RecordReaderDataSetIterator(
            CSVRecordReader(path), batch_size,
            label_index=int(label_idx), num_classes=int(n_classes))
    raise SystemExit(f"unknown --data {spec!r} "
                     "(mnist|cifar10|iris|csv:<path>:<label>:<classes>)")


def cmd_train(args) -> int:
    net = guess_and_load_model(args.model_path)
    it = _data_iterator(args.data, args.batch_size,
                        num_examples=args.num_examples)

    listeners = []
    from deeplearning4j_tpu.train.listeners import ScoreIterationListener

    listeners.append(ScoreIterationListener(args.print_every,
                                            print_fn=print))
    ui_server = None
    if args.ui_port is not None:
        from deeplearning4j_tpu.ui import (
            InMemoryStatsStorage,
            StatsListener,
            UIServer,
        )

        from deeplearning4j_tpu.ui import ConvolutionalIterationListener

        storage = InMemoryStatsStorage()
        net.set_collect_stats(True)
        sl = StatsListener(storage, histogram_bins=20)
        listeners.append(sl)
        listeners.append(ConvolutionalIterationListener(
            storage, sl.session_id, frequency=10))
        ui_server = UIServer(storage, port=args.ui_port)
        print(f"training UI on http://127.0.0.1:{ui_server.start()}/train")
    net.set_listeners(*listeners)

    if args.workers > 1:
        # workers>1 keeps the facade for its minibatch-stacking semantics
        from deeplearning4j_tpu.parallel import (
            ParallelWrapper,
            data_parallel_mesh,
        )

        ParallelWrapper(net, data_parallel_mesh(),
                        workers=args.workers).fit(it, epochs=args.epochs)
    else:
        if args.data_parallel:
            net.set_mesh()  # multi-device fit() would attach one anyway
        net.fit(it, epochs=args.epochs)

    if args.output:
        from deeplearning4j_tpu.utils.model_serializer import save_model

        save_model(net, args.output)
        print(f"saved trained model to {args.output}")
    if ui_server is not None and args.ui_hold:
        print("training done; UI still serving (ctrl-C to exit)")
        try:
            import time

            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
    return 0


def cmd_evaluate(args) -> int:
    net = guess_and_load_model(args.model_path)
    it = _data_iterator(args.data, args.batch_size, train=False)
    ev = net.evaluate(it)
    print(ev.stats())
    return 0


def cmd_knn_server(args) -> int:
    from deeplearning4j_tpu.serving.knnserver import main as knn_main

    knn_main([
        "--ndarrayPath", args.ndarray_path,
        "--nearestNeighborsPort", str(args.port),
        "--similarityFunction", args.similarity_function,
    ] + (["--invert"] if args.invert else []))
    return 0


def cmd_inference_server(args) -> int:
    from deeplearning4j_tpu.serving.inference_server import main as inf_main

    argv = [
        "--modelPath", args.model_path,
        "--port", str(args.port),
        "--maxBatchSize", str(args.max_batch_size),
        "--batchTimeoutMs", str(args.batch_timeout_ms),
    ]
    if args.buckets:
        argv += ["--buckets", args.buckets]
    if args.warmup_shape:
        argv += ["--warmupShape", args.warmup_shape]
    if args.replicas != 1:
        argv += ["--replicas", str(args.replicas)]
    if args.decode_slots:
        argv += ["--decodeSlots", str(args.decode_slots)]
        if args.decode_eos is not None:
            argv += ["--decodeEos", str(args.decode_eos)]
        argv += ["--decodeMaxTokens", str(args.decode_max_tokens)]
    inf_main(argv)
    return 0


def cmd_ui_server(args) -> int:
    from deeplearning4j_tpu.ui import FileStatsStorage, UIServer

    storage = FileStatsStorage(args.stats_file)
    server = UIServer(storage, port=args.port)
    port = server.start()
    print(f"ui server on http://127.0.0.1:{port}/train "
          f"({len(storage.list_session_ids())} sessions)")
    try:
        import time

        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


def cmd_report(args) -> int:
    """Standalone HTML training report from a stats file — the
    ui-components path: no server, one self-contained artifact
    (ui/report.py)."""
    import os

    from deeplearning4j_tpu.ui import FileStatsStorage
    from deeplearning4j_tpu.ui.report import write_training_report

    if not os.path.exists(args.stats_file):
        # FileStatsStorage creates missing files — a typo'd path would
        # silently produce an empty report instead of an error
        print(f"stats file not found: {args.stats_file}", file=sys.stderr)
        return 2
    storage = FileStatsStorage(args.stats_file)
    out = write_training_report(storage, args.output,
                                session_id=args.session,
                                title=args.title)
    print(f"wrote {out} ({len(storage.list_session_ids())} sessions "
          f"in {args.stats_file})")
    return 0


def cmd_profile(args) -> int:
    """Aggregate a captured jax-profiler trace directory into the op-family
    device-time breakdown used by the PROFILE_*.md tables; --json exports
    it as a machine-readable artifact so bench runs attach breakdowns
    mechanically instead of by hand (utils/profiler.py). With --preset
    the static cost model (analysis/costmodel) rides along: per-family
    flops/bytes columns and roofline context next to the measured times."""
    from deeplearning4j_tpu.utils.profiler import (
        family_summary,
        format_summary,
        op_summary,
        roofline_columns,
        write_profile_json,
    )

    cost_model = None
    if args.preset:
        from deeplearning4j_tpu.analysis.costmodel import train_step_cost
        from deeplearning4j_tpu.utils.flops import _helpers_disabled

        net = _preset_network(args)
        with _helpers_disabled():
            cost_model = train_step_cost(
                net, batch_size=args.batch,
                timesteps=args.timesteps).to_dict()
        # the static columns are only comparable to the measured trace
        # when the dims match what the trace ran — say what was modeled
        print(f"static cost model: {args.preset} train step at batch "
              f"{args.batch} (set --batch to the batch the trace "
              f"actually ran, or the flops/bytes columns will not match "
              f"the measured ms)")
    if args.json:  # single parse — the xplane decode dominates runtime
        payload = write_profile_json(args.log_dir, args.json,
                                     top_ops=args.top,
                                     cost_model=cost_model)
        if not payload["families_ms"]:
            print(f"no device ops found in {args.log_dir} (missing trace "
                  f"or xplane proto unavailable)", file=sys.stderr)
        print(f"wrote {args.json} ({len(payload['families_ms'])} op "
              f"families, {payload['total_device_sec'] * 1e3:.3f} ms device)")
        return 0
    rows = op_summary(args.log_dir, top=1_000_000)
    if not rows:
        print(f"no device ops found in {args.log_dir} (missing trace or "
              f"xplane proto unavailable)", file=sys.stderr)
    fams = dict(family_summary(rows))
    annotated = roofline_columns(
        {k: round(v * 1e3, 3) for k, v in fams.items()}, cost_model)
    print("device time by op family:")
    for fam, sec in sorted(fams.items(), key=lambda kv: -kv[1])[:args.top]:
        row = annotated.get(fam) or {}
        extra = ""
        if row.get("flops") is not None:
            extra = (f"  [{row['flops'] / 1e9:8.3f} GFLOP "
                     f"{row['bytes'] / 2**20:8.1f} MiB moved]")
        print(f"  {sec * 1e3:9.3f} ms  {fam}{extra}")
    print(format_summary(rows[:args.top]))
    if cost_model:
        print(f"\nstatic cost model (per step at batch "
              f"{cost_model.get('batch')}, cost-model families):")
        for name, fc in sorted(cost_model["families"].items(),
                               key=lambda kv: -kv[1]["flops"])[:args.top]:
            print(f"  {fc['flops'] / 1e9:10.4f} GFLOP "
                  f"{fc['bytes'] / 2**20:9.1f} MiB  {name}")
    return 0


def cmd_perf(args) -> int:
    """Static device cost model of a preset's train step
    (analysis/costmodel): per-primitive-family FLOPs, bytes moved and
    compute- vs memory-bound roofline verdicts, the liveness-based
    activation-peak and residency estimates, an optional XLA
    cost_analysis cross-check (--xla — a real compile; findings JX007 on
    divergence, JX008 on HBM overflow), and a FLOP-drift check against
    the newest committed BENCH_r*.json so accounting changes surface as
    accounting. Exit 1 on ERROR-severity findings."""
    import json as _json

    from deeplearning4j_tpu.analysis import costmodel
    from deeplearning4j_tpu.analysis.findings import (
        format_findings,
        has_errors,
    )
    from deeplearning4j_tpu.utils.flops import _helpers_disabled

    net = _preset_network(args)
    with _helpers_disabled():
        cm, xla_stats, findings = costmodel.check_network(
            net, batch_size=args.batch, timesteps=args.timesteps,
            tolerance=args.tolerance, compile_xla=args.xla)
    roof = cm.roofline()
    rows = cm.table()
    vs_prior = None if args.no_vs_prior else _perf_vs_prior(args.preset)
    # per-conv-instance Pallas kernel routing (covered / declined-by-
    # roofline / unsupported) — config-graph walking only, so it rides
    # along free for any conv-bearing preset
    coverage = None
    try:
        from deeplearning4j_tpu.analysis import kernelcoverage

        cov_rows = kernelcoverage.coverage_table(net.conf,
                                                 batch=args.batch)
        if cov_rows:
            coverage = {"rows": cov_rows,
                        "summary": kernelcoverage.coverage_summary(
                            cov_rows)}
    except Exception as e:  # a coverage bug must not kill the cost model
        coverage = {"error": f"{type(e).__name__}: {e}"}
    if args.json:
        payload = {
            "preset": args.preset,
            "batch": args.batch,
            "cost_model": cm.to_dict(),
            "roofline": roof,
            "families": rows,
            "kernel_coverage": coverage,
            "xla": xla_stats,
            "vs_prior": vs_prior,
            "findings": [f.to_dict() for f in findings],
        }
        if args.json == "-":
            print(_json.dumps(payload, indent=2, default=str))
        else:
            with open(args.json, "w") as f:
                _json.dump(payload, f, indent=2, default=str)
            print(f"wrote {args.json}")
        return 1 if has_errors(findings) else 0

    print(f"cost model — {args.preset} train step (batch {args.batch})")
    print(f"  model FLOPs (MXU): {cm.model_flops:.4g}   "
          f"total FLOPs: {cm.flops_total:.4g}   "
          f"bytes moved: {cm.bytes_total:.4g}")
    print(f"  activation peak (liveness est): "
          f"{cm.activation_peak_bytes / 2**20:.2f} MiB   "
          f"resident: {cm.resident_bytes / 2**20:.2f} MiB "
          f"(params {cm.param_bytes / 2**20:.2f} + updater "
          f"{cm.updater_bytes / 2**20:.2f} + data "
          f"{cm.data_bytes / 2**20:.2f} + activations)")
    print(f"  roofline @ {roof['peak_flops'] / 1e12:.0f} TFLOP/s, "
          f"{roof['hbm_bandwidth'] / 1e9:.0f} GB/s "
          f"(ridge {roof['ridge_intensity']:.0f} FLOP/B): "
          f"step >= {roof['step_time_lower_bound_seconds'] * 1e3:.3f} ms "
          f"({roof['bound']}-bound), MFU ceiling "
          f"{roof['mfu_ceiling']:.3f}")
    print(f"  {'family':<28} {'calls':>6} {'GFLOPs':>10} {'MiB':>9} "
          f"{'FLOP/B':>8}  verdict")
    for row in rows[:args.top]:
        print(f"  {row['family']:<28} {row['count']:>6} "
              f"{row['flops'] / 1e9:>10.4f} {row['bytes'] / 2**20:>9.1f} "
              f"{row['intensity']:>8.2f}  {row['verdict']}"
              + ("  (MXU)" if row["mxu"] else ""))
    if args.xla:
        if xla_stats:
            rel = (cm.xla_comparable_flops - xla_stats["flops"]) \
                / xla_stats["flops"]
            print(f"  XLA cross-check: model {cm.xla_comparable_flops:.4g} "
                  f"vs cost_analysis {xla_stats['flops']:.4g} "
                  f"({rel:+.1%}, tolerance {args.tolerance:.0%})")
        else:
            print("  XLA cross-check: cost_analysis unavailable on this "
                  "backend (skipped)")
    if coverage and coverage.get("rows"):
        from deeplearning4j_tpu.analysis import kernelcoverage

        print()
        print(kernelcoverage.format_table(coverage["rows"]))
    elif coverage and coverage.get("error"):
        print(f"  kernel coverage: unavailable ({coverage['error']})")
    if vs_prior:
        note = vs_prior.get("note")
        if note:
            print(f"  vs prior: {note}")
        else:
            print(f"  vs {vs_prior['source']} {vs_prior['workload']}: "
                  f"prior {vs_prior['prior_model_flops_per_step']:.4g} "
                  f"({vs_prior['prior_flops_source']}) vs cost model "
                  f"{vs_prior['costmodel_flops_per_step']:.4g} -> ratio "
                  f"{vs_prior['ratio']}"
                  + ("  ** FLOP accounting drifted — MFU not comparable "
                     "across rounds **" if vs_prior["drifted"] else ""))
    if findings:
        print(format_findings(findings))
    return 1 if has_errors(findings) else 0


def _newest_bench(bench_dir: str = None):
    """Newest committed BENCH_r*.json — same contract as
    bench._prior_bench, reimplemented here so the CLI works without the
    repo-root bench.py on sys.path. Returns (basename, result-with-
    workloads) or (None, None)."""
    import glob
    import json as _json
    import os
    import re

    if bench_dir is None:
        bench_dir = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))

    def round_no(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    for path in sorted(glob.glob(os.path.join(bench_dir, "BENCH_r*.json")),
                       key=round_no, reverse=True):
        try:
            with open(path) as f:
                doc = _json.load(f)
        except (OSError, _json.JSONDecodeError):
            continue
        if not isinstance(doc, dict):
            continue
        if "workloads" in doc:
            return os.path.basename(path), doc
        for line in reversed(str(doc.get("tail", "")).strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    result = _json.loads(line)
                except _json.JSONDecodeError:
                    continue
                if "workloads" in result:
                    return os.path.basename(path), result
    return None, None


def _perf_vs_prior(preset: str) -> dict:
    """FLOP-drift check vs the newest committed bench round: recompute
    the static model at the PRIOR round's workload dims (not the dims
    of the current invocation) and compare with the
    model_flops_per_step it recorded — a reported (never fatal)
    verdict, so a FLOP-accounting change shows up as accounting."""
    wl_name = {"resnet50": "resnet50", "charlstm": "char_lstm"}.get(preset)
    if wl_name is None:
        return None
    prior_name, prior = _newest_bench()
    if not prior:
        return None
    wl = (prior.get("workloads") or {}).get(wl_name) or {}
    pf, batch = wl.get("model_flops_per_step"), wl.get("batch")
    if not pf or not batch:
        return {"source": prior_name,
                "note": f"prior {wl_name} has no model_flops_per_step"}
    from deeplearning4j_tpu.analysis.costmodel import train_step_cost
    from deeplearning4j_tpu.utils.flops import _helpers_disabled

    try:
        with _helpers_disabled():
            if preset == "resnet50":
                from deeplearning4j_tpu.models.resnet import resnet50_network

                img = int(wl.get("image_size") or 224)
                # `classes` is recorded from PR 9 on; older committed
                # rounds fall back to the config convention (CPU smoke
                # ran 10 classes at small images, TPU the 1000-way head)
                classes = int(wl.get("classes")
                              or (1000 if img >= 224 else 10))
                net = resnet50_network(num_classes=classes,
                                       image_size=img)
                prior_cm = train_step_cost(net, batch_size=int(batch))
            else:
                from deeplearning4j_tpu.models.charlstm import (
                    char_lstm_network,
                )

                # `vocab` is recorded from PR 9 on; older rounds ran
                # the default 77-symbol charset
                net = char_lstm_network(
                    vocab_size=int(wl.get("vocab") or 77),
                    hidden=int(wl.get("hidden") or 200),
                    tbptt_length=int(wl.get("tbptt") or 50))
                prior_cm = train_step_cost(
                    net, batch_size=int(batch),
                    timesteps=int(wl.get("seq_len") or 200))
    except Exception as e:
        return {"source": prior_name,
                "note": f"recompute at prior dims failed: "
                        f"{type(e).__name__}: {e}"}
    cur = prior_cm.model_flops
    ratio = cur / pf
    return {
        "source": prior_name,
        "workload": wl_name,
        "prior_model_flops_per_step": pf,
        "prior_flops_source": wl.get("flops_source", "analytic"),
        "costmodel_flops_per_step": cur,
        "ratio": round(ratio, 4),
        "drifted": abs(ratio - 1.0) > 0.01,
    }


def cmd_metrics(args) -> int:
    """Metrics snapshot -> stdout or a JSON file. With --url, scrape a
    running server (inference-server /metrics; any endpoint speaking the
    same routes); without it, dump THIS process's registry — useful from
    scripts that embed training/serving in-process (bench.py does the
    same thing per workload). --watch <secs> re-scrapes on that period
    and prints counter/histogram DELTAS plus gauge values, so health and
    stall series are observable live without a Prometheus stack."""
    import json as _json
    import urllib.request

    if getattr(args, "ledger", None):
        return _metrics_replay(args)
    if args.watch is not None:
        return _metrics_watch(args)
    if args.url:
        url = args.url.rstrip("/") + "/metrics"
        if args.format == "prometheus":
            url += "?format=prometheus"
        with urllib.request.urlopen(url, timeout=args.timeout) as r:
            text = r.read().decode()
    else:
        from deeplearning4j_tpu.utils.metrics import get_registry

        reg = get_registry()
        text = (reg.to_prometheus() if args.format == "prometheus"
                else _json.dumps(reg.snapshot(), indent=2))
    if args.output:
        with open(args.output, "w") as f:
            f.write(text if text.endswith("\n") else text + "\n")
        print(f"wrote {args.output}")
    else:
        print(text)
    return 0


def _scrape_scalars(url, timeout: float) -> dict:
    """One flat {series: value} sample — from a server's JSON /metrics
    snapshot, or the local process registry when url is None."""
    from deeplearning4j_tpu.utils.metrics import get_registry

    if url is None:
        return get_registry().scalar_values()
    import json as _json
    import urllib.request

    with urllib.request.urlopen(url.rstrip("/") + "/metrics?format=registry",
                                timeout=timeout) as r:
        snap = _json.loads(r.read().decode())
    out = {}
    for name, fam in snap.items():
        for v in fam.get("values", []):
            labels = v.get("labels") or {}
            lab = ("{" + ",".join(f'{k}="{labels[k]}"'
                                  for k in sorted(labels)) + "}"
                   if labels else "")
            if fam.get("type") == "histogram":
                out[f"{name}{lab}:count"] = float(v.get("count", 0))
                out[f"{name}{lab}:sum"] = float(v.get("sum", 0.0))
            elif v.get("value") is not None:
                out[f"{name}{lab}"] = float(v["value"])
    return out


def _print_metrics_tick(prev: dict, now: dict, header: str):
    """One watch/replay tick: counters and histogram counts as deltas,
    gauges as changed current values — the ONE delta rendering shared by
    the live watch loop and the ledger replay."""
    print(header)
    for key in sorted(now):
        if ":bucket:" in key:  # ledger samples carry buckets; the
            continue           # tick view stays the scalar one
        v = now[key]
        is_rate = key.endswith((":count", ":sum")) \
            or key.split("{")[0].endswith("_total")
        if is_rate:
            dv = v - prev.get(key, 0.0)
            if dv:
                print(f"  {key}  +{dv:g}  (total {v:g})")
        elif v != prev.get(key):
            print(f"  {key}  {v:g}")


def _metrics_watch(args) -> int:
    """Periodic re-scrape: counters and histogram counts print as deltas
    per tick, gauges as current values. Ctrl-C (or --watch-count) ends."""
    import time as _time

    period = max(0.05, float(args.watch))
    prev = _scrape_scalars(args.url, args.timeout)
    ticks = 0
    try:
        while args.watch_count <= 0 or ticks < args.watch_count:
            _time.sleep(period)
            now = _scrape_scalars(args.url, args.timeout)
            ticks += 1
            stamp = _time.strftime("%H:%M:%S")
            _print_metrics_tick(
                prev, now, f"-- {stamp} (every {period:g}s, tick {ticks}) --")
            prev = now
    except KeyboardInterrupt:
        pass
    return 0


def _metrics_replay(args) -> int:
    """`cli metrics --ledger <path>`: replay a recorded run ledger
    tick-by-tick with the live watch's delta rendering — post-mortems
    read the same view the operator would have watched, without the
    process being alive. `--watch-count` caps the ticks printed."""
    import os
    import time as _time

    from deeplearning4j_tpu.utils import runledger

    if not os.path.exists(args.ledger):
        print(f"ledger not found: {args.ledger}", file=sys.stderr)
        return 2
    doc = runledger.read_ledger(args.ledger)
    man = doc["manifest"]
    print(f"replaying {args.ledger} — run {man.get('run_id')} "
          f"(sampled every {man.get('sample_every')}s)")
    alert_rows = list(runledger.iter_alerts(doc))
    prev: dict = {}
    ticks = 0
    t_prev = None
    for ts, now in runledger.iter_samples(doc):
        ticks += 1
        if args.watch_count > 0 and ticks > args.watch_count:
            print(f"... ({args.watch_count} of the recorded ticks shown; "
                  "raise --watch-count for more)")
            break
        stamp = _time.strftime("%H:%M:%S", _time.localtime(ts))
        dt = f" (+{ts - t_prev:.1f}s)" if t_prev is not None else ""
        _print_metrics_tick(prev, now, f"-- {stamp}{dt} tick {ticks} --")
        for a in alert_rows:
            if (t_prev or 0) < a["ts"] <= ts:
                print(f"  !! SLO {a['rule']} -> {a['to']} "
                      f"(value {a.get('value')})")
        prev, t_prev = now, ts
    return 0


def cmd_tenants(args) -> int:
    """Per-tenant chip-budget readout (utils/resourcemeter). Three
    sources, one rendering: no flags shows THIS process's spend+books,
    --url asks a running server's GET /tenants, --ledger rebuilds the
    spend table offline from a recorded run's final sample — all three
    parse the same flat scalar-values vocabulary through
    resourcemeter.spend_table(), so live and replay agree by
    construction."""
    import json as _json

    from deeplearning4j_tpu.utils import resourcemeter

    if getattr(args, "ledger", None):
        import os

        from deeplearning4j_tpu.utils import runledger

        if not os.path.exists(args.ledger):
            print(f"ledger not found: {args.ledger}", file=sys.stderr)
            return 2
        led = runledger.read_ledger(args.ledger)
        values: dict = {}
        for _ts, sample in runledger.iter_samples(led):
            values = sample  # the run's final recorded sample wins
        doc = {
            "tenants": resourcemeter.spend_table(values),
            # offline there are no live book-keepers: spend conservation
            # is judged for real, books vacuously
            "conservation": resourcemeter.conservation(values, books={}),
            "source": (f"ledger {args.ledger} "
                       f"(run {led['manifest'].get('run_id')})"),
        }
    elif args.url:
        import urllib.request

        with urllib.request.urlopen(args.url.rstrip("/") + "/tenants",
                                    timeout=args.timeout) as r:
            doc = _json.loads(r.read().decode())
        doc["source"] = args.url
    else:
        doc = resourcemeter.snapshot()
        doc["source"] = "in-process"
    if args.json:
        print(_json.dumps(doc, indent=2, default=str))
        return 0
    print(f"tenants — {doc.get('source', '')}")
    tenants = doc.get("tenants") or {}
    if not tenants:
        print("  (no tenant has been admitted or metered yet)")
    for t in sorted(tenants):
        rec = tenants[t] or {}
        parts = []
        dev = rec.get("device_seconds") or {}
        if dev:
            parts.append("dev[s] " + " ".join(
                f"{tier}={s:.4g}" for tier, s in sorted(dev.items())))
        wire = rec.get("wire_bytes") or {}
        if wire:
            parts.append("wire[B] " + " ".join(
                f"{tier}={int(b)}" for tier, b in sorted(wire.items())))
        if rec.get("tokens"):
            parts.append(f"tokens {int(rec['tokens'])}")
        if rec.get("examples"):
            parts.append(f"examples {int(rec['examples'])}")
        if rec.get("hbm_bytes"):
            parts.append(f"hbm[B] {int(rec['hbm_bytes'])}")
        books = rec.get("books")
        if books:
            ok = "" if books.get("conservation_ok", True) else " !LEAK"
            parts.append(
                f"books adm={books.get('admitted', 0)} "
                f"done={books.get('completed', 0)} "
                f"shed={books.get('shed', 0)} "
                f"fail={books.get('failed', 0)} "
                f"rej={books.get('rejected', 0)}{ok}")
        print(f"  {t:<16} " + ("  ".join(parts) if parts else "(idle)"))
    cons = doc.get("conservation") or {}
    if cons:
        print(f"  conservation: books_ok={cons.get('books_ok')} "
              f"spend_ok={cons.get('spend_ok')} ok={cons.get('ok')}")
    firing = doc.get("slo_firing")
    if firing:
        print(f"  !! per-tenant SLO firing: "
              f"{', '.join(str(r) for r in firing)}")
    if doc.get("note"):
        print(f"  note: {doc['note']}")
    return 0


def cmd_slo(args) -> int:
    """Offline SLO re-evaluation of a recorded run ledger
    (utils/runledger + analysis/slo): replay the sample stream through
    the rule-set — the one embedded in the ledger's manifest by
    default, or `--rules <json>` to re-judge the same run under
    different objectives — and report each rule's lifecycle. With
    `--check`, exit 1 when any ERROR-severity rule fired at any point:
    the CI/soak gate (`bench.py parallel_inference --overload` records
    exactly such a ledger)."""
    import json as _json
    import os

    from deeplearning4j_tpu.analysis import slo
    from deeplearning4j_tpu.utils import runledger

    if not os.path.exists(args.ledger):
        print(f"ledger not found: {args.ledger}", file=sys.stderr)
        return 2
    doc = runledger.read_ledger(args.ledger)
    if args.rules:
        with open(args.rules) as f:
            ruleset = slo.SLORuleSet.from_json(f.read())
    else:
        rule_dicts = doc["manifest"].get("rules") or []
        if not rule_dicts:
            print("ledger carries no rules (recorded without a rule "
                  "pack) — pass --rules <json>", file=sys.stderr)
            return 2
        ruleset = slo.SLORuleSet.from_dicts(rule_dicts)
    report = slo.evaluate_ledger(runledger.iter_samples(doc),
                                 ruleset.rules)
    report["ledger"] = args.ledger
    report["run_id"] = doc["manifest"].get("run_id")
    # recorded live transitions ride along so an offline/live divergence
    # (rules changed since the run) is visible, not silent
    report["recorded_alerts"] = list(runledger.iter_alerts(doc))
    if args.json == "-":
        print(_json.dumps(report, indent=2, default=str))
    elif args.json:
        with open(args.json, "w") as f:
            _json.dump(report, f, indent=2, default=str)
        print(f"wrote {args.json}")
    else:
        print(f"slo — run {report['run_id']} "
              f"({report['samples']} samples, "
              f"{len(ruleset.rules)} rules)")
        for r in report["rules"]:
            mark = {"firing": "!!", "pending": " ~"}.get(r["state"], "  ")
            fired = (f"  fired x{r['fired_total']}"
                     if r["fired_total"] else "")
            print(f"  {mark} {r['rule']:<28} {r['state']:<8} "
                  f"[{r['severity']}]{fired}  {r['detail']}")
        for t in report["transitions"]:
            print(f"    {t['ts']:.3f}  {t['rule']} -> {t['to']} "
                  f"(value {t['value']})")
        verdict = "ok" if report["ok"] else (
            f"ERROR rules fired: {', '.join(report['ever_fired_errors'])}")
        print(f"  verdict: {verdict}")
    if args.check:
        return 0 if report["ok"] else 1
    return 0


def cmd_runs(args) -> int:
    """Run-ledger operations: list the recorded runs in a directory, or
    `runs compare <reference> <candidate>` for per-metric regression
    deltas between two ledgers — the bench `vs_baseline` idea
    generalized from one-shot workloads to whole runs (counters compare
    by rate, gauges/latency means by mean; series moving more than
    --threshold are flagged with their metric family)."""
    import json as _json

    from deeplearning4j_tpu.utils import runledger

    if args.paths and args.paths[0] == "compare":
        if len(args.paths) != 3:
            print("usage: runs compare <reference.jsonl> "
                  "<candidate.jsonl>", file=sys.stderr)
            return 2
        import os

        for p in args.paths[1:]:
            if not os.path.exists(p):
                print(f"ledger not found: {p}", file=sys.stderr)
                return 2
        ref = runledger.summarize_run(
            runledger.read_ledger(args.paths[1]))
        cand = runledger.summarize_run(
            runledger.read_ledger(args.paths[2]))
        report = runledger.compare_runs(ref, cand,
                                        threshold=args.threshold)
        if args.json == "-":
            print(_json.dumps(report, indent=2, default=str))
        elif args.json:
            with open(args.json, "w") as f:
                _json.dump(report, f, indent=2, default=str)
            print(f"wrote {args.json}")
        else:
            print(f"compare — reference {report['reference']['run_id']} "
                  f"vs candidate {report['candidate']['run_id']} "
                  f"(threshold {report['threshold']:.0%})")
            if not report["regressions"]:
                print("  no series moved past the threshold")
            for row in report["regressions"][:args.top]:
                r = row["ratio"]
                print(f"  {row['series']:<52} {row['basis']:>5} "
                      f"{row['reference']:>12.6g} -> "
                      f"{row['candidate']:>12.6g}  "
                      f"x{r if r is not None else float('nan'):.3f}")
            if report["regression_families"]:
                print("  families moved: "
                      + ", ".join(report["regression_families"]))
        return 0
    directory = args.dir or (args.paths[0] if args.paths else ".")
    entries = runledger.list_ledgers(directory)
    if args.json == "-":
        print(_json.dumps(entries, indent=2, default=str))
        return 0
    if not entries:
        print(f"no run ledgers in {directory!r}")
        return 0
    print(f"{len(entries)} run(s) in {directory}:")
    for e in entries:
        print(f"  {e['run_id']}  rules={e['rules']}  {e['path']}")
    return 0


def cmd_trace(args) -> int:
    """Distributed-trace readout (analysis/tracecrit): reconstruct span
    trees from a JSONL export — a file written by TracingListener /
    `cli chaos --trace-out`, or a live server's GET /trace — and report
    the top-k slowest traces with critical path and per-stage self-time
    breakdown. `--trace-id` resolves one specific trace (paste a
    histogram exemplar's trace_id from GET /metrics); exit 1 when it
    (or any trace at all) is missing from the export."""
    import json as _json
    import os

    from deeplearning4j_tpu.analysis import tracecrit

    src = args.source
    if src.startswith(("http://", "https://")):
        import urllib.request

        url = src if "/trace" in src.split("://", 1)[1] \
            else src.rstrip("/") + "/trace"
        with urllib.request.urlopen(url, timeout=args.timeout) as r:
            text = r.read().decode()
    else:
        if not os.path.exists(src):
            print(f"trace export not found: {src}", file=sys.stderr)
            return 2
        with open(src) as f:
            text = f.read()
    events = tracecrit.parse_jsonl(text)
    report = tracecrit.analyze(events, top=args.top,
                               trace_id=args.trace_id)
    if args.json == "-":
        print(_json.dumps(report, indent=2))
    elif args.json:
        with open(args.json, "w") as f:
            _json.dump(report, f, indent=2)
        print(f"wrote {args.json}")
    else:
        print(tracecrit.format_report(report))
    if not report["traces"]:
        print("no matching trace in the export "
              "(tracing off, ring aged out, or wrong --trace-id?)",
              file=sys.stderr)
        return 1
    return 0


def cmd_blackbox(args) -> int:
    """Render a flight-recorder crash dump (utils/blackbox — written by
    install_crash_hooks on SIGTERM/fatal error, by the watchdog on a
    hang, or on demand): the final-steps timeline, events, component
    health, and every thread's stack at dump time."""
    import json as _json
    import os

    from deeplearning4j_tpu.utils.blackbox import render_dump

    if not os.path.exists(args.dump):
        print(f"dump not found: {args.dump}", file=sys.stderr)
        return 2
    with open(args.dump) as f:
        doc = _json.load(f)
    if args.json:
        print(_json.dumps(doc, indent=2, default=str))
    else:
        print(render_dump(doc, max_steps=args.steps))
    return 0


def cmd_resume(args) -> int:
    """Operator half of the resume contract (train/checkpoint): describe
    the newest checkpoint in a directory — iteration/epoch/reason/age and
    the mid-epoch TrainState it carries — verify its per-entry SHA-256
    digest manifest, and prove the zip actually loads. Exit 0 when a
    loadable, integrity-clean checkpoint exists; 1 when the directory is
    empty, every checkpoint is torn/unreadable, or the newest one fails
    digest verification (the per-entry status is printed so the operator
    sees WHICH entry rotted): scriptable as a pre-flight gate before
    `fit(resume_from=...)` (or as the init container of a preemptible
    training pod). Pre-digest legacy checkpoints carry no manifest and
    pass with a note — nothing to verify against; `--no-validate` stays
    metadata-only (digest verification reads the payload, so it is
    skipped there too)."""
    import json as _json

    from deeplearning4j_tpu.train.checkpoint import describe_latest
    from deeplearning4j_tpu.utils.model_serializer import verify_checkpoint

    info = describe_latest(args.directory)
    if info is None:
        print(f"resume: no checkpoint in {args.directory!r} "
              "(empty directory = fresh start)", file=sys.stderr)
        return 1
    rc = 0
    integrity = None
    if not args.no_validate:
        # digest verification reads the payload, so it respects the
        # --no-validate "metadata only" contract
        integrity = verify_checkpoint(info["path"])
        info["integrity"] = integrity
        if not integrity["ok"]:
            rc = 1
    if not args.no_validate and integrity["ok"]:
        # the describe is metadata-level; this proves the full payload
        # (config, params, layer/updater state) deserializes
        from deeplearning4j_tpu.utils.model_serializer import load_model

        try:
            model = load_model(info["path"])
        except Exception as e:
            print(f"resume: newest checkpoint {info['path']} does not "
                  f"load: {type(e).__name__}: {e}", file=sys.stderr)
            return 1
        info["network_type"] = type(model).__name__
        info["num_params"] = int(model.num_params())
    if args.json:
        print(_json.dumps(info, indent=2, default=str))
        return rc
    age = info.get("age_seconds")
    print(f"checkpoint: {info['path']}")
    print(f"  iteration: {info.get('iteration')}  "
          f"epoch: {info.get('epoch')}  reason: {info.get('reason')}")
    if age is not None:
        print(f"  age: {age:.1f}s")
    if integrity is None:
        pass  # --no-validate: metadata only, payload never opened
    elif integrity.get("legacy"):
        print("  integrity: no digest manifest (pre-digest checkpoint) "
              "— nothing to verify against")
    elif integrity.get("error"):
        print(f"  integrity: FAILED — {integrity['error']}")
    else:
        n_ok = sum(1 for e in integrity["entries"].values()
                   if e["status"] == "ok")
        verdict = ("ok" if integrity["ok"]
                   else "FAILED — restore would fall back to the "
                        "previous good checkpoint")
        print(f"  integrity: {verdict} ({n_ok}/"
              f"{len(integrity['entries'])} entries, sha256)")
        for name, e in sorted(integrity["entries"].items()):
            status = e["status"]
            extra = ""
            if status == "mismatch":
                extra = (f"  (expected {e.get('expected')}…, got "
                         f"{e.get('got')}…)")
            elif status == "unreadable":
                extra = f"  ({e.get('error')})"
            print(f"    {status:<10} {name}{extra}")
    if info.get("network_type"):
        print(f"  model: {info['network_type']} "
              f"({info.get('num_params')} params)  validated: loads OK")
    ts = info.get("train_state")
    if ts:
        print(f"  mid-epoch state: epoch {ts.get('epoch')}, "
              f"{ts.get('batch_in_epoch')} batch(es) into it"
              + (" (+ iterator state)" if ts.get("iterator_state")
                 else ""))
    else:
        print("  mid-epoch state: none (resume restarts its epoch)")
    return rc


def cmd_doctor(args) -> int:
    """Model doctor: static shape/dtype-flow check of a model's
    configuration plus a jaxpr audit of its train-step loss
    (analysis/shapeflow + analysis/jaxpr_audit via net.doctor()). Exit 0
    when no ERROR-severity finding; 1 otherwise — scriptable as a
    pre-training/pre-serving gate."""
    import json as _json

    from deeplearning4j_tpu.analysis import (
        format_findings,
        has_errors,
        summarize,
    )

    if bool(args.model_path) == bool(args.preset):
        print("doctor: pass exactly one of --model-path or --preset",
              file=sys.stderr)
        return 2
    if args.model_path:
        net = guess_and_load_model(args.model_path)
    else:
        net = _preset_network(args)
    devices = getattr(args, "devices", None)
    if devices and devices > 1:
        # audit the SHARDED step signature: attach a data mesh over N
        # devices (clamped to the platform) so the jaxpr trace and the
        # JX006 donation check see exactly what a multi-chip fit builds
        import jax as _jax

        from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh

        avail = _jax.devices()
        if len(avail) < devices:
            print(f"doctor: --devices {devices} clamped to the "
                  f"{len(avail)} visible device(s) (force more with "
                  f"XLA_FLAGS=--xla_force_host_platform_device_count=N "
                  f"on cpu)", file=sys.stderr)
            devices = len(avail)
        net._require_init()
        net.set_mesh(data_parallel_mesh(avail[:devices]))
        print(f"doctor: auditing the sharded train step over "
              f"{net._mesh_plan.describe()}")
        # surface the chosen gradient-collective schedule next to the
        # donation audit: bucket count/sizes, wire dtype and bytes per
        # step, ring-time estimate — the knobs set_mesh(bucket_bytes=,
        # grad_dtype=) control
        try:
            coll = net._mesh_plan.collective_describe(net)
        except Exception as e:
            print(f"doctor: collective schedule unavailable "
                  f"({type(e).__name__}: {e})")
        else:
            sizes = coll.get("bucket_sizes_bytes")
            sched = ("monolithic (single tail-end all-reduce)"
                     if coll["mode"] == "monolithic" else
                     f"{coll['n_buckets']} bucket(s) "
                     f"{[f'{b / 2**20:.2f}MiB' for b in sizes]} "
                     f"(bucket_bytes={coll['bucket_bytes']}, "
                     f"{coll['bucketed_leaves']} leaves bucketed, "
                     f"{coll['unbucketed_leaves']} unbucketed)")
            print(f"doctor: gradient collective: {sched}; wire dtype "
                  f"{coll['grad_dtype']}, "
                  f"{coll['wire_bytes_per_step']} bytes/step, ring "
                  f"estimate {coll['ring_estimate_seconds']:.2e}s")
    findings = net.doctor(batch_size=args.batch, timesteps=args.timesteps,
                          jaxpr=not args.no_jaxpr)
    if args.json == "-":
        print(_json.dumps(summarize(findings), indent=2))
    elif args.json:
        with open(args.json, "w") as f:
            _json.dump(summarize(findings), f, indent=2)
        print(f"wrote {args.json}")
    else:
        print(format_findings(findings))
        # concurrency section: the repo-wide lock-discipline audit —
        # lexical always, plus witnessed runtime edges when the
        # DL4J_LOCKCHECK sanitizer is armed in this process. Display
        # only: the gated form is scripts/t1.sh's `T1 LOCK AUDIT:` step
        # (cli locks --smoke --baseline scripts/lock_baseline.txt)
        try:
            from deeplearning4j_tpu.analysis import (
                concurrency_audit as _ca,
            )

            cdoc = _ca.report(runtime=True)
            mode = ("static+runtime" if cdoc["runtime"]
                    else "static only — arm with DL4J_LOCKCHECK=1")
            print(f"concurrency: {len(cdoc['edges'])} lock-order "
                  f"edge(s), {cdoc['summary']['errors']} error(s) / "
                  f"{cdoc['summary']['warnings']} warning(s) [{mode}]")
            if cdoc["findings"]:
                print(format_findings(cdoc["findings"]))
        except Exception as e:
            print(f"concurrency: audit unavailable "
                  f"({type(e).__name__}: {e})")
    return 1 if has_errors(findings) else 0


def _preset_network(args):
    """Built-in model configs for doctor runs without a serialized model."""
    preset = args.preset
    if preset == "resnet50":
        from deeplearning4j_tpu.models.resnet import resnet50_network

        return resnet50_network(num_classes=args.classes or 1000,
                                image_size=args.image_size or 224)
    if preset == "tiny_resnet":
        from deeplearning4j_tpu.models.resnet import tiny_resnet_conf
        from deeplearning4j_tpu.nn.compgraph import ComputationGraph

        return ComputationGraph(tiny_resnet_conf()).init()
    if preset == "charlstm":
        from deeplearning4j_tpu.models.charlstm import char_lstm_network

        return char_lstm_network()
    if preset == "recsys":
        from deeplearning4j_tpu.models.recsys import recsys_network

        return recsys_network(host_resident=True)
    raise SystemExit(f"unknown --preset {preset!r} "
                     "(resnet50|tiny_resnet|charlstm|recsys)")


def _chaos_net(n_in: int = 8):
    """Small dense net shared by the chaos presets: big enough to have a
    real forward/backward, small enough that a replay run is seconds."""
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
        Updater,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(7).updater(Updater.SGD)
            .learning_rate(0.05).weight_init("xavier").list()
            .layer(DenseLayer(n_in=n_in, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _chaos_budget(plan) -> float:
    """Join budget for a chaos run: generous base plus the longest hang
    the plan can inject — a run past this is WEDGED, the one verdict a
    chaos replay must never produce."""
    hangs = [r.hang_seconds for r in plan.rules if r.kind == "hang"]
    return 60.0 + (max(hangs) if hangs else 0.0)


def _chaos_unhealthy(wait: float = 10.0) -> list:
    """Components still not `ok` after the run — a hang release recovers
    them asynchronously, so give the watchdog a scan or two to flip them
    back before judging. The chaos process is fresh, so every registered
    component belongs to the run under test."""
    import time as _time

    from deeplearning4j_tpu.utils import health as _health

    def _bad():
        comps = _health.get_health().status()["components"]
        return sorted(k for k, v in comps.items()
                      if v.get("status") != "ok")

    healthy_by = _time.monotonic() + wait
    unhealthy = _bad()
    while unhealthy and _time.monotonic() < healthy_by:
        _time.sleep(0.1)
        unhealthy = _bad()
    return unhealthy


def _chaos_serving(plan, requests: int, clients: int,
                   deadline_ms) -> dict:
    """Serving preset: concurrent closed-loop clients (two tenants)
    against one ParallelInference under the plan. Invariants checked:
    every client terminates inside the budget, the books balance
    (admitted == completed + shed + failed) PER TENANT as well as in
    aggregate, metered device-seconds sum to the process total, and the
    serving components end healthy."""
    import threading

    import numpy as np

    from deeplearning4j_tpu.parallel.inference import (
        DeadlineExceeded,
        ParallelInference,
        RequestRejected,
    )
    from deeplearning4j_tpu.utils import faultpoints as fp
    from deeplearning4j_tpu.utils import resourcemeter

    resourcemeter.enable()  # spend conservation judged non-vacuously
    n_in = 8
    net = _chaos_net(n_in)
    pi = ParallelInference(net, max_batch_size=4, batch_timeout_ms=2.0,
                           queue_capacity=64, health_stall_after=20.0,
                           component_prefix="chaos_cli")
    counts = {"ok": 0, "fault": 0, "shed": 0, "error": 0}
    lock = threading.Lock()
    rng = np.random.default_rng(0)
    reqs = [rng.standard_normal((1 + i % 4, n_in)).astype(np.float32)
            for i in range(16)]
    per = max(1, requests // clients)

    def client(ci):
        for j in range(per):
            try:
                pi.output(reqs[(ci * 7 + j) % len(reqs)],
                          deadline_ms=deadline_ms,
                          tenant="a" if ci % 2 else "b")
                k = "ok"
            except fp.FaultInjected:
                k = "fault"
            except (DeadlineExceeded, RequestRejected):
                k = "shed"
            except Exception:
                k = "error"
            with lock:
                counts[k] += 1

    wedged = []
    try:
        pi.warmup((n_in,))
        # under --trace-out, tracing was enabled before warmup: drop the
        # per-bucket compile forwards from the ring, or they dominate the
        # export's slowest-traces report as standalone warmup noise
        from deeplearning4j_tpu.utils import tracing as _tracing

        if _tracing.is_enabled():
            _tracing.get_tracer().clear()
        with fp.active(plan):
            threads = [threading.Thread(target=client, args=(i,),
                                        daemon=True,
                                        name=f"dl4j-chaos-cli-{i}")
                       for i in range(clients)]
            for t in threads:
                t.start()
            budget = _chaos_budget(plan)
            for t in threads:
                t.join(timeout=budget)
                if t.is_alive():
                    wedged.append(t.name)
        m = pi.metrics()
        unhealthy = _chaos_unhealthy()
        from deeplearning4j_tpu.utils.metrics import get_registry

        spend_cons = resourcemeter.conservation(
            get_registry().scalar_values())
    finally:
        pi.shutdown()
    # the per-tenant law, non-vacuously: every tenant the workload
    # assigned must actually appear in the books ("a" and "b" alternate
    # by client index — a single-client run only ever offers one)
    offered = {"a" if ci % 2 else "b" for ci in range(clients)}
    tenant_books_ok = (
        offered <= set(m["tenants"])
        and all(b["conservation_ok"] for b in m["tenants"].values()))
    return {
        "workload": {"requests": per * clients, "clients": clients,
                     "deadline_ms": deadline_ms, "outcomes": counts},
        "metrics": {k: m[k] for k in ("admitted", "completed", "shed",
                                      "failed", "rejected")},
        "shed_by": m["shed_by"],
        "tenants": m["tenants"],
        "tenant_conservation": spend_cons,
        "conservation_ok":
            m["admitted"] == m["completed"] + m["shed"] + m["failed"]
            and tenant_books_ok and spend_cons["ok"],
        "wedged_threads": wedged,
        "unhealthy_components": unhealthy,
        "outcome": "wedged" if wedged else "recovered",
    }


def _chaos_training(plan, steps: int) -> dict:
    """Training preset: one epoch over a multi-worker ETL iterator with
    async checkpointing, under the plan — `etl_worker`, `device_put`,
    `ckpt_write` (and `helper_fn` where helpers are registered) all sit
    on this path. A fit that raises is a CLEAN failure; only a fit that
    outlives the budget is a wedge."""
    import tempfile
    import threading

    import numpy as np

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.prefetch import ParallelDataSetIterator
    from deeplearning4j_tpu.train.checkpoint import CheckpointListener
    from deeplearning4j_tpu.utils import faultpoints as fp

    n_in = 8
    net = _chaos_net(n_in)
    rng = np.random.default_rng(0)
    base = [DataSet(rng.standard_normal((8, n_in)).astype(np.float32),
                    np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)])
            for _ in range(steps)]
    ckdir = tempfile.mkdtemp(prefix="dl4j-chaos-ckpt-")
    listener = CheckpointListener(
        ckdir, every_n_iterations=max(2, steps // 4),
        every_n_epochs=None, keep_last=2, async_save=True)
    net.set_listeners(listener)
    result = {}

    def run():
        try:
            net.fit(ParallelDataSetIterator(base, workers=2,
                                            stage="chaos_cli_etl"),
                    epochs=1, async_prefetch=True)
            result["outcome"] = "recovered"
        except fp.FaultInjected as e:
            result["outcome"] = "cleanly_failed"
            result["failure"] = f"FaultInjected: {e}"
        except Exception as e:
            result["outcome"] = "cleanly_failed"
            result["failure"] = f"{type(e).__name__}: {e}"

    with fp.active(plan):
        t = threading.Thread(target=run, daemon=True,
                             name="dl4j-chaos-cli-fit")
        t.start()
        t.join(timeout=_chaos_budget(plan))
        wedged = t.is_alive()
    listener.close()
    if wedged:
        result["outcome"] = "wedged"
    from deeplearning4j_tpu.utils.metrics import get_registry

    scalars = get_registry().scalar_values()
    return {
        "workload": {"steps": steps, "checkpoint_dir": ckdir},
        "checkpoint_write_failures": scalars.get(
            "checkpoint_save_failures_total", 0.0),
        "conservation_ok": True,  # no serving books in this preset
        "wedged_threads": (["dl4j-chaos-cli-fit"] if wedged else []),
        "unhealthy_components": _chaos_unhealthy(),
        **result,
    }


def _chaos_decode(plan, requests: int, clients: int,
                  deadline_ms) -> dict:
    """Decode preset: closed-loop generate() clients against one
    continuous-batching DecodeEngine under the plan (latency + a hang on
    the `decode_step` point). Invariants checked: every client
    terminates inside the budget, the per-tenant books conserve, the
    watchdog actually TRIPPED on the injected hang (a vacuously-green
    run fails), the engine ends healthy again, and carried deadlines
    were shed — not served late — while the step was wedged."""
    import threading

    import numpy as np

    from deeplearning4j_tpu.models.charlstm import char_lstm_network
    from deeplearning4j_tpu.parallel.inference import (
        DeadlineExceeded,
        RequestRejected,
    )
    from deeplearning4j_tpu.serving.decode import DecodeEngine
    from deeplearning4j_tpu.utils import faultpoints as fp
    from deeplearning4j_tpu.utils import health as _health
    from deeplearning4j_tpu.utils import resourcemeter

    resourcemeter.enable()  # spend conservation judged non-vacuously
    vocab = 11
    net = char_lstm_network(vocab_size=vocab, hidden=16, layers=1,
                            tbptt_length=8)
    eng = DecodeEngine(net, n_slots=4,
                       tenant_weights={"a": 2.0, "b": 1.0},
                       default_max_tokens=6, queue_capacity=64,
                       health_stall_after=0.6,
                       component_prefix="chaos_decode")
    counts = {"ok": 0, "shed": 0, "error": 0}
    lock = threading.Lock()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, vocab, size=1 + i % 4).tolist()
               for i in range(16)]
    per = max(1, requests // clients)
    health_seq0 = _health.get_health().last_seq()

    def client(ci):
        for j in range(per):
            try:
                eng.generate_sync(prompts[(ci * 7 + j) % len(prompts)],
                                  max_new_tokens=3 + j % 4,
                                  tenant="a" if ci % 2 else "b",
                                  deadline_ms=deadline_ms)
                k = "ok"
            except (DeadlineExceeded, RequestRejected):
                k = "shed"
            except Exception:
                k = "error"
            with lock:
                counts[k] += 1

    wedged = []
    try:
        # warmup outside the plan: the compile must not eat a hang
        eng.generate([1, 2], max_new_tokens=2, tenant="a").result(60)
        with fp.active(plan):
            threads = [threading.Thread(target=client, args=(i,),
                                        daemon=True,
                                        name=f"dl4j-chaos-dec-{i}")
                       for i in range(clients)]
            for t in threads:
                t.start()
            budget = _chaos_budget(plan)
            for t in threads:
                t.join(timeout=budget)
                if t.is_alive():
                    wedged.append(t.name)
        m = eng.metrics()
        unhealthy = _chaos_unhealthy()
        from deeplearning4j_tpu.utils.metrics import get_registry

        spend_cons = resourcemeter.conservation(
            get_registry().scalar_values())
        tripped = [
            tr for tr in _health.get_health().transitions_since(health_seq0)
            if str(tr.get("component", "")).startswith("chaos_decode")
            and tr.get("to") != "ok"]
    finally:
        eng.shutdown()
    return {
        "workload": {"requests": per * clients, "clients": clients,
                     "deadline_ms": deadline_ms, "outcomes": counts},
        "metrics": {k: m[k] for k in ("admitted", "completed", "shed",
                                      "failed", "rejected")},
        "shed_by": m["shed_by"],
        "tenants": m["tenants"],
        "tenant_conservation": spend_cons,
        "conservation_ok": (m["conservation_ok"]
                            and {"a", "b"} <= set(m["tenants"])
                            and spend_cons["ok"]),
        "watchdog_tripped": bool(tripped),
        "sheds_during_wedge": m["shed"],
        # the gate must not be vacuous: the injected hang must have
        # degraded the engine AND expired carries must have shed
        "loop_exercised": bool(tripped) and m["shed"] >= 1,
        "wedged_threads": wedged,
        "unhealthy_components": unhealthy,
        "outcome": "wedged" if wedged else "recovered",
    }


def _chaos_default_plan(preset: str, seed: int, steps: int = 24):
    from deeplearning4j_tpu.utils import faultpoints as fp

    if preset == "decode":
        # steady latency jitter plus ONE hang long enough to trip the
        # engine's watchdog (stall 0.6s) and outlive every carried
        # deadline — proving degrade -> shed -> recover end to end
        return (fp.FaultPlan(seed=seed)
                .add("decode_step", "latency", p=0.1, latency_ms=15.0)
                .add("decode_step", "hang", every_nth=25, max_fires=1,
                     hang_seconds=2.5))
    if preset == "serving":
        # replica_forward only: the preset drives ParallelInference
        # in-process, so an http_handler rule would never fire — exactly
        # the vacuously-green rule faultpoints.py warns about
        return (fp.FaultPlan(seed=seed)
                .add("replica_forward", "error", p=0.08)
                .add("replica_forward", "latency", p=0.2,
                     latency_ms=10.0))
    if preset == "divergence":
        # seeded NaN at step k (mid-run, past the first checkpoint) —
        # the deterministic rehearsal of detect -> quarantine ->
        # rollback -> recover; the sentinel must bring the fit home
        # with a finite final loss or the run exits 1
        k = max(2, steps // 2)
        return (fp.FaultPlan(seed=seed)
                .add("train_step", "nan", between=(k, k)))
    return (fp.FaultPlan(seed=seed)
            .add("etl_worker", "latency", p=0.2, latency_ms=10.0)
            .add("ckpt_write", "error", every_nth=2, max_fires=1)
            .add("device_put", "latency", p=0.1, latency_ms=5.0))


def _chaos_divergence(plan, steps: int) -> dict:
    """Divergence preset: a deterministic fit with checkpointing and
    the divergence sentinel armed, under a seeded NaN-at-step-k plan
    (the `nan` fault kind taints the batch through the REAL dispatch).
    The resilience loop under test: the sentinel must catch the
    non-finite loss, quarantine the batch, roll back to the last-good
    checkpoint, replay past it, and finish with a FINITE final loss —
    anything else (a raise, a wedge, a NaN final score) is a violated
    verdict."""
    import tempfile
    import threading

    import numpy as np

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.train.checkpoint import CheckpointListener
    from deeplearning4j_tpu.train.sentinel import (
        DivergenceSentinel,
        TrainingDivergedError,
    )
    from deeplearning4j_tpu.utils import faultpoints as fp

    n_in = 8
    net = _chaos_net(n_in)
    rng = np.random.default_rng(0)
    full = DataSet(
        rng.standard_normal((8 * steps, n_in)).astype(np.float32),
        np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8 * steps)])
    ckdir = tempfile.mkdtemp(prefix="dl4j-chaos-ckpt-")
    listener = CheckpointListener(
        ckdir, every_n_iterations=max(2, steps // 6),
        every_n_epochs=None, keep_last=4, async_save=False)
    sentinel = DivergenceSentinel(rollback_after=1, max_rollbacks=2)
    net.set_listeners(listener)
    net.set_sentinel(sentinel)
    result = {}

    def run():
        try:
            net.fit(ListDataSetIterator(full, 8), epochs=1,
                    async_prefetch=False)
            final = float(np.asarray(net._score))
            result["final_score"] = final
            result["final_score_finite"] = bool(np.isfinite(final))
            result["outcome"] = ("recovered" if result["final_score_finite"]
                                 else "diverged")
        except TrainingDivergedError as e:
            result["outcome"] = "diverged"
            result["failure"] = f"TrainingDivergedError: {e}"
            result["dump_path"] = e.dump_path
        except Exception as e:
            result["outcome"] = "diverged"
            result["failure"] = f"{type(e).__name__}: {e}"

    with fp.active(plan):
        t = threading.Thread(target=run, daemon=True,
                             name="dl4j-chaos-cli-fit")
        t.start()
        t.join(timeout=_chaos_budget(plan))
        wedged = t.is_alive()
    if wedged:
        result["outcome"] = "wedged"
    return {
        "workload": {"steps": steps, "checkpoint_dir": ckdir},
        "sentinel": {
            "anomalies": sentinel.anomalies,
            "quarantined": sentinel.quarantined,
            "rollbacks": sentinel.rollbacks,
            "quarantine_records": list(sentinel.records),
            "findings": [f.to_dict() for f in sentinel.findings],
        },
        "conservation_ok": True,  # no serving books in this preset
        "final_score_finite": result.get("final_score_finite", False),
        # the gate must not be vacuous: a finite final loss only counts
        # when the injected divergence actually reached the sentinel —
        # a broken injection chain must fail the rehearsal, not pass it
        "loop_exercised": (sentinel.anomalies >= 1
                           and sentinel.quarantined >= 1),
        "wedged_threads": (["dl4j-chaos-cli-fit"] if wedged else []),
        "unhealthy_components": _chaos_unhealthy(),
        **result,
    }


def _chaos_trace_report(preset: str, path: str) -> dict:
    """Write the run's span export and — for the serving preset — check
    the fault-to-trace linkage: every injected fault's marker must sit
    in a trace that also carries serve/* lifecycle spans, i.e. a chaos
    fault is attributable to the concrete request it hit."""
    from deeplearning4j_tpu.analysis import tracecrit
    from deeplearning4j_tpu.utils import tracing as _tracing

    tracer = _tracing.get_tracer()
    events = tracer.recent()
    tracer.write_jsonl(path)
    traces = tracecrit.group_traces(events)
    faults = [e for e in events if e.get("name") == "fault/injected"]
    linked = sum(
        1 for ev in faults
        if any(e.get("name", "").startswith("serve/")
               for e in traces.get(ev.get("trace"), [])))
    out = {"path": path, "fault_spans": len(faults)}
    if preset == "serving":
        out["fault_spans_linked"] = linked
        out["fault_trace_ok"] = linked == len(faults)
    return out


def cmd_chaos(args) -> int:
    """Replay a seeded FaultPlan outside pytest (utils/faultpoints): run
    the serving or training preset workload under the plan and report
    the canonical event log plus the invariant verdict. Exit 0 when the
    run ends recovered or cleanly failed with the serving books
    balanced; 1 when an invariant broke (a wedge, a conservation
    violation, a component left unhealthy, or — with --trace-out on the
    serving preset — an injected fault whose trace lacks the request's
    lifecycle spans). Two runs of the same plan + preset produce the
    same event log — diff the --json artifacts to prove a replay."""
    import json as _json

    from deeplearning4j_tpu.utils import faultpoints as fp
    from deeplearning4j_tpu.utils import tracing as _tracing

    if args.plan:
        with open(args.plan) as f:
            plan = fp.FaultPlan.from_json(f.read())
        if args.seed is not None:
            plan.seed = int(args.seed)
    else:
        plan = _chaos_default_plan(args.preset, args.seed or 0,
                                   steps=args.steps)
    # the serving/decode rehearsals double as lock-sanitizer coverage:
    # arm DL4J_LOCKCHECK for the run so the fault-riddled schedules
    # (hangs, sheds, swap races) also witness lock-acquisition orders.
    # Disarmed again afterwards — chaos runs in-process under pytest
    # too, and the patches must not outlive the rehearsal there
    lock_audit = None
    lock_armed_here = False
    if args.preset in ("serving", "decode"):
        from deeplearning4j_tpu.utils import locktrace as _locktrace

        if not _locktrace.enabled():
            _locktrace.install()
            lock_armed_here = True
    trace_out = args.trace_out
    if trace_out:
        prev_tracing = _tracing.is_enabled()
        _tracing.get_tracer().clear()
        _tracing.enable(True)
    try:
        if args.preset == "serving":
            report = _chaos_serving(plan, args.requests, args.clients,
                                    args.deadline_ms)
        elif args.preset == "decode":
            report = _chaos_decode(plan, args.requests, args.clients,
                                   args.deadline_ms)
        elif args.preset == "divergence":
            report = _chaos_divergence(plan, args.steps)
        else:
            report = _chaos_training(plan, args.steps)
    finally:
        if trace_out:
            _tracing.enable(prev_tracing)
        if args.preset in ("serving", "decode"):
            # harvest the witnessed graph BEFORE disarming (and disarm
            # even when the preset raised)
            from deeplearning4j_tpu.analysis import (
                concurrency_audit as _ca,
            )

            try:
                cdoc = _ca.report(runtime=True)
                lock_audit = {
                    "edges": len(cdoc["edges"]),
                    "errors": cdoc["summary"]["errors"],
                    "warnings": cdoc["summary"]["warnings"],
                    "findings": [f.name for f in cdoc["findings"]],
                }
            finally:
                if lock_armed_here:
                    _locktrace.uninstall()
    report = {
        "preset": args.preset,
        "plan": _json.loads(plan.to_json()),
        "events": plan.event_log(),
        "invocations": plan.invocations(),
        **report,
    }
    if trace_out:
        report["trace"] = _chaos_trace_report(args.preset, trace_out)
    if lock_audit is not None:
        report["lock_audit"] = lock_audit
    ok = (report["outcome"] in ("recovered", "cleanly_failed")
          and report["conservation_ok"]
          and not report["unhealthy_components"]
          and report.get("loop_exercised", True)
          and report.get("trace", {}).get("fault_trace_ok", True)
          and (lock_audit is None or lock_audit["errors"] == 0))
    report["verdict"] = "ok" if ok else "violated"
    if args.json == "-":
        print(_json.dumps(report, indent=2, default=str))
    elif args.json:
        with open(args.json, "w") as f:
            _json.dump(report, f, indent=2, default=str)
        print(f"wrote {args.json}")
    else:
        print(f"chaos[{args.preset}] seed={plan.seed} "
              f"rules={len(plan.rules)}")
        print(f"  injected: {len(report['events'])} fault(s) over "
              f"{sum(report['invocations'].values())} point "
              f"invocation(s)")
        for e in report["events"][:20]:
            print(f"    {e['point']}#{e['invocation']} {e['kind']} "
                  f"(rule {e['rule']})")
        if len(report["events"]) > 20:
            print(f"    ... {len(report['events']) - 20} more")
        if "metrics" in report:
            print(f"  books: {report['metrics']} "
                  f"(conserved: {report['conservation_ok']})")
        if "sentinel" in report:
            s = report["sentinel"]
            print(f"  sentinel: {s['anomalies']} anomaly(ies), "
                  f"{s['quarantined']} quarantined, "
                  f"{s['rollbacks']} rollback(s)"
                  + (f", final loss {report.get('final_score'):.6g} "
                     f"(finite: {report['final_score_finite']})"
                     if report.get("final_score") is not None else ""))
        if report.get("failure"):
            print(f"  failure: {report['failure']}")
        if lock_audit is not None:
            print(f"  lock audit: {lock_audit['edges']} order edge(s), "
                  f"{lock_audit['errors']} error(s) / "
                  f"{lock_audit['warnings']} warning(s) (sanitizer "
                  f"armed for the rehearsal)")
        if report.get("trace"):
            tr = report["trace"]
            print(f"  trace export: {tr['path']} "
                  f"({tr['fault_spans']} fault span(s)"
                  + (f", {tr.get('fault_spans_linked')} linked to request "
                     f"traces" if "fault_trace_ok" in tr else "")
                  + ")")
        print(f"  outcome: {report['outcome']}  "
              f"verdict: {report['verdict']}")
    return 0 if ok else 1


def cmd_lint(args) -> int:
    """Concurrency/robustness lint over source paths (analysis/lint.py,
    CC001-CC006). The t1 gate wraps this via scripts/lint.sh with the
    committed baseline; here it is exposed directly for ad-hoc runs."""
    from deeplearning4j_tpu.analysis.lint import main as lint_main

    argv = list(args.paths)
    if args.json:
        argv += ["--json", args.json]
    if args.baseline:
        argv += ["--baseline", args.baseline]
    return lint_main(argv)


def cmd_locks(args) -> int:
    """Merged lock-discipline audit (analysis/concurrency_audit,
    CN001-CN003): the lexical lock-order graph always; the runtime
    sanitizer's witnessed edges too when it is armed (DL4J_LOCKCHECK=1)
    or when --smoke runs the serving+decode+sparse exercise in-process.
    scripts/t1.sh wraps the --smoke --baseline form as the
    `T1 LOCK AUDIT:` gate."""
    from deeplearning4j_tpu.analysis.concurrency_audit import main as ca_main

    argv = []
    if args.smoke:
        argv.append("--smoke")
    if args.json:
        argv += ["--json", args.json]
    if args.baseline:
        argv += ["--baseline", args.baseline]
    return ca_main(argv)


def main(argv=None) -> int:
    # honor JAX_PLATFORMS even when a sitecustomize imported jax before
    # this process's env was consulted (config update beats env once the
    # interpreter is up; backends initialize lazily)
    import os

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    ap = argparse.ArgumentParser(prog="deeplearning4j_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("train", help="train a serialized model from flags")
    t.add_argument("--model-path", required=True)
    t.add_argument("--data", required=True)
    t.add_argument("--epochs", type=int, default=1)
    t.add_argument("--batch-size", type=int, default=32)
    t.add_argument("--workers", type=int, default=1)
    t.add_argument("--data-parallel", action="store_true",
                   help="shard batches over all visible devices")
    t.add_argument("--output", default=None)
    t.add_argument("--print-every", type=int, default=10)
    t.add_argument("--num-examples", type=int, default=None,
                   help="cap the training set size (mnist/cifar10/lfw)")
    t.add_argument("--ui-port", type=int, default=None)
    t.add_argument("--ui-hold", action="store_true")
    t.set_defaults(fn=cmd_train)

    e = sub.add_parser("evaluate", help="evaluate a serialized model")
    e.add_argument("--model-path", required=True)
    e.add_argument("--data", required=True)
    e.add_argument("--batch-size", type=int, default=128)
    e.set_defaults(fn=cmd_evaluate)

    k = sub.add_parser("knn-server", help="REST k-NN server over a VPTree")
    k.add_argument("--ndarray-path", required=True)
    k.add_argument("--port", type=int, default=9000)
    k.add_argument("--similarity-function", default="euclidean")
    k.add_argument("--invert", action="store_true")
    k.set_defaults(fn=cmd_knn_server)

    i = sub.add_parser(
        "inference-server",
        help="REST model serving (bucketed+pipelined ParallelInference)")
    i.add_argument("--model-path", required=True)
    i.add_argument("--port", type=int, default=9100)
    i.add_argument("--max-batch-size", type=int, default=64)
    i.add_argument("--batch-timeout-ms", type=float, default=2.0)
    i.add_argument("--buckets", default=None,
                   help="comma-separated batch-size buckets")
    i.add_argument("--warmup-shape", default=None,
                   help="feature shape to precompile, e.g. 784 or 28,28,1")
    i.add_argument("--replicas", type=int, default=1,
                   help=">=2 serves through a self-healing ReplicaPool")
    i.add_argument("--decode-slots", type=int, default=0,
                   help=">0 mounts the continuous-batching decode "
                        "engine (POST /generate) with this many slots")
    i.add_argument("--decode-eos", type=int, default=None,
                   help="EOS token id ending a generated sequence early")
    i.add_argument("--decode-max-tokens", type=int, default=64,
                   help="default max_tokens for /generate requests")
    i.set_defaults(fn=cmd_inference_server)

    u = sub.add_parser("ui-server", help="dashboard over a stats file")
    u.add_argument("--stats-file", required=True)
    u.add_argument("--port", type=int, default=9090)
    u.set_defaults(fn=cmd_ui_server)

    r = sub.add_parser(
        "report", help="standalone self-contained HTML training report")
    r.add_argument("--stats-file", required=True)
    r.add_argument("--output", required=True)
    r.add_argument("--session", default=None,
                   help="session id (default: newest)")
    r.add_argument("--title", default="training report")
    r.set_defaults(fn=cmd_report)

    p = sub.add_parser(
        "profile",
        help="op-family device-time breakdown from a jax-profiler trace")
    p.add_argument("--log-dir", required=True,
                   help="directory a jax.profiler trace was captured into")
    p.add_argument("--json", default=None,
                   help="write the aggregation to this path as JSON")
    p.add_argument("--top", type=int, default=40)
    p.add_argument("--preset", default=None,
                   help="attach the static cost model of this preset's "
                        "train step (resnet50|tiny_resnet|charlstm): "
                        "per-family flops/bytes columns + roofline "
                        "context next to the measured times")
    p.add_argument("--batch", type=int, default=8,
                   help="cost-model batch size (--preset)")
    p.add_argument("--timesteps", type=int, default=16,
                   help="cost-model sequence length for recurrent "
                        "presets (--preset)")
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--classes", type=int, default=None)
    p.set_defaults(fn=cmd_profile)

    pf = sub.add_parser(
        "perf",
        help="static device cost model of a preset train step: "
             "per-family FLOPs/bytes, roofline verdicts, activation-peak "
             "estimate, XLA cross-check (analysis/costmodel; exit 1 on "
             "JX007/JX008)")
    pf.add_argument("--preset", required=True,
                    choices=("resnet50", "tiny_resnet", "charlstm"))
    pf.add_argument("--batch", type=int, default=8,
                    help="abstract batch size to model the step at")
    pf.add_argument("--timesteps", type=int, default=16,
                    help="abstract sequence length for recurrent presets")
    pf.add_argument("--image-size", type=int, default=None,
                    help="override preset image size (resnet50)")
    pf.add_argument("--classes", type=int, default=None,
                    help="override preset class count (resnet50)")
    pf.add_argument("--tolerance", type=float, default=0.10,
                    help="JX007 cross-check tolerance vs XLA "
                         "cost_analysis")
    pf.add_argument("--xla", action="store_true",
                    help="compile the step for the XLA cost_analysis "
                         "cross-check (expensive; skipped when the "
                         "backend does not expose it)")
    pf.add_argument("--no-vs-prior", action="store_true",
                    help="skip the FLOP-drift check against the newest "
                         "committed BENCH_r*.json")
    pf.add_argument("--top", type=int, default=20,
                    help="family-table rows to print")
    pf.add_argument("--json", default=None, metavar="PATH",
                    help="machine-readable report ('-' = stdout)")
    pf.set_defaults(fn=cmd_perf)

    m = sub.add_parser(
        "metrics",
        help="metrics snapshot: scrape a server's /metrics or dump this "
             "process's registry (utils/metrics.py)")
    m.add_argument("--url", default=None,
                   help="base URL of a running server, e.g. "
                        "http://127.0.0.1:9100 (omit to dump the local "
                        "process registry)")
    m.add_argument("--format", choices=("json", "prometheus"),
                   default="json")
    m.add_argument("--output", default=None,
                   help="write to this file instead of stdout")
    m.add_argument("--timeout", type=float, default=10.0)
    m.add_argument("--watch", type=float, default=None, metavar="SECS",
                   help="re-scrape every SECS seconds, printing counter "
                        "deltas and gauge values (ctrl-C to stop)")
    m.add_argument("--watch-count", type=int, default=0,
                   help="stop after N watch ticks (0 = until ctrl-C)")
    m.add_argument("--ledger", default=None, metavar="PATH",
                   help="replay a recorded run ledger tick-by-tick with "
                        "the --watch delta rendering (post-mortems "
                        "without the process alive); --watch-count caps "
                        "the ticks")
    m.set_defaults(fn=cmd_metrics)

    tn = sub.add_parser(
        "tenants",
        help="per-tenant chip-budget readout: device-seconds by tier, "
             "wire/HBM bytes, tokens, admission books, conservation "
             "(utils/resourcemeter) — in-process, from a server's "
             "GET /tenants, or replayed from a run ledger")
    tn.add_argument("--url", default=None,
                    help="base URL of a running inference server (its "
                         "GET /tenants is appended; omit for the local "
                         "process view)")
    tn.add_argument("--ledger", default=None, metavar="PATH",
                    help="rebuild the spend table from a recorded run "
                         "ledger's final sample instead of a live "
                         "process (same parse as the live view)")
    tn.add_argument("--timeout", type=float, default=10.0)
    tn.add_argument("--json", action="store_true",
                    help="print the raw document instead of rendering")
    tn.set_defaults(fn=cmd_tenants)

    sl = sub.add_parser(
        "slo",
        help="offline SLO re-evaluation of a recorded run ledger "
             "(analysis/slo); --check exits 1 when ERROR rules fired — "
             "the CI/soak gate")
    sl.add_argument("--ledger", required=True, metavar="PATH",
                    help="run-ledger JSONL artifact (utils/runledger)")
    sl.add_argument("--rules", default=None, metavar="JSON",
                    help="rule-set JSON (list of SLORule dicts, or "
                         "{'rules': [...]}); default: the pack embedded "
                         "in the ledger's manifest")
    sl.add_argument("--check", action="store_true",
                    help="exit 1 when any ERROR-severity rule fired at "
                         "any point during the run")
    sl.add_argument("--json", default=None, metavar="PATH",
                    help="machine-readable report ('-' = stdout)")
    sl.set_defaults(fn=cmd_slo)

    rn = sub.add_parser(
        "runs",
        help="list recorded run ledgers, or `runs compare A B` for "
             "per-metric regression deltas between two runs")
    rn.add_argument("paths", nargs="*",
                    help="a directory to list, or: compare "
                         "<reference.jsonl> <candidate.jsonl>")
    rn.add_argument("--dir", default=None,
                    help="directory to list ledgers from (default: .)")
    rn.add_argument("--threshold", type=float, default=0.25,
                    help="flag series whose rate/mean ratio moves more "
                         "than this fraction (compare)")
    rn.add_argument("--top", type=int, default=20,
                    help="flagged rows to print (compare)")
    rn.add_argument("--json", default=None, metavar="PATH",
                    help="machine-readable report ('-' = stdout)")
    rn.set_defaults(fn=cmd_runs)

    tr = sub.add_parser(
        "trace",
        help="distributed-trace readout: span trees, critical path and "
             "per-stage breakdown from a JSONL export or a live server's "
             "GET /trace (analysis/tracecrit)")
    tr.add_argument("source",
                    help="JSONL span export file, or a server base URL "
                         "(e.g. http://127.0.0.1:9100 — /trace is "
                         "appended)")
    tr.add_argument("--top", type=int, default=5,
                    help="how many of the slowest traces to report")
    tr.add_argument("--trace-id", default=None,
                    help="resolve one specific trace (accepts a unique "
                         "prefix) — paste a histogram exemplar's "
                         "trace_id from GET /metrics")
    tr.add_argument("--timeout", type=float, default=10.0)
    tr.add_argument("--json", default=None, metavar="PATH",
                    help="machine-readable report ('-' = stdout)")
    tr.set_defaults(fn=cmd_trace)

    bb = sub.add_parser(
        "blackbox",
        help="render a flight-recorder crash dump (final-steps timeline, "
             "events, component health, thread stacks)")
    bb.add_argument("dump", help="path to a blackbox JSON dump "
                                 "(utils/blackbox.install_crash_hooks)")
    bb.add_argument("--steps", type=int, default=32,
                    help="how many of the final steps to render")
    bb.add_argument("--json", action="store_true",
                    help="pretty-print the raw dump instead of rendering")
    bb.set_defaults(fn=cmd_blackbox)

    rs = sub.add_parser(
        "resume",
        help="describe + validate the newest checkpoint in a directory "
             "(exit 1 when empty/torn) — pre-flight for "
             "fit(resume_from=...)")
    rs.add_argument("directory", help="checkpoint directory "
                                      "(train.checkpoint.CheckpointListener)")
    rs.add_argument("--json", action="store_true",
                    help="machine-readable output")
    rs.add_argument("--no-validate", action="store_true",
                    help="skip the digest verification and full model "
                         "load (metadata only)")
    rs.set_defaults(fn=cmd_resume)

    d = sub.add_parser(
        "doctor",
        help="static model analysis: config shape/dtype flow + jaxpr "
             "train-step audit (exit 1 on ERROR findings)")
    d.add_argument("--model-path", default=None,
                   help="serialized model (this framework's zip, DL4J zip, "
                        "or Keras .h5)")
    d.add_argument("--preset", default=None,
                   help="built-in config instead of a file: "
                        "resnet50|tiny_resnet|charlstm")
    d.add_argument("--image-size", type=int, default=None,
                   help="override preset image size (resnet50)")
    d.add_argument("--classes", type=int, default=None,
                   help="override preset class count (resnet50)")
    d.add_argument("--batch", type=int, default=2,
                   help="abstract batch size for the jaxpr audit")
    d.add_argument("--timesteps", type=int, default=8,
                   help="abstract sequence length for recurrent models")
    d.add_argument("--devices", type=int, default=None, metavar="N",
                   help="audit the sharded multi-chip step: attach a "
                        "data mesh over N devices (clamped to the "
                        "platform) before the jaxpr/donation audit")
    d.add_argument("--no-jaxpr", action="store_true",
                   help="config shapeflow only (skip the abstract trace)")
    d.add_argument("--json", default=None, metavar="PATH",
                   help="machine-readable findings ('-' = stdout)")
    d.set_defaults(fn=cmd_doctor)

    ch = sub.add_parser(
        "chaos",
        help="replay a seeded FaultPlan over a preset workload "
             "(utils/faultpoints; exit 1 on wedge/conservation "
             "violation)")
    ch.add_argument("--preset", required=True,
                    choices=("serving", "training", "divergence",
                             "decode"),
                    help="workload to run under the plan (divergence: "
                         "seeded NaN-at-step-k fit with the sentinel "
                         "armed — exit 1 unless quarantine/rollback "
                         "recover a finite final loss; decode: a "
                         "continuous-batching engine under decode_step "
                         "latency + hang — exit 1 unless the watchdog "
                         "degraded/recovered it with carried deadlines "
                         "shed and books conserved)")
    ch.add_argument("--plan", default=None, metavar="JSON",
                    help="FaultPlan JSON file (default: a built-in plan "
                         "for the preset)")
    ch.add_argument("--seed", type=int, default=None,
                    help="override the plan's seed (default plan: 0)")
    ch.add_argument("--requests", type=int, default=60,
                    help="serving preset: total requests")
    ch.add_argument("--clients", type=int, default=6,
                    help="serving preset: concurrent client threads")
    ch.add_argument("--deadline-ms", type=float, default=2000.0,
                    help="serving preset: per-request deadline budget")
    ch.add_argument("--steps", type=int, default=24,
                    help="training preset: batches in the epoch")
    ch.add_argument("--json", default=None, metavar="PATH",
                    help="machine-readable report ('-' = stdout) — diff "
                         "two runs' `events` to prove a replay")
    ch.add_argument("--trace-out", default=None, metavar="PATH",
                    help="run with tracing on and write the span export "
                         "(JSONL) here; the serving preset additionally "
                         "gates on every injected fault being linked to "
                         "a request trace (render with `cli trace`)")
    ch.set_defaults(fn=cmd_chaos)

    ln = sub.add_parser(
        "lint",
        help="concurrency/robustness lint over source paths "
             "(analysis/lint.py; scripts/lint.sh is the gated form)")
    ln.add_argument("paths", nargs="*",
                    help="files/dirs (default: deeplearning4j_tpu + "
                         "bench.py)")
    ln.add_argument("--json", default=None, metavar="PATH",
                    help="machine-readable findings ('-' = stdout)")
    ln.add_argument("--baseline", default=None, metavar="FILE",
                    help="suppress baselined ERROR names; exit 1 only on "
                         "new ones")
    ln.set_defaults(fn=cmd_lint)

    lk = sub.add_parser(
        "locks",
        help="merged static+runtime lock-discipline audit "
             "(analysis/concurrency_audit, CN001-CN003; "
             "DL4J_LOCKCHECK=1 arms the runtime half)")
    lk.add_argument("--smoke", action="store_true",
                    help="arm the sanitizer and run the serving + decode "
                         "+ sparse exercise before reporting")
    lk.add_argument("--json", default=None, metavar="PATH",
                    help="machine-readable report ('-' = stdout)")
    lk.add_argument("--baseline", default=None, metavar="FILE",
                    help="suppress baselined CN names "
                         "(scripts/lock_baseline.txt); exit 1 only on "
                         "new ones")
    lk.set_defaults(fn=cmd_locks)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
