"""Always-on device performance & memory accounting — the runtime half
of the device observability layer (analysis/costmodel.py is the static
half; each checks the other).

The fit loop's host-side phase timers (PR 3) can say the host is not
the bottleneck, but every *device*-side number — step time, MFU,
FLOP/s — previously existed only in bench runs. This module makes them
first-class, always-on series at fixed cost:

* **Sampled device time**: every `sample_every`-th dispatch the
  profiler runs ONE `block_until_ready` on that step's score; wall time
  between consecutive samples divided by the steps in between is the
  per-step device-visible time. Unsampled steps cost two integer ops —
  the async dispatch pipeline never bubbles between samples. Under
  tier-1 sampling is OFF (`sample_every=0`, set by tests/conftest.py)
  so the suite's timing stays stable.
* **Live MFU**: `step_mfu` and `step_flops_per_second` gauges computed
  from the measured window × the net's model FLOPs — sourced from the
  jaxpr cost model when one was attached (`net.attach_cost_model`,
  which bench.py and `cli perf` do), else from the analytic per-layer
  estimator (`utils/flops`); the `source` label says which, so an MFU
  number can always be traced to its FLOP accounting.
* **HBM watermarks**: `device_memory_bytes{kind=params|updater|
  activations_est|live}` gauges polled at each sample — params/updater
  from the net's buffers, `activations_est` from the attached static
  model, `live` from JAX device memory stats where the backend exposes
  them (TPU/GPU; on CPU the sum of live jax arrays stands in). The
  flight recorder folds these into its periodic registry deltas, so a
  post-crash dump shows the memory trajectory leading into an OOM.
* **OOM forensics**: `is_oom()` recognizes RESOURCE_EXHAUSTED escaping
  the fit loop or the serving dispatcher; `oom_forensics()` records the
  largest live device buffers alongside the static activation estimate
  and dumps the flight recorder — rendered by `cli blackbox` as an "OOM
  forensics" section. Deterministically injectable: the `oom` fault
  kind (utils/faultpoints) raises an error that takes exactly this
  path.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import List, Optional

from deeplearning4j_tpu.utils import blackbox as _blackbox
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import resourcemeter as _resourcemeter

logger = logging.getLogger("deeplearning4j_tpu")

# every Nth fit dispatch pays one blocking score read; 0 disables the
# sampled sync entirely (tier-1 sets this — timing-stable tests)
DEFAULT_SAMPLE_EVERY = int(os.environ.get("DL4J_DEVPROF_SAMPLE_EVERY", "16"))

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "Resource exhausted")


def is_oom(exc: BaseException) -> bool:
    """Does this exception look like a device allocator failure? XLA
    surfaces OOM as XlaRuntimeError('RESOURCE_EXHAUSTED: ...'); the
    injected `oom` fault kind carries the same marker by construction."""
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def largest_live_buffers(top: int = 12) -> List[dict]:
    """The biggest live device arrays right now — the "what is actually
    holding HBM" half of an OOM dump. Never raises (forensics must not
    shadow the failure being diagnosed)."""
    try:
        arrays = _jax().live_arrays()
    except Exception:
        return []
    seen = []
    for a in arrays:
        try:
            seen.append({
                "shape": tuple(int(s) for s in a.shape),
                "dtype": str(a.dtype),
                "nbytes": int(a.nbytes),
            })
        except Exception:
            continue
    seen.sort(key=lambda d: -d["nbytes"])
    return seen[:top]


def _jax():
    import jax

    return jax


class DeviceProfiler:
    """Process-global step accounting. One instance (`get_profiler()`);
    per-net sampling state lives on the net (`net._devprof_state`) so
    concurrent fits never share a window."""

    def __init__(self, sample_every: Optional[int] = None):
        self.sample_every = (DEFAULT_SAMPLE_EVERY if sample_every is None
                             else int(sample_every))
        self._ins = None
        self._lock = threading.Lock()

    def configure(self, sample_every: int) -> "DeviceProfiler":
        """0 disables the sampled device sync (repo 0-disables
        convention); the memory/MFU gauges then only move when a sample
        is forced (`sample_now`) or a cost model is attached."""
        self.sample_every = int(sample_every)
        return self

    def _instruments(self):
        ins = self._ins
        if ins is None:
            reg = _metrics.get_registry()
            with self._lock:
                ins = self._ins
                if ins is None:
                    ins = self._ins = {
                        "mfu": reg.gauge(
                            "step_mfu",
                            "measured model-FLOPs utilization over the "
                            "last devprof sample window", ("source",)),
                        "fps": reg.gauge(
                            "step_flops_per_second",
                            "model FLOP/s over the last devprof sample "
                            "window", ("source",)),
                        "step_seconds": reg.gauge(
                            "step_device_seconds",
                            "per-step device-visible time over the last "
                            "devprof sample window"),
                        "samples": reg.counter(
                            "devprof_samples_total",
                            "sampled block_until_ready device-time "
                            "measurements").labels(),
                        "memory": reg.gauge(
                            "device_memory_bytes",
                            "device memory watermarks polled at devprof "
                            "samples", ("kind",)),
                        "oom": reg.counter(
                            "oom_total",
                            "RESOURCE_EXHAUSTED failures that reached "
                            "the OOM forensics path", ("where",)),
                    }
        return ins

    # -- the fit-loop hook ---------------------------------------------------

    def on_step(self, net, n_examples: int, score) -> None:
        """Called by netbase._timed_fit after every dispatch. Unsampled
        steps: two integer adds and a modulo — the fixed cost the
        overhead A/B test pins <1% of the fit loop."""
        se = self.sample_every
        if se <= 0:
            return
        st = self._state(net)
        st["dispatches"] += 1
        st["examples"] += n_examples
        if st["dispatches"] % se:
            return
        self._sample(net, st, score)

    def sample_now(self, net, score=None) -> None:
        """Force one sample outside the cadence (tests; end-of-fit)."""
        self._sample(net, self._state(net), score)

    @staticmethod
    def _state(net) -> dict:
        st = getattr(net, "_devprof_state", None)
        if st is None:
            st = net._devprof_state = {
                "dispatches": 0, "examples": 0, "last_t": None,
                "iter_at_last": None,
                "params_bytes": None, "updater_bytes": None,
            }
        return st

    def _sample(self, net, st: dict, score) -> None:
        ins = self._instruments()
        try:
            if score is not None:
                _jax().block_until_ready(score)
        except Exception:
            pass  # a failed sync is the step's problem, not the sampler's
        now = time.perf_counter()
        last = st["last_t"]
        iteration = int(getattr(net, "iteration", 0))
        dt = 0.0
        window_examples = st["examples"]
        if last is not None and now > last and st["examples"] > 0:
            dt = now - last
            per_example, source = net.model_flops_per_example()
            # optimizer steps, NOT dispatches: one fused/TBPTT dispatch
            # advances the iteration counter by its whole segment count,
            # and per-step device time must divide by that
            prev_iter = st.get("iter_at_last")
            steps = max(1, iteration - prev_iter) if prev_iter is not None \
                else max(1, st["dispatches"])
            ins["step_seconds"].labels().set(dt / steps)
            if per_example:
                # PER-CHIP accounting: a mesh-attached net consumes the
                # global batch across n data shards, so the model FLOP/s
                # divide by n before meeting the per-chip peak —
                # otherwise multi-chip MFU over-reports n×
                n_chips = _data_shards_of(net)
                fps = per_example * st["examples"] / dt / n_chips
                from deeplearning4j_tpu.utils.flops import (
                    peak_flops_per_chip,
                )

                ins["fps"].labels(source).set(fps)
                ins["mfu"].labels(source).set(fps / peak_flops_per_chip())
            ins["samples"].inc()
        st["last_t"] = now
        st["iter_at_last"] = iteration
        st["examples"] = 0
        self.poll_memory(net, st)
        if dt > 0:
            # tenant chip-budget attribution rides the SAME measured
            # window (no extra sync): after poll_memory so the cached
            # params/updater byte sums exist for the HBM gauge. One
            # module-global read when the process is unmetered.
            _resourcemeter.note_device_window(net, dt,
                                              examples=window_examples)

    # -- memory watermarks ---------------------------------------------------

    def poll_memory(self, net=None, st: Optional[dict] = None) -> dict:
        """Refresh the `device_memory_bytes{kind}` gauges. Cheap:
        params/updater byte sums are cached per net (their shapes are
        static for a fit); `live` reads the backend allocator where
        available, else sums live jax arrays (CPU stand-in)."""
        ins = self._instruments()
        out = {}
        if net is not None:
            if st is None:
                st = getattr(net, "_devprof_state", None) or {}
            pb = st.get("params_bytes")
            if pb is None:
                pb = st["params_bytes"] = _tree_bytes(net.params_list)
                st["updater_bytes"] = _tree_bytes(net.upd_state)
            out["params"] = pb
            out["updater"] = st.get("updater_bytes", 0)
            attached = getattr(net, "_cost_model_meta", None)
            if attached and attached.get("activation_peak_bytes"):
                # activations are batch-sharded on a mesh-attached net:
                # the per-chip estimate divides by the data-axis size
                out["activations_est"] = (
                    attached["activation_peak_bytes"]
                    // _data_shards_of(net))
        live = device_bytes_in_use()
        if live is not None:
            out["live"] = live
        for kind, v in out.items():
            ins["memory"].labels(kind).set(float(v))
        return out

    # -- OOM forensics -------------------------------------------------------

    def oom_forensics(self, where: str, exc: BaseException,
                      net=None) -> Optional[str]:
        """RESOURCE_EXHAUSTED escaped a hot path: record the largest
        live buffers and the static memory picture, then dump the
        flight recorder. Returns the dump path (None when the dump
        itself failed — never raises; the OOM is the story)."""
        try:
            ins = self._instruments()
            ins["oom"].labels(where).inc()
            top = largest_live_buffers()
            static = {}
            if net is not None:
                try:
                    static["params_bytes"] = _tree_bytes(net.params_list)
                    static["updater_bytes"] = _tree_bytes(net.upd_state)
                except Exception:
                    pass
                meta = getattr(net, "_cost_model_meta", None)
                if meta is None:
                    # no model attached: one abstract trace now, CACHED
                    # on the net — a fit-path OOM pays it while dying,
                    # and a serving-path OOM (the process survives,
                    # clients retry) must not re-trace per failing
                    # request. Failures cache too, for the same reason.
                    try:
                        from deeplearning4j_tpu.analysis.costmodel import (
                            train_step_cost,
                        )

                        cm = train_step_cost(net, batch_size=2)
                        meta = {
                            "activation_peak_bytes":
                                cm.activation_peak_bytes,
                            "resident_bytes": cm.resident_bytes,
                            "largest_activation": cm.largest_activation,
                            "source": "costmodel(post-hoc, batch=2)",
                        }
                    except Exception:
                        meta = {"source": "unavailable"}
                    try:
                        net._cost_model_meta = meta
                    except Exception:
                        pass
                if meta and meta.get("source") != "unavailable":
                    static["activation_peak_bytes"] = meta.get(
                        "activation_peak_bytes")
                    static["largest_activation"] = meta.get(
                        "largest_activation")
                    static["flops_source"] = meta.get("source")
            live = device_bytes_in_use()
            if live is not None:
                static["live_bytes"] = live
            rec = _blackbox.get_recorder()
            rec.record_event("oom", where=where,
                             error=str(exc)[:400],
                             top_buffers=top, static=static)
            return rec.dump(reason=f"RESOURCE_EXHAUSTED in {where}: "
                                   f"{str(exc)[:200]}")
        except Exception:
            logger.exception("OOM forensics failed")
            return None


def _data_shards_of(net) -> int:
    """Data-axis shard count of a mesh-attached net (1 otherwise) —
    the divisor that keeps every per-chip number per-chip."""
    plan = getattr(net, "_mesh_plan", None)
    n = getattr(plan, "n_data_shards", 1) if plan is not None else 1
    return max(1, int(n))


def _tree_bytes(tree) -> int:
    """PER-CHIP byte sum of a pytree: sharded leaves (a tp split, a
    data-sharded batch) count their per-device shard, replicated leaves
    their full size — `device_memory_bytes{kind}` is a single chip's
    watermark, not the global footprint."""
    total = 0
    try:
        for leaf in _jax().tree_util.tree_leaves(tree):
            nb = getattr(leaf, "nbytes", None)
            if nb is None:
                continue
            nb = int(nb)
            sh = getattr(leaf, "sharding", None)
            if sh is not None:
                try:
                    shard = sh.shard_shape(leaf.shape)
                    size = 1
                    for s in shard:
                        size *= int(s)
                    nb = size * int(leaf.dtype.itemsize)
                except Exception:
                    pass
            total += nb
    except Exception:
        return 0
    return total


def device_bytes_in_use() -> Optional[int]:
    """Allocator bytes-in-use of device 0 where the backend reports it
    (TPU/GPU memory_stats); on CPU the sum of live jax array bytes —
    a weaker but still trajectory-shaped signal. None when neither
    works."""
    try:
        jax = _jax()
        dev = jax.devices()[0]
        stats = getattr(dev, "memory_stats", lambda: None)()
        if stats and stats.get("bytes_in_use") is not None:
            return int(stats["bytes_in_use"])
        return sum(int(a.nbytes) for a in jax.live_arrays())
    except Exception:
        return None


# -- the process-global profiler ----------------------------------------------

_PROFILER = DeviceProfiler()


def get_profiler() -> DeviceProfiler:
    return _PROFILER


def configure(sample_every: int) -> DeviceProfiler:
    return _PROFILER.configure(sample_every)


def oom_forensics(where: str, exc: BaseException, net=None) -> Optional[str]:
    return _PROFILER.oom_forensics(where, exc, net=net)
