"""Stats storage SPI + implementations.

Reference contract: api/storage/StatsStorage.java (sessions -> static
info + ordered updates, with attachable listeners notified on new
records) and the StatsStorageRouter producer side. Impls here:

- InMemoryStatsStorage — dict-backed (reference: InMemoryStatsStorage)
- FileStatsStorage     — append-only log of binary records (codec.py),
  readable cold (reference: FileStatsStorage)
- SqliteStatsStorage   — indexed durable store (reference:
  MapDBStatsStorage / J7FileStatsStorage)
- RemoteUIStatsStorageRouter — HTTP POST producer for a remote UI server
  (reference: RemoteReceiverModule + remote-iterationlisteners)
"""

from __future__ import annotations

import json
import os
import queue
import struct
import threading
import urllib.request
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.ui.codec import decode_record, encode_record
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import tracing as _tracing
from deeplearning4j_tpu.utils.concurrency import QueueAborted, get_abortable
from deeplearning4j_tpu.utils.jsonhttp import traced_headers


class StatsStorageRouter:
    """Producer-side SPI (reference: api/storage/StatsStorageRouter.java)."""

    def put_static_info(self, session_id: str, info: dict) -> None:
        raise NotImplementedError

    def put_update(self, session_id: str, record: dict) -> None:
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Reader-side SPI (reference: api/storage/StatsStorage.java)."""

    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def get_static_info(self, session_id: str) -> Optional[dict]:
        raise NotImplementedError

    def get_updates(self, session_id: str,
                    since_iteration: int = -1) -> List[dict]:
        raise NotImplementedError

    def latest_session_id(self) -> Optional[str]:
        """Most recently ACTIVE session — newest update timestamp, falling
        back to the static start_time for sessions that have not reported
        an update yet. The ONE definition of "current session" shared by
        the dashboard (ui/server.py) and the standalone report
        (ui/report.py); random session-id suffixes don't sort by age."""
        ids = self.list_session_ids()
        if not ids:
            return None

        def last_ts(sid):
            ups = self.get_updates(sid)
            if ups:
                return ups[-1].get("ts", 0.0)
            st = self.get_static_info(sid) or {}
            return st.get("start_time", 0.0)

        return max(ids, key=last_ts)

    # listener routing (reference: StatsStorageListener)
    def register_listener(self, fn: Callable[[str, dict], None]) -> None:
        if not hasattr(self, "_listeners"):
            self._listeners = []
        self._listeners.append(fn)

    def _notify(self, session_id: str, record: dict) -> None:
        for fn in getattr(self, "_listeners", []):
            fn(session_id, record)


class InMemoryStatsStorage(StatsStorage):
    def __init__(self):
        self._static: Dict[str, dict] = {}
        self._updates: Dict[str, List[dict]] = {}
        self._lock = threading.Lock()

    def put_static_info(self, session_id, info):
        with self._lock:
            self._static[session_id] = dict(info)
            self._updates.setdefault(session_id, [])

    def put_update(self, session_id, record):
        with self._lock:
            self._updates.setdefault(session_id, []).append(dict(record))
        self._notify(session_id, record)

    def list_session_ids(self):
        with self._lock:
            return sorted(set(self._static) | set(self._updates))

    def get_static_info(self, session_id):
        with self._lock:
            return self._static.get(session_id)

    def get_updates(self, session_id, since_iteration=-1):
        with self._lock:
            ups = list(self._updates.get(session_id, []))
        return [u for u in ups if u.get("iteration", 0) > since_iteration]


def _downsample_oldest(rows: List[dict], cap: int) -> List[dict]:
    """Retention/compaction policy shared by the durable stats stores:
    keep the NEWEST `cap // 2` rows raw and thin the older remainder by
    uniform stride so the total lands back at <= `cap` — history keeps
    its full time extent at reduced resolution while recent records stay
    exact (the rollup idea from utils/runledger, applied to the
    reference's unbounded StatsStorage). The newest row always survives
    and order is preserved, so `get_updates(since_iteration=...)`
    answers consistently on a capped store."""
    if len(rows) <= cap:
        return rows
    tail_n = max(1, cap // 2)
    head, tail = rows[:-tail_n], rows[-tail_n:]
    keep_n = max(1, cap - tail_n)
    stride = max(1, -(-len(head) // keep_n))  # ceil division
    return head[::stride] + tail


class FileStatsStorage(StatsStorage):
    """Append-only log: [u8 kind][u16 session_len][session utf8]
    [u32 payload_len][payload] where kind 0 = static JSON, 1 = binary
    update record. Cold-readable — open an existing path to browse a
    finished run (the dashboard does exactly this).

    `max_updates_per_session` bounds the per-session update rows: past
    the cap the OLDEST records are downsampled (uniform stride over the
    older half; the newest half stays raw) and the log is compacted via
    tmp + os.replace — a reference FileStatsStorage fed by a week-long
    soak grows without bound; this one converges to ~cap rows per
    session. 0/None disables (the reference behavior)."""

    _KIND_STATIC = 0
    _KIND_UPDATE = 1

    def __init__(self, path: str,
                 max_updates_per_session: Optional[int] = None):
        self.path = path
        self.max_updates_per_session = (
            int(max_updates_per_session) if max_updates_per_session
            else None)
        if self.max_updates_per_session is not None \
                and self.max_updates_per_session < 2:
            raise ValueError("max_updates_per_session must be >= 2")
        self._lock = threading.Lock()
        self._static: Dict[str, dict] = {}
        self._updates: Dict[str, List[dict]] = {}
        if os.path.exists(path):
            self._load()
            if self.max_updates_per_session is not None:
                with self._lock:
                    self._compact_locked()
        else:
            open(path, "wb").close()

    def _load(self):
        with open(self.path, "rb") as f:
            data = f.read()
        off = 0
        while off < len(data):
            kind = data[off]
            off += 1
            (slen,) = struct.unpack_from("<H", data, off)
            off += 2
            session = data[off:off + slen].decode()
            off += slen
            (plen,) = struct.unpack_from("<I", data, off)
            off += 4
            payload = data[off:off + plen]
            off += plen
            if kind == self._KIND_STATIC:
                self._static[session] = json.loads(payload)
            else:
                self._updates.setdefault(session, []).append(
                    decode_record(payload))

    def _append(self, kind: int, session_id: str, payload: bytes):
        sb = session_id.encode()
        with open(self.path, "ab") as f:
            f.write(bytes([kind]) + struct.pack("<H", len(sb)) + sb
                    + struct.pack("<I", len(payload)) + payload)

    def put_static_info(self, session_id, info):
        with self._lock:
            self._static[session_id] = dict(info)
            self._append(self._KIND_STATIC, session_id,
                         json.dumps(info).encode())

    def put_update(self, session_id, record):
        encoded = encode_record(record)
        with self._lock:
            rows = self._updates.setdefault(session_id, [])
            rows.append(decode_record(encoded))
            self._append(self._KIND_UPDATE, session_id, encoded)
            cap = self.max_updates_per_session
            if cap is not None and len(rows) > cap + cap // 2:
                # compact only past cap*1.5, so the rewrite amortizes
                # over cap/2 appends instead of running per record
                self._compact_locked()
        self._notify(session_id, record)

    def _compact_locked(self):
        cap = self.max_updates_per_session
        changed = False
        for sid, rows in self._updates.items():
            if len(rows) > cap:
                self._updates[sid] = _downsample_oldest(rows, cap)
                changed = True
        if not changed:
            return
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            for sid, info in self._static.items():
                payload = json.dumps(info).encode()
                sb = sid.encode()
                f.write(bytes([self._KIND_STATIC])
                        + struct.pack("<H", len(sb)) + sb
                        + struct.pack("<I", len(payload)) + payload)
            for sid, rows in self._updates.items():
                sb = sid.encode()
                for u in rows:
                    payload = encode_record(u)
                    f.write(bytes([self._KIND_UPDATE])
                            + struct.pack("<H", len(sb)) + sb
                            + struct.pack("<I", len(payload)) + payload)
        os.replace(tmp, self.path)

    def list_session_ids(self):
        with self._lock:
            return sorted(set(self._static) | set(self._updates))

    def get_static_info(self, session_id):
        with self._lock:
            return self._static.get(session_id)

    def get_updates(self, session_id, since_iteration=-1):
        with self._lock:
            ups = list(self._updates.get(session_id, []))
        return [u for u in ups if u.get("iteration", 0) > since_iteration]


class SqliteStatsStorage(StatsStorage):
    """Indexed durable storage — the MapDBStatsStorage /
    J7FileStatsStorage analog (reference:
    deeplearning4j-ui-model/.../storage/mapdb/MapDBStatsStorage.java,
    sqlite J7FileStatsStorage): unlike the append-only FileStatsStorage
    (which replays the whole log on open), records live in an indexed
    database, so `get_updates(since_iteration=...)` is a range query and
    opening a million-record run does not re-parse a million records.
    stdlib sqlite3, same binary record codec as the file store."""

    def __init__(self, path: str,
                 max_updates_per_session: Optional[int] = None):
        import sqlite3

        self.path = path
        # same retention contract as FileStatsStorage: past the cap,
        # the oldest rows per session are downsampled by uniform stride
        # (DELETE by rowid — no file rewrite needed here)
        self.max_updates_per_session = (
            int(max_updates_per_session) if max_updates_per_session
            else None)
        if self.max_updates_per_session is not None \
                and self.max_updates_per_session < 2:
            raise ValueError("max_updates_per_session must be >= 2")
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        # WAL + NORMAL: per-record commits without a per-record fsync —
        # durable to application crash, and ~100x the insert rate of the
        # default rollback journal (the J7FileStatsStorage role demands
        # per-iteration inserts)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS static_info ("
            " session TEXT PRIMARY KEY, info TEXT NOT NULL)")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS updates ("
            " session TEXT NOT NULL, iteration INTEGER NOT NULL,"
            " ts REAL NOT NULL, record BLOB NOT NULL)")
        self._db.execute(
            "CREATE INDEX IF NOT EXISTS idx_updates"
            " ON updates (session, iteration)")
        self._db.commit()

    def put_static_info(self, session_id, info):
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO static_info VALUES (?, ?)",
                (session_id, json.dumps(info)))
            self._db.commit()

    def put_update(self, session_id, record):
        encoded = encode_record(record)
        with self._lock:
            self._db.execute(
                "INSERT INTO updates VALUES (?, ?, ?, ?)",
                (session_id, int(record.get("iteration", 0)),
                 float(record.get("ts", 0.0)), encoded))
            cap = self.max_updates_per_session
            if cap is not None:
                n = self._db.execute(
                    "SELECT COUNT(*) FROM updates WHERE session = ?",
                    (session_id,)).fetchone()[0]
                if n > cap + cap // 2:
                    self._compact_session_locked(session_id, n)
            self._db.commit()
        self._notify(session_id, record)

    def _compact_session_locked(self, session_id: str, n: int):
        """Oldest-first downsample to <= cap rows: the newest cap//2
        stay raw, the older remainder keeps every stride-th row (rowid
        order == insertion order) — same policy as FileStatsStorage's
        _downsample_oldest, expressed as a DELETE."""
        cap = self.max_updates_per_session
        rowids = [r[0] for r in self._db.execute(
            "SELECT rowid FROM updates WHERE session = ?"
            " ORDER BY iteration, rowid", (session_id,))]
        tail_n = max(1, cap // 2)
        head = rowids[:-tail_n]
        keep_n = max(1, cap - tail_n)
        stride = max(1, -(-len(head) // keep_n))
        keep = set(head[::stride])
        drop = [(rid,) for rid in head if rid not in keep]
        self._db.executemany("DELETE FROM updates WHERE rowid = ?", drop)

    def list_session_ids(self):
        with self._lock:
            rows = self._db.execute(
                "SELECT session FROM static_info UNION "
                "SELECT DISTINCT session FROM updates ORDER BY 1"
            ).fetchall()
        return [r[0] for r in rows]

    def get_static_info(self, session_id):
        with self._lock:
            row = self._db.execute(
                "SELECT info FROM static_info WHERE session = ?",
                (session_id,)).fetchone()
        return json.loads(row[0]) if row else None

    def get_updates(self, session_id, since_iteration=-1):
        with self._lock:
            rows = self._db.execute(
                "SELECT record FROM updates WHERE session = ? AND"
                " iteration > ? ORDER BY iteration",
                (session_id, since_iteration)).fetchall()
        return [decode_record(r[0]) for r in rows]

    def latest_session_id(self):
        """Indexed override of the base scan: the dashboard polls this
        per request — decoding every record of every session to find the
        newest timestamp would defeat this store's purpose."""
        with self._lock:
            row = self._db.execute(
                "SELECT session FROM updates ORDER BY ts DESC LIMIT 1"
            ).fetchone()
            if row is None:
                row = self._db.execute(
                    "SELECT session, json_extract(info, '$.start_time')"
                    " AS st FROM static_info ORDER BY st DESC LIMIT 1"
                ).fetchone()
        return row[0] if row else None

    def close(self):
        with self._lock:
            self._db.close()


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """POSTs records to a UIServer's /remote endpoint (reference:
    RemoteUIStatsStorageRouter + RemoteReceiverModule). Fire-and-forget:
    records go through a bounded queue drained by a daemon thread, so a
    slow or dead dashboard never blocks the training loop — when the
    queue is full the OLDEST record is dropped."""

    def __init__(self, url: str, timeout: float = 2.0,
                 queue_size: int = 256):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self._q: "queue.Queue[tuple]" = queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        # liveness: busy only while posting one record — a wedged
        # dashboard connection past its timeout shows up as a
        # `component_health{component=ui_remote_router}` stall
        self._hb = _health.get_health().register(
            "ui_remote_router", stall_after=max(60.0, 4.0 * timeout))
        self._worker = threading.Thread(target=self._drain, daemon=True,
                                        name="dl4j-ui-remote-router")
        self._worker.start()

    def close(self):
        """Stop accepting records and retire the drain thread. Records
        already queued are still posted (the drain empties the queue
        before honoring the stop); close() waits up to ~10s for that —
        call flush() first when delivery must be confirmed."""
        self._stop.set()
        self._worker.join(timeout=10)
        _health.get_health().unregister(self._hb)

    def _drain(self):
        while True:
            try:
                route, session_id, body, ctype, ctx = get_abortable(
                    self._q, self._stop)
            except QueueAborted:
                return
            try:
                # attach the enqueue-time span context so the POST (and
                # its traceparent header) joins the training step's trace
                # across this queue hop instead of rooting a fresh one
                with self._hb.busy(), _tracing.attached_ctx(ctx):
                    req = urllib.request.Request(
                        f"{self.url}{route}", data=body,
                        headers=traced_headers(
                            {"Content-Type": ctype,
                             "X-Session-Id": session_id}))
                    with _tracing.span("ui/remote_post", route=route):
                        urllib.request.urlopen(
                            req, timeout=self.timeout).read()
            except OSError:
                pass  # dashboard unreachable — drop the record
            finally:
                self._q.task_done()

    def _enqueue(self, item):
        while True:
            try:
                self._q.put_nowait(item)
                return
            except queue.Full:
                try:
                    self._q.get_nowait()  # shed the oldest
                    self._q.task_done()
                except queue.Empty:
                    pass

    def flush(self, timeout: float = 10.0):
        """Block until queued records are posted (tests / end of run).
        Waits on unfinished_tasks, not empty(): the final record leaves
        the queue BEFORE its POST completes, and flush returning inside
        that window hands the caller a storage missing it."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while self._q.unfinished_tasks and _time.monotonic() < deadline:
            _time.sleep(0.02)

    def put_static_info(self, session_id, info):
        self._enqueue(("/remote/static", session_id,
                       json.dumps(info).encode(), "application/json",
                       _tracing.current_context()))

    def put_update(self, session_id, record):
        self._enqueue(("/remote/update", session_id,
                       encode_record(record), "application/octet-stream",
                       _tracing.current_context()))
