"""Standalone training report — one self-contained HTML file, no server.

Reference: deeplearning4j-ui-components' standalone rendering path (build
Component trees from training results, emit a static page) — the artifact
you attach to an experiment record. Assembled from the same stats-storage
records the live dashboard reads (ui/codec.py stream), so any run that
used a StatsListener (or a FileStatsStorage on disk) can be rendered
after the fact:

    from deeplearning4j_tpu.ui import FileStatsStorage
    from deeplearning4j_tpu.ui.report import write_training_report
    write_training_report(FileStatsStorage("stats.bin"), "report.html")

or from the CLI: `python -m deeplearning4j_tpu.cli report --stats-file
stats.bin --output report.html`.
"""

from __future__ import annotations

import time
from typing import List, Optional

from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartLine,
    Component,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    register_component,
    render_page,
)
from deeplearning4j_tpu.ui.stats import split_stat_key
from deeplearning4j_tpu.ui.storage import StatsStorage


# -- flow (layer-graph) view --------------------------------------------------

def _graph_depths(nodes, edges):
    """Longest-path depth per node id (layered layout columns)."""
    ids = [n["id"] for n in nodes]
    indeg = {i: 0 for i in ids}
    outs = {i: [] for i in ids}
    for src, dst in edges:
        if src in outs and dst in indeg:
            outs[src].append(dst)
            indeg[dst] += 1
    depth = {i: 0 for i in ids}
    queue = [i for i in ids if indeg[i] == 0]
    while queue:
        cur = queue.pop(0)
        for nxt in outs[cur]:
            depth[nxt] = max(depth[nxt], depth[cur] + 1)
            indeg[nxt] -= 1
            if indeg[nxt] == 0:
                queue.append(nxt)
    return depth


@register_component
class FlowGraph(Component):
    """The flow view: the model DAG laid out in depth columns, each node a
    box with its label and (when known) parameter count + latest mean
    |param| (reference: FlowListenerModule's per-layer boxes)."""

    component_type = "FlowGraph"

    NODE_W, NODE_H, GAP_X, GAP_Y = 148, 40, 40, 14

    def __init__(self, graph: dict, layer_stats: Optional[dict] = None):
        self.graph = graph or {"nodes": [], "edges": []}
        self.layer_stats = layer_stats or {}

    def to_dict(self):
        return {"componentType": self.component_type, "graph": self.graph,
                "layerStats": self.layer_stats}

    @classmethod
    def _from_dict(cls, d):
        return cls(d.get("graph"), d.get("layerStats"))

    def render_html(self):
        import html as _h

        nodes = self.graph.get("nodes", [])
        edges = self.graph.get("edges", [])
        if not nodes:
            return "<div class='chart'><h3>flow</h3>(no graph)</div>"
        depth = _graph_depths(nodes, edges)
        cols: dict = {}
        for n in nodes:
            cols.setdefault(depth[n["id"]], []).append(n)
        pos = {}
        for d, members in cols.items():
            for r, n in enumerate(members):
                pos[n["id"]] = (
                    8 + d * (self.NODE_W + self.GAP_X),
                    8 + r * (self.NODE_H + self.GAP_Y),
                )
        w = 16 + (max(cols) + 1) * (self.NODE_W + self.GAP_X)
        h = 16 + max(len(m) for m in cols.values()) * (
            self.NODE_H + self.GAP_Y)
        parts = []
        for src, dst in edges:
            if src not in pos or dst not in pos:
                continue
            x0, y0 = pos[src]
            x1, y1 = pos[dst]
            parts.append(
                f'<line x1="{x0 + self.NODE_W}" y1="{y0 + self.NODE_H / 2}" '
                f'x2="{x1}" y2="{y1 + self.NODE_H / 2}" stroke="#999" '
                'marker-end="url(#arr)"/>')
        for n in nodes:
            x, y = pos[n["id"]]
            li = n.get("layer_index")
            stat = self.layer_stats.get(str(li)) or self.layer_stats.get(li)
            label = n["label"].split("\n")
            fill = "#e3f2fd" if li is not None else "#eeeeee"
            parts.append(
                f'<rect x="{x}" y="{y}" width="{self.NODE_W}" '
                f'height="{self.NODE_H}" rx="4" fill="{fill}" '
                'stroke="#90a4ae"/>')
            parts.append(
                f'<text x="{x + 6}" y="{y + 15}" font-size="10" '
                f'font-weight="bold">{_h.escape(label[0][:24])}</text>')
            sub = label[1] if len(label) > 1 else ""
            if stat:
                sub = (f"{stat.get('n_params', '?')}p"
                       + (f"  |w|~{stat['param_mean']:.3g}"
                          if "param_mean" in stat else ""))
            if sub:
                parts.append(
                    f'<text x="{x + 6}" y="{y + 30}" font-size="9" '
                    f'fill="#555">{_h.escape(str(sub)[:28])}</text>')
        defs = ('<defs><marker id="arr" markerWidth="8" markerHeight="8" '
                'refX="7" refY="3" orient="auto"><path d="M0,0 L7,3 L0,6" '
                'fill="none" stroke="#999"/></marker></defs>')
        return (f'<div class="chart"><h3>model flow</h3>'
                f'<svg width="{w}" height="{h}">{defs}{"".join(parts)}'
                "</svg></div>")


# -- report assembly ----------------------------------------------------------

def _series(ups: List[dict], key: str):
    return [(u["iteration"], u[key]) for u in ups if key in u]


def _layer_stats_latest(ups: List[dict], static: dict) -> dict:
    """Per layer-index: n_params + latest mean |param| (averaged over the
    layer's param tensors)."""
    out = {}
    for meta in static.get("layers", []):
        out[str(meta["index"])] = {"n_params": meta["n_params"]}
    for u in reversed(ups):
        pm = u.get("param_mm")
        if not pm:
            continue
        per: dict = {}
        for k, v in pm.items():
            li, _ = split_stat_key(k)
            per.setdefault(li, []).append(v)
        for li, vals in per.items():
            out.setdefault(li, {})["param_mean"] = sum(vals) / len(vals)
        break
    return out


def build_report_components(storage: StatsStorage,
                            session_id: Optional[str] = None
                            ) -> List[Component]:
    """Component tree for one session's training run (newest session when
    not named)."""
    if session_id is None:
        session_id = storage.latest_session_id()
        if session_id is None:
            return [ComponentText("no sessions in storage", bold=True)]
    static = storage.get_static_info(session_id) or {}
    ups = [u for u in storage.get_updates(session_id) if "score" in u]

    comps: List[Component] = []
    rows = [["session", session_id]]
    for key in ("model_class", "backend", "device", "n_devices",
                "total_params"):
        if key in static:
            rows.append([key, static[key]])
    if ups:
        rows.append(["iterations", ups[-1]["iteration"] + 1])
        rows.append(["final score", f"{ups[-1]['score']:.6g}"])
        if static.get("start_time"):
            rows.append(["started",
                         time.strftime("%Y-%m-%d %H:%M:%S",
                                       time.localtime(static["start_time"]))])
    comps.append(ComponentDiv(
        [ComponentTable(["key", "value"], rows)], "run summary"))

    charts: List[Component] = []
    if _series(ups, "score"):
        charts.append(ChartLine("score vs iteration",
                                {"score": _series(ups, "score")}))
    if _series(ups, "samples_per_sec"):
        charts.append(ChartLine("throughput (samples/sec)",
                                {"samples/sec":
                                 _series(ups, "samples_per_sec")}))
    if _series(ups, "etl_ms"):
        charts.append(ChartLine("ETL wait (ms)",
                                {"etl ms": _series(ups, "etl_ms")}))
    if charts:
        comps.append(ComponentDiv(charts, "training progress"))

    # per-layer mean-magnitude series (grad/update/param), one chart per
    # layer with its params as series
    layer_series: dict = {}
    for group, label in (("grad_mm", "grad"), ("update_mm", "update"),
                         ("param_mm", "param")):
        for u in ups:
            for k, v in (u.get(group) or {}).items():
                li, pname = split_stat_key(k)
                layer_series.setdefault(li, {}).setdefault(
                    f"{label} |{pname}|", []).append((u["iteration"], v))
    if layer_series:
        layer_charts = [
            ChartLine(f"layer {li}", series)
            for li, series in sorted(layer_series.items(),
                                     key=lambda kv: int(kv[0]))
        ]
        comps.append(ComponentDiv(layer_charts,
                                  "per-layer mean magnitudes"))

    for u in reversed(ups):
        if "hists" in u:
            hcomps = [
                ChartHistogram(name, h["edges"], h["counts"])
                for name, h in u["hists"].items()
            ]
            comps.append(ComponentDiv(
                hcomps, f"parameter histograms (iteration "
                        f"{u['iteration']})"))
            break

    graph = static.get("graph")
    if graph:
        comps.append(ComponentDiv(
            [FlowGraph(graph, _layer_stats_latest(ups, static))],
            "model flow"))
    return comps


def render_training_report(storage: StatsStorage,
                           session_id: Optional[str] = None,
                           title: str = "training report") -> str:
    return render_page(title, build_report_components(storage, session_id))


def write_training_report(storage: StatsStorage, out_path: str,
                          session_id: Optional[str] = None,
                          title: str = "training report") -> str:
    html = render_training_report(storage, session_id, title)
    with open(out_path, "w") as f:
        f.write(html)
    return out_path
