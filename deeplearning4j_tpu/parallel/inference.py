"""ParallelInference — multi-request serving over the device mesh.

Reference: deeplearning4j-scaleout/.../parallelism/ParallelInference.java
(:33-126) — a pool of model replicas fed from a queue, with
InferenceMode.SEQUENTIAL (one request per replica call) vs BATCHED (dynamic
batching via BatchedInferenceObservable, inference/observers/).

TPU-native design: one set of replicated parameters on the mesh; the
"replica pool" is replaced by batch sharding — a dynamically-batched
request group is sharded across the data axis and executed once. Dynamic
batching (the BATCHED mode) carries over from the reference; two
serving-specific mechanisms go beyond it:

* **Shape buckets** — every forward runs at one of a small fixed set of
  batch sizes (powers of two up to `max_batch_size` by default): a fused
  group of n examples is padded up to the smallest bucket >= n by
  cyclically wrapping rows (`mesh.pad_wrap`) and the pad rows sliced off
  the result. Only ~log2(max_batch_size) forward traces ever compile no
  matter how request sizes vary; without bucketing every distinct group
  size is a fresh `jax.jit` trace of `model.output` — a compile storm.
  `warmup()` precompiles all buckets before traffic, and `metrics()`
  exposes per-bucket hit counts plus the model's `output_compile_count`
  so retraces are a visible number, not mystery tail latency.

* **Pipelined collect → dispatch** — the BATCHED collector is split into
  two stages joined by a bounded handoff queue: the *collect* thread
  drains the request queue, concatenates and bucket-pads on the host, and
  hands the prepared group off; the *dispatch* thread runs the device
  forward and scatters results to the waiting callers. Host batch
  assembly of group k+1 overlaps device execution of group k (double
  buffering — same idea as the training-side async prefetch,
  data/iterators.AsyncDataSetIterator).

* **Deadlines + admission control + load shedding** — every request may
  carry a `deadline_ms` budget (or inherits `default_deadline_ms`), and
  expired work is SHED at every stage instead of served late: admission
  (already expired, queue at `queue_capacity`, or predicted to miss —
  estimated wait is queued-examples-in-groups × the rolling p50 batch
  latency), the collector (expired while queued), the dispatcher
  (expired before the device forward), and the ReplicaPool resubmit
  loop (expired mid-failover, or out of retry budget). Under sustained
  overload the queue depth stays bounded and excess load turns into
  fast, explicit rejections (HTTP 429 + Retry-After at the REST layer)
  rather than an unbounded queue where EVERY request times out
  client-side. Accounting is exact and scrape-able:
  `serving_shed_total{stage,reason}` plus per-endpoint
  admitted/completed/shed/failed counters obeying the conservation law
  `admitted == completed + shed + failed` (rejections happen before
  admission and are counted separately) — tests/test_chaos.py asserts
  it under injected faults.

* **Request lifecycle tracing** — with tracing on (utils/tracing), every
  request is one trace: a `serve/admission` span on the caller's thread
  whose SpanContext rides the queue item and the handoff tuple, so the
  collector's retroactive `serve/queued` span, the dispatcher's
  `serve/dispatch` → `serve/forward` spans, and every `serve/shed`
  marker (tagged {stage, reason} like serving_shed_total) keep their
  parentage across the pipeline threads. Fused groups attach the first
  live member's context for the real spans and record per-member
  retroactive copies, so each request's trace is complete. Off by
  default; every hook is one flag check when disabled.
"""

from __future__ import annotations

import logging
import math
import queue
import threading
import time
import weakref
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import List, Optional, Sequence

import jax
import numpy as np

from deeplearning4j_tpu.parallel.mesh import (
    batch_sharded,
    data_parallel_mesh,
    data_shards,
    pad_wrap,
    replicated,
)
from deeplearning4j_tpu.utils import blackbox as _blackbox
from deeplearning4j_tpu.utils import faultpoints as _faults
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import metrics as _metrics
from deeplearning4j_tpu.utils import resourcemeter as _resourcemeter
from deeplearning4j_tpu.utils import runledger as _runledger
from deeplearning4j_tpu.utils import tenancy as _tenancy
from deeplearning4j_tpu.utils import tracing as _tracing

# canonical home moved to utils/resourcemeter (the shared tenant-keyed
# implementation every tier books through); re-exported here because
# this module is where serving callers historically imported it from
from deeplearning4j_tpu.utils.resourcemeter import AdmissionBooks
from deeplearning4j_tpu.utils.concurrency import (
    QueueAborted,
    get_abortable,
    put_abortable,
)
from deeplearning4j_tpu.utils.latency import LatencyTracker

logger = logging.getLogger("deeplearning4j_tpu")

# how long a deadline-carrying caller waits PAST its deadline before
# shedding its own future (stage="wait"): long enough that a live
# collector/dispatcher always sheds first (keeping the stage-precise
# books and the device-work saving), short enough that a wedged pipeline
# cannot hold callers hostage
_WAIT_SHED_GRACE = 0.25

# the wait estimator is fed ONLY by completed forwards, and admission
# consults it before admitting — so a rolling p50 pushed past every
# caller's deadline by one bad window (GIL stall, transient device
# slowness) would starve itself of the very samples that let it
# recover: 100% shed, forever. When the pipeline is idle and no forward
# has landed within max(4 x p50, this floor), the estimate is STALE and
# admission lets one probe through to re-learn reality
_ESTIMATOR_STALE_MIN = 1.0


class InferenceMode:
    SEQUENTIAL = "sequential"
    BATCHED = "batched"


class RequestValidationError(ValueError):
    """The REQUEST was malformed (empty, or feature shape mismatching the
    endpoint's) — distinguishes client faults from server-side ValueErrors
    so REST layers can map 400 vs 500 correctly."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline expired before its result could be
    produced — shed, not served late. `stage` names where it was caught
    (admission / collector / dispatch / resubmit, or `wait`: the
    caller's own bounded wait on a wedged pipeline). REST maps this to
    429: the work was never done, so the client may retry with a fresh
    budget."""

    def __init__(self, message: str, stage: str = "admission",
                 retry_after: float = 0.0):
        super().__init__(message)
        self.stage = stage
        self.retry_after = float(retry_after)


class RequestRejected(RuntimeError):
    """Admission control refused the request: the queue is at capacity,
    or the estimated wait (queue depth × rolling p50 batch latency)
    already exceeds the request's remaining deadline. `retry_after` is
    the server's wait estimate in seconds — the Retry-After hint the
    REST layer returns with the 429."""

    def __init__(self, message: str, reason: str = "queue_full",
                 retry_after: float = 0.0, stage: str = "admission"):
        super().__init__(message)
        self.reason = reason
        self.retry_after = float(retry_after)
        self.stage = stage


class ReplicaUnavailable(RuntimeError):
    """This replica could not take — or had to give back — the request
    BEFORE its device forward ran: admission after shutdown/abort, or a
    queued future failed by an eviction sweep. The request never touched
    the model, so it is safe to resubmit verbatim; ReplicaPool does
    exactly that on a healthy sibling. Contrast the plain RuntimeError an
    abort() puts on IN-FLIGHT futures (the group inside the device
    forward): those may have side effects in flight and are genuinely
    lost — the only failures the eviction contract lets callers see."""


def _queue_depth(ref) -> int:
    pi = ref()
    if pi is None:
        return 0
    return pi._q.qsize() + pi._handoff.qsize()


def _trace_shed_span(stage: str, reason: str,
                     ctx: Optional[_tracing.SpanContext] = None):
    """Record a zero-duration serve/shed span tagged {stage, reason}
    (mirroring serving_shed_total's labels) under the request's context —
    ctx when the shed happens on a pipeline thread, the current context
    when it happens on the caller's. The ONE place the shed-span shape
    lives: ParallelInference stages and ReplicaPool resubmit sheds both
    record through it. One flag check when tracing is off."""
    if not _tracing.is_enabled():
        return
    if ctx is None:
        ctx = _tracing.current_context()
    if ctx is None:
        return
    now = time.perf_counter()
    _tracing.record_complete("serve/shed", now, now, ctx,
                             stage=stage, reason=reason)


def power_of_two_buckets(max_batch_size: int) -> List[int]:
    """Default bucket set: 1, 2, 4, ... up to and including
    `max_batch_size` (appended as-is when not itself a power of two)."""
    out, b = [], 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(int(max_batch_size))
    return out


class ParallelInference:
    def __init__(
        self,
        model,
        mesh=None,
        inference_mode: str = InferenceMode.BATCHED,
        max_batch_size: int = 64,
        batch_timeout_ms: float = 2.0,
        buckets: Optional[Sequence[int]] = None,
        handoff_capacity: int = 2,
        health_stall_after: float = 30.0,
        component_prefix: str = "serving",
        queue_capacity: int = 1024,
        default_deadline_ms: Optional[float] = None,
        run_ledger=None,
    ):
        self.model = model
        # run-ledger opt-in (ONE knob, same contract as fit()): a path
        # builds a RunLedger there (closed at shutdown — the per-run
        # artifact); an instance is attached and left open for its
        # owner. None keeps the serving hook at one flag check.
        self._owned_ledger = self._attached_ledger = None
        if run_ledger is not None:
            if isinstance(run_ledger, str):
                self._owned_ledger = _runledger.RunLedger(run_ledger)
                self._attached_ledger = _runledger.attach(
                    self._owned_ledger)
            else:
                self._attached_ledger = _runledger.attach(run_ledger)
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.mode = inference_mode
        self.max_batch_size = int(max_batch_size)
        # overload protection: the request queue is BOUNDED (capacity
        # enforced at admission, under the lock — the queue object stays
        # unbounded so the shutdown sentinel can never block) and every
        # request may carry a deadline. 0 disables the bound.
        self.queue_capacity = max(0, int(queue_capacity))
        self.default_deadline_ms = (None if default_deadline_ms is None
                                    else float(default_deadline_ms))
        if self.max_batch_size < 1:
            raise ValueError(
                f"max_batch_size must be >= 1, got {max_batch_size}")
        self.batch_timeout = batch_timeout_ms / 1e3
        self.n_shards = data_shards(self.mesh)
        if buckets is None:
            self.buckets = power_of_two_buckets(self.max_batch_size)
        else:
            self.buckets = sorted({int(b) for b in buckets})
            if not self.buckets or self.buckets[0] < 1:
                raise ValueError(f"invalid bucket set {buckets}")
            if self.buckets[-1] < self.max_batch_size:
                raise ValueError(
                    f"largest bucket {self.buckets[-1]} < max_batch_size "
                    f"{self.max_batch_size}: a full fused group would have "
                    f"no bucket to land in"
                )
        model._require_init()
        rep = replicated(self.mesh)
        model.params_list = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, rep), model.params_list
        )
        # one lock guards admission (shutdown flag + expected shape) and
        # the stats counters; device work happens outside it
        self._lock = threading.Lock()
        self._q: "queue.Queue" = queue.Queue()
        self._handoff: "queue.Queue" = queue.Queue(maxsize=handoff_capacity)
        self._expected_shape = None  # set by the first request (under lock)
        # flipped by the first SUCCESSFUL forward: until then the pinned
        # shape is provisional and a failed forward unpins it, so one
        # malformed first request cannot poison the endpoint forever
        self._shape_confirmed = False
        self._shutdown = False
        # hard-stop flag (abort(), the ReplicaPool eviction path): the
        # pipeline threads exit at their next queue poll instead of
        # draining; queued + in-flight futures fail explicitly
        self._abort = threading.Event()
        # futures of the group the dispatcher currently holds (set just
        # before the device forward): the only requests abort() cannot
        # re-route — they fail, everything else is retriable upstream
        self._inflight: List[Future] = []
        # _stats is PER-INSTANCE (the JSON /metrics schema: this
        # endpoint's traffic); the registry counters below are
        # process-global aggregates across every ParallelInference in the
        # process — deriving either from the other would conflate the two
        # scopes, so both are maintained
        self._stats = {
            "requests": 0,
            "examples": 0,
            "batches": 0,
            "oversized": 0,
            "bucket_hits": {b: 0 for b in self.buckets},
        }
        # exact request accounting (the conservation law):
        #   admitted == completed + shed + failed
        # `rejected` counts admission-control refusals — those happened
        # BEFORE admission, so they sit outside the law. The shared
        # AdmissionBooks shape (utils/resourcemeter), booked per tenant
        # (requests carry one via output(tenant=) / X-Tenant; the rest
        # land under the default tenant), mutated under self._lock.
        self._books = AdmissionBooks()
        _resourcemeter.register_books(_resourcemeter.TIER_SERVING,
                                      self._books)
        # examples currently waiting in _q (admission's queue-depth
        # estimate in GROUP units: examples / max_batch_size)
        self._queued_examples = 0
        # rolling device-forward latency: the p50 here × groups-ahead is
        # the admission-control wait estimate
        self._batch_lat = LatencyTracker(window=64)
        # monotonic time the last counted forward landed (None until the
        # first): the staleness clock for the estimator-poison probe.
        # Written by the dispatcher without the lock (GIL-atomic float
        # store), read under it at admission
        self._last_forward_mono: Optional[float] = None
        # shared-registry serving instruments (same registry as training's
        # fit_step_* / compile_total — ONE scrape sees both). Children are
        # resolved here once; the request path only touches the cached
        # handles. The queue-depth gauge reads through a weakref so a
        # shut-down ParallelInference is not kept alive by the registry
        # (the newest instance owns the gauge).
        reg = _metrics.get_registry()
        self._m_requests = reg.counter(
            "serving_requests_total", "inference requests admitted").labels()
        self._m_examples = reg.counter(
            "serving_examples_total", "inference examples admitted").labels()
        self._m_bucket = reg.counter(
            "serving_bucket_hits_total",
            "fused groups served, by landing bucket", ("bucket",))
        self._m_oversized = reg.counter(
            "serving_oversized_total",
            "requests larger than every bucket (ran unfused)").labels()
        self._m_handoff = reg.histogram(
            "serving_handoff_stall_seconds",
            "collector time blocked handing a prepared group to the "
            "dispatcher (device a full group behind = backpressure)"
        ).labels()
        self._m_shed = reg.counter(
            "serving_shed_total",
            "requests shed instead of served late, by pipeline stage "
            "and reason", ("stage", "reason"))
        self._m_admitted = reg.counter(
            "serving_admitted_total",
            "requests past admission control (the conservation law's "
            "left-hand side)").labels()
        self._m_probe = reg.counter(
            "serving_admission_probe_total",
            "predicted-late requests admitted anyway because the wait "
            "estimate was stale (idle pipeline, no recent forward) — "
            "the self-healing path out of a poisoned rolling p50"
        ).labels()
        self._m_completed = reg.counter(
            "serving_completed_total",
            "admitted requests resolved with a result").labels()
        # completed-request latency at THIS layer (admission to result),
        # below any HTTP front-end: the histogram the SLO burn-rate
        # objective ("99% of requests under default_deadline_ms",
        # analysis/slo) judges from its bucket counts — sheds never
        # observe here, so the objective grades what was actually served
        self._m_output_latency = reg.histogram(
            "serving_output_seconds",
            "ParallelInference.output latency of completed requests "
            "(admission to result; sheds/failures excluded)").labels()
        self._m_failed = reg.counter(
            "serving_failed_total",
            "admitted requests resolved with an error "
            "(model/abort/shutdown)").labels()
        ref = weakref.ref(self)
        reg.gauge(
            "serving_queue_depth",
            "requests + prepared groups waiting for the device"
        ).set_function(lambda: _queue_depth(ref))
        self._collect_t: Optional[threading.Thread] = None
        self._dispatch_t: Optional[threading.Thread] = None
        # liveness (utils/health): each pipeline stage holds a busy slot
        # only while it OWNS work — waiting on an empty request queue is
        # idle, but a dispatcher wedged inside a device forward (or a
        # collector blocked handing off to a dead device) goes stale and
        # the watchdog flips `component_health{component=...}`. GET
        # /health on the serving layer aggregates exactly this.
        self._hb_collect: Optional[_health.Heartbeat] = None
        self._hb_dispatch: Optional[_health.Heartbeat] = None
        self.component_prefix = component_prefix
        if self.mode == InferenceMode.BATCHED:
            hreg = _health.get_health()
            self._hb_collect = hreg.register(
                f"{component_prefix}_collector",
                stall_after=health_stall_after)
            self._hb_dispatch = hreg.register(
                f"{component_prefix}_dispatcher",
                stall_after=health_stall_after)
            self._collect_t = threading.Thread(
                target=self._collector, daemon=True,
                name="dl4j-serving-collector")
            self._dispatch_t = threading.Thread(
                target=self._dispatcher, daemon=True,
                name="dl4j-serving-dispatch")
            self._collect_t.start()
            self._dispatch_t.start()

    # -- public --------------------------------------------------------------

    def output(self, x, deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None):
        """Thread-safe inference. In BATCHED mode the call may be fused
        with concurrent callers' batches (reference:
        BatchedInferenceObservable). `deadline_ms` is the request's
        total latency budget from this call (falls back to
        `default_deadline_ms`; None = no deadline): a request that
        cannot make it is shed — DeadlineExceeded / RequestRejected —
        instead of served late. `tenant` names who this request books
        under (admission books + device-second spend); None falls back
        to the thread's ambient tenant (utils/tenancy), then the
        default tenant."""
        # run-ledger hook first (one global read when no ledger is
        # attached), then the end-to-end latency of COMPLETED requests
        # into serving_output_seconds — sheds raise out of _output_impl
        # and never observe, so the SLO objective judges served work
        _runledger.note_request()
        t0 = time.perf_counter()
        out = self._output_impl(x, deadline_ms, tenant)
        self._m_output_latency.observe(time.perf_counter() - t0)
        return out

    def _output_impl(self, x, deadline_ms: Optional[float] = None,
                     tenant: Optional[str] = None):
        xx = np.asarray(x)
        # one canonical label for the whole request lifecycle: explicit
        # arg wins, then the ambient thread tenant (REST handlers attach
        # it from X-Tenant), interned through the bounded registry
        tenant = _tenancy.intern(
            tenant if tenant is not None else _tenancy.current_tenant())
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        elif not math.isfinite(float(deadline_ms)):
            # a NaN budget makes every deadline comparison False: the
            # request would be admitted, then unconditionally shed in
            # the collector — a malformed request, not a shed
            raise RequestValidationError(
                f"deadline_ms must be finite, got {deadline_ms!r}")
        deadline = (None if deadline_ms is None
                    else time.monotonic() + float(deadline_ms) / 1e3)
        # the request's lifecycle root below the caller's span: the
        # admission decision runs inside it, and its context rides the
        # queue item so every downstream stage (queued/dispatch/forward/
        # shed) parents here even when completed on a pipeline thread.
        # Disabled path: NULL_SPAN + None ctx after one flag check each.
        adm_span = _tracing.span("serve/admission", rows=int(xx.shape[0]),
                                 tenant=tenant)
        with adm_span:
            fut, ctx = self._admit(xx, deadline, tenant)
        if fut is not None:
            if deadline is None:
                return fut.result()
            # bounded wait: the collector/dispatcher are the PRIMARY
            # shedders (they see the expiry first while the pipeline is
            # alive, and their skip saves the device work) — but when
            # the pipeline itself wedges nothing downstream will ever
            # touch the future, so after a short grace past the deadline
            # the waiter sheds it here. _fail is race-safe: a concurrent
            # resolve/shed that beat us wins and is what the caller gets
            try:
                return fut.result(
                    timeout=max(0.0, deadline - time.monotonic())
                    + _WAIT_SHED_GRACE)
            except FutureTimeoutError:
                exc = DeadlineExceeded(
                    "deadline expired waiting on a stalled pipeline",
                    stage="wait")
                if self._fail(fut, exc, outcome="shed", stage="wait",
                              reason="expired"):
                    self._trace_shed("wait", "expired", ctx)
                    raise exc from None
                return fut.result()
        # SEQUENTIAL mode, or an oversized request: run it alone instead of
        # overshooting a fused group arbitrarily (device work off-lock).
        # The unfused path honors the deadline like the fused one does:
        # expired before the forward = dispatch-stage shed (saves the
        # device work); finished past deadline + grace = wait-stage shed
        # (the fused waiter's backstop — a late result is never served)
        if deadline is not None and time.monotonic() >= deadline:
            self._count_outcome("shed", stage="dispatch", reason="expired",
                                tenant=tenant)
            self._trace_shed("dispatch", "expired", ctx)
            raise DeadlineExceeded(
                "deadline expired before the unfused forward",
                stage="dispatch")
        t_fwd0 = time.perf_counter()
        try:
            with _tracing.attached_ctx(ctx):
                out = self._run(xx)
        except BaseException:
            self._count_outcome("failed", tenant=tenant)
            raise
        # unfused forwards charge their own device window (the fused
        # path's dispatcher charges per group); no-op when unmetered
        _resourcemeter.note_serving_forward(
            time.perf_counter() - t_fwd0, {tenant: int(xx.shape[0])})
        if deadline is not None \
                and time.monotonic() >= deadline + _WAIT_SHED_GRACE:
            self._count_outcome("shed", stage="wait", reason="expired",
                                tenant=tenant)
            self._trace_shed("wait", "expired", ctx)
            raise DeadlineExceeded(
                "deadline expired during the unfused forward",
                stage="wait")
        self._count_outcome("completed", tenant=tenant)
        return out

    def _admit(self, xx: np.ndarray, deadline: Optional[float],
               tenant: str):
        """Validation + admission control + (for fusable requests) the
        enqueue, all under ONE lock hold. Returns (future, span_context):
        the future is None for requests that must run unfused on the
        caller's thread; the context is the serve/admission span's (the
        caller opens it around this call) — it rides the queue item so
        downstream lifecycle spans keep parentage across the pipeline
        threads, and is None when tracing is off. `tenant` (already
        interned) books the admission; it rides the Future itself
        (`_dl4j_tenant`) so every later outcome — resolve, fail, shed
        from any pipeline thread — lands in the right tenant's books
        without widening the queue/handoff tuples."""
        ctx = _tracing.current_context()
        with self._lock:
            # shutdown check and enqueue under ONE lock: a request admitted
            # here is visible to shutdown()'s drain, so its Future always
            # resolves (result or explicit shutdown error) — never hangs
            if self._shutdown:
                raise ReplicaUnavailable(
                    "ParallelInference has been shut down")
            if xx.shape[0] == 0:
                # 0 is a multiple of every bucket, so an empty request
                # would sail through _pad at 0 rows and compile a fresh
                # 0-shape trace — reject it at admission instead
                raise RequestValidationError("empty request (0 examples)")
            if self._expected_shape is None:
                # under the lock: two concurrent FIRST callers must not both
                # see None and admit mismatched shapes into one fused group
                self._expected_shape = xx.shape[1:]
            elif xx.shape[1:] != self._expected_shape:
                # validate HERE, not deep inside the collector where a bad
                # request would fail the whole fused group
                raise RequestValidationError(
                    f"request feature shape {xx.shape[1:]} does not match "
                    f"this ParallelInference's {self._expected_shape}"
                )
            self._stats["requests"] += 1
            self._stats["examples"] += xx.shape[0]
            self._m_requests.inc()
            self._m_examples.inc(xx.shape[0])
            fusable = (self.mode == InferenceMode.BATCHED
                       and xx.shape[0] <= self.max_batch_size)
            # -- admission control (still under the lock: the queue-depth
            # facts it reads are mutated under it) --------------------------
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                self._shed_locked("admission", "expired", tenant=tenant)
                self._trace_shed("admission", "expired", ctx)
                raise DeadlineExceeded(
                    "deadline expired before admission",
                    stage="admission")
            # one percentile pass (a sort under the admission lock)
            # shared by the wait estimate, the staleness check, and the
            # Retry-After hint — and skipped entirely on the no-deadline
            # below-capacity fast path, where no decision would read it
            need_estimate = fusable and (
                deadline is not None
                or (self.queue_capacity
                    and self._q.qsize() >= self.queue_capacity))
            p50 = (self._batch_lat.percentile_seconds(50)
                   if need_estimate else None)
            est_wait = (self._estimate_wait_locked(p50)
                        if need_estimate else 0.0)
            if fusable and self.queue_capacity \
                    and self._q.qsize() >= self.queue_capacity:
                self._shed_locked("admission", "queue_full", tenant=tenant)
                self._trace_shed("admission", "queue_full", ctx)
                raise RequestRejected(
                    f"request queue at capacity "
                    f"({self.queue_capacity} requests)",
                    reason="queue_full", retry_after=est_wait)
            if fusable and deadline is not None \
                    and now + est_wait > deadline:
                if not self._estimator_stale_locked(now, p50):
                    self._shed_locked("admission", "predicted_late",
                                      tenant=tenant)
                    self._trace_shed("admission", "predicted_late", ctx)
                    raise RequestRejected(
                        f"estimated wait {est_wait * 1e3:.0f}ms exceeds "
                        f"the request's remaining deadline "
                        f"{(deadline - now) * 1e3:.0f}ms",
                        reason="predicted_late", retry_after=est_wait)
                # stale estimate + idle pipeline: admit this request as a
                # probe so the rolling p50 re-learns post-stall reality
                # (it may be served late — bounded by the wait backstop —
                # but without it a poisoned estimator sheds 100% forever).
                # The enqueue below makes the pipeline non-idle, so
                # concurrent callers go back to shedding: one probe per
                # staleness window, not a floodgate
                self._m_probe.inc()
            self._books.admit(tenant)
            self._m_admitted.inc()
            fut: Optional[Future] = None
            if fusable:
                fut = Future()
                fut._dl4j_tenant = tenant
                self._queued_examples += xx.shape[0]
                # put_nowait: the queue OBJECT is unbounded (the capacity
                # bound is the admission check above), so this is exactly
                # `put` — minus the lint-rejected blocking form. The item
                # carries the admission span's context plus the enqueue
                # timestamp: the collector turns them into the
                # serve/queued span when it picks the request up.
                self._q.put_nowait(
                    (xx, fut, deadline, ctx, time.perf_counter()))
        return fut, ctx

    # -- overload accounting --------------------------------------------------

    def _estimate_wait_locked(self, p50: Optional[float]) -> float:
        """Expected queue wait for a newly admitted request: groups
        ahead of it — queued examples at bucket granularity, plus the
        already-assembled groups parked in the handoff, plus the group
        the device holds — × `p50`, the caller-supplied rolling p50
        device-forward latency (computed once per admission: the
        percentile pass sorts the window under the admission lock).
        (The group in the collector's hands stays invisible: the
        estimate is honest to ±1 group.) Zero until the first forward
        lands — cold admission is optimistic."""
        if p50 is None:
            return 0.0
        groups_ahead = (self._queued_examples / float(self.max_batch_size)
                        + self._handoff.qsize())
        return (groups_ahead + 1.0) * p50

    def _estimator_stale_locked(self, now: float,
                                p50: Optional[float]) -> bool:
        """True when the wait estimate can no longer be trusted: the
        pipeline is idle (nothing queued, nothing handed off) yet no
        forward has landed within max(4 x p50, _ESTIMATOR_STALE_MIN).
        That shape is the post-stall poison — one contended window
        pushed the rolling p50 past every deadline, admission went to
        100% shed, and the tracker is starved of the fresh samples that
        would let it recover. (Only reached when a p50 exists: with an
        empty tracker the estimate is 0 and nothing is predicted late.)"""
        if self._queued_examples or self._handoff.qsize():
            return False
        if p50 is None:
            return False
        last = self._last_forward_mono
        return last is None \
            or now - last > max(4.0 * p50, _ESTIMATOR_STALE_MIN)

    def estimated_wait(self) -> float:
        with self._lock:
            return self._estimate_wait_locked(
                self._batch_lat.percentile_seconds(50))

    def _shed_locked(self, stage: str, reason: str,
                     admitted: bool = False,
                     tenant: Optional[str] = None):
        """Book one shed under the (already-held) lock. Post-admission
        sheds land in `shed` (the conservation law's term); admission
        refusals land in `rejected` — the request never entered the
        system. Both feed serving_shed_total{stage,reason}, keyed by
        the request's tenant in the books."""
        self._books.shed(stage, reason, tenant=tenant, admitted=admitted)
        self._m_shed.labels(stage, reason).inc()

    def _count_outcome(self, outcome: str, stage: Optional[str] = None,
                       reason: Optional[str] = None,
                       tenant: Optional[str] = None):
        with self._lock:
            if outcome == "shed":
                self._shed_locked(stage, reason, admitted=True,
                                  tenant=tenant)
                return
            if outcome == "completed":
                self._books.complete(tenant)
            else:
                self._books.fail(tenant)
        (self._m_completed if outcome == "completed"
         else self._m_failed).inc()

    def _resolve(self, fut: Future, value) -> bool:
        """Deliver a result; count `completed` only when OUR set won (an
        abort may have failed the future concurrently — whoever's set
        lands does the counting, so every future is counted once)."""
        try:
            fut.set_result(value)
        except Exception:
            return False
        self._count_outcome("completed",
                            tenant=getattr(fut, "_dl4j_tenant", None))
        return True

    def _fail(self, fut: Future, exc: Exception, outcome: str = "failed",
              stage: Optional[str] = None,
              reason: Optional[str] = None) -> bool:
        try:
            fut.set_exception(exc)
        except Exception:
            return False
        self._count_outcome(outcome, stage, reason,
                            tenant=getattr(fut, "_dl4j_tenant", None))
        return True

    def _dequeued(self, item):
        with self._lock:
            self._queued_examples -= item[0].shape[0]

    def _trace_shed(self, stage: str, reason: str,
                    ctx: Optional[_tracing.SpanContext] = None):
        _trace_shed_span(stage, reason, ctx)

    def _shed_if_expired(self, item, stage: str) -> bool:
        """Shed a queued request whose deadline passed while it waited —
        serving it would burn device time on a result nobody reads."""
        fut, deadline = item[1], item[2]
        if deadline is None or time.monotonic() < deadline:
            return False
        if self._fail(
                fut,
                DeadlineExceeded(f"deadline expired in {stage}",
                                 stage=stage),
                outcome="shed", stage=stage, reason="expired"):
            # span only when OUR fail won (and counted): a waiter that
            # already shed this future recorded ITS span — the trace must
            # mirror serving_shed_total, one shed, one stage
            self._trace_shed(stage, "expired", item[3])
        return True

    def warmup(self, feature_shape: Optional[Sequence[int]] = None,
               dtype=np.float32):
        """Precompile the forward for every bucket before traffic, so the
        first requests never pay a trace+compile. Fixes the expected
        feature shape (or uses the one already fixed by a request)."""
        with self._lock:
            if feature_shape is not None:
                fs = tuple(feature_shape)
                if self._expected_shape is None:
                    self._expected_shape = fs
                elif fs != self._expected_shape:
                    raise ValueError(
                        f"warmup shape {fs} does not match this "
                        f"ParallelInference's {self._expected_shape}"
                    )
            fs = self._expected_shape
        if fs is None:
            raise ValueError(
                "warmup() needs a feature shape: pass feature_shape= or "
                "serve one request first"
            )
        for b in self.buckets:
            self._run(np.zeros((b,) + fs, dtype), count=False)
        return self

    def metrics(self) -> dict:
        """Point-in-time serving counters. `forward_compiles` is the
        model's trace count — in steady state it equals the number of
        distinct post-padding shapes (≤ len(buckets)); growth under
        traffic means something is defeating the buckets."""
        with self._lock:
            m = {
                "mode": self.mode,
                "requests": self._stats["requests"],
                "examples": self._stats["examples"],
                "batches": self._stats["batches"],
                "oversized": self._stats["oversized"],
                "bucket_hits": dict(self._stats["bucket_hits"]),
                **self._books.totals(),
                "tenants": self._books.per_tenant(),
                "conservation_ok": self._books.conservation_ok(),
            }
        m["buckets"] = list(self.buckets)
        m["max_batch_size"] = self.max_batch_size
        m["batch_timeout_ms"] = self.batch_timeout * 1e3
        m["queue_depth"] = self._q.qsize() + self._handoff.qsize()
        m["queue_capacity"] = self.queue_capacity
        m["default_deadline_ms"] = self.default_deadline_ms
        m["estimated_wait_ms"] = round(self.estimated_wait() * 1e3, 3)
        m["forward_compiles"] = int(
            getattr(self.model, "output_compile_count", 0))
        return m

    def shutdown(self):
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
        workers_exited = True
        if self._collect_t is not None:
            # the admission lock above guarantees the sentinel is the LAST
            # item: everything already queued drains normally (served),
            # then the pipeline exits stage by stage (unbounded queue:
            # put_nowait is exact)
            self._q.put_nowait(None)
            self._collect_t.join(timeout=10)
            self._dispatch_t.join(timeout=10)
            workers_exited = (not self._collect_t.is_alive()
                              and not self._dispatch_t.is_alive())
        for hb in (self._hb_collect, self._hb_dispatch):
            if hb is not None:
                _health.get_health().unregister(hb)
        # the serving ledger scope ends AFTER the drain/joins: the
        # owned ledger's final sample must see the end-of-run books
        # (in-flight futures resolved), not a mid-drain truncation
        if self._owned_ledger is not None:
            self._owned_ledger.close()
        elif self._attached_ledger is not None:
            _runledger.detach(self._attached_ledger)
        if not workers_exited:
            # a slow in-flight forward (e.g. first compile) outlived the
            # join timeout: the pipeline is still draining and will resolve
            # every Future itself — sweeping now would steal its sentinel
            # and fail work it was about to serve
            return
        # post-drain sweep: if a worker died abnormally, fail any stranded
        # Future explicitly instead of hanging its caller forever
        self._sweep_futures(RuntimeError("ParallelInference shut down"))

    def abort(self, reason: str = "aborted"):
        """Hard stop — the ReplicaPool eviction path. Unlike shutdown()
        (which drains: everything queued is still served), abort() stops
        the pipeline at its next poll and FAILS queued and in-flight
        futures with a RuntimeError naming `reason`. Callers routing
        through a ReplicaPool never see those failures — the pool
        retries admission-level RuntimeErrors on a healthy replica;
        only requests already inside the device forward are lost, which
        is exactly the eviction contract (fail only in-flight)."""
        with self._lock:
            already = self._shutdown and self._abort.is_set()
            self._shutdown = True
        if already:
            return
        self._abort.set()
        for t in (self._collect_t, self._dispatch_t):
            if t is not None:
                # a healthy thread exits within one queue poll; a WEDGED
                # one (the reason for the eviction) is left behind as a
                # daemon — its heartbeat is unregistered below, so it
                # cannot re-trip the watchdog
                t.join(timeout=2.0)
        # in-flight futures (inside the device forward) are genuinely
        # lost — non-retryable; everything still QUEUED never ran and
        # fails retryable, so a pool re-routes it with zero caller-visible
        # errors
        err = RuntimeError(f"ParallelInference {reason} (in flight)")
        for fut in list(self._inflight):
            self._fail(fut, err)  # no-op if it lost to a completing forward
        self._sweep_futures(ReplicaUnavailable(f"ParallelInference {reason}"))
        for hb in (self._hb_collect, self._hb_dispatch):
            if hb is not None:
                _health.get_health().unregister(hb)

    def _sweep_futures(self, err: Exception):
        for q in (self._q, self._handoff):
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
                if item is None:
                    continue
                if q is self._q:
                    self._dequeued(item)
                    futs = [item[1]]
                else:
                    futs = item[3]
                for fut in futs:
                    self._fail(fut, err)

    # -- internals -----------------------------------------------------------

    def _bucket_for(self, n: int) -> Optional[int]:
        for b in self.buckets:
            if n <= b:
                return b
        return None

    def _pad(self, batch: np.ndarray):
        """Bucket-pad then shard-pad. Returns (padded, n, bucket). The
        post-padding shape is what the jit trace sees, so the distinct
        trace count is len({shard-padded bucket sizes}), not the number of
        distinct request/group sizes."""
        n = batch.shape[0]
        b = self._bucket_for(n)
        if b is not None:
            batch = pad_wrap(batch, b)
        # non-divisible sizes are padded by wrapping and sliced — sharded
        # execution with a stable trace shape instead of a replicated
        # fallback
        batch = pad_wrap(batch, self.n_shards)
        return batch, n, b

    def _count_batch(self, b: Optional[int]):
        with self._lock:
            self._stats["batches"] += 1
            if b is None:
                self._stats["oversized"] += 1
            else:
                self._stats["bucket_hits"][b] += 1
        if b is None:
            self._m_oversized.inc()
        else:
            self._m_bucket.labels(str(b)).inc()

    def _forward_padded(self, padded: np.ndarray, n: int,
                        b: Optional[int], count: bool = True):
        """The ONE device forward both paths (caller-thread `_run` and the
        BATCHED dispatcher) go through: sharded dispatch, host readback,
        pad rows sliced off. A multi-output ComputationGraph returns a
        list; the batch slice applies per output, not to the list."""
        t0 = time.perf_counter()
        try:
            # chaos hook: an `error` fault here is a device-forward
            # failure (the whole fused group fails; a ReplicaPool retries
            # nothing — in-flight is the one non-retryable stage); a
            # `hang` is the wedged-dispatcher scenario the watchdog and
            # eviction path exist for
            _faults.fault_point("replica_forward", bucket=b, rows=n)
            with _tracing.span("serve/forward", bucket=b, rows=n):
                out = self.model.output(
                    jax.device_put(padded, batch_sharded(self.mesh)))
            if isinstance(out, (list, tuple)):
                out = [np.asarray(o)[:n] for o in out]
            else:
                out = np.asarray(out)[:n]
        except BaseException as e:
            from deeplearning4j_tpu.utils import devprof as _devprof

            if _devprof.is_oom(e):
                # a serving-forward allocator failure gets the same
                # forensics as a fit-loop one: top live buffers + static
                # estimate into a flight-recorder dump, then the group
                # fails as usual (ReplicaPool does not retry in-flight)
                _devprof.oom_forensics("serving_forward", e,
                                       net=self.model)
            with self._lock:
                if (not self._shape_confirmed
                        and self._expected_shape == padded.shape[1:]):
                    # the shape that pinned _expected_shape never ran
                    # successfully (e.g. a feature width the model
                    # rejects): unpin, so later well-formed requests can
                    # re-pin instead of being rejected forever. The
                    # equality guard keeps a stale failing group from
                    # clobbering a NEWER pin by a different shape
                    self._expected_shape = None
            raise
        with self._lock:
            self._shape_confirmed = True
        if count:  # after the forward: a failed batch is not a served one
            # rolling batch latency (successful SERVED batches only): the
            # admission-control wait estimate reads its p50. Warmup runs
            # (count=False) are excluded — they pay trace+compile, and a
            # window seeded with ~1s compile samples would predicted-late
            # every deadline-carrying request before real traffic ever
            # lands a steady-state sample
            self._batch_lat.record(time.perf_counter() - t0)
            self._last_forward_mono = time.monotonic()
            self._count_batch(b)
        return out

    @staticmethod
    def _rows(out, start: int, stop: int):
        if isinstance(out, list):
            return [o[start:stop] for o in out]
        return out[start:stop]

    def _run(self, xx: np.ndarray, count: bool = True):
        padded, n, b = self._pad(xx)
        return self._forward_padded(padded, n, b, count)

    def _put_handoff(self, item, futs=()) -> bool:
        """Backpressured put toward the dispatcher. Blocks while the
        device is a full group behind (that IS the backpressure), but
        aborts — failing the group's futures instead of wedging the
        collector forever — if the dispatcher thread died or the
        pipeline was abort()ed."""
        try:
            put_abortable(
                self._handoff, item,
                abort=lambda: (self._abort.is_set()
                               or (self._dispatch_t is not None
                                   and not self._dispatch_t.is_alive())))
            return True
        except QueueAborted:
            err = ReplicaUnavailable(
                "ParallelInference dispatcher unavailable "
                "(died or aborted)")
            for fut in futs:
                # never dispatched — retryable on another replica
                self._fail(fut, err)
            return False

    # BATCHED pipeline, stage 1: drain + concatenate + pad on the host
    def _collector(self):
        pending = None  # request that would overflow the current group
        hb = self._hb_collect
        while True:
            if pending is not None:
                item, pending = pending, None
            else:
                # poll-loop get (abort predicate: only the hard-stop
                # flag — the graceful-shutdown sentinel must drain the
                # queue in order, so the collector never exits ahead of
                # it). No busy slot while waiting here: an EMPTY request
                # queue is idle, not a stall.
                try:
                    item = get_abortable(self._q, abort=self._abort)
                except QueueAborted:
                    return  # abort(): sweep fails whatever is queued
                if item is not None:
                    self._dequeued(item)
            if item is None:
                self._put_handoff(None)
                return
            # shed, don't serve, a request that expired while queued
            if self._shed_if_expired(item, "collector"):
                continue
            # work in hand: from here until the handoff completes this
            # thread owes progress (a block inside _emit's handoff put
            # means the device is wedged — exactly what should degrade)
            with hb.busy():
                self._trace_queued(item)
                group = [item]
                count = item[0].shape[0]
                # drain more requests until batch limit or short timeout
                while count < self.max_batch_size:
                    try:
                        nxt = self._q.get(timeout=self.batch_timeout)
                    except queue.Empty:
                        break
                    if nxt is None:
                        self._emit(group)
                        self._put_handoff(None)
                        return
                    self._dequeued(nxt)
                    if self._shed_if_expired(nxt, "collector"):
                        continue
                    if (count + nxt[0].shape[0] > self.max_batch_size
                            or nxt[0].shape[1:] != item[0].shape[1:]):
                        # would overflow max_batch_size (and possibly fall
                        # off the bucket set) — or, during an unpin/re-pin
                        # window before the first successful forward, has
                        # a different feature shape (admission normally
                        # guarantees uniformity; this makes mixed-shape
                        # fusion structurally impossible) — start the
                        # next group
                        pending = nxt
                        break
                    self._trace_queued(nxt)
                    group.append(nxt)
                    count += nxt[0].shape[0]
                self._emit(group)

    def _trace_queued(self, item):
        """Retroactive serve/queued span for a request entering a fused
        group: enqueue time to now, parented to its admission span via
        the context carried on the queue item — the explicit-context
        handoff that keeps parentage across the collector thread."""
        if item[3] is not None and _tracing.is_enabled():
            _tracing.record_complete("serve/queued", item[4],
                                     time.perf_counter(), item[3])

    def _emit(self, group):
        """Host-side batch assembly; blocks on the bounded handoff queue
        when the device is a full group behind (backpressure)."""
        try:
            batch = (np.concatenate([g[0] for g in group], axis=0)
                     if len(group) > 1 else group[0][0])
            padded, n, b = self._pad(batch)
        except BaseException as e:  # propagate to all waiting callers
            for g in group:
                self._fail(g[1], e)
            return
        t0 = time.perf_counter()
        futs = [g[1] for g in group]
        # span contexts ride the handoff next to the futures: the second
        # explicit-context hop, so dispatch/forward spans completed on
        # the dispatcher thread still parent to each request's admission
        self._put_handoff(
            (padded, n, b, futs, [g[0].shape[0] for g in group],
             [g[2] for g in group], [g[3] for g in group]), futs)
        self._m_handoff.observe(time.perf_counter() - t0)

    # BATCHED pipeline, stage 2: device forward + scatter results
    def _dispatcher(self):
        while True:
            try:
                # exits on the collector's sentinel; the abort predicate
                # covers the hard stop and a collector that died WITHOUT
                # delivering one, so the dispatcher cannot outlive its
                # feeder
                work = get_abortable(
                    self._handoff,
                    abort=lambda: (self._abort.is_set()
                                   or (self._collect_t is not None
                                       and not self._collect_t.is_alive()
                                       and self._handoff.empty())))
            except QueueAborted:
                return
            if work is None:
                return
            padded, n, b, futs, sizes, deadlines, ctxs = work
            # shed expired members BEFORE burning device time on them;
            # when the WHOLE group expired while the device was behind,
            # skip the forward entirely (that skip is what keeps an
            # overloaded device from serving a backlog nobody is
            # waiting for). The padded batch still carries the shed
            # rows when only some expired — harmless: their results are
            # simply not delivered.
            now = time.monotonic()
            live = [fut for fut, d in zip(futs, deadlines)
                    if d is None or now < d]
            for fut, d, c in zip(futs, deadlines, ctxs):
                if d is not None and now >= d:
                    if self._fail(
                            fut,
                            DeadlineExceeded("deadline expired before the "
                                             "device forward",
                                             stage="dispatch"),
                            outcome="shed", stage="dispatch",
                            reason="expired"):
                        # span mirrors the counter: only when our fail
                        # won the race against the waiter's own shed
                        self._trace_shed("dispatch", "expired", c)
            if not live:
                continue
            live_ctxs = [c for c, d in zip(ctxs, deadlines)
                         if (d is None or now < d) and c is not None]
            # per-tenant device-second attribution for this fused group:
            # the forward's wall time splits over the LIVE rows by
            # tenant (shed members burned nothing). Built only when the
            # meter is armed — the unmetered dispatcher pays one read.
            shares = None
            if _resourcemeter.is_enabled():
                shares = {}
                for fut, k, d in zip(futs, sizes, deadlines):
                    if d is None or now < d:
                        t = (getattr(fut, "_dl4j_tenant", None)
                             or _tenancy.DEFAULT_TENANT)
                        shares[t] = shares.get(t, 0) + k
            # busy only while a group is in hand: a forward that never
            # returns (device wedge) leaves this slot stale and the
            # watchdog flips serving_dispatcher to degraded/unhealthy
            with self._hb_dispatch.busy():
                self._inflight = live
                # the dispatch span runs ATTACHED to the first live
                # request's admission context — the fused group's real
                # spans (dispatch + nested serve/forward) join that
                # request's trace; the other members get retroactive
                # copies below so every trace in the group is complete
                t_disp = time.perf_counter()
                try:
                    with _tracing.attached_ctx(
                            live_ctxs[0] if live_ctxs else None):
                        with _tracing.span("serve/dispatch",
                                           bucket=b, rows=n):
                            t_fwd0 = time.perf_counter()
                            out = self._forward_padded(padded, n, b)
                            t_fwd1 = time.perf_counter()
                    if shares:
                        _resourcemeter.note_serving_forward(
                            t_fwd1 - t_fwd0, shares)
                    off = 0
                    for fut, k in zip(futs, sizes):
                        # abort() may fail the future concurrently;
                        # _resolve counts only when our set wins
                        if not fut.done():
                            self._resolve(fut, self._rows(out, off, off + k))
                        off += k
                    self._trace_group_copies(live_ctxs[1:], t_disp,
                                             t_fwd0, t_fwd1, b, n)
                except BaseException as e:  # propagate to waiting callers
                    for fut in futs:
                        self._fail(fut, e)
                finally:
                    self._inflight = []

    def _trace_group_copies(self, ctxs, t_disp, t_fwd0, t_fwd1, b, n):
        """Retroactive dispatch+forward spans for the fused group's
        NON-primary members: the device forward ran once, but each
        member's trace must still show when its work was dispatched and
        executed — otherwise every trace but the first ends at its
        queued span."""
        if not ctxs or not _tracing.is_enabled():
            return
        t1 = time.perf_counter()
        for ctx in ctxs:
            dctx = _tracing.record_complete(
                "serve/dispatch", t_disp, t1, ctx, bucket=b, rows=n,
                fused_copy=True)
            if dctx is not None:
                _tracing.record_complete(
                    "serve/forward", t_fwd0, t_fwd1, dctx, bucket=b,
                    rows=n, fused_copy=True)


class ReplicaPool:
    """Self-healing pool of N ParallelInference replicas — the recovery
    half of the PR 6 health model (reference: ParallelInference.java's
    worker pool, grown an immune system).

    Each replica registers its collector/dispatcher heartbeats under
    `<prefix>_r<i>_*`, so the watchdog sees every replica separately. The
    pool subscribes to health transitions: when any component of replica
    i flips UNHEALTHY (a dispatcher wedged inside a device forward, a
    collector blocked against a dead handoff — the PR 6 stall model), a
    supervisor thread EVICTS the replica (abort(): queued work fails
    retryable and is re-routed here; only the group already inside the
    device forward is lost) and RESPAWNS a fresh one under the same
    component names. Requests route round-robin over in-rotation
    replicas; a request that lands on a replica mid-eviction comes back
    as ReplicaUnavailable and is resubmitted on a healthy sibling, so
    callers never see an error for work that never ran.

    Observable by construction: `serving_replica_evictions_total` /
    `serving_replica_respawns_total{replica}` counters and the
    `serving_replicas_in_rotation` gauge live in the shared registry
    (one /metrics scrape shows the self-healing happening), each
    eviction/respawn lands in the flight recorder, and the
    `component_health{component=<prefix>_r<i>_*}` transition history
    shows the unhealthy→ok cycle.

    `model_factory` (optional) builds a fresh model per spawn — without
    it every replica shares `model` (one set of replicated params, the
    TPU-native reading of a "replica": what multiplies is the serving
    pipeline, not the weights)."""

    def __init__(
        self,
        model=None,
        n_replicas: int = 2,
        mesh=None,
        inference_mode: str = InferenceMode.BATCHED,
        max_batch_size: int = 64,
        batch_timeout_ms: float = 2.0,
        buckets: Optional[Sequence[int]] = None,
        handoff_capacity: int = 2,
        health_stall_after: float = 30.0,
        component_prefix: str = "serving",
        model_factory=None,
        auto_heal: bool = True,
        retry_window: float = 5.0,
        retry_budget: int = 4,
        queue_capacity: int = 1024,
        default_deadline_ms: Optional[float] = None,
    ):
        if model is None and model_factory is None:
            raise ValueError("ReplicaPool needs a model or a model_factory")
        if int(n_replicas) < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.n_replicas = int(n_replicas)
        self.component_prefix = component_prefix
        self.auto_heal = bool(auto_heal)
        self.retry_window = float(retry_window)
        # resubmits-per-request cap: an eviction storm must not turn one
        # request into unbounded retry load (failover amplification)
        self.retry_budget = max(0, int(retry_budget))
        self._factory = (model_factory if model_factory is not None
                         else (lambda: model))
        self._pi_kwargs = dict(
            mesh=mesh, inference_mode=inference_mode,
            max_batch_size=int(max_batch_size),
            batch_timeout_ms=float(batch_timeout_ms), buckets=buckets,
            handoff_capacity=handoff_capacity,
            health_stall_after=health_stall_after,
            queue_capacity=queue_capacity,
            default_deadline_ms=default_deadline_ms)
        self._lock = threading.Lock()
        self._rr = 0
        self._gen = [0] * self.n_replicas
        self._warmup_shape = None
        self._shutdown = False
        # THIS pool's lifecycle counts (the registry counters below are
        # process-global across every pool the process ever built)
        self._evictions = 0
        self._respawns = 0
        reg = _metrics.get_registry()
        self._m_evict = reg.counter(
            "serving_replica_evictions_total",
            "replicas evicted from the pool (unhealthy or explicit)",
            ("replica",))
        self._m_respawn = reg.counter(
            "serving_replica_respawns_total",
            "replicas respawned into the pool after an eviction",
            ("replica",))
        self._m_rerouted = reg.counter(
            "serving_replica_rerouted_total",
            "requests retried on a sibling after a retryable replica "
            "failure (never user-visible)").labels()
        self._m_shed = reg.counter(
            "serving_shed_total",
            "requests shed instead of served late, by pipeline stage "
            "and reason", ("stage", "reason"))
        self._gauge = reg.gauge(
            "serving_replicas_in_rotation",
            "replicas currently taking traffic").labels()
        # pool-level sheds (resubmit stage) so metrics()["shed_by"]
        # mirrors serving_shed_total — the replicas never see these
        self._pool_shed_by: dict = {}
        # evicted replicas' final books, folded in at eviction time so
        # the JSON aggregate keeps agreeing with the registry counters
        # (which survive respawn via get_or_create) after an eviction
        self._retired: dict = {
            k: 0 for k in ("requests", "examples", "batches", "oversized",
                           "admitted", "completed", "shed", "failed",
                           "rejected")}
        self._retired["shed_by"] = {}
        self._retired["bucket_hits"] = {}
        # slots hold None while a replica is mid-respawn (out of rotation)
        self._replicas: List[Optional[ParallelInference]] = [None] * \
            self.n_replicas
        for i in range(self.n_replicas):
            self._replicas[i] = self._spawn(i)
        self._gauge.set(self.n_replicas)
        # eviction requests flow through a queue to the supervisor: the
        # health listener fires on the dl4j-watchdog thread, which must
        # never block on an abort()'s thread joins
        self._evict_q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._supervisor = threading.Thread(
            target=self._supervise, daemon=True,
            name=f"dl4j-replica-supervisor-{component_prefix}")
        self._supervisor.start()
        _health.get_health().add_listener(self._on_health_transition)

    # -- spawning / routing ---------------------------------------------------

    def _prefix(self, idx: int) -> str:
        return f"{self.component_prefix}_r{idx}"

    def _spawn(self, idx: int) -> ParallelInference:
        pi = ParallelInference(self._factory(),
                               component_prefix=self._prefix(idx),
                               **self._pi_kwargs)
        if self._warmup_shape is not None:
            try:
                pi.warmup(self._warmup_shape)
            except Exception:
                logger.exception("replica %d warmup failed (serving "
                                 "anyway; first requests pay the compile)",
                                 idx)
        return pi

    def _pick(self) -> Optional[ParallelInference]:
        with self._lock:
            if self._shutdown:
                raise RuntimeError("ReplicaPool has been shut down")
            for _ in range(self.n_replicas):
                idx = self._rr % self.n_replicas
                self._rr += 1
                pi = self._replicas[idx]
                if pi is not None:
                    return pi
        return None

    def _pool_shed(self, reason: str):
        """Book a resubmit-stage shed on the pool's own ledger AND the
        shared serving_shed_total family, so the JSON metrics() books
        agree with the Prometheus scrape and the 429 the caller gets."""
        with self._lock:
            key = f"resubmit/{reason}"
            self._pool_shed_by[key] = self._pool_shed_by.get(key, 0) + 1
        self._m_shed.labels("resubmit", reason).inc()
        _trace_shed_span("resubmit", reason)  # caller-thread shed

    def output(self, x, deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None):
        """Thread-safe inference with failover: retryable replica
        failures (eviction races, mid-respawn gaps) are resubmitted on a
        healthy sibling — but each request spends a bounded
        `retry_budget` of resubmits and never retries past its own
        deadline, so a failover storm cannot multiply offered load.
        Non-retryable failures — a group already inside a device forward
        at eviction time, a genuine model error, or an admission shed
        (DeadlineExceeded / RequestRejected: retrying a load-shed
        request IS the amplification admission control exists to stop)
        — reach the caller directly."""
        req_deadline = (None if deadline_ms is None
                        else time.monotonic() + float(deadline_ms) / 1e3)
        retry_by = time.monotonic() + self.retry_window
        if req_deadline is not None:
            retry_by = min(retry_by, req_deadline)
        resubmits = 0
        last: Optional[Exception] = None
        while True:
            pi = self._pick()
            if pi is None:
                last = last or RuntimeError("no replica in rotation")
            else:
                try:
                    remaining_ms = (
                        None if req_deadline is None
                        else max(0.0, (req_deadline - time.monotonic()))
                        * 1e3)
                    return pi.output(x, deadline_ms=remaining_ms,
                                     tenant=tenant)
                except RequestValidationError:
                    raise  # the client's fault on ANY replica
                except (DeadlineExceeded, RequestRejected):
                    raise  # shed is shed — resubmitting amplifies load
                except ReplicaUnavailable as e:
                    last = e
                    resubmits += 1
                    if resubmits > self.retry_budget:
                        # booked as a shed, surfaced as one too: the
                        # REST layer must answer 429 (retry later, the
                        # work was never done), not a 500 that reads as
                        # a genuine server failure
                        self._pool_shed("retry_budget")
                        raise RequestRejected(
                            f"retry budget spent ({self.retry_budget} "
                            f"resubmits)", reason="retry_budget",
                            stage="resubmit") from last
                    self._m_rerouted.inc()
                    # the retry runs on the caller's thread, so the next
                    # replica's admission span joins this trace by stack;
                    # the marker makes the failover hop itself visible
                    _tracing.instant("serve/resubmit", resubmit=resubmits)
            now = time.monotonic()
            if req_deadline is not None and now >= req_deadline:
                self._pool_shed("expired")
                raise DeadlineExceeded(
                    "deadline expired during replica failover",
                    stage="resubmit") from last
            if now >= retry_by:
                raise RuntimeError(
                    f"no healthy replica within {self.retry_window:.1f}s"
                ) from last
            # a respawn is at most an abort-join + constructor away;
            # breathe instead of spinning the admission lock
            time.sleep(0.005)

    def warmup(self, feature_shape: Optional[Sequence[int]] = None,
               dtype=np.float32):
        """Precompile every bucket on every replica; the shape is kept so
        respawned replicas warm themselves before re-entering rotation."""
        with self._lock:
            replicas = [pi for pi in self._replicas if pi is not None]
        for pi in replicas:
            pi.warmup(feature_shape, dtype)
        if feature_shape is not None:
            self._warmup_shape = tuple(feature_shape)
        elif replicas and replicas[0]._expected_shape is not None:
            self._warmup_shape = replicas[0]._expected_shape
        return self

    # -- self-healing ---------------------------------------------------------

    def _on_health_transition(self, tr: dict):
        if tr.get("to") != _health.UNHEALTHY or self._shutdown:
            return
        comp = tr.get("component", "")
        for idx in range(self.n_replicas):
            if comp.startswith(self._prefix(idx) + "_"):
                self.request_eviction(
                    idx, reason=f"{comp} unhealthy "
                    f"({tr.get('stalled_for_seconds')}s stall)")
                return

    def request_eviction(self, idx: int, reason: str):
        """Queue an eviction for the supervisor thread (safe from any
        thread, including the watchdog's transition callback). The
        replica's CURRENT generation rides along: two components of one
        wedged replica both flipping UNHEALTHY queue two requests, and
        the stale second one must not evict the healthy respawn the
        first one produced."""
        idx = int(idx)
        with self._lock:
            gen = self._gen[idx]
        self._evict_q.put_nowait((idx, gen, reason))

    def _supervise(self):
        while True:
            try:
                idx, gen, reason = get_abortable(self._evict_q, self._stop)
            except QueueAborted:
                return
            try:
                self.evict(idx, reason, if_generation=gen)
            except Exception:
                logger.exception("replica %d eviction failed", idx)

    def evict(self, idx: int, reason: str = "evicted",
              if_generation: Optional[int] = None):
        """Take replica `idx` out of rotation, abort it (queued work
        fails retryable and re-routes; only in-flight work is lost), and
        — under auto_heal — respawn a fresh replica into the slot.
        `if_generation` makes the eviction conditional: a no-op when the
        slot has already been respawned past that generation."""
        with self._lock:
            pi = self._replicas[idx]
            if pi is None or self._shutdown:
                return  # already mid-respawn, or shutting down
            if if_generation is not None and self._gen[idx] != if_generation:
                logger.info(
                    "replica %d eviction request for gen %d is stale "
                    "(slot is at gen %d) — skipping", idx, if_generation,
                    self._gen[idx])
                return
            self._replicas[idx] = None
            self._gen[idx] += 1
            gen = self._gen[idx]
        self._gauge.set(self._in_rotation())
        with self._lock:
            self._evictions += 1
        self._m_evict.labels(str(idx)).inc()
        _blackbox.get_recorder().record_event(
            "replica_evicted", replica=idx, generation=gen, reason=reason)
        logger.warning("replica %d evicted (gen %d): %s", idx, gen, reason)
        pi.abort(f"replica {idx} evicted: {reason}")
        # abort() settled the replica's books (queued futures failed);
        # fold its final counters into the retired ledger so its sheds
        # and outcomes don't vanish from metrics() with the slot
        try:
            final = pi.metrics()
        except Exception:
            logger.exception("replica %d final metrics unreadable — its "
                             "books drop from the JSON aggregate", idx)
            final = None
        if final is not None:
            with self._lock:
                r = self._retired
                for k in ("requests", "examples", "batches", "oversized",
                          "admitted", "completed", "shed", "failed",
                          "rejected"):
                    r[k] += final[k]
                for sb, v in final["shed_by"].items():
                    r["shed_by"][sb] = r["shed_by"].get(sb, 0) + v
                for b, v in final["bucket_hits"].items():
                    r["bucket_hits"][b] = r["bucket_hits"].get(b, 0) + v
        if not self.auto_heal or self._shutdown:
            return
        fresh = self._spawn(idx)
        with self._lock:
            if self._shutdown:
                fresh.abort("pool shut down during respawn")
                return
            self._replicas[idx] = fresh
        self._gauge.set(self._in_rotation())
        with self._lock:
            self._respawns += 1
        self._m_respawn.labels(str(idx)).inc()
        _blackbox.get_recorder().record_event(
            "replica_respawned", replica=idx, generation=gen)
        logger.info("replica %d respawned (gen %d)", idx, gen)

    def _in_rotation(self) -> int:
        with self._lock:
            return sum(1 for pi in self._replicas if pi is not None)

    # -- introspection / lifecycle -------------------------------------------

    @property
    def model(self):
        with self._lock:
            for pi in self._replicas:
                if pi is not None:
                    return pi.model
        return None

    @property
    def buckets(self) -> List[int]:
        with self._lock:
            for pi in self._replicas:
                if pi is not None:
                    return list(pi.buckets)
        return []

    @property
    def _expected_shape(self):
        # duck-typing for InferenceServer's /health feature_shape field
        with self._lock:
            for pi in self._replicas:
                if pi is not None and pi._expected_shape is not None:
                    return pi._expected_shape
        return self._warmup_shape

    def metrics(self) -> dict:
        """Pool-aggregated serving counters in the ParallelInference
        schema (requests/examples/batches/bucket_hits summed over live
        replicas PLUS the retired books of evicted ones, so eviction
        never erases history from the JSON aggregate), plus the pool's
        own lifecycle numbers and a per-replica breakdown. `shed_by` mirrors serving_shed_total —
        replica stages plus the pool's resubmit stage — while `shed`
        stays the per-attempt conservation term (a resubmit shed's final
        attempt is already booked `failed` on its replica)."""
        with self._lock:
            replicas = list(self._replicas)
            gens = list(self._gen)
            pool_shed_by = dict(self._pool_shed_by)
            retired = {k: (dict(v) if isinstance(v, dict) else v)
                       for k, v in self._retired.items()}
        per, agg = [], None
        for idx, pi in enumerate(replicas):
            if pi is None:
                per.append({"replica": idx, "generation": gens[idx],
                            "in_rotation": False})
                continue
            m = pi.metrics()
            per.append({"replica": idx, "generation": gens[idx],
                        "in_rotation": True, "requests": m["requests"],
                        "examples": m["examples"], "batches": m["batches"],
                        "queue_depth": m["queue_depth"]})
            if agg is None:
                agg = m
            else:
                for k in ("requests", "examples", "batches", "oversized",
                          "admitted", "completed", "shed", "failed",
                          "rejected"):
                    agg[k] += m[k]
                for sb, v in m["shed_by"].items():
                    agg["shed_by"][sb] = agg["shed_by"].get(sb, 0) + v
                for b, v in m["bucket_hits"].items():
                    agg["bucket_hits"][b] = agg["bucket_hits"].get(b, 0) + v
                agg["queue_depth"] += m["queue_depth"]
                agg["forward_compiles"] = max(agg["forward_compiles"],
                                              m["forward_compiles"])
        if agg is None:  # every slot mid-respawn: still a valid scrape
            agg = {"mode": self._pi_kwargs["inference_mode"], "requests": 0,
                   "examples": 0, "batches": 0, "oversized": 0,
                   "bucket_hits": {}, "buckets": [],
                   "admitted": 0, "completed": 0, "shed": 0, "failed": 0,
                   "rejected": 0, "shed_by": {},
                   "max_batch_size": self._pi_kwargs["max_batch_size"],
                   "batch_timeout_ms":
                       self._pi_kwargs["batch_timeout_ms"],
                   "queue_depth": 0, "forward_compiles": 0}
        for k in ("requests", "examples", "batches", "oversized",
                  "admitted", "completed", "shed", "failed", "rejected"):
            agg[k] += retired[k]
        for sb, v in retired["shed_by"].items():
            agg["shed_by"][sb] = agg["shed_by"].get(sb, 0) + v
        for b, v in retired["bucket_hits"].items():
            agg["bucket_hits"][b] = agg["bucket_hits"].get(b, 0) + v
        for sb, v in pool_shed_by.items():
            agg["shed_by"][sb] = agg["shed_by"].get(sb, 0) + v
        agg["replicas"] = per
        agg["n_replicas"] = self.n_replicas
        agg["in_rotation"] = sum(1 for pi in replicas if pi is not None)
        with self._lock:
            agg["evictions"] = self._evictions
            agg["respawns"] = self._respawns
        return agg

    def shutdown(self):
        """Graceful: drain every replica (queued work is served), stop
        the supervisor, unsubscribe from health transitions."""
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            replicas = list(self._replicas)
            self._replicas = [None] * self.n_replicas
        _health.get_health().remove_listener(self._on_health_transition)
        self._stop.set()
        self._supervisor.join(timeout=10)
        for pi in replicas:
            if pi is not None:
                pi.shutdown()
        self._gauge.set(0)
