"""Deterministic fault injection + overload robustness (ISSUE 8).

Two contracts under test:

* **Replayable chaos** — a seeded `FaultPlan` over named fault points
  produces the SAME injected fault sequence every run (event-log
  equality), so "the failure from Tuesday" is a JSON file, not a shell
  history. Every injection rides the real failure path of its call site
  (a `replica_forward` error is a model failure, an `etl_worker` error
  propagates in-position, a `helper_fn` error trips the PR 2
  auto-disable), and the system under fault either recovers or fails
  loudly — never wedges past the watchdog budget.

* **Graceful degradation** — requests carry deadlines, expired work is
  shed at every pipeline stage, admission control bounds the queue, and
  the books balance exactly: `admitted == completed + shed + failed`
  (rejections happen before admission and are counted separately).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.prefetch import ParallelDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops import helpers as _helpers
from deeplearning4j_tpu.parallel.inference import (
    DeadlineExceeded,
    ParallelInference,
    RequestRejected,
)
from deeplearning4j_tpu.serving import InferenceServer
from deeplearning4j_tpu.train.checkpoint import CheckpointListener
from deeplearning4j_tpu.utils import faultpoints as fp
from deeplearning4j_tpu.utils import health as _health

N_IN = 6


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    """Every test leaves the process with NO active plan and no thread
    parked on a hang fault — chaos must never leak into a neighbor."""
    fp.clear()
    yield
    fp.clear()


def _net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Updater.SGD).learning_rate(0.05).weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _x(rows=2, seed=0):
    return np.random.default_rng(seed).standard_normal(
        (rows, N_IN)).astype(np.float32)


def _wait_until(pred, timeout=10.0, every=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


def _conserved(m):
    """The conservation law over a metrics snapshot."""
    assert m["admitted"] == m["completed"] + m["shed"] + m["failed"], m
    return m


# -- the plan itself: schedules, determinism, serde ---------------------------


def test_plan_schedules_exact():
    plan = fp.FaultPlan(seed=0)
    plan.add("replica_forward", "error", every_nth=3)
    plan.add("etl_worker", "error", between=(2, 4))
    plan.add("ckpt_write", "error", every_nth=1, max_fires=2)
    fires = {"replica_forward": [], "etl_worker": [], "ckpt_write": []}
    for point in fires:
        for _ in range(10):
            d = plan.decide(point)
            if d is not None:
                fires[point].append(d[1])
    assert fires["replica_forward"] == [3, 6, 9]
    assert fires["etl_worker"] == [2, 3, 4]
    assert fires["ckpt_write"] == [1, 2]  # max_fires caps every_nth=1


def test_plan_replay_determinism_and_serde():
    plan = fp.FaultPlan(seed=42)
    plan.add("replica_forward", "error", p=0.5)
    plan.add("http_handler", "latency", every_nth=4, latency_ms=1.0)

    def run(p):
        for _ in range(60):
            p.decide("replica_forward")
            p.decide("http_handler")
        return p.event_log()

    log1 = run(plan)
    assert log1, "p=0.5 over 60 draws fired nothing — seeding is broken"
    plan.reset()
    assert run(plan) == log1  # same plan object, replayed
    assert run(fp.FaultPlan.from_json(plan.to_json())) == log1  # serde
    other = fp.FaultPlan(seed=43)
    other.add("replica_forward", "error", p=0.5)
    other.add("http_handler", "latency", every_nth=4, latency_ms=1.0)
    assert run(other) != log1  # the seed is load-bearing


def test_plan_validation():
    with pytest.raises(ValueError):
        fp.FaultRule("no_such_point", "error", every_nth=1)
    with pytest.raises(ValueError):
        fp.FaultRule("ckpt_write", "explode", every_nth=1)
    with pytest.raises(ValueError):
        fp.FaultRule("ckpt_write", "error")  # no schedule
    with pytest.raises(ValueError):
        fp.FaultRule("ckpt_write", "error", every_nth=1, p=0.5)  # two
    with pytest.raises(ValueError):
        fp.FaultRule("ckpt_write", "error", between=(4, 2))
    with pytest.raises(ValueError):
        fp.FaultRule("ckpt_write", "error", p=1.5)


def test_fault_point_without_plan_is_a_noop():
    fp.clear()
    fp.fault_point("replica_forward")  # nothing installed: free
    with fp.active(fp.FaultPlan(seed=1).add("ckpt_write", "error",
                                            every_nth=1)):
        fp.fault_point("replica_forward")  # no rule for this point
        plan = fp.get_plan()
        assert plan.invocations() == {"replica_forward": 1}
        assert plan.event_log() == []
    assert fp.get_plan() is None  # scope cleared


# -- serving: injected forwards fail loudly, books balance, replay holds ------


def _run_serving_error_round(plan, n_requests=12):
    """One warmed-up ParallelInference, `n_requests` SEQUENTIAL requests
    under `plan` (sequential ⇒ one device forward per request ⇒ the
    per-point invocation sequence is deterministic). Returns (event log,
    outcome string, successful outputs)."""
    net = _net()
    pi = ParallelInference(net, max_batch_size=4, batch_timeout_ms=1.0,
                           component_prefix="chaos_seq")
    outcomes, outputs = [], []
    try:
        pi.warmup((N_IN,))  # compile + confirm shape BEFORE the chaos
        with fp.active(plan):
            for i in range(n_requests):
                x = _x(rows=2, seed=i)
                try:
                    outputs.append((x, np.asarray(pi.output(x))))
                    outcomes.append("ok")
                except fp.FaultInjected:
                    outcomes.append("fault")
        m = _conserved(pi.metrics())
    finally:
        pi.shutdown()
    return plan.event_log(), "".join(
        "F" if o == "fault" else "." for o in outcomes), outputs, m


def test_serving_error_injection_conservation_and_replay():
    plan = fp.FaultPlan(seed=7).add("replica_forward", "error",
                                    every_nth=3)
    log1, pattern1, outputs, m = _run_serving_error_round(plan)
    # every 3rd forward fails, the OTHER requests are untouched
    assert pattern1 == "..F..F..F..F"
    assert m["admitted"] == 12 and m["failed"] == 4
    assert m["completed"] == 8 and m["shed"] == 0
    # no silently wrong result: survivors equal the direct model output
    ref = _net(seed=7)
    for x, out in outputs:
        np.testing.assert_allclose(out, np.asarray(ref.output(x)),
                                   rtol=1e-5, atol=1e-6)
    # the acceptance criterion: same seed + plan ⇒ same fault sequence
    plan.reset()
    log2, pattern2, _, _ = _run_serving_error_round(plan)
    assert log2 == log1 and pattern2 == pattern1
    assert [e["invocation"] for e in log1] == [3, 6, 9, 12]


def test_deadline_expired_at_admission_is_shed_not_served():
    net = _net()
    pi = ParallelInference(net, max_batch_size=4, batch_timeout_ms=1.0,
                           component_prefix="chaos_adm")
    try:
        pi.warmup((N_IN,))
        with pytest.raises(DeadlineExceeded) as ei:
            pi.output(_x(), deadline_ms=0.0)
        assert ei.value.stage == "admission"
        m = _conserved(pi.metrics())
        # never admitted: the rejection sits OUTSIDE the conservation law
        # (warmup bypasses admission — it is the server's own traffic)
        assert m["rejected"] == 1 and m["admitted"] == 0
        assert m["shed_by"] == {"admission/expired": 1}
    finally:
        pi.shutdown()


def test_queue_full_rejection_and_predicted_late():
    """Wedge the single device forward (hang fault) so the pipeline
    backs up: handoff fills, the collector blocks, the request queue
    grows to `queue_capacity` — and the NEXT caller is rejected
    immediately instead of queueing unboundedly. After release, the
    recorded (huge) batch latency makes a tight-deadline request
    predictably late — the cost-based half of admission."""
    net = _net()
    # forward 1 hangs (the wedge); forwards 2-5 carry a 20ms injected
    # latency so the rolling p50 the wait estimate reads is a KNOWN
    # ~20ms — not the organic sub-ms forward of whatever box runs this
    plan = (fp.FaultPlan(seed=1)
            .add("replica_forward", "hang", between=(1, 1),
                 hang_seconds=30.0)
            .add("replica_forward", "latency", between=(2, 6),
                 latency_ms=20.0))
    pi = ParallelInference(net, max_batch_size=1, batch_timeout_ms=1.0,
                           queue_capacity=2, handoff_capacity=1,
                           component_prefix="chaos_qf")
    threads = []
    try:
        with fp.active(plan):
            # r1 hangs in the forward; r2 fills the handoff; r3 is in the
            # collector's hand; r4, r5 sit in the queue (capacity 2)
            for i in range(5):
                t = threading.Thread(
                    target=lambda i=i: pi.output(_x(rows=1, seed=i)),
                    daemon=True, name=f"dl4j-test-client-{i}")
                t.start()
                threads.append(t)
                # let the pipeline drain each submission as far as it
                # can before the next (deterministic stage occupancy)
                _wait_until(lambda: pi.metrics()["requests"] == i + 1)
            assert _wait_until(lambda: pi._q.qsize() >= 2), \
                "pipeline never backed up"
            with pytest.raises(RequestRejected) as ei:
                pi.output(_x(rows=1, seed=99))
            assert ei.value.reason == "queue_full"
            assert ei.value.retry_after >= 0.0
            plan.release()  # un-wedge: everything queued completes
            for t in threads:
                t.join(timeout=30.0)
                assert not t.is_alive(), "client wedged past release"
            m = _conserved(pi.metrics())
            assert m["completed"] == 5
            assert m["shed_by"].get("admission/queue_full") == 1
            # with the injected ~20ms forwards in the rolling window the
            # p50-based estimate is deterministically >> a 1ms budget
            # (the one hung forward nudges the p50 without dominating it)
            assert pi.estimated_wait() > 0.01
            # pin the staleness clock: on a contention-stalled box >1s
            # can pass between the last forward and this call, and the
            # stale-estimator probe would then legitimately ADMIT the
            # tight-deadline request (that path has its own test) —
            # this test pins the fresh-estimate rejection path
            pi._last_forward_mono = time.monotonic()
            with pytest.raises(RequestRejected) as ei:
                pi.output(_x(rows=1, seed=100), deadline_ms=1.0)
            assert ei.value.reason == "predicted_late"
            assert ei.value.retry_after > 0.0
    finally:
        pi.shutdown()


def test_stale_estimator_probe_self_heals_admission():
    """A rolling p50 poisoned past every caller's deadline (one
    contended window) must not shed 100% forever — the estimator is fed
    only by completed forwards, so pure predicted-late shedding would
    starve it of the samples that let it recover. Pins all three layers:
    warmup compile runs never enter the estimator, a FRESH slow estimate
    sheds predicted_late, and once the pipeline has sat idle past the
    staleness window ONE probe is admitted to re-learn reality."""
    net = _net()
    pi = ParallelInference(net, max_batch_size=1, batch_timeout_ms=1.0,
                           queue_capacity=4,
                           component_prefix="chaos_probe")
    try:
        pi.warmup((N_IN,))
        # warmup compiled every bucket but recorded nothing: admission
        # starts cold-optimistic, not poisoned by trace+compile latency
        assert pi.estimated_wait() == 0.0
        # poison: a window of 1s forwards, the last landed just now
        for _ in range(8):
            pi._batch_lat.record(1.0)
        pi._last_forward_mono = time.monotonic()
        with pytest.raises(RequestRejected) as ei:
            pi.output(_x(rows=1), deadline_ms=50.0)
        assert ei.value.reason == "predicted_late"
        # the stall clears, but nothing re-feeds the estimator…
        pi._last_forward_mono = time.monotonic() - 10.0
        # …until a probe slips through: est 1s > the 500ms budget, but
        # the estimate is stale (idle pipeline, no forward in 10s)
        out = pi.output(_x(rows=1), deadline_ms=500.0)
        assert np.asarray(out).shape[0] == 1
        m = _conserved(pi.metrics())
        assert m["completed"] == 1
        assert m["shed_by"].get("admission/predicted_late") == 1
        # a trickle, not a floodgate: the probe's landing refreshed the
        # staleness clock, so while the window is still mostly slow a
        # tight deadline goes right back to shedding
        with pytest.raises(RequestRejected) as ei:
            pi.output(_x(rows=1), deadline_ms=50.0)
        assert ei.value.reason == "predicted_late"
    finally:
        pi.shutdown()


def test_requests_expired_in_queue_are_shed_not_forwarded():
    """Requests whose deadline passes WHILE queued behind a wedged
    forward are shed (collector or dispatch stage) — the device never
    burns time on results nobody is waiting for."""
    net = _net()
    plan = fp.FaultPlan(seed=2).add("replica_forward", "hang",
                                    between=(1, 1), hang_seconds=30.0)
    pi = ParallelInference(net, max_batch_size=1, batch_timeout_ms=1.0,
                           component_prefix="chaos_exp")
    results = {}

    def client(i, deadline_ms):
        try:
            results[i] = ("ok", pi.output(_x(rows=1, seed=i),
                                          deadline_ms=deadline_ms))
        except DeadlineExceeded as e:
            results[i] = ("shed", e.stage)
        except Exception as e:  # pragma: no cover - diagnostic
            results[i] = ("err", repr(e))

    try:
        # warmup compiles without feeding the admission estimator
        # (compile latency is not steady state), so the 80ms clients are
        # ADMITTED under the cold-optimistic estimate and post-release
        # shedding happens at the collector/dispatch stages — the paths
        # this test pins — well inside the callers' wait-backstop grace
        pi.warmup((N_IN,))
        with fp.active(plan):
            t0 = threading.Thread(target=client, args=(0, None),
                                  daemon=True, name="dl4j-test-c0")
            t0.start()  # hangs inside the forward
            assert _wait_until(lambda: pi.metrics()["admitted"] >= 1)
            late = []
            for i in range(1, 4):
                t = threading.Thread(target=client, args=(i, 80.0),
                                     daemon=True, name=f"dl4j-test-c{i}")
                t.start()
                late.append(t)
            time.sleep(0.15)  # all three banked deadlines expire
            plan.release()
            for t in [t0] + late:
                t.join(timeout=30.0)
                assert not t.is_alive()
        assert results[0][0] == "ok"  # the hung one still completed
        for i in range(1, 4):
            assert results[i][0] == "shed", results[i]
            assert results[i][1] in ("collector", "dispatch")
        m = _conserved(pi.metrics())
        assert m["shed"] == 3 and m["completed"] == 1  # r0 only
    finally:
        pi.shutdown()


def test_wedged_pipeline_wait_backstop_sheds_the_caller():
    """When the pipeline itself wedges, no downstream stage will ever
    touch the future — the caller's own bounded wait (deadline + grace)
    sheds it with stage="wait", and the late-completing forward after
    release must NOT double-count the request."""
    from deeplearning4j_tpu.parallel.inference import _WAIT_SHED_GRACE

    net = _net()
    plan = fp.FaultPlan(seed=12).add("replica_forward", "hang",
                                     between=(1, 1), hang_seconds=30.0)
    pi = ParallelInference(net, max_batch_size=2, batch_timeout_ms=1.0,
                           component_prefix="chaos_wait")
    try:
        pi.warmup((N_IN,))
        with fp.active(plan):
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded) as ei:
                pi.output(_x(), deadline_ms=100.0)
            waited = time.monotonic() - t0
            assert ei.value.stage == "wait"
            # bounded: deadline + grace, not the 30s hang
            assert waited < 0.1 + _WAIT_SHED_GRACE + 2.0, waited
            plan.release()
        # the released forward resolves against an already-failed
        # future: a no-op, so the books stay exactly-once
        assert _wait_until(
            lambda: _conserved(pi.metrics())["shed"] == 1)
        m = pi.metrics()
        assert m["completed"] == 0 and m["shed_by"] == {"wait/expired": 1}
    finally:
        pi.shutdown()


def test_hang_fault_trips_watchdog_then_recovers():
    """An injected hang IS a device wedge: the dispatcher's heartbeat
    goes stale, the watchdog degrades the component, and release()
    recovers it — the no-wedge guarantee chaos plans rely on."""
    net = _net()
    plan = fp.FaultPlan(seed=3).add("replica_forward", "hang",
                                    between=(1, 1), hang_seconds=30.0)
    pi = ParallelInference(net, max_batch_size=2, batch_timeout_ms=1.0,
                           health_stall_after=0.25,
                           component_prefix="chaos_wd")
    comp = "chaos_wd_dispatcher"
    try:
        pi.warmup((N_IN,))
        with fp.active(plan):
            t = threading.Thread(target=lambda: pi.output(_x()),
                                 daemon=True, name="dl4j-test-hang")
            t.start()
            assert _wait_until(
                lambda: _health.get_health().status()["components"]
                .get(comp, {}).get("status") in ("degraded", "unhealthy"),
                timeout=10.0), "watchdog never saw the injected wedge"
            plan.release()
            t.join(timeout=30.0)
            assert not t.is_alive()
        assert _wait_until(
            lambda: _health.get_health().status()["components"]
            .get(comp, {}).get("status") == "ok", timeout=10.0)
        _conserved(pi.metrics())
    finally:
        pi.shutdown()


# -- the other fault points ride their real failure paths ---------------------


def test_etl_worker_fault_surfaces_in_position():
    base = [DataSet(np.full((2, 3), i, np.float32),
                    np.zeros((2, 2), np.float32)) for i in range(6)]
    plan = fp.FaultPlan(seed=4).add("etl_worker", "error", between=(3, 3))
    seen = []
    with fp.active(plan):
        with pytest.raises(fp.FaultInjected):
            # workers=1: the 3rd invocation IS the 3rd item
            for ds in ParallelDataSetIterator(base, workers=1,
                                              stage="chaos_etl"):
                seen.append(float(np.asarray(ds.features)[0, 0]))
    assert seen == [0.0, 1.0]  # items before the fault, in order
    assert [e["invocation"] for e in plan.event_log()] == [3]


def test_ckpt_write_fault_leaves_no_torn_state(tmp_path):
    net = _net()
    ckdir = str(tmp_path / "ck")
    listener = CheckpointListener(ckdir)
    plan = fp.FaultPlan(seed=5).add("ckpt_write", "error", every_nth=1,
                                    max_fires=1)
    with fp.active(plan):
        with pytest.raises(fp.FaultInjected):
            listener.save(net, reason="chaos")
        # the fault fired before the tmp write: no orphan, no zip, and
        # the NEXT save (fault budget spent) succeeds cleanly
        assert list((tmp_path / "ck").glob("*.tmp")) == []
        assert list((tmp_path / "ck").glob("*.zip")) == []
        listener.save(net, reason="after-chaos")
    assert len(list((tmp_path / "ck").glob("*.zip"))) == 1
    meta = json.loads((tmp_path / "ck" / "latest.json").read_text())
    assert meta["reason"] == "after-chaos"


def test_helper_fn_fault_rides_the_auto_disable_path():
    calls = []
    _helpers.register_helper("chaos_test_op", lambda v: calls.append(v),
                             name="chaos-helper")
    try:
        plan = fp.FaultPlan(seed=6).add("helper_fn", "error", every_nth=1)
        with fp.active(plan):
            guarded = _helpers.get_helper("chaos_test_op")
            assert guarded is not None
            with pytest.raises(_helpers.HelperError):
                guarded(1)
        assert calls == []  # the injected failure preempted the kernel
        # the REAL degradation story: helper disabled, builtin path next
        assert _helpers.helper_enabled("chaos_test_op") is False
        assert _helpers.get_helper("chaos_test_op") is None
    finally:
        _helpers._HELPERS.pop("chaos_test_op", None)


def test_http_handler_fault_is_a_500_and_the_server_survives():
    net = _net()
    server = InferenceServer(net, max_batch_size=4, warmup_shape=(N_IN,))
    port = server.start()
    base = f"http://127.0.0.1:{port}"
    x = _x().tolist()

    def predict(payload):
        req = urllib.request.Request(
            f"{base}/predict", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(req, timeout=15).read())

    plan = fp.FaultPlan(seed=8).add("http_handler", "error",
                                    between=(2, 2))
    try:
        with fp.active(plan):
            assert "predictions" in predict({"features": x})  # inv 1
            with pytest.raises(urllib.error.HTTPError) as ei:
                predict({"features": x})                      # inv 2: boom
            assert ei.value.code == 500
            assert "FaultInjected" in json.loads(
                ei.value.read())["error"]
            assert "predictions" in predict({"features": x})  # recovered
        # a shed request is a 429 + Retry-After, NOT the 5xx family —
        # and /health stays 200 (503 is reserved for real degradation)
        with pytest.raises(urllib.error.HTTPError) as ei:
            predict({"features": x, "deadline_ms": 0})
        assert ei.value.code == 429
        body = json.loads(ei.value.read())
        assert body["shed"] is True and body["stage"] == "admission"
        # Retry-After must be RFC 9110 integer delta-seconds or
        # conforming clients silently drop the hint
        assert int(ei.value.headers["Retry-After"]) >= 1
        h = json.loads(urllib.request.urlopen(
            f"{base}/health", timeout=15).read())
        assert h["status"] == "ok"
        # the header spelling of the same budget — deliberately NOT the
        # canonical casing (urllib sends this as "X-deadline-ms"):
        # header names compare case-insensitively, as any HTTP/2 proxy
        # that lowercases them requires
        req = urllib.request.Request(
            f"{base}/predict", data=json.dumps({"features": x}).encode(),
            headers={"x-deadline-ms": "0"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=15)
        assert ei.value.code == 429
        # a NaN budget is MALFORMED input (every deadline comparison
        # would be False: admitted, then unconditionally shed with a
        # misleading 429) — it must 400 at validation instead.
        # json.dumps spells float('nan') as bare NaN, which the server's
        # json.loads accepts — exactly the hostile payload
        for payload in ({"features": x, "deadline_ms": float("nan")},
                        {"features": x, "deadline_ms": float("inf")}):
            with pytest.raises(urllib.error.HTTPError) as ei:
                predict(payload)
            assert ei.value.code == 400
            assert "finite" in json.loads(ei.value.read())["error"]
        # metrics surface the shed accounting on the same scrape
        m = json.loads(urllib.request.urlopen(
            f"{base}/metrics", timeout=15).read())
        assert m["rejected"] >= 2
        assert m["admitted"] == m["completed"] + m["shed"] + m["failed"]
    finally:
        server.stop()
        server.inference.shutdown()


def test_paramserver_retry_deadline_cap():
    """A caller deadline caps TOTAL retry spend: against a dead endpoint
    the pull surfaces the failure while the budget can still pay for a
    fallback, instead of burning minutes of exponential backoff
    (max_retries=50 would otherwise sleep for ~2**50 * 50ms)."""
    from deeplearning4j_tpu.parallel.paramserver import EmbeddingPSClient

    client = EmbeddingPSClient(["http://127.0.0.1:1"], max_retries=50,
                               retry_backoff=0.05)
    plan = fp.FaultPlan(seed=9).add("paramserver_rpc", "error",
                                    every_nth=1)
    try:
        with fp.active(plan):
            t0 = time.monotonic()
            with pytest.raises(fp.FaultInjected):
                client.pull("emb", np.array([0, 1]), deadline_ms=120.0)
            elapsed = time.monotonic() - t0
        # the budget, plus one jittered backoff of slack — nowhere near
        # the 50-retry exponential schedule
        assert elapsed < 1.0, f"deadline cap ignored ({elapsed:.2f}s)"
        assert plan.invocations()["paramserver_rpc"] >= 2  # it DID retry
    finally:
        client.close()


def test_cli_chaos_replay_and_verdict(tmp_path):
    """`cli chaos` replays a plan outside pytest: same plan file, two
    runs, identical canonical event logs — and the ok verdict (exit 0)
    means recovered-or-cleanly-failed with the books balanced."""
    from deeplearning4j_tpu.cli import main as cli_main

    plan_file = tmp_path / "plan.json"
    plan_file.write_text(fp.FaultPlan(seed=21).add(
        "replica_forward", "error", every_nth=4).to_json())
    reports = []
    for name in ("r1.json", "r2.json"):
        out = tmp_path / name
        # one client => sequential forwards => the invocation sequence
        # (and so the event log) is identical across runs
        rc = cli_main(["chaos", "--preset", "serving",
                       "--plan", str(plan_file), "--requests", "12",
                       "--clients", "1", "--json", str(out)])
        assert rc == 0
        reports.append(json.loads(out.read_text()))
    assert reports[0]["events"] == reports[1]["events"]
    assert [e["invocation"] for e in reports[0]["events"]] == [4, 8, 12]
    assert reports[0]["verdict"] == "ok"
    assert reports[0]["conservation_ok"] is True
    assert reports[0]["outcome"] == "recovered"


# -- randomized-but-seeded chaos sweeps (slow) --------------------------------


def _chaos_serving_plan(seed):
    return (fp.FaultPlan(seed=seed)
            .add("replica_forward", "error", p=0.08)
            .add("replica_forward", "latency", p=0.25, latency_ms=15.0))


@pytest.mark.slow
def test_chaos_serving_sweep_invariants():
    """Concurrent clients under seeded random faults: every run must end
    with the books balanced, every client terminated (no wedge), and the
    watchdog quiet — 'recovered or cleanly failed, never wedged'."""
    for seed in (11, 23, 47):
        net = _net()
        plan = _chaos_serving_plan(seed)
        pi = ParallelInference(net, max_batch_size=4, batch_timeout_ms=2.0,
                               queue_capacity=64, health_stall_after=20.0,
                               component_prefix=f"chaos_sw{seed}")
        counts = {"ok": 0, "fault": 0, "shed": 0}
        lock = threading.Lock()

        def client(i):
            for j in range(10):
                try:
                    pi.output(_x(rows=1 + (i + j) % 4, seed=i * 100 + j),
                              deadline_ms=2000.0)
                    k = "ok"
                except fp.FaultInjected:
                    k = "fault"
                except (DeadlineExceeded, RequestRejected):
                    k = "shed"
                with lock:
                    counts[k] += 1

        try:
            pi.warmup((N_IN,))
            with fp.active(plan):
                threads = [threading.Thread(target=client, args=(i,),
                                            daemon=True,
                                            name=f"dl4j-test-sw{i}")
                           for i in range(6)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=120.0)
                    assert not t.is_alive(), "client wedged"
            m = _conserved(pi.metrics())
            assert counts["ok"] + counts["fault"] + counts["shed"] == 60
            assert counts["fault"] > 0, "p=0.08 over 60 fired nothing"
            assert plan.event_log()  # the injections are on the record
            comps = _health.get_health().status()["components"]
            for name, d in comps.items():
                if name.startswith(f"chaos_sw{seed}"):
                    assert d["status"] == "ok", (name, d)
        finally:
            pi.shutdown()


@pytest.mark.slow
def test_overload_sheds_instead_of_queueing():
    """The acceptance criterion: at ~2× sustained capacity the server
    sheds (429-path) instead of queueing unboundedly — queue depth stays
    bounded, ADMITTED requests still meet their SLO at p99, the
    conservation law holds exactly, and the watchdog never opens a
    stall."""
    net = _net()

    class Slow:
        """Fixed ~15ms forward: capacity ≈ max_batch/0.015 examples/s."""

        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, k):
            return getattr(self._inner, k)

        def output(self, x):
            time.sleep(0.015)
            return self._inner.output(x)

    slo_ms = 250.0
    pi = ParallelInference(Slow(net), max_batch_size=2,
                           batch_timeout_ms=1.0, queue_capacity=4,
                           handoff_capacity=1, default_deadline_ms=slo_ms,
                           health_stall_after=20.0,
                           component_prefix="chaos_ovl")
    stalls_before = _health.get_health().last_seq()
    lat_ok, shed = [], [0]
    lock = threading.Lock()
    stop = threading.Event()
    max_depth = [0]

    def client(i):
        # input built ONCE: the loop must spend its time in the server,
        # not in per-request rng construction — client-side CPU burn on
        # a small box stretches the very latencies the test measures
        x = _x(rows=1, seed=i)
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                pi.output(x)
                with lock:
                    lat_ok.append(time.monotonic() - t0)
            except (DeadlineExceeded, RequestRejected):
                with lock:
                    shed[0] += 1
                time.sleep(0.002)  # a real client would back off

    try:
        pi.warmup((N_IN,))
        # capacity ≈ 133 rows/s; the pipeline + queue absorb at most
        # ~8 outstanding 1-row requests (2 in forward, 2 in handoff,
        # 4 queued) — 16 closed-loop clients keep ≈ 2× that outstanding,
        # so admission must shed the excess for the books to balance
        threads = [threading.Thread(target=client, args=(i,), daemon=True,
                                    name=f"dl4j-test-ovl{i}")
                   for i in range(16)]
        for t in threads:
            t.start()
        t_end = time.monotonic() + 3.0
        while time.monotonic() < t_end:
            max_depth[0] = max(max_depth[0], pi._q.qsize())
            time.sleep(0.01)
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
            assert not t.is_alive(), "overload client wedged"
        m = _conserved(pi.metrics())
        total_shed = m["shed"] + m["rejected"]
        assert total_shed > 0, "2x overload shed nothing"
        assert m["completed"] > 50, "server served almost nothing"
        # bounded queue: depth never exceeded capacity
        assert max_depth[0] <= 4, max_depth[0]
        # overload turned into fast rejections, not universal lateness:
        # the TYPICAL admitted request clears well inside the SLO…
        lat_ok.sort()
        p50 = lat_ok[len(lat_ok) // 2]
        assert p50 <= slo_ms / 1e3, f"p50 {p50 * 1e3:.1f}ms"
        # …and the worst served request is hard-bounded by the wait
        # backstop (deadline + grace): a group can enter the forward
        # just under its deadline and stretch under GIL contention —
        # in-flight work is the one stage that cannot shed — but
        # nothing is EVER served past the backstop bound
        from deeplearning4j_tpu.parallel.inference import (
            _WAIT_SHED_GRACE,
        )

        p99 = lat_ok[min(len(lat_ok) - 1, int(0.99 * len(lat_ok)))]
        bound = slo_ms / 1e3 + _WAIT_SHED_GRACE + 0.15
        assert p99 <= bound, f"p99 {p99 * 1e3:.1f}ms > {bound * 1e3:.0f}ms"
        # the watchdog saw no stall on the serving components
        for tr in _health.get_health().transitions_since(stalls_before):
            assert not tr["component"].startswith("chaos_ovl"), tr
    finally:
        stop.set()
        pi.shutdown()
