"""MultiLayerNetwork — the sequential network.

Analog of the reference's nn/multilayer/MultiLayerNetwork.java (2,853 LoC).
The capability map (SURVEY.md §3.1) translates TPU-first:

- reference: per-minibatch Solver.optimize -> feedForward (per-layer JNI
  ops) -> backprop (hand-written) -> updater -> step.
- here: ONE jitted train step = forward + loss + autodiff backward +
  gradient normalization + updater + parameter update, compiled by XLA into
  a single TPU program with donated buffers. Host code only feeds batches
  and reads back the score when a listener asks.

Parameters are a list of per-layer dicts (pytree); the flattened view
(reference: flattenedParams, MultiLayerNetwork.java:102-104) is provided by
nn/params.py for serialization/averaging APIs. Mutable non-trainable state
(batchnorm running stats; LSTM h/c during TBPTT and rnnTimeStep streaming)
is a parallel list, threaded functionally through the step.

TBPTT (reference: :1074-1076, truncatedBPTTGradient :1333) segments the
time axis host-side and carries RNN state between segment steps.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common.dtypes import policy_from_name
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator,
    DataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.network import BackpropType, MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers.registry import (
    LayerContext,
    forward_layer,
    init_layer_params,
    init_layer_state,
)
from deeplearning4j_tpu.nn.params import (
    flat_to_params,
    num_params,
    param_table,
    params_to_flat,
)
from deeplearning4j_tpu.ops.losses import loss_value
from deeplearning4j_tpu.train.evaluation import Evaluation, RegressionEvaluation
from deeplearning4j_tpu.train.updaters import (
    normalize_gradients,
    schedule_lr,
    updater_from_conf,
)

logger = logging.getLogger("deeplearning4j_tpu")

_OUTPUT_LAYER_TYPES = (L.OutputLayer, L.RnnOutputLayer, L.LossLayer,
                       L.CenterLossOutputLayer)


def _is_recurrent(conf) -> bool:
    inner = conf.inner if isinstance(conf, L.FrozenLayer) else conf
    return isinstance(inner, (L.LSTM, L.GravesLSTM))


def _is_frozen(conf) -> bool:
    return isinstance(conf, L.FrozenLayer)


def _regularizable(name: str) -> bool:
    """Weight-style params get l1/l2; biases and batchnorm affine params do
    not (reference: each ParamInitializer flags regularizable params;
    BatchNormalizationParamInitializer marks gamma/beta non-regularizable)."""
    if name in ("gamma", "beta"):
        return False
    base = name.rsplit("_", 1)[-1]
    return base in ("W", "RW", "pI", "pF", "pO")


def _preout_of_output_layer(conf, params, x):
    """Pre-activation of the final (output) layer — the quantity losses
    consume (reference: BaseOutputLayer.preOutput2d)."""
    if isinstance(conf, L.LossLayer):
        return x
    if isinstance(conf, L.RnnOutputLayer):
        return jnp.einsum("bti,io->bto", x, params["W"]) + params["b"]
    return x @ params["W"] + params["b"]


class MultiLayerNetwork:
    """Sequential network. API mirrors the reference: init, fit, output,
    score, evaluate, params/set_params, rnn_time_step."""

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layer_confs: List[L.LayerConf] = list(conf.layers)
        self.net_conf = conf.net_conf
        self.policy = policy_from_name(self.net_conf.precision)
        self.updater_def = updater_from_conf(self.net_conf)
        self.listeners = []
        self.iteration = 0
        self.epoch = 0
        self.params_list = None
        self.state_list = None
        self.upd_state = None
        self._rnn_states = None  # streaming inference state (rnn_time_step)
        self._train_step_fn = None
        self._output_fn = None
        self._score = None  # last minibatch score (device array, lazy read)
        self._last_etl_ms = 0.0
        # hook applied to each DataSet before the step — installed by
        # parallel.ParallelWrapper to shard the batch across the mesh
        self._batch_transform = None

    # -- init ----------------------------------------------------------------

    def init(self) -> "MultiLayerNetwork":
        key = jax.random.PRNGKey(self.net_conf.seed)
        dtype = self.policy.param_dtype
        self.params_list = []
        self.state_list = []
        for i, conf in enumerate(self.layer_confs):
            self.params_list.append(
                init_layer_params(jax.random.fold_in(key, i), conf, dtype)
            )
            self.state_list.append(init_layer_state(conf, dtype))
        self.upd_state = self.updater_def.init_tree(self.params_list)
        return self

    def _require_init(self):
        if self.params_list is None:
            self.init()

    # -- listeners -----------------------------------------------------------

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listener(self, listener):
        self.listeners.append(listener)
        return self

    # -- forward -------------------------------------------------------------

    def _forward(self, params, states, x, *, training, rng, f_mask=None,
                 stateful=False, preout_last=False, to_layer=None):
        """Pure forward. Returns (out, new_states). Used under jit."""
        confs = self.layer_confs
        pps = self.conf.preprocessors
        new_states: List[Optional[dict]] = [None] * len(confs)
        timesteps = x.shape[1] if x.ndim == 3 else None
        n = len(confs) if to_layer is None else to_layer
        for i in range(n):
            conf = confs[i]
            pp = pps.get(str(i))
            if pp is not None:
                x = pp(x, {"timesteps": timesteps})
            if hasattr(x, "ndim") and x.ndim == 3:
                timesteps = x.shape[1]
            st = states[i]
            if stateful and _is_recurrent(conf) and st is None:
                st = {}  # empty dict triggers zero-state seed + state return
            ctx = LayerContext(
                training=training,
                rng=jax.random.fold_in(rng, i) if rng is not None else None,
                mask=f_mask if (hasattr(x, "ndim") and x.ndim == 3) else None,
                timesteps=timesteps,
                state=st,
            )
            is_last = i == len(confs) - 1
            if preout_last and is_last and isinstance(conf, _OUTPUT_LAYER_TYPES):
                x = _preout_of_output_layer(conf, params[i], x)
                ns = None
            else:
                x, ns = forward_layer(conf, params[i], x, ctx)
            new_states[i] = ns
        return x, new_states

    def _merge_states(self, old, new):
        return [n if n is not None else o for o, n in zip(old, new)]

    # -- loss ----------------------------------------------------------------

    def _loss(self, params, states, x, y, f_mask, l_mask, rng, training=True):
        last = self.layer_confs[-1]
        if not isinstance(last, _OUTPUT_LAYER_TYPES):
            raise ValueError(
                "the final layer must be an OutputLayer/RnnOutputLayer/"
                "LossLayer to compute a training loss"
            )
        x = self.policy.cast_input(x)
        preout, new_states = self._forward(
            params, states, x, training=training, rng=rng, f_mask=f_mask,
            preout_last=True,
        )
        preout = self.policy.cast_output(preout)
        per_ex = loss_value(last.loss, y, preout, last.activation, l_mask)
        score = jnp.mean(per_ex)
        # L1/L2 penalties (reference: BaseLayer.calcL1/calcL2 added to score;
        # gradients come from differentiating this same expression)
        reg = 0.0
        for conf, p in zip(self.layer_confs, params):
            inner = conf.inner if isinstance(conf, L.FrozenLayer) else conf
            l1 = getattr(inner, "l1", 0.0) or 0.0
            l2 = getattr(inner, "l2", 0.0) or 0.0
            if l1 == 0.0 and l2 == 0.0:
                continue
            for name, w in p.items():
                if _regularizable(name):
                    if l1:
                        reg = reg + l1 * jnp.sum(jnp.abs(w))
                    if l2:
                        reg = reg + 0.5 * l2 * jnp.sum(w * w)
        return score + reg, new_states

    # -- train step ----------------------------------------------------------

    def _lr_mult_tree(self):
        """Per-leaf learning-rate multiplier (per-layer learning_rate and
        bias_learning_rate overrides, reference: layer conf learningRate)."""
        base = self.net_conf.learning_rate
        out = []
        for conf, p in zip(self.layer_confs, self.params_list):
            inner = conf.inner if isinstance(conf, L.FrozenLayer) else conf
            layer_lr = getattr(inner, "learning_rate", None)
            bias_lr = getattr(inner, "bias_learning_rate", None)
            mult = {}
            for name in p:
                if name == "b" and bias_lr is not None:
                    mult[name] = bias_lr / base
                elif layer_lr is not None:
                    mult[name] = layer_lr / base
                else:
                    mult[name] = 1.0
            out.append(mult)
        return out

    def _trainable_mask(self):
        return [
            {k: (0.0 if _is_frozen(conf) else 1.0) for k in p}
            for conf, p in zip(self.layer_confs, self.params_list)
        ]

    def _build_train_step(self):
        gnorm = self.net_conf.gradient_normalization
        gthresh = self.net_conf.gradient_normalization_threshold
        mults = self._lr_mult_tree()
        tmask = self._trainable_mask()
        updater = self.updater_def
        minimize = self.net_conf.minimize

        def step(params, states, upd_state, x, y, f_mask, l_mask, lr, t, rng):
            def loss_fn(p):
                return self._loss(p, states, x, y, f_mask, l_mask, rng)

            (score, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            if not minimize:
                grads = jax.tree_util.tree_map(lambda g: -g, grads)
            grads = [
                {k: g[k] * m[k] for k in g} for g, m in zip(grads, tmask)
            ]
            grads = normalize_gradients(grads, gnorm, gthresh)
            lr_tree = [
                {k: lr * m[k] for k in g} for g, m in zip(grads, mults)
            ]
            updates, new_upd = updater.apply_tree(grads, upd_state, lr_tree, t)
            new_params = jax.tree_util.tree_map(jnp.add, params, updates)
            merged = self._merge_states(states, new_states)
            return new_params, merged, new_upd, score

        backend = jax.default_backend()
        donate = (0, 2) if backend != "cpu" else ()
        return jax.jit(step, donate_argnums=donate)

    def _fit_step(self, x, y, f_mask, l_mask, stateful_states=None):
        """One optimizer step. Returns the (device) score."""
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
        lr = schedule_lr(self.net_conf, self.iteration)
        rng = jax.random.fold_in(
            jax.random.PRNGKey(self.net_conf.seed ^ 0x5EED), self.iteration
        )
        states = stateful_states if stateful_states is not None else self.state_list
        params, states, upd, score = self._train_step_fn(
            self.params_list, states, self.upd_state,
            jnp.asarray(x), jnp.asarray(y),
            None if f_mask is None else jnp.asarray(f_mask),
            None if l_mask is None else jnp.asarray(l_mask),
            jnp.asarray(lr, jnp.float32), jnp.asarray(float(self.iteration)),
            rng,
        )
        self.params_list = params
        self.upd_state = upd
        self._score = score
        self.iteration += 1
        return states, score

    # -- fit -----------------------------------------------------------------

    def fit(self, data, labels=None, *, epochs: int = 1, batch_size: int = 32,
            async_prefetch: bool = True):
        """Train. Accepts (features, labels) arrays, a DataSet, or a
        DataSetIterator (reference: MultiLayerNetwork.fit overloads
        :1019)."""
        self._require_init()
        iterator = self._as_iterator(data, labels, batch_size)
        if async_prefetch and not isinstance(iterator, AsyncDataSetIterator):
            iterator = AsyncDataSetIterator(iterator)
        for ep in range(epochs):
            for lst in self.listeners:
                lst.on_epoch_start(self, self.epoch)
            t_etl = time.perf_counter()
            for ds in iterator:
                self._last_etl_ms = (time.perf_counter() - t_etl) * 1e3
                self._fit_dataset(ds)
                t_etl = time.perf_counter()
            for lst in self.listeners:
                lst.on_epoch_end(self, self.epoch)
            self.epoch += 1
            iterator.reset()
        return self

    def _as_iterator(self, data, labels, batch_size) -> DataSetIterator:
        if isinstance(data, DataSetIterator):
            return data
        if isinstance(data, DataSet):
            return ListDataSetIterator(data, batch_size)
        x = np.asarray(data)
        y = np.asarray(labels)
        return ListDataSetIterator(DataSet(x, y), batch_size)

    def _fit_dataset(self, ds: DataSet):
        if self._batch_transform is not None:
            ds = self._batch_transform(ds)
        tbptt = (
            self.conf.backprop_type == BackpropType.TRUNCATED_BPTT
            and ds.features.ndim == 3
        )
        if tbptt:
            self._fit_tbptt(ds)
        else:
            states, score = self._fit_step(
                ds.features, ds.labels, ds.features_mask, ds.labels_mask
            )
            self.state_list = states
            self._notify(ds.num_examples())

    def _fit_tbptt(self, ds: DataSet):
        """Truncated BPTT: split time into segments of tbptt_fwd_length and
        carry RNN state across segments (reference:
        MultiLayerNetwork.doTruncatedBPTT :1333)."""
        T = ds.features.shape[1]
        seg = int(self.conf.tbptt_fwd_length)
        # seed zero RNN state for recurrent layers
        states = list(self.state_list)
        for i, conf in enumerate(self.layer_confs):
            if _is_recurrent(conf) and states[i] is None:
                states[i] = {}
        for start in range(0, T, seg):
            sl = slice(start, min(start + seg, T))
            fm = None if ds.features_mask is None else ds.features_mask[:, sl]
            lm = None if ds.labels_mask is None else ds.labels_mask[:, sl]
            labels = ds.labels[:, sl] if ds.labels.ndim == 3 else ds.labels
            states, _ = self._fit_step(
                ds.features[:, sl], labels, fm, lm, stateful_states=states
            )
            self._notify(ds.num_examples())
        # persist only non-RNN state (running stats); RNN carry is per-batch
        self.state_list = [
            st if not _is_recurrent(conf) else self.state_list[i]
            for i, (conf, st) in enumerate(zip(self.layer_confs, states))
        ]

    def _notify(self, batch_size):
        if not self.listeners:
            return
        info = {
            "score": lambda: self._score,
            "batch_size": batch_size,
            "etl_ms": self._last_etl_ms,
        }
        for lst in self.listeners:
            lst.iteration_done(self, self.iteration - 1, info)

    # -- inference -----------------------------------------------------------

    def output(self, x, training: bool = False):
        """Full forward pass (reference: MultiLayerNetwork.output)."""
        self._require_init()
        if self._output_fn is None:
            def fwd(params, states, xx):
                xx = self.policy.cast_input(xx)
                out, _ = self._forward(params, states, xx, training=False, rng=None)
                return self.policy.cast_output(out)

            self._output_fn = jax.jit(fwd)
        return self._output_fn(self.params_list, self.state_list, jnp.asarray(x))

    def feed_forward(self, x):
        """Per-layer activations list (reference: feedForward family
        :725-831). Not jitted — debugging/inspection path."""
        self._require_init()
        acts = []
        xx = jnp.asarray(x)
        timesteps = xx.shape[1] if xx.ndim == 3 else None
        for i, conf in enumerate(self.layer_confs):
            pp = self.conf.preprocessors.get(str(i))
            if pp is not None:
                xx = pp(xx, {"timesteps": timesteps})
            if xx.ndim == 3:
                timesteps = xx.shape[1]
            ctx = LayerContext(training=False, state=self.state_list[i],
                               timesteps=timesteps)
            xx, _ = forward_layer(conf, self.params_list[i], xx, ctx)
            acts.append(xx)
        return acts

    def score(self, data, labels=None) -> float:
        """Loss on a dataset without updating (reference:
        MultiLayerNetwork.score(DataSet))."""
        self._require_init()
        if isinstance(data, DataSet):
            ds = data
        else:
            ds = DataSet(np.asarray(data), np.asarray(labels))
        s, _ = self._loss(
            self.params_list, self.state_list,
            jnp.asarray(ds.features), jnp.asarray(ds.labels),
            None if ds.features_mask is None else jnp.asarray(ds.features_mask),
            None if ds.labels_mask is None else jnp.asarray(ds.labels_mask),
            rng=None, training=False,
        )
        return float(s)

    def evaluate(self, data, labels=None, batch_size: int = 256) -> Evaluation:
        """Classification evaluation (reference: evaluate/doEvaluation
        :2605-2646)."""
        ev = Evaluation()
        for ds in self._eval_batches(data, labels, batch_size):
            out = self.output(ds.features)
            ev.eval_batch(ds.labels, out, ds.labels_mask)
        return ev

    def evaluate_regression(self, data, labels=None, batch_size: int = 256):
        ev = RegressionEvaluation()
        for ds in self._eval_batches(data, labels, batch_size):
            out = self.output(ds.features)
            ev.eval_batch(ds.labels, out, ds.labels_mask)
        return ev

    def _eval_batches(self, data, labels, batch_size):
        if isinstance(data, DataSetIterator):
            yield from data
        elif isinstance(data, DataSet):
            yield from data.split_batches(batch_size)
        else:
            yield from DataSet(np.asarray(data), np.asarray(labels)).split_batches(batch_size)

    # -- rnn streaming inference ---------------------------------------------

    def rnn_time_step(self, x):
        """Stateful streaming inference (reference:
        MultiLayerNetwork.rnnTimeStep). x: [batch, time, nIn] (or
        [batch, nIn] for a single step)."""
        self._require_init()
        xx = jnp.asarray(x)
        single = xx.ndim == 2
        if single:
            xx = xx[:, None, :]
        states = self._rnn_states
        if states is None:
            states = [
                {} if _is_recurrent(c) else self.state_list[i]
                for i, c in enumerate(self.layer_confs)
            ]
        out, new_states = self._forward(
            self.params_list, states, self.policy.cast_input(xx),
            training=False, rng=None, stateful=True,
        )
        self._rnn_states = self._merge_states(states, new_states)
        out = self.policy.cast_output(out)
        return out[:, 0] if single else out

    def rnn_clear_previous_state(self):
        self._rnn_states = None

    # -- params API ----------------------------------------------------------

    def params(self) -> jnp.ndarray:
        """Flattened parameter vector (reference: Model.params())."""
        self._require_init()
        return params_to_flat(self.layer_confs, self.params_list)

    def set_params(self, flat):
        self._require_init()
        self.params_list = flat_to_params(self.layer_confs, self.params_list, flat)

    def num_params(self) -> int:
        self._require_init()
        return num_params(self.layer_confs, self.params_list)

    def param_table(self):
        self._require_init()
        return param_table(self.layer_confs, self.params_list)

    def summary(self) -> str:
        self._require_init()
        lines = ["=" * 70]
        total = 0
        for i, (conf, p) in enumerate(zip(self.layer_confs, self.params_list)):
            n = sum(int(np.prod(v.shape)) for v in p.values())
            total += n
            lines.append(f"{i:>3}  {type(conf).__name__:<28} params: {n}")
        lines.append(f"total params: {total}")
        lines.append("=" * 70)
        return "\n".join(lines)

    def clone(self) -> "MultiLayerNetwork":
        import copy

        other = MultiLayerNetwork(copy.deepcopy(self.conf))
        if self.params_list is not None:
            other.init()
            other.params_list = jax.tree_util.tree_map(lambda a: a, self.params_list)
            other.state_list = [
                None if s is None else dict(s) for s in self.state_list
            ]
        return other
