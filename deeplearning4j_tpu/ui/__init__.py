"""Observability pipeline (reference: deeplearning4j-ui-parent, ~30k LoC).

Capability map:
- StatsListener (ui/stats.py)       <- BaseStatsListener.java:51,103-124
- storage SPI + impls (ui/storage.py) <- api/storage/StatsStorage.java:
  InMemoryStatsStorage / FileStatsStorage (append-only log) /
  SqliteStatsStorage (indexed durable store — the MapDBStatsStorage /
  J7FileStatsStorage analog)
- compact wire codec (ui/codec.py)  <- SBE-generated codecs (ui/stats/sbe/)
- dashboard server (ui/server.py)   <- PlayUIServer + TrainModule routes
  (/train/overview, /train/model, /train/flow, /train/system) +
  RemoteReceiverModule
- report DSL (ui/components.py)     <- deeplearning4j-ui-components'
  chart/table/text Component JSON + standalone rendering
- standalone report (ui/report.py)  <- ui-components report path + the
  FlowListenerModule layer-graph view, server-free HTML artifact
"""

from deeplearning4j_tpu.ui.stats import (ConvolutionalIterationListener,
    StatsListener)
from deeplearning4j_tpu.ui.storage import (
    FileStatsStorage,
    InMemoryStatsStorage,
    RemoteUIStatsStorageRouter,
    SqliteStatsStorage,
    StatsStorage,
)
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartLine,
    ChartScatter,
    Component,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    render_page,
)
from deeplearning4j_tpu.ui.report import (
    FlowGraph,
    render_training_report,
    write_training_report,
)

__all__ = [
    "ConvolutionalIterationListener",
    "StatsListener",
    "StatsStorage",
    "InMemoryStatsStorage",
    "FileStatsStorage",
    "SqliteStatsStorage",
    "RemoteUIStatsStorageRouter",
    "UIServer",
    "Component",
    "ComponentText",
    "ComponentTable",
    "ComponentDiv",
    "ChartLine",
    "ChartHistogram",
    "ChartScatter",
    "FlowGraph",
    "render_page",
    "render_training_report",
    "write_training_report",
]
