"""Special layers: variational autoencoder, frozen-layer wrapper.

Reference impls: nn/layers/variational/VariationalAutoencoder.java (1,120
LoC — internal encoder/decoder MLP, ELBO objective, pluggable
reconstruction distributions) and nn/layers/FrozenLayer.java.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.registry import (
    LayerContext,
    forward_layer,
    init_layer_params,
    init_layer_state,
    param_order,
    register_layer,
)
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import apply_activation


# -- variational autoencoder -------------------------------------------------

def _mlp_params(key, sizes, conf, dtype, prefix):
    params = {}
    for i in range(len(sizes) - 1):
        key, k = jax.random.split(key)
        n_in, n_out = int(sizes[i]), int(sizes[i + 1])
        params[f"{prefix}{i}_W"] = init_weights(
            k, (n_in, n_out), n_in, n_out, conf.weight_init, conf.dist, dtype
        )
        params[f"{prefix}{i}_b"] = jnp.zeros((n_out,), dtype)
    return params


def vae_init(key, conf: L.VariationalAutoencoder, dtype):
    n_in, n_z = int(conf.n_in), int(conf.n_out)
    enc = [n_in] + list(conf.encoder_layer_sizes)
    dec = [n_z] + list(conf.decoder_layer_sizes)
    k1, k2, k3, k4, k5, k6 = jax.random.split(key, 6)
    params = {}
    params.update(_mlp_params(k1, enc, conf, dtype, "enc_"))
    last_e = int(enc[-1])
    params["pzx_mean_W"] = init_weights(k2, (last_e, n_z), last_e, n_z,
                                        conf.weight_init, conf.dist, dtype)
    params["pzx_mean_b"] = jnp.zeros((n_z,), dtype)
    params["pzx_logstd_W"] = init_weights(k3, (last_e, n_z), last_e, n_z,
                                          conf.weight_init, conf.dist, dtype)
    params["pzx_logstd_b"] = jnp.zeros((n_z,), dtype)
    params.update(_mlp_params(k4, dec, conf, dtype, "dec_"))
    last_d = int(dec[-1])
    # reconstruction distribution parameters: gaussian needs mean+logstd
    # (2*n_in outputs), bernoulli needs n_in probabilities
    dist = (conf.reconstruction_distribution or {"type": "bernoulli"})
    out_mult = 2 if dist.get("type", "bernoulli") == "gaussian" else 1
    params["pxz_W"] = init_weights(k5, (last_d, out_mult * n_in), last_d, n_in,
                                   conf.weight_init, conf.dist, dtype)
    params["pxz_b"] = jnp.zeros((out_mult * n_in,), dtype)
    return params


def _vae_encode(conf, params, x):
    h = x
    for i in range(len(conf.encoder_layer_sizes)):
        h = apply_activation(conf.activation, h @ params[f"enc_{i}_W"] + params[f"enc_{i}_b"])
    mean = apply_activation(conf.pzx_activation,
                            h @ params["pzx_mean_W"] + params["pzx_mean_b"])
    log_std = h @ params["pzx_logstd_W"] + params["pzx_logstd_b"]
    return mean, log_std


def _vae_decode(conf, params, z):
    h = z
    for i in range(len(conf.decoder_layer_sizes)):
        h = apply_activation(conf.activation, h @ params[f"dec_{i}_W"] + params[f"dec_{i}_b"])
    return h @ params["pxz_W"] + params["pxz_b"]


def vae_forward(conf: L.VariationalAutoencoder, params, x, ctx: LayerContext):
    """Supervised path: the layer's activation is the mean of p(z|x)
    (reference: VariationalAutoencoder.activate returns the pzxMean)."""
    mean, _ = _vae_encode(conf, params, x)
    return mean, None


def vae_elbo(conf: L.VariationalAutoencoder, params, x, rng, training=True):
    """Negative ELBO per example (the unsupervised pretraining objective;
    reference: VariationalAutoencoder.computeGradientAndScore). Monte-Carlo
    with conf.num_samples samples via the reparameterization trick."""
    mean, log_std = _vae_encode(conf, params, x)
    # KL(q(z|x) || N(0,I)), analytic
    var = jnp.exp(2.0 * log_std)
    kl = 0.5 * jnp.sum(mean * mean + var - 2.0 * log_std - 1.0, axis=-1)
    dist = (conf.reconstruction_distribution or {"type": "bernoulli"})
    kind = dist.get("type", "bernoulli")
    n_in = int(conf.n_in)

    recon = 0.0
    n_samples = int(conf.num_samples) if training else 1
    for s in range(n_samples):
        rng, k = jax.random.split(rng)
        eps = jax.random.normal(k, mean.shape, mean.dtype)
        z = mean + jnp.exp(log_std) * eps
        out = _vae_decode(conf, params, z)
        if kind == "gaussian":
            act = dist.get("activation", "identity")
            r_mean = apply_activation(act, out[:, :n_in])
            r_logstd = out[:, n_in:]
            # -log N(x; r_mean, exp(r_logstd)^2)
            nll = 0.5 * jnp.sum(
                ((x - r_mean) ** 2) * jnp.exp(-2.0 * r_logstd)
                + 2.0 * r_logstd + math.log(2.0 * math.pi),
                axis=-1,
            )
        elif kind == "exponential":
            # reference: ExponentialReconstructionDistribution — the
            # activation of the decoder preout gives log(lambda);
            # -log p(x) = -log(lambda) + lambda*x
            log_lambda = apply_activation(
                dist.get("activation", "identity"), out)
            nll = jnp.sum(-log_lambda + jnp.exp(log_lambda) * x, axis=-1)
        elif kind == "loss_wrapper":
            # reference: LossFunctionWrapper — any ILossFunction as the
            # reconstruction objective (per-example value)
            from deeplearning4j_tpu.ops.losses import loss_value

            nll = loss_value(dist.get("loss", "mse"), x, out,
                             dist.get("activation", "identity"), None)
        elif kind == "bernoulli":
            # stable from logits
            nll = jnp.sum(
                x * jax.nn.softplus(-out) + (1.0 - x) * jax.nn.softplus(out), axis=-1
            )
        else:
            raise ValueError(
                f"unknown reconstruction distribution {kind!r} "
                "(gaussian | bernoulli | exponential | loss_wrapper)")
        recon = recon + nll
    recon = recon / n_samples
    return recon + kl


def vae_order(conf: L.VariationalAutoencoder):
    names = []
    for i in range(len(conf.encoder_layer_sizes)):
        names += [f"enc_{i}_W", f"enc_{i}_b"]
    names += ["pzx_mean_W", "pzx_mean_b", "pzx_logstd_W", "pzx_logstd_b"]
    for i in range(len(conf.decoder_layer_sizes)):
        names += [f"dec_{i}_W", f"dec_{i}_b"]
    names += ["pxz_W", "pxz_b"]
    return tuple(names)


register_layer(L.VariationalAutoencoder, vae_init, vae_forward, order_fn=vae_order)


# -- frozen wrapper ----------------------------------------------------------

def frozen_init(key, conf: L.FrozenLayer, dtype):
    return init_layer_params(key, conf.inner, dtype)


def frozen_state(conf: L.FrozenLayer, dtype):
    return init_layer_state(conf.inner, dtype)


def frozen_forward(conf: L.FrozenLayer, params, x, ctx: LayerContext):
    """Delegates to the inner layer in inference mode (no dropout; frozen
    BN uses running stats) — reference: FrozenLayer applies the layer as in
    test time. Gradient zeroing happens in the updater via trainable masks."""
    inner_ctx = LayerContext(training=False, rng=ctx.rng, mask=ctx.mask,
                             timesteps=ctx.timesteps, state=ctx.state)
    y, _ = forward_layer(conf.inner, params, x, inner_ctx)
    return y, None


register_layer(L.FrozenLayer, frozen_init, frozen_forward,
               order_fn=lambda c: param_order(c.inner), state_fn=frozen_state)
