"""DL4J model-zip import (modelimport/dl4j.py).

Round-trip strategy (the reference's own regressiontest/ approach needs
release-era zip artifacts; none ship in-tree): export writes the exact
reference layouts — f-order flat views per nn/params/*, IFOG gate order
with DL4J's candidate/input-gate block semantics, Graves peephole columns
— and import must reconstruct a network whose forward output matches the
original to float precision. A hand-built coefficients buffer additionally
pins the gate permutation itself (not just invertibility).
"""

import io
import numpy as np
import pytest

from deeplearning4j_tpu.modelimport.dl4j import (
    export_dl4j_zip,
    import_dl4j_multilayer,
    read_nd4j_array,
    write_nd4j_array,
    _perm_ifog,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    DenseLayer,
    GravesLSTM,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
    ConvolutionLayer,
)
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def test_nd4j_binary_round_trip():
    rng = np.random.default_rng(0)
    for arr in (rng.standard_normal(17).astype(np.float32),
                rng.standard_normal((3, 5)).astype(np.float64)):
        buf = io.BytesIO()
        write_nd4j_array(arr, buf)
        buf.seek(0)
        back = read_nd4j_array(buf)
        np.testing.assert_array_equal(back.reshape(-1), arr.reshape(-1))


def test_perm_ifog_blocks():
    """DL4J [I,F,O,G] -> framework [i,f,g,o] means [G,F,I,O]."""
    H = 2
    cols = np.array([[10, 11, 20, 21, 30, 31, 40, 41]], np.float32)
    out = _perm_ifog(cols, H)
    np.testing.assert_array_equal(
        out[0], [40, 41, 20, 21, 10, 11, 30, 31])


def _mlp_net(seed=5):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=9, activation="tanh"))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def test_mlp_zip_round_trip(tmp_path):
    net = _mlp_net()
    # give BN non-trivial running stats
    x = np.random.default_rng(0).standard_normal((32, 6)).astype(np.float32)
    y = np.zeros((32, 4), np.float32)
    y[np.arange(32), np.random.default_rng(1).integers(0, 4, 32)] = 1.0
    net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)

    path = str(tmp_path / "mlp.zip")
    export_dl4j_zip(net, path)
    back = import_dl4j_multilayer(path)
    np.testing.assert_allclose(
        np.asarray(back.output(x)), np.asarray(net.output(x)),
        rtol=1e-5, atol=1e-6)


def test_graves_lstm_zip_round_trip_golden_forward(tmp_path):
    """The headline case (VERDICT missing #6): gate permutation + peephole
    column mapping proven by forward equality on a Graves LSTM."""
    conf = (NeuralNetConfiguration.builder().seed(11)
            .weight_init("xavier").list()
            .layer(GravesLSTM(n_out=7, activation="tanh"))
            .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(5)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(2).standard_normal((4, 10, 5)).astype(np.float32)
    golden = np.asarray(net.output(x))

    path = str(tmp_path / "graves.zip")
    export_dl4j_zip(net, path)
    back = import_dl4j_multilayer(path)
    np.testing.assert_allclose(np.asarray(back.output(x)), golden,
                               rtol=1e-5, atol=1e-6)
    # peephole vectors landed in the right slots
    for k in ("pI", "pF", "pO"):
        np.testing.assert_allclose(np.asarray(back.params_list[0][k]),
                                   np.asarray(net.params_list[0][k]),
                                   rtol=1e-6)


def test_vanilla_lstm_zip_round_trip(tmp_path):
    conf = (NeuralNetConfiguration.builder().seed(3)
            .weight_init("xavier").list()
            .layer(LSTM(n_out=6, activation="tanh"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(4)).build())
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(4).standard_normal((3, 8, 4)).astype(np.float32)
    path = str(tmp_path / "lstm.zip")
    export_dl4j_zip(net, path)
    back = import_dl4j_multilayer(path)
    np.testing.assert_allclose(np.asarray(back.output(x)),
                               np.asarray(net.output(x)),
                               rtol=1e-5, atol=1e-6)


def test_length_mismatch_detected(tmp_path):
    net = _mlp_net()
    path = str(tmp_path / "bad.zip")
    export_dl4j_zip(net, path)
    import zipfile, json

    with zipfile.ZipFile(path) as zf:
        conf = zf.read("configuration.json")
        coeff = zf.read("coefficients.bin")
    # truncate the flat buffer: drop the final 4 bytes (one float)
    buf = io.BytesIO(coeff)
    arr = read_nd4j_array(buf)
    short = np.asarray(arr).reshape(-1)[:-1]
    out = io.BytesIO()
    write_nd4j_array(short, out)
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("configuration.json", conf)
        zf.writestr("coefficients.bin", out.getvalue())
    with pytest.raises(ValueError, match="too short|mismatch"):
        import_dl4j_multilayer(path)


# -- ComputationGraph zips ----------------------------------------------------

from deeplearning4j_tpu.modelimport.dl4j import (
    export_dl4j_graph,
    import_dl4j_computation_graph,
    _dl4j_topo_names,
)
from deeplearning4j_tpu.nn.compgraph import ComputationGraph
from deeplearning4j_tpu.nn.conf.graph import (
    ElementWiseVertex,
    MergeVertex,
)


def _graph_net(seed=11):
    """Diamond graph: dense branches -> merge, plus a residual elementwise
    add and a BN layer — exercises vertex mapping AND the topological flat
    walk (branch params interleave)."""
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .weight_init("xavier").graph_builder()
            .add_inputs("in")
            .add_layer("a", DenseLayer(n_in=8, n_out=6, activation="tanh"),
                       "in")
            .add_layer("b", DenseLayer(n_in=8, n_out=6, activation="relu"),
                       "in")
            .add_vertex("add", ElementWiseVertex(op="add"), "a", "b")
            .add_vertex("m", MergeVertex(), "a", "add")
            .add_layer("bn", BatchNormalization(n_in=12), "m")
            .add_layer("out", OutputLayer(n_in=12, n_out=3,
                                          activation="softmax",
                                          loss="mcxent"), "bn")
            .set_outputs("out").build())
    return ComputationGraph(conf).init()


def test_graph_zip_round_trip(tmp_path):
    net = _graph_net()
    x = np.random.default_rng(3).standard_normal((5, 8)).astype(np.float32)
    want = np.asarray(net.output(x))
    path = tmp_path / "graph.zip"
    export_dl4j_graph(net, str(path))
    back = import_dl4j_computation_graph(str(path))
    got = np.asarray(back.output(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_graph_import_frozen_reference_fixture():
    """Byte-frozen fixture zip in the exact Jackson shape (WRAPPER_OBJECT
    vertices, networkInputs/vertexInputs names, vertices deliberately
    listed OUT of topological order, Adam updaterState.bin) — the
    reference's regressiontest discipline (RegressionTest080.java loads
    release-era artifacts) rather than JSON built adjacent to the code
    under test. Regenerate ONLY with tests/fixtures/make_cg_fixture.py
    and only for deliberate format-version bumps."""
    import os as _os

    from deeplearning4j_tpu.modelimport.dl4j import updater_state_to_flat

    fixtures = _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                             "fixtures")
    path = _os.path.join(fixtures, "cg_adam_v1.zip")
    expected = np.load(_os.path.join(fixtures, "cg_adam_v1_expected.npz"))

    net = import_dl4j_computation_graph(path)
    np.testing.assert_allclose(np.asarray(net.output(expected["x"])),
                               expected["out"], rtol=1e-5, atol=1e-6)
    # resume state: iteration counter + the Adam [m|v] block view survive
    assert net.iteration == int(expected["iteration"])
    assert net.net_conf.updater == "adam"
    # flat-walk order: FIFO Kahn over JSON-order vertex numbers -> b, a, out
    np.testing.assert_allclose(
        updater_state_to_flat(
            net, indexed_layer_confs=[
                (net._pidx[n], net.conf.vertices[n].layer)
                for n in ("b", "a", "out")]),
        expected["updater_state"], atol=0, rtol=0)


def test_dl4j_topo_matches_reference_kahn():
    """FIFO Kahn with ascending-index tie-break: inputs first, then both
    ready children in vertex-number order, etc."""
    order = _dl4j_topo_names(
        ["in"], ["z", "a", "out"],
        {"z": ["in"], "a": ["in"], "out": ["z", "a"]})
    assert order == ["in", "z", "a", "out"]
    # diamond where JSON order disagrees with readiness
    order = _dl4j_topo_names(
        ["x"], ["c", "b"], {"c": ["b"], "b": ["x"]})
    assert order == ["x", "b", "c"]


def test_bn_lock_gamma_beta_import(tmp_path):
    """lockGammaBeta zips carry only mean/var (2*nOut floats); gamma/beta
    come from the conf constants (ADVICE r3 + reference
    BatchNormalizationParamInitializer)."""
    import io as _io
    import json as _json
    import zipfile as _zipfile
    from deeplearning4j_tpu.modelimport.dl4j import write_nd4j_array

    rng = np.random.default_rng(9)
    n = 4
    W = rng.standard_normal((n, 2)).astype(np.float32)
    b = rng.standard_normal(2).astype(np.float32)
    mean = rng.standard_normal(n).astype(np.float32)
    var = (rng.random(n).astype(np.float32) + 0.5)
    conf = {"confs": [
        {"layer": {"batchNormalization": {
            "nin": n, "nout": n, "eps": 1e-5, "decay": 0.9,
            "lockGammaBeta": True, "gamma": 2.0, "beta": 0.5}}},
        {"layer": {"output": {"nin": n, "nout": 2,
                              "activationFn": "softmax",
                              "lossFn": "mcxent"}}},
    ]}
    flat = np.concatenate([mean, var, W.reshape(-1, order="F"), b])
    buf = _io.BytesIO()
    write_nd4j_array(flat, buf)
    p = tmp_path / "bn_locked.zip"
    with _zipfile.ZipFile(p, "w") as zf:
        zf.writestr("configuration.json", _json.dumps(conf))
        zf.writestr("coefficients.bin", buf.getvalue())
    net = import_dl4j_multilayer(str(p))
    p0 = net.params_list[0]
    np.testing.assert_allclose(np.asarray(p0["gamma"]), np.full(n, 2.0))
    np.testing.assert_allclose(np.asarray(p0["beta"]), np.full(n, 0.5))
    st = net.state_list[0]
    np.testing.assert_allclose(np.asarray(st["mean"]), mean, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(st["var"]), var, rtol=1e-6)
    # and the forward APPLIES the locked constants (gamma*xhat + beta),
    # matching the reference's lockGammaBeta semantics
    x = rng.standard_normal((6, n)).astype(np.float32)
    xhat = (x - mean) / np.sqrt(var + 1e-5)
    logits = (2.0 * xhat + 0.5) @ W + b
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    want = e / e.sum(axis=1, keepdims=True)
    np.testing.assert_allclose(np.asarray(net.output(x)), want,
                               rtol=1e-4, atol=1e-5)
