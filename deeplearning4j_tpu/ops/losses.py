"""Loss functions.

Covers the reference's LossFunctions.LossFunction enum and ILossFunction SPI
(used throughout deeplearning4j-nn; the full implementation set is exercised
by LossFunctionGradientCheck.java). Signature follows the reference's
ILossFunction contract: a loss sees the layer's *pre-output* (logits) plus
the output activation, which lets us fuse softmax+cross-entropy into the
numerically stable log-softmax form — the TPU-friendly formulation — instead
of computing probabilities first the way the reference does.

All functions return a per-example score vector of shape [batch]; the
network averages over the batch (reference: BaseOutputLayer.computeScore
sums then divides by minibatch). Masks multiply per-element scores before
the feature-axis reduction (reference: LossUtil / masked score arrays).

Gradients are never hand-written: jax.grad differentiates through these.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.activations import apply_activation

_EPS = 1e-8

# name -> fn(labels, preout, activation, mask) -> per-example score [batch]
_REGISTRY: Dict[str, Callable] = {}


def register_loss(name: str, fn: Callable) -> None:
    """Custom-loss SPI (reference: ILossFunction implementations)."""
    _REGISTRY[name.lower()] = fn


def _reduce(per_elem, mask):
    """Apply an element mask then sum over all non-batch axes."""
    if mask is not None:
        # mask may be [batch], [batch, 1] or full element shape; broadcast.
        while mask.ndim < per_elem.ndim:
            mask = mask[..., None]
        per_elem = per_elem * mask
    axes = tuple(range(1, per_elem.ndim))
    return jnp.sum(per_elem, axis=axes) if axes else per_elem


def _out(preout, activation):
    return apply_activation(activation, preout)


def _loss(name):
    def deco(fn):
        register_loss(name, fn)
        return fn

    return deco


@_loss("mse")
def mse(labels, preout, activation, mask=None):
    out = _out(preout, activation)
    d = out - labels
    n = labels.shape[-1]
    return _reduce(d * d, mask) / n


@_loss("l2")
def l2(labels, preout, activation, mask=None):
    # Reference LossL2 = sum of squared errors (no 1/n)
    out = _out(preout, activation)
    d = out - labels
    return _reduce(d * d, mask)


@_loss("l1")
def l1(labels, preout, activation, mask=None):
    out = _out(preout, activation)
    return _reduce(jnp.abs(out - labels), mask)


@_loss("mean_absolute_error")
def mean_absolute_error(labels, preout, activation, mask=None):
    return l1(labels, preout, activation, mask) / labels.shape[-1]


@_loss("mean_absolute_percentage_error")
def mape(labels, preout, activation, mask=None):
    out = _out(preout, activation)
    per = jnp.abs((labels - out) / (labels + _EPS)) * 100.0
    return _reduce(per, mask) / labels.shape[-1]


@_loss("mean_squared_logarithmic_error")
def msle(labels, preout, activation, mask=None):
    out = _out(preout, activation)
    d = jnp.log1p(out) - jnp.log1p(labels)
    return _reduce(d * d, mask) / labels.shape[-1]


@_loss("xent")
def xent(labels, preout, activation, mask=None):
    """Binary cross-entropy. Stable path when activation is sigmoid:
    computed from logits directly."""
    if activation == "sigmoid":
        # log(sigmoid(z)) = -softplus(-z); log(1-sigmoid(z)) = -softplus(z)
        per = labels * jax.nn.softplus(-preout) + (1.0 - labels) * jax.nn.softplus(preout)
    else:
        out = _out(preout, activation)
        out = jnp.clip(out, _EPS, 1.0 - _EPS)
        per = -(labels * jnp.log(out) + (1.0 - labels) * jnp.log(1.0 - out))
    return _reduce(per, mask)


@_loss("mcxent")
def mcxent(labels, preout, activation, mask=None):
    """Multi-class cross-entropy. Fused log-softmax path when the output
    activation is softmax (the common OutputLayer configuration)."""
    if activation == "softmax":
        logp = jax.nn.log_softmax(preout, axis=-1)
    else:
        out = _out(preout, activation)
        logp = jnp.log(jnp.clip(out, _EPS, None))
    return _reduce(-labels * logp, mask)


@_loss("negativeloglikelihood")
def negativeloglikelihood(labels, preout, activation, mask=None):
    # Reference LossNegativeLogLikelihood extends LossMCXENT.
    return mcxent(labels, preout, activation, mask)


@_loss("kl_divergence")
def kl_divergence(labels, preout, activation, mask=None):
    out = _out(preout, activation)
    out = jnp.clip(out, _EPS, 1.0 - _EPS)
    lab = jnp.clip(labels, _EPS, 1.0 - _EPS)
    return _reduce(lab * (jnp.log(lab) - jnp.log(out)), mask)


@_loss("reconstruction_crossentropy")
def reconstruction_crossentropy(labels, preout, activation, mask=None):
    return xent(labels, preout, activation, mask)


@_loss("cosine_proximity")
def cosine_proximity(labels, preout, activation, mask=None):
    out = _out(preout, activation)
    if mask is not None:
        m = mask
        while m.ndim < out.ndim:
            m = m[..., None]
        out = out * m
        labels = labels * m
    dot = jnp.sum(labels * out, axis=-1)
    norm = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(out, axis=-1)
    cos = dot / jnp.maximum(norm, _EPS)
    # reduce any remaining time axes
    while cos.ndim > 1:
        cos = jnp.sum(cos, axis=-1)
    return -cos


@_loss("hinge")
def hinge(labels, preout, activation, mask=None):
    # labels in {-1, 1}
    out = _out(preout, activation)
    return _reduce(jnp.maximum(0.0, 1.0 - labels * out), mask)


@_loss("squared_hinge")
def squared_hinge(labels, preout, activation, mask=None):
    out = _out(preout, activation)
    h = jnp.maximum(0.0, 1.0 - labels * out)
    return _reduce(h * h, mask)


@_loss("poisson")
def poisson(labels, preout, activation, mask=None):
    out = _out(preout, activation)
    return _reduce(out - labels * jnp.log(jnp.clip(out, _EPS, None)), mask)


@_loss("squared_loss")
def squared_loss(labels, preout, activation, mask=None):
    return l2(labels, preout, activation, mask)


@_loss("rmse_xent")
def rmse_xent(labels, preout, activation, mask=None):
    # Reference legacy LossFunction; implemented as sqrt of per-example SSE.
    out = _out(preout, activation)
    d = out - labels
    return jnp.sqrt(_reduce(d * d, mask) + _EPS)


class LossFunction:
    """Enum-style names mirroring LossFunctions.LossFunction."""

    MSE = "mse"
    L1 = "l1"
    L2 = "l2"
    XENT = "xent"
    MCXENT = "mcxent"
    SQUARED_LOSS = "squared_loss"
    RECONSTRUCTION_CROSSENTROPY = "reconstruction_crossentropy"
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    COSINE_PROXIMITY = "cosine_proximity"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    KL_DIVERGENCE = "kl_divergence"
    MEAN_ABSOLUTE_ERROR = "mean_absolute_error"
    MEAN_ABSOLUTE_PERCENTAGE_ERROR = "mean_absolute_percentage_error"
    MEAN_SQUARED_LOGARITHMIC_ERROR = "mean_squared_logarithmic_error"
    POISSON = "poisson"
    RMSE_XENT = "rmse_xent"


def example_presence(per_ex, mask: Optional[jax.Array]):
    """[batch] 0/1 presence from a labels mask: an example whose mask is
    all-zero (a pad row from ParallelWrapper's pad-and-mask tail handling)
    is absent. None mask -> all present."""
    if mask is None:
        return jnp.ones(per_ex.shape[0], per_ex.dtype)
    m = mask
    while m.ndim > 1:
        m = jnp.max(m, axis=-1)
    return (m > 0).astype(per_ex.dtype)


def masked_example_mean(per_ex, mask: Optional[jax.Array]):
    """Mean of per-example losses over PRESENT examples only. Identical to
    jnp.mean when no example is fully masked; excludes zero-mask pad rows
    so a padded tail batch yields exactly the unpadded score/gradients.

    Intentional deviation from the reference: DL4J divides by the full
    batch count even when sequences are fully masked, so batches with
    more padding train with a silently smaller effective lr. Dividing by
    the present count keeps the per-REAL-example gradient scale constant
    across batches — and is what makes ParallelWrapper's pad-and-mask
    tail numerically exact."""
    if mask is None:
        return jnp.mean(per_ex)
    present = example_presence(per_ex, mask)
    return jnp.sum(per_ex * present) / jnp.maximum(jnp.sum(present), 1.0)


def loss_value(name: str, labels, preout, activation: str, mask: Optional[jax.Array] = None):
    """Per-example loss [batch] for the named loss function."""
    try:
        fn = _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(f"unknown loss {name!r}; known: {sorted(_REGISTRY)}") from None
    return fn(labels, preout, activation, mask)
