"""Keras 1.x HDF5 import tests (reference: deeplearning4j-modelimport test
strategy — load stored archives, assert config + forward parity).

Fixtures are written in-test with h5py in the exact Keras 1.x
``save_model()`` layout: ``model_config``/``training_config`` JSON file
attrs + per-layer weight groups under ``model_weights`` with
``layer_names``/``weight_names`` attributes (KerasModel.java:73-75,299-360).
Golden forwards are computed with plain numpy, so the dim-ordering
transposes are verified against an independent implementation.
"""

import json

import numpy as np
import pytest

h5py = pytest.importorskip("h5py")

from deeplearning4j_tpu.modelimport import (  # noqa: E402
    KerasImportError,
    import_keras_model_and_weights,
    import_keras_sequential_config,
    import_keras_sequential_model_and_weights,
)
from deeplearning4j_tpu.nn.conf import layers as L  # noqa: E402


def _seq_config(layers):
    return json.dumps({"class_name": "Sequential", "config": layers})


def _training_config(loss="categorical_crossentropy"):
    return json.dumps({"loss": loss, "optimizer": {"name": "sgd"}})


def write_keras_h5(path, model_config, weights, training_config=None):
    """weights: {layer_name: {param_name_without_suffix: array}} — written
    with the TF-backend ':0' suffix Keras 1.x emits."""
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = np.bytes_(model_config)
        if training_config is not None:
            f.attrs["training_config"] = np.bytes_(training_config)
        mw = f.create_group("model_weights")
        mw.attrs["layer_names"] = np.array(
            [name.encode() for name in weights], dtype="S64"
        )
        for lname, params in weights.items():
            g = mw.create_group(lname)
            wnames = [f"{lname}_{p}:0" for p in params]
            g.attrs["weight_names"] = np.array(
                [n.encode() for n in wnames], dtype="S64"
            )
            for wn, (pname, arr) in zip(wnames, params.items()):
                g.create_dataset(wn, data=np.asarray(arr, np.float32))


def _softmax(z):
    e = np.exp(z - z.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


def test_sequential_mlp_golden(tmp_path):
    rng = np.random.default_rng(0)
    W1, b1 = rng.normal(size=(4, 8)).astype(np.float32), rng.normal(size=8).astype(np.float32)
    W2, b2 = rng.normal(size=(8, 3)).astype(np.float32), rng.normal(size=3).astype(np.float32)
    mc = _seq_config([
        {"class_name": "Dense",
         "config": {"name": "dense_1", "output_dim": 8, "activation": "relu",
                    "batch_input_shape": [None, 4], "init": "glorot_uniform"}},
        {"class_name": "Dense",
         "config": {"name": "dense_2", "output_dim": 3, "activation": "softmax",
                    "init": "glorot_uniform"}},
    ])
    path = tmp_path / "mlp.h5"
    write_keras_h5(path, mc,
                   {"dense_1": {"W": W1, "b": b1}, "dense_2": {"W": W2, "b": b2}},
                   training_config=_training_config())
    net = import_keras_sequential_model_and_weights(str(path))
    # final Dense under a training config becomes the fused loss head
    assert isinstance(net.layer_confs[-1], L.OutputLayer)
    assert net.layer_confs[-1].loss == "mcxent"
    x = rng.normal(size=(5, 4)).astype(np.float32)
    got = np.asarray(net.output(x))
    want = _softmax(np.maximum(x @ W1 + b1, 0.0) @ W2 + b2)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def _np_conv_valid(x, W, b):
    """NHWC x HWIO valid cross-correlation, straight loops."""
    n, h, w, cin = x.shape
    kh, kw, _, cout = W.shape
    oh, ow = h - kh + 1, w - kw + 1
    out = np.zeros((n, oh, ow, cout), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, i:i + kh, j:j + kw, :].reshape(n, -1)
            out[:, i, j, :] = patch @ W.reshape(-1, cout)
    return out + b


def _np_maxpool(x, k):
    n, h, w, c = x.shape
    oh, ow = h // k, w // k
    out = np.zeros((n, oh, ow, c), np.float32)
    for i in range(oh):
        for j in range(ow):
            out[:, i, j, :] = x[:, i * k:(i + 1) * k, j * k:(j + 1) * k, :].max((1, 2))
    return out


def _cnn_model_config(dim_ordering="tf"):
    input_shape = [None, 8, 8, 3] if dim_ordering == "tf" else [None, 3, 8, 8]
    return _seq_config([
        {"class_name": "Convolution2D",
         "config": {"name": "convolution2d_1", "nb_filter": 4, "nb_row": 3,
                    "nb_col": 3, "border_mode": "valid", "subsample": [1, 1],
                    "dim_ordering": dim_ordering, "activation": "relu",
                    "batch_input_shape": input_shape, "init": "glorot_uniform"}},
        {"class_name": "MaxPooling2D",
         "config": {"name": "maxpooling2d_1", "pool_size": [2, 2],
                    "strides": [2, 2], "border_mode": "valid",
                    "dim_ordering": dim_ordering}},
        {"class_name": "Flatten", "config": {"name": "flatten_1"}},
        {"class_name": "Dense",
         "config": {"name": "dense_1", "output_dim": 5, "activation": "softmax",
                    "init": "glorot_uniform"}},
    ])


def test_cnn_tf_ordering_golden(tmp_path):
    rng = np.random.default_rng(1)
    Wc = rng.normal(size=(3, 3, 3, 4)).astype(np.float32)  # HWIO == Keras tf
    bc = rng.normal(size=4).astype(np.float32)
    Wd = rng.normal(size=(3 * 3 * 4, 5)).astype(np.float32)
    bd = rng.normal(size=5).astype(np.float32)
    path = tmp_path / "cnn.h5"
    write_keras_h5(path, _cnn_model_config("tf"),
                   {"convolution2d_1": {"W": Wc, "b": bc},
                    "dense_1": {"W": Wd, "b": bd}},
                   training_config=_training_config())
    net = import_keras_sequential_model_and_weights(str(path))
    x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    got = np.asarray(net.output(x))
    conv = np.maximum(_np_conv_valid(x, Wc, bc), 0.0)
    flat = _np_maxpool(conv, 2).reshape(2, -1)
    want = _softmax(flat @ Wd + bd)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_cnn_theano_kernel_transpose(tmp_path):
    """A Theano-ordering archive must produce the same network as the
    equivalent tf-ordering one: W_th = rot180(W_tf) permuted to OIHW
    (KerasConvolution.java:119-138)."""
    rng = np.random.default_rng(2)
    Wc = rng.normal(size=(3, 3, 3, 4)).astype(np.float32)
    bc = rng.normal(size=4).astype(np.float32)
    # build the th-ordering view of the same kernel: HWIO -> OIHW + rot180
    W_th = Wc.transpose(3, 2, 0, 1)[:, :, ::-1, ::-1]
    # NOTE: theano Flatten flattens (C,H,W) — restrict to the conv output
    # by pooling globally so the dense row-order difference is moot
    mc = _seq_config([
        {"class_name": "Convolution2D",
         "config": {"name": "convolution2d_1", "nb_filter": 4, "nb_row": 3,
                    "nb_col": 3, "border_mode": "valid", "subsample": [1, 1],
                    "dim_ordering": "th", "activation": "linear",
                    "batch_input_shape": [None, 3, 8, 8],
                    "init": "glorot_uniform"}},
        {"class_name": "GlobalAveragePooling2D",
         "config": {"name": "gap_1", "dim_ordering": "th"}},
    ])
    path = tmp_path / "cnn_th.h5"
    write_keras_h5(path, mc, {"convolution2d_1": {"W": W_th, "b": bc}})
    net = import_keras_sequential_model_and_weights(str(path))
    x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)  # network is NHWC
    got = np.asarray(net.output(x))
    want = _np_conv_valid(x, Wc, bc).mean(axis=(1, 2))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_lstm_gate_packing(tmp_path):
    """Keras's 12 LSTM arrays must land in the fused [i|f|g|o] blocks:
    verify the imported net's forward against a manual numpy LSTM."""
    rng = np.random.default_rng(3)
    n_in, H, T, B = 3, 4, 5, 2
    ks = {}
    for g in ("i", "f", "c", "o"):
        ks[f"W_{g}"] = rng.normal(size=(n_in, H)).astype(np.float32)
        ks[f"U_{g}"] = rng.normal(size=(H, H)).astype(np.float32)
        ks[f"b_{g}"] = rng.normal(size=H).astype(np.float32)
    mc = _seq_config([
        {"class_name": "LSTM",
         "config": {"name": "lstm_1", "output_dim": H, "activation": "tanh",
                    "inner_activation": "sigmoid", "return_sequences": True,
                    "batch_input_shape": [None, T, n_in],
                    "init": "glorot_uniform", "inner_init": "orthogonal",
                    "forget_bias_init": "one"}},
    ])
    path = tmp_path / "lstm.h5"
    write_keras_h5(path, mc, {"lstm_1": ks})
    net = import_keras_sequential_model_and_weights(str(path))
    x = rng.normal(size=(B, T, n_in)).astype(np.float32)
    got = np.asarray(net.output(x))

    def sig(z):
        return 1.0 / (1.0 + np.exp(-z))

    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    want = np.zeros((B, T, H), np.float32)
    for t in range(T):
        xt = x[:, t, :]
        i = sig(xt @ ks["W_i"] + h @ ks["U_i"] + ks["b_i"])
        f = sig(xt @ ks["W_f"] + h @ ks["U_f"] + ks["b_f"])
        g = np.tanh(xt @ ks["W_c"] + h @ ks["U_c"] + ks["b_c"])
        o = sig(xt @ ks["W_o"] + h @ ks["U_o"] + ks["b_o"])
        c = f * c + i * g
        h = o * np.tanh(c)
        want[:, t, :] = h
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_batchnorm_running_stats(tmp_path):
    rng = np.random.default_rng(4)
    n = 6
    gamma = rng.normal(size=n).astype(np.float32)
    beta = rng.normal(size=n).astype(np.float32)
    mean = rng.normal(size=n).astype(np.float32)
    var = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    mc = _seq_config([
        {"class_name": "Dense",
         "config": {"name": "dense_1", "output_dim": n, "activation": "linear",
                    "batch_input_shape": [None, n], "init": "glorot_uniform"}},
        {"class_name": "BatchNormalization",
         "config": {"name": "batchnormalization_1", "mode": 0,
                    "epsilon": 1e-5, "momentum": 0.99}},
    ])
    W = np.eye(n, dtype=np.float32)
    b = np.zeros(n, np.float32)
    path = tmp_path / "bn.h5"
    write_keras_h5(path, mc, {
        "dense_1": {"W": W, "b": b},
        "batchnormalization_1": {
            "gamma": gamma, "beta": beta,
            "running_mean": mean, "running_std": var,
        },
    })
    net = import_keras_sequential_model_and_weights(str(path))
    x = rng.normal(size=(3, n)).astype(np.float32)
    got = np.asarray(net.output(x))  # inference: uses running stats
    want = gamma * (x - mean) / np.sqrt(var + 1e-5) + beta
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_functional_model_merge(tmp_path):
    """Two-input functional Model with a concat Merge -> ComputationGraph."""
    rng = np.random.default_rng(5)
    W1 = rng.normal(size=(3, 4)).astype(np.float32)
    b1 = rng.normal(size=4).astype(np.float32)
    W2 = rng.normal(size=(2, 4)).astype(np.float32)
    b2 = rng.normal(size=4).astype(np.float32)
    W3 = rng.normal(size=(8, 3)).astype(np.float32)
    b3 = rng.normal(size=3).astype(np.float32)
    mc = json.dumps({
        "class_name": "Model",
        "config": {
            "name": "model_1",
            "layers": [
                {"class_name": "InputLayer", "name": "input_1",
                 "config": {"name": "input_1", "batch_input_shape": [None, 3]},
                 "inbound_nodes": []},
                {"class_name": "InputLayer", "name": "input_2",
                 "config": {"name": "input_2", "batch_input_shape": [None, 2]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "dense_a",
                 "config": {"name": "dense_a", "output_dim": 4,
                            "activation": "tanh", "init": "glorot_uniform"},
                 "inbound_nodes": [[["input_1", 0, 0]]]},
                {"class_name": "Dense", "name": "dense_b",
                 "config": {"name": "dense_b", "output_dim": 4,
                            "activation": "tanh", "init": "glorot_uniform"},
                 "inbound_nodes": [[["input_2", 0, 0]]]},
                {"class_name": "Merge", "name": "merge_1",
                 "config": {"name": "merge_1", "mode": "concat"},
                 "inbound_nodes": [[["dense_a", 0, 0], ["dense_b", 0, 0]]]},
                {"class_name": "Dense", "name": "dense_out",
                 "config": {"name": "dense_out", "output_dim": 3,
                            "activation": "softmax", "init": "glorot_uniform"},
                 "inbound_nodes": [[["merge_1", 0, 0]]]},
            ],
            "input_layers": [["input_1", 0, 0], ["input_2", 0, 0]],
            "output_layers": [["dense_out", 0, 0]],
        },
    })
    path = tmp_path / "func.h5"
    write_keras_h5(path, mc, {
        "dense_a": {"W": W1, "b": b1},
        "dense_b": {"W": W2, "b": b2},
        "dense_out": {"W": W3, "b": b3},
    }, training_config=_training_config())
    net = import_keras_model_and_weights(str(path))
    xa = rng.normal(size=(4, 3)).astype(np.float32)
    xb = rng.normal(size=(4, 2)).astype(np.float32)
    out = net.output(xa, xb)
    got = np.asarray(out[0] if isinstance(out, (list, tuple)) else out)
    merged = np.concatenate([np.tanh(xa @ W1 + b1), np.tanh(xb @ W2 + b2)], axis=1)
    want = _softmax(merged @ W3 + b3)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_config_only_and_errors(tmp_path):
    conf, names = import_keras_sequential_config(_seq_config([
        {"class_name": "Dense",
         "config": {"name": "d", "output_dim": 2, "activation": "relu",
                    "batch_input_shape": [None, 3], "init": "glorot_uniform"}},
    ]))
    assert len(conf.layers) == 1 and names == ["d"]
    with pytest.raises(KerasImportError):
        import_keras_sequential_config(
            json.dumps({"class_name": "Graph", "config": []}))
    # archive without model_config
    path = tmp_path / "bad.h5"
    with h5py.File(path, "w") as f:
        f.create_group("model_weights")
    with pytest.raises(KerasImportError):
        import_keras_sequential_model_and_weights(str(path))
