"""MeshPlan — the mainline multi-chip train-step sharding authority.

This is the SPMD data-parallel recipe (Megatron-style in-graph
collectives) promoted from `parallel/wrapper.py`'s opt-in batch-transform
hook into the thing `fit()` does by default on a multi-device platform:

* parameters + updater state are committed to the mesh **replicated**
  (or left in whatever NamedSharding a tp/pp helper already placed them
  with — `shard_params_tp` placements are honored, never clobbered);
* every global batch is **sharded on the "data" axis** (dim 0), padded
  and loss-masked to a stable shard-divisible shape so the tail batch
  neither recompiles nor drops to replicated execution;
* the optimizer step is ONE jitted program built with explicit
  `NamedSharding` in-shardings and the single-sourced donation rule
  (`netbase._step_donate_argnums`, audited by JX006), with the gradient
  all-reduce pinned **inside the program** by a sharding constraint at
  the grad site — there is no host-side averaging anywhere in the step
  path (the DL4J ParallelWrapper semantics this replaces: per-step
  gradient psum/mean == parameter averaging with frequency 1, see
  tests/test_parallel.py::test_allreduce_equals_parameter_averaging);
* the reduction itself is **bucketed** (`CollectivePlan`): the flattened
  gradient leaves are grouped reverse-topologically (the last layers'
  grads finish first in the backward pass) into ~`bucket_bytes` flat
  payloads, each reduced by its own in-graph collective — the PyTorch
  DDP / Horovod bucketing design at the GSPMD level. Each bucket depends
  only on its own leaves, so XLA's latency-hiding scheduler can launch
  early buckets' collectives while the remaining backward still
  computes, instead of one tail-end reduction gated on the LAST grad.
  The f32 bucketed path is bit-identical to the monolithic constraint
  (concat/split is exact; the per-element cross-device sum order is
  unchanged — pinned by tests/test_collectives.py). `bucket_bytes=0`
  restores the monolithic tail-end constraint;
* opt-in `set_mesh(..., grad_dtype="bf16")` casts bucket payloads to
  bf16 before the reduce and back to f32 after — halving the wire bytes
  (`allreduce_bytes_total` and the ring estimate account the bf16
  payload) at a bounded trajectory cost. Never the default.

Attach with `net.set_mesh(mesh)` (None = 1-D "data" mesh over all
devices). `fit()` attaches one automatically when more than one device
is visible — disable with `DL4J_AUTO_MESH=0` (tests/conftest.py does,
so the 8-virtual-device tier-1 suite doesn't shard every tiny fit; the
dedicated sharding tests and the t1.sh 2-device smoke opt back in).

tp/pp/sp compose via config: build the mesh with `mesh_2d` and apply
`shard_params_tp` BEFORE `set_mesh` — `place_net` keeps any leaf
already committed to this mesh, and `jit_step` derives per-leaf
in-shardings from the live placement, so Megatron column/row splits ride
the same jitted step. The pipeline/sequence helpers (`pipeline_apply`,
`ring_self_attention`) stay shard_map-level building blocks for models
that need them.
"""

from __future__ import annotations

import inspect
import os
import time
from typing import List, Optional, Tuple

import numpy as np

# DDP-style default bucket size. Small enough that a ResNet-50-class
# gradient tree splits into ~25 buckets (overlap granularity), large
# enough that per-collective launch latency stays amortized.
DEFAULT_BUCKET_BYTES = 4 << 20


def auto_mesh_enabled() -> bool:
    """Should `fit()` auto-attach a data-parallel mesh on a multi-device
    platform? Default yes — the mainline multi-chip path. `DL4J_AUTO_MESH=0`
    disables (read per fit, so tests can flip it per-case)."""
    return os.environ.get("DL4J_AUTO_MESH", "1") not in ("0", "false", "no")


def default_bucket_bytes() -> int:
    """The gradient-bucket size knob: `DL4J_GRAD_BUCKET_BYTES` (0 =
    monolithic tail-end reduction), else the DDP-style 4 MiB default."""
    env = os.environ.get("DL4J_GRAD_BUCKET_BYTES")
    if env is not None:
        return int(env)
    return DEFAULT_BUCKET_BYTES


def _jax():
    import jax

    return jax


class CollectivePlan:
    """Bucketed gradient-reduction schedule over one net's flattened
    gradient leaves.

    Buckets are assigned in REVERSE leaf order — the params list is in
    layer topo order, so reversed leaves approximate backward-pass
    completion order (the output head's grads are ready first). Each
    bucket holds consecutive same-dtype leaves up to ~`bucket_bytes` of
    wire payload and is reduced as ONE flat concatenated collective; a
    leaf whose target sharding is not fully replicated (tp/pp splits)
    stays outside the buckets and keeps its per-leaf constraint (its
    gradient is deliberately sharded — there is nothing to all-reduce).

    `grad_dtype="bf16"` prices (and casts) the wire payload at 2
    bytes/element; accumulation back into the f32 gradient happens after
    the reduce (`MeshPlan.reduce_grads`)."""

    def __init__(self, buckets: List[dict], unbucketed: List[int],
                 n_leaves: int, bucket_bytes: int,
                 grad_dtype: Optional[str]):
        self.buckets = buckets          # [{"leaves": [flat idx], "bytes", "dtype"}]
        self.unbucketed = unbucketed    # flat leaf indices constrained per-leaf
        self.n_leaves = n_leaves
        self.bucket_bytes = bucket_bytes
        self.grad_dtype = grad_dtype or "f32"

    @classmethod
    def build(cls, leaves, sharding_leaves, replicated, bucket_bytes: int,
              grad_dtype: Optional[str]) -> "CollectivePlan":
        bf16 = grad_dtype == "bf16"
        buckets: List[dict] = []
        unbucketed: List[int] = []
        cur: List[int] = []
        cur_bytes = 0
        cur_dtype = None

        def flush():
            nonlocal cur, cur_bytes, cur_dtype
            if cur:
                buckets.append({"leaves": cur, "bytes": cur_bytes,
                                "dtype": cur_dtype})
            cur, cur_bytes, cur_dtype = [], 0, None

        for i in reversed(range(len(leaves))):
            leaf = leaves[i]
            if sharding_leaves[i] != replicated:
                unbucketed.append(i)
                continue
            dt = str(leaf.dtype)
            nb = int(leaf.size) * (2 if bf16 else leaf.dtype.itemsize)
            if cur and (dt != cur_dtype
                        or cur_bytes + nb > max(1, bucket_bytes)):
                flush()
            cur.append(i)
            cur_bytes += nb
            cur_dtype = dt
        flush()
        return cls(buckets, unbucketed, len(leaves), bucket_bytes,
                   grad_dtype)

    def wire_bytes(self) -> int:
        """Total wire payload of one step's bucketed collectives."""
        return sum(b["bytes"] for b in self.buckets)

    def describe(self) -> dict:
        sizes = [b["bytes"] for b in self.buckets]
        return {
            "bucket_bytes": self.bucket_bytes,
            "grad_dtype": self.grad_dtype,
            "n_buckets": len(self.buckets),
            "bucketed_leaves": sum(len(b["leaves"]) for b in self.buckets),
            "unbucketed_leaves": len(self.unbucketed),
            "wire_bytes_per_step": self.wire_bytes(),
            "bucket_sizes_bytes": sizes,
        }


class MeshPlan:
    """Sharding plan of one net over one `jax.sharding.Mesh`.

    Single source of truth for: parameter/updater placement, batch
    sharding (the `_batch_transform` the input pipeline runs off the
    dispatch critical path), the step jit's in-shardings + donation, the
    in-graph gradient-reduction constraint, and the per-step collective
    accounting (`allreduce_bytes_total` / `train_step_collective_seconds`).
    """

    def __init__(self, mesh, *, bucket_bytes: Optional[int] = None,
                 grad_dtype: Optional[str] = None):
        from jax.sharding import NamedSharding, PartitionSpec

        from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, data_shards

        if DATA_AXIS not in mesh.axis_names:
            raise ValueError(
                f"mesh axes {mesh.axis_names} have no '{DATA_AXIS}' axis — "
                "the sharded train step needs one to split the batch over")
        if grad_dtype not in (None, "f32", "bf16"):
            raise ValueError(
                f"grad_dtype must be 'f32' or 'bf16', got {grad_dtype!r}")
        self.mesh = mesh
        self.n_data_shards = data_shards(mesh)
        self.replicated = NamedSharding(mesh, PartitionSpec())
        # batch dim 0 over "data"; stacked variants (fused multi-batch
        # programs, [K, B, ...]) shard dim 1
        self.batch = NamedSharding(mesh, PartitionSpec(DATA_AXIS))
        self.batch_stacked = NamedSharding(
            mesh, PartitionSpec(None, DATA_AXIS))
        # collective knobs: bucket size (0 = monolithic tail-end
        # constraint) and the opt-in bf16 wire payload
        self.bucket_bytes = (default_bucket_bytes() if bucket_bytes is None
                             else int(bucket_bytes))
        self.grad_dtype = "f32" if grad_dtype is None else grad_dtype
        # pad-up-to target: largest shard-divisible batch seen this fit,
        # so a short tail reuses the full batches' executable (reset by
        # the fit loop at each run start)
        self._pad_target = 0
        # per-net cached gradient payload bytes (the allreduce books)
        self._payload_bytes: Optional[int] = None
        # per-net cached bucket schedule + measured-collective probe
        self._cplan: Optional[CollectivePlan] = None
        self._probe = None               # (jitted fn, staged args)
        self._probe_steps = 0            # sharded steps since last sample

    # -- placement -----------------------------------------------------------

    def _on_this_mesh(self, a) -> bool:
        jax = _jax()
        if not isinstance(a, jax.Array):
            return False
        sh = getattr(a, "sharding", None)
        return getattr(sh, "mesh", None) == self.mesh

    def place_net(self, net) -> "MeshPlan":
        """Commit the net's params, layer state and updater state to the
        mesh, replicated — the once-per-attach analog of the reference
        copying the source model into every worker replica. Leaves a
        tp/pp helper already committed to THIS mesh keep their sharding
        (re-putting them replicated would silently all-gather a
        deliberately distributed weight)."""
        jax = _jax()

        def put(a):
            if a is None or self._on_this_mesh(a):
                return a
            return jax.device_put(a, self.replicated)

        tm = lambda t: jax.tree_util.tree_map(put, t)
        net.params_list = tm(net.params_list)
        net.state_list = tm(net.state_list)
        net.upd_state = tm(net.upd_state)
        self._payload_bytes = None
        self._cplan = None
        self._probe = None
        return self

    def tree_shardings(self, tree):
        """Per-leaf NamedShardings of a live pytree — the in-shardings of
        the params/updater arguments. Leaves not committed to this mesh
        (e.g. freshly-restored checkpoint numpy) fall back to replicated,
        which is what the step's first dispatch will commit them to."""
        jax = _jax()
        return jax.tree_util.tree_map(
            lambda a: a.sharding if self._on_this_mesh(a) else self.replicated,
            tree)

    # -- batch sharding ------------------------------------------------------

    def reset_pad_target(self) -> None:
        """Per-fit state: a later fit with a smaller batch size must not
        keep padding to the old larger shape."""
        self._pad_target = 0

    def _stage_array(self, a, sh, pad: int, target: int):
        """One batch array onto the mesh. Fast paths, in order: already
        committed with the target sharding -> zero-copy passthrough
        (the `_pipeline_staged` contract extended to sharded placement —
        a pre-staged batch is never transferred twice); already a device
        array and no pad needed -> device-side reshard, no host hop.
        Only a padded tail takes the host round-trip (np.resize wrap)."""
        jax = _jax()
        if a is None:
            return None
        if pad == 0 and isinstance(a, jax.Array):
            cur = getattr(a, "sharding", None)
            if cur == sh:
                return a
            try:
                if cur is not None and cur.is_equivalent_to(sh, a.ndim):
                    return a
            except Exception:
                pass
            return jax.device_put(a, sh)
        from deeplearning4j_tpu.parallel.mesh import pad_wrap

        return jax.device_put(pad_wrap(np.asarray(a), target), sh)

    def shard_batch(self, ds):
        """Shard a global batch's dim 0 across the data axis (DataSet or
        MultiDataSet — ComputationGraph fit yields the latter). Installed
        as the net's `_batch_transform`, so under async_prefetch it runs
        inside the device-prefetch worker thread, off the dispatch
        critical path.

        Pad-and-mask tail handling (moved verbatim from the old
        ParallelWrapper): a batch not divisible by the shard count is
        padded to the next multiple by WRAPPING examples and the pad rows
        are excluded from the loss via an all-zero labels-mask row
        (losses use masked_example_mean, so the padded step computes
        exactly the unpadded score/gradients). A labels mask of ones is
        supplied for full batches too, keeping ONE trace signature — the
        tail batch neither recompiles nor drops to replicated serial
        execution. Wrapped pad rows do still enter batch-norm batch
        statistics — a stochastic duplicate-sample effect on the tail
        step only."""
        jax = _jax()
        from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet

        n = ds.num_examples()
        target = max(n + ((-n) % self.n_data_shards), self._pad_target)
        self._pad_target = target
        pad = target - n
        sh = self.batch

        def stage(a):
            return self._stage_array(a, sh, pad, target)

        def pad_lmask(lm):
            """Existing labels mask: pad rows of zeros. Absent: 0/1
            vector."""
            if lm is not None:
                if pad == 0:
                    return stage(lm)
                lm = np.asarray(lm)
                z = np.zeros((pad,) + lm.shape[1:], lm.dtype)
                return jax.device_put(np.concatenate([lm, z]), sh)
            m = np.ones((n + pad,), np.float32)
            if pad:
                m[n:] = 0.0
            return jax.device_put(m, sh)

        if isinstance(ds, MultiDataSet):
            lmasks = ds.labels_masks
            if lmasks is None:
                lmasks = [None] * len(ds.labels)
            out = MultiDataSet(
                [stage(f) for f in ds.features],
                [stage(l) for l in ds.labels],
                None if ds.features_masks is None
                else [stage(m) for m in ds.features_masks],
                [pad_lmask(m) for m in lmasks],
            )
        else:
            out = DataSet(
                stage(ds.features),
                stage(ds.labels),
                stage(ds.features_mask),
                pad_lmask(ds.labels_mask),
            )
        # listeners/counters must see the REAL example count, not the pad
        out.reported_examples = getattr(ds, "reported_examples", None) or n
        return out

    # -- the sharded step jit ------------------------------------------------

    def jit_step(self, net, step, *, donate_argnums: Tuple[int, ...],
                 data_argnums: Tuple[int, ...] = (3,),
                 stacked_data: bool = False):
        """jit an optimizer-step body with explicit NamedSharding
        in-shardings: per-leaf placements for params (argnum 0) and
        updater state (argnum 2) — which is what lets tp-sharded weights
        ride the same program — the batch sharding for the data argnums,
        replicated for everything else (layer state, lr, t, rng). The
        donation rule arrives from the ONE definition every step builder
        uses (`netbase._step_donate_argnums`, recorded on the net for the
        JX006 audit); donated in/out layouts match because the step body
        constrains its gradient (and hence its outputs) back to the
        parameter shardings."""
        jax = _jax()
        n_args = len(inspect.signature(step).parameters)
        data_sh = self.batch_stacked if stacked_data else self.batch
        in_shardings = []
        for i in range(n_args):
            if i == 0:
                in_shardings.append(self.tree_shardings(net.params_list))
            elif i == 2:
                in_shardings.append(self.tree_shardings(net.upd_state))
            elif i in data_argnums:
                in_shardings.append(data_sh)
            else:
                in_shardings.append(self.replicated)
        return jax.jit(step, in_shardings=tuple(in_shardings),
                       donate_argnums=donate_argnums)

    def grad_shardings(self, net):
        """Per-leaf shardings the step body constrains its gradients to
        (`with_sharding_constraint` right after value_and_grad): the
        parameter shardings. For replicated dp params this pins the
        cross-device psum/mean INSIDE the program at the grad site —
        the in-graph all-reduce; tp-sharded params keep their sharded
        gradients (no gather)."""
        return self.tree_shardings(net.params_list)

    # -- the bucketed in-graph reduction -------------------------------------

    def collective_plan(self, net) -> Optional[CollectivePlan]:
        """The bucket schedule for this net's gradient tree (cached —
        shapes are static for a fit). None when bucketing is off
        (`bucket_bytes=0` and f32 wire): the step body then falls back
        to the monolithic whole-tree sharding constraint."""
        if self.bucket_bytes <= 0 and self.grad_dtype != "bf16":
            return None
        if self._cplan is None:
            jax = _jax()
            leaves = jax.tree_util.tree_leaves(net.params_list)
            sh_leaves = jax.tree_util.tree_leaves(
                self.grad_shardings(net))
            # bucket_bytes=0 with bf16 wire: one bucket per leaf (the
            # cast/reduce/uncast still applies, just unbatched)
            bb = self.bucket_bytes if self.bucket_bytes > 0 else 1
            self._cplan = CollectivePlan.build(
                leaves, sh_leaves, self.replicated, bb, self.grad_dtype)
        return self._cplan

    def reduce_grads(self, net, grads):
        """Emit the in-graph gradient reduction inside a step body
        (called under trace by `_make_step_body`). Monolithic mode is
        the historical whole-tree `with_sharding_constraint`; bucketed
        mode concatenates each bucket's flattened leaves into ONE flat
        payload, constrains it replicated (ONE collective per bucket),
        and splits it back — bit-identical for f32 (the per-element
        cross-device sum order is unchanged; concat/reshape are exact).
        bf16 wire casts the payload before the constraint and
        accumulates back into the leaf dtype after."""
        jax = _jax()
        import jax.numpy as jnp

        gshard = self.grad_shardings(net)
        cplan = self.collective_plan(net)
        if cplan is None:
            return jax.lax.with_sharding_constraint(grads, gshard)
        bf16 = cplan.grad_dtype == "bf16"
        flat, treedef = jax.tree_util.tree_flatten(grads)
        sflat = jax.tree_util.tree_leaves(gshard)
        for b in cplan.buckets:
            idxs = b["leaves"]
            if len(idxs) == 1 and not bf16:
                # a lone leaf needs no concat round-trip
                i = idxs[0]
                flat[i] = jax.lax.with_sharding_constraint(
                    flat[i], sflat[i])
                continue
            parts = [flat[i] for i in idxs]
            payload = (parts[0].reshape(-1) if len(parts) == 1
                       else jnp.concatenate([p.reshape(-1) for p in parts]))
            acc_dtype = payload.dtype
            if bf16 and acc_dtype != jnp.bfloat16:
                payload = payload.astype(jnp.bfloat16)
            payload = jax.lax.with_sharding_constraint(
                payload, self.replicated)
            if payload.dtype != acc_dtype:
                payload = payload.astype(acc_dtype)
            off = 0
            for i in idxs:
                sz = int(flat[i].size)
                piece = jax.lax.slice_in_dim(payload, off, off + sz)
                off += sz
                flat[i] = jax.lax.with_sharding_constraint(
                    piece.reshape(flat[i].shape), sflat[i])
        for i in cplan.unbucketed:
            flat[i] = jax.lax.with_sharding_constraint(flat[i], sflat[i])
        return jax.tree_util.tree_unflatten(treedef, flat)

    # -- collective accounting ----------------------------------------------

    def grad_payload_bytes(self, net) -> int:
        """Logical all-reduce WIRE payload of ONE optimizer step: the
        summed gradient leaf bytes at the wire dtype (== parameter bytes
        for f32; half that under `grad_dtype="bf16"`). Cached — shapes
        are static for a fit."""
        if self._payload_bytes is None:
            jax = _jax()
            bf16 = self.grad_dtype == "bf16"
            total = 0
            for leaf in jax.tree_util.tree_leaves(net.params_list):
                size = getattr(leaf, "size", None)
                if not size:
                    continue
                itemsize = 2 if bf16 else leaf.dtype.itemsize
                total += int(size) * itemsize
            self._payload_bytes = total
        return self._payload_bytes

    def collective_seconds_estimate(self, net) -> float:
        """Cost-model ESTIMATE of one step's gradient all-reduce time:
        ring all-reduce moves 2(n-1)/n of the wire payload over each
        chip's ICI links (`flops.ici_bandwidth_per_chip`); a bf16 wire
        halves the payload. An estimate, not a measurement — labeled as
        such on the metric; the roofline's honesty discipline (every
        published number names its source). The `source="measured"`
        sibling (`maybe_measure_collective`) is what falsifies it."""
        n = self.n_data_shards
        if n <= 1:
            return 0.0
        from deeplearning4j_tpu.utils.flops import ici_bandwidth_per_chip

        wire = 2.0 * (n - 1) / n * self.grad_payload_bytes(net)
        return wire / ici_bandwidth_per_chip()

    def _collective_probe(self, net):
        """A jitted reduction-only program with the live bucket schedule:
        one data-sharded input per bucket, summed over the sharded dim
        into a replicated result — GSPMD lowers that to exactly the
        cross-device all-reduce the train step's bucket runs, on the
        same backend/interconnect. Built (and warmed) once; the staged
        zero inputs stay resident so a sample is one dispatch."""
        if self._probe is None:
            jax = _jax()
            import jax.numpy as jnp

            cplan = self.collective_plan(net)
            if cplan is not None and cplan.buckets:
                shapes = [(b["bytes"] // max(1, _np_dtype(b["dtype"],
                                                          cplan.grad_dtype).itemsize),
                           _np_dtype(b["dtype"], cplan.grad_dtype))
                          for b in cplan.buckets]
            else:
                bf16 = self.grad_dtype == "bf16"
                dt = np.dtype("float32") if not bf16 else _np_dtype(
                    "float32", "bf16")
                shapes = [(self.grad_payload_bytes(net) // dt.itemsize, dt)]
            n = self.n_data_shards
            rep = self.replicated

            def probe(*bufs):
                return tuple(
                    jax.lax.with_sharding_constraint(b.sum(axis=0), rep)
                    for b in bufs)

            fn = jax.jit(probe, in_shardings=(self.batch,) * len(shapes))
            args = tuple(
                jax.device_put(jnp.zeros((n, max(1, int(elems))), dtype=dt),
                               self.batch)
                for elems, dt in shapes)
            jax.block_until_ready(fn(*args))  # warm: exclude compile time
            self._probe = (fn, args)
        return self._probe

    def maybe_measure_collective(self, net, n_steps: int,
                                 sample_every: int) -> Optional[float]:
        """Sampled MEASUREMENT of the collective cost, devprof-style:
        every `sample_every`-th sharded step, time one blocking dispatch
        of the reduction-only probe and attribute it to every step since
        the last sample. Returns the attributed seconds (probe wall time
        x steps covered) or None off-sample. `sample_every=0` disables —
        the same knob that keeps devprof's blocking reads out of tier-1."""
        if self.n_data_shards <= 1 or not sample_every:
            return None
        self._probe_steps += int(n_steps)
        if self._probe_steps < sample_every:
            return None
        covered, self._probe_steps = self._probe_steps, 0
        jax = _jax()
        fn, args = self._collective_probe(net)
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        return (time.perf_counter() - t0) * covered

    def describe(self) -> dict:
        return {
            "devices": int(self.mesh.devices.size),
            "axes": {name: int(self.mesh.shape[name])
                     for name in self.mesh.axis_names},
            "data_shards": self.n_data_shards,
        }

    def collective_describe(self, net) -> dict:
        """The chosen collective schedule, for `cli doctor` and the
        bench artifact: bucket count/sizes, wire dtype and bytes, and
        the ring estimate they imply."""
        cplan = self.collective_plan(net)
        out = {
            "mode": "monolithic" if cplan is None else "bucketed",
            "grad_dtype": self.grad_dtype,
            "wire_bytes_per_step": self.grad_payload_bytes(net),
            "ring_estimate_seconds": round(
                self.collective_seconds_estimate(net), 6),
        }
        if cplan is not None:
            out.update(cplan.describe())
        return out


def _np_dtype(name: str, grad_dtype: str) -> np.dtype:
    """Wire dtype of a bucket for the measured-collective probe: bf16
    wire (or bf16 param leaves) uses ml_dtypes' bfloat16 when importable
    (jax ships it), else f16 — SAME byte width, so the probe payload
    stays honest even without the exact dtype."""
    if grad_dtype == "bf16" or name == "bfloat16":
        try:
            import ml_dtypes

            return np.dtype(ml_dtypes.bfloat16)
        except Exception:
            return np.dtype("float16")
    try:
        return np.dtype(name)
    except TypeError:
        return np.dtype("float32")
