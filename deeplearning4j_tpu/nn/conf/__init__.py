"""Configuration DSL.

Analog of the reference's nn/conf package: a declarative, JSON-serializable
description of a network (NeuralNetConfiguration.java, 1,189 LoC;
MultiLayerConfiguration.java; layer configs in nn/conf/layers/). The JSON
form is the persistence/compat surface, exactly as in the reference
(SURVEY.md §5 "Config/flag system").
"""

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    AutoEncoder,
    BatchNormalization,
    CenterLossOutputLayer,
    Convolution1DLayer,
    ConvolutionLayer,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    GravesBidirectionalLSTM,
    GravesLSTM,
    LocalResponseNormalization,
    LossLayer,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    Subsampling1DLayer,
    SubsamplingLayer,
    VariationalAutoencoder,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.conf.network import (
    BackpropType,
    GradientNormalization,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    Updater,
)
from deeplearning4j_tpu.nn.conf.graph import (
    ComputationGraphConfiguration,
    DuplicateToTimeSeriesVertex,
    ElementWiseVertex,
    GraphBuilder,
    L2NormalizeVertex,
    L2Vertex,
    LastTimeStepVertex,
    LayerVertex,
    MergeVertex,
    PreprocessorVertex,
    ReshapeVertex,
    ScaleVertex,
    ShiftVertex,
    StackVertex,
    SubsetVertex,
    UnstackVertex,
)
from deeplearning4j_tpu.nn.conf.serde import config_from_dict, config_to_dict
