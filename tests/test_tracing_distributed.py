"""Distributed-tracing tests: trace identity (roots mint, children
inherit), W3C traceparent round-trips, explicit-context thread handoff
(attach/detach), the serving request lifecycle across the collector/
dispatcher threads, cross-process propagation through a subprocess
paramserver, histogram exemplars resolving to traces via the
critical-path analyzer, trace ids in JSON logs and flight-recorder
events, and the <10µs disabled-path guard extended to the context-
propagation hooks."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import deeplearning4j_tpu as dl4j
from deeplearning4j_tpu.analysis import tracecrit
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils import metrics as metrics_mod
from deeplearning4j_tpu.utils import tracing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _tracing_off_after():
    """Tracing is process-global state; never leak an enabled tracer (or
    a dirty span buffer) into other tests."""
    yield
    tracing.enable(False)
    tracing.get_tracer().clear()


def _mlp_conf(seed=7, n_in=12):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Updater.SGD)
        .learning_rate(0.05)
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_in=n_in, n_out=16, activation="tanh"))
        .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                           loss="mcxent"))
        .build()
    )


def _spans():
    return tracing.get_tracer().recent()


def _chain_names(evs, leaf):
    """Span names from `leaf` up to its root via parent links."""
    by_id = {e["id"]: e for e in evs}
    names, cur = [], leaf
    while cur is not None:
        names.append(cur["name"])
        cur = by_id.get(cur.get("parent"))
    return names


# -- trace identity + context objects -----------------------------------------

def test_root_mints_trace_children_inherit():
    tracing.get_tracer().clear()
    tracing.enable(True)
    with tracing.span("outer") as outer:
        with tracing.span("inner"):
            tracing.instant("marker")
    with tracing.span("other_root"):
        pass
    evs = _spans()
    by_name = {e["name"]: e for e in evs}
    t = by_name["outer"]["trace"]
    assert t and len(t) == 32 and int(t, 16)  # 128-bit hex
    assert by_name["inner"]["trace"] == t
    assert by_name["marker"]["trace"] == t
    # a sibling root is a DIFFERENT trace
    assert by_name["other_root"]["trace"] != t
    # the span's context survives the with-block (exemplar linkage)
    assert outer.context.trace_id == t


def test_traceparent_roundtrip_and_malformed():
    ctx = tracing.SpanContext("ab" * 16, 12345)
    tp = tracing.format_traceparent(ctx)
    assert tp == f"00-{'ab' * 16}-0000000000003039-01"
    back = tracing.parse_traceparent(tp)
    assert back.trace_id == ctx.trace_id and back.span_id == 12345
    for bad in (None, "", "garbage", "00-short-0000000000003039-01",
                "00-" + "0" * 32 + "-0000000000003039-01",  # zero trace
                "00-" + "ab" * 16 + "-0000000000000000-01",  # zero span
                "ff-" + "ab" * 16 + "-0000000000003039-01",  # bad version
                "00-" + "zz" * 16 + "-0000000000003039-01",  # non-hex
                # int(x, 16) traps: signs / underscores are NOT hex
                "00-" + "a" * 30 + "_1-0000000000003039-01",
                "+0-" + "ab" * 16 + "-0000000000003039-01",
                "00-" + "ab" * 16 + "-+000000000003039-01",
                # version 00 is exactly 4 fields
                "00-" + "ab" * 16 + "-0000000000003039-01-extra"):
        assert tracing.parse_traceparent(bad) is None, bad
    # a FUTURE version may carry extra fields — still parses
    fut = tracing.parse_traceparent(
        "01-" + "ab" * 16 + "-0000000000003039-01-extra")
    assert fut is not None and fut.span_id == 12345


def test_attach_keeps_parentage_across_threads():
    tracing.get_tracer().clear()
    tracing.enable(True)
    with tracing.span("producer") as sp:
        ctx = sp.context

        def worker():
            tok = tracing.attach(ctx)
            try:
                with tracing.span("consumer"):
                    pass
                tracing.instant("consumer_marker")
            finally:
                tracing.detach(tok)
            # after detach the thread roots fresh traces again
            with tracing.span("detached_root"):
                pass

        t = threading.Thread(target=worker, daemon=True,
                             name="dl4j-test-trace-worker")
        t.start()
        t.join(10)
    evs = _spans()
    by_name = {e["name"]: e for e in evs}
    assert by_name["consumer"]["parent"] == ctx.span_id
    assert by_name["consumer"]["trace"] == ctx.trace_id
    assert by_name["consumer_marker"]["trace"] == ctx.trace_id
    assert by_name["detached_root"]["trace"] != ctx.trace_id
    assert by_name["detached_root"]["parent"] is None


def test_disabled_path_overhead_under_10us():
    """The overhead contract extended to context propagation: with
    tracing OFF, span creation AND every propagation hook are a flag
    check — pinned well under 10µs/call (the devprof on_step bound)."""
    assert not tracing.is_enabled()
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        tracing.span("hot/span")
    per_span = (time.perf_counter() - t0) / n
    t0 = time.perf_counter()
    for _ in range(n):
        tracing.current_context()
        tracing.current_traceparent()
    per_ctx = (time.perf_counter() - t0) / n
    t0 = time.perf_counter()
    for _ in range(n):
        tracing.detach(tracing.attach(None))
        tracing.record_complete("x", 0.0, 0.0)
    per_hop = (time.perf_counter() - t0) / n
    assert per_span < 10e-6, f"span() cost {per_span * 1e6:.2f}us"
    assert per_ctx < 10e-6, f"context reads cost {per_ctx * 1e6:.2f}us"
    assert per_hop < 10e-6, f"attach/record cost {per_hop * 1e6:.2f}us"


# -- serving lifecycle across pipeline threads --------------------------------

def test_fused_group_dispatch_parents_to_admission():
    """The cross-thread orphaning fix, pinned: a fused group's dispatch
    span (completed on the dispatcher thread) parents to each member
    request's admission span through the explicit-context handoff at
    both queues — no more thread-local fresh roots."""
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    net = MultiLayerNetwork(_mlp_conf()).init()
    pi = ParallelInference(net, max_batch_size=2, buckets=[2],
                           batch_timeout_ms=500.0,
                           component_prefix="trace_fuse")
    try:
        pi.warmup((12,))
        tracing.get_tracer().clear()
        tracing.enable(True)
        errs = []

        def call(i):
            try:
                with tracing.span(f"client{i}"):
                    pi.output(np.zeros((1, 12), np.float32))
            except Exception as e:  # pragma: no cover - failure report
                errs.append(e)

        threads = [threading.Thread(target=call, args=(i,), daemon=True,
                                    name=f"dl4j-test-fuse-{i}")
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errs
        assert pi.metrics()["batches"] == 1, "requests did not fuse"
    finally:
        tracing.enable(False)
        pi.shutdown()
    evs = _spans()
    admissions = [e for e in evs if e["name"] == "serve/admission"]
    dispatches = [e for e in evs if e["name"] == "serve/dispatch"]
    forwards = [e for e in evs if e["name"] == "serve/forward"]
    queued = [e for e in evs if e["name"] == "serve/queued"]
    assert len(admissions) == 2
    assert len(dispatches) == 2  # one real + one fused copy
    assert len(forwards) == 2
    assert len(queued) == 2
    adm_ids = {e["id"] for e in admissions}
    # EVERY member's dispatch span parents to an admission span, and the
    # two dispatches cover both members' traces
    assert {d["parent"] for d in dispatches} == adm_ids
    assert ({d["trace"] for d in dispatches}
            == {a["trace"] for a in admissions})
    assert {q["parent"] for q in queued} == adm_ids
    disp_ids = {d["id"] for d in dispatches}
    assert {f["parent"] for f in forwards} == disp_ids
    # each client's trace is complete: client -> admission -> dispatch
    for d in dispatches:
        chain = _chain_names(evs, d)
        assert chain[1] == "serve/admission", chain
        assert chain[-1].startswith("client"), chain


def _http_json(port, path, payload=None, headers=None):
    import urllib.request

    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data,
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.read().decode()


def test_rest_request_yields_one_trace_with_full_lifecycle():
    """Acceptance: one /predict with tracing on -> a single trace whose
    span tree carries HTTP server, admission, queued, dispatch and
    device-forward spans in parent order across three threads; and a
    caller-provided traceparent makes that trace the CALLER's."""
    from deeplearning4j_tpu.serving import InferenceServer

    net = MultiLayerNetwork(_mlp_conf()).init()
    server = InferenceServer(net, port=0, warmup_shape=(12,))
    port = server.start()
    tracing.get_tracer().clear()
    tracing.enable(True)
    caller = tracing.SpanContext(os.urandom(16).hex(), 77)
    try:
        _http_json(port, "/predict",
                   {"features": np.zeros((2, 12)).tolist()},
                   headers={"traceparent": caller.traceparent()})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not any(
                e["name"] == "serve/forward"
                for e in _spans()):
            time.sleep(0.05)
    finally:
        tracing.enable(False)
        server.stop()
    evs = _spans()
    fw = [e for e in evs if e["name"] == "serve/forward"]
    assert fw, "no device-forward span recorded"
    chain = _chain_names(evs, fw[0])
    assert chain == ["serve/forward", "serve/dispatch", "serve/admission",
                     "serve/predict", "http/server"]
    lifecycle = [e for e in evs
                 if e["name"].startswith(("serve/", "http/"))]
    traces = {e["trace"] for e in lifecycle}
    assert traces == {caller.trace_id}, \
        "request spans split across traces (or ignored the traceparent)"
    # the queued span is in the same trace, parented at admission
    queued = [e for e in evs if e["name"] == "serve/queued"]
    adm = next(e for e in evs if e["name"] == "serve/admission")
    assert queued and queued[0]["parent"] == adm["id"]
    # the http/server root joined the CALLER's span id
    http = next(e for e in evs if e["name"] == "http/server")
    assert http["parent"] == caller.span_id


def test_no_header_request_gets_fresh_root():
    """A request without (or with a malformed) traceparent must root a
    complete fresh trace — never a half-empty context."""
    from deeplearning4j_tpu.utils.jsonhttp import (
        JsonHttpServer,
        json_response,
    )

    server = JsonHttpServer(get=lambda p, b, h: json_response({"ok": 1}))
    port = server.start()
    tracing.get_tracer().clear()
    tracing.enable(True)
    try:
        _http_json(port, "/x")
        _http_json(port, "/y", headers={"traceparent": "garbage-header"})
    finally:
        tracing.enable(False)
        server.stop()
    https = [e for e in _spans() if e["name"] == "http/server"]
    assert len(https) == 2
    for e in https:
        assert e["parent"] is None
        assert e["trace"] and len(e["trace"]) == 32
    assert https[0]["trace"] != https[1]["trace"]


# -- exemplars -> cli trace (the scrape-to-trace link) ------------------------

def test_exemplar_resolves_to_trace_critical_path():
    """Acceptance: a latency-histogram exemplar from GET /metrics names a
    trace_id; pulling GET /trace and running the critical-path analyzer
    on that id yields a complete trace whose critical-path sum is within
    tolerance of the recorded request latency."""
    from deeplearning4j_tpu.serving import InferenceServer

    # fresh latency family: earlier traced tests in this process may have
    # pinned bucket exemplars whose (still-young) traces were cleared
    # from the span ring — this test asserts the fresh-request link
    metrics_mod.get_registry().unregister("serving_request_seconds")
    net = MultiLayerNetwork(_mlp_conf(seed=23)).init()
    server = InferenceServer(net, port=0, warmup_shape=(12,))
    port = server.start()
    tracing.get_tracer().clear()
    tracing.enable(True)
    try:
        _http_json(port, "/predict",
                   {"features": np.zeros((3, 12)).tolist()})
        metrics = json.loads(_http_json(port, "/metrics"))
        exemplars = metrics["latency_ms"]["exemplars"]
        assert exemplars, "no latency exemplar after a traced request"
        trace_text = _http_json(port, "/trace")
    finally:
        tracing.enable(False)
        server.stop()
    events = tracecrit.parse_jsonl(trace_text)
    exported = {e.get("trace") for e in events}
    ex = next(e for e in exemplars if e["trace_id"] in exported)
    report = tracecrit.analyze(events, trace_id=ex["trace_id"])
    assert len(report["traces"]) == 1
    tr = report["traces"][0]
    names = {s["name"] for s in tr["critical_path"]}
    assert "http/server" in names and "serve/forward" in names
    crit_s = tr["critical_path_us"] / 1e6
    latency_s = ex["value_ms"] / 1e3  # latency_ms fields are all ms
    # the critical path covers the http/server root, which brackets the
    # measured /predict latency; tolerance absorbs handler overhead and
    # a loaded 2-core CI box
    assert abs(crit_s - latency_s) <= max(0.15, 0.5 * latency_s), \
        (crit_s, latency_s)


def test_exemplars_bounded_one_per_bucket_and_trace_gated():
    reg = metrics_mod.MetricsRegistry()
    h = reg.histogram("x_seconds", buckets=(0.01, 0.1, 1.0)).labels()
    # no trace, no tracing -> no exemplars, ever
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    assert h.exemplars() == []
    # explicit trace ids: bounded at one (max-value) exemplar per bucket
    for i in range(50):
        h.observe(0.001 * (i + 1), trace_id=f"t{i}")
        h.observe(0.02 * (i + 1), trace_id=f"u{i}")
    h.observe(5.0, trace_id="overflow")
    ex = h.exemplars()
    assert len(ex) <= 4  # 3 bounds + the +Inf bucket
    by_le = {e["le"]: e for e in ex}
    assert by_le[0.01]["trace_id"] == "t9"  # 0.010 is the bucket max
    assert by_le[1.0]["trace_id"] == "u49"  # 0.02*50 = 1.0, le semantics
    assert by_le["+Inf"]["trace_id"] == "overflow"
    # snapshot carries them, strict-JSON safe
    snap = reg.snapshot()
    json.dumps(snap, allow_nan=False)
    assert snap["x_seconds"]["values"][0]["exemplars"] == ex


# -- cross-process propagation (paramserver) ----------------------------------

def test_paramserver_pull_joins_trace_across_process(tmp_path):
    """Acceptance satellite: the client's traceparent shows up as the
    subprocess server's route-span parentage in its exported JSONL."""
    from deeplearning4j_tpu.parallel.paramserver import EmbeddingPSClient

    child_out = str(tmp_path / "child_spans.jsonl")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("T1_BLACKBOX_ARTIFACT", None)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "tracing_ps_child.py"),
         child_out],
        env=env, cwd=REPO, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    client = None
    try:
        line = proc.stdout.readline()
        assert line.startswith("PORT "), \
            f"child failed to start: {line!r} / {proc.stderr.read()[:2000]}"
        port = int(line.split()[1])
        tracing.get_tracer().clear()
        tracing.enable(True)
        client = EmbeddingPSClient([f"http://127.0.0.1:{port}"])
        with tracing.span("test/pull") as sp:
            got = client.pull("syn0", np.array([1, 3]))
            parent_trace = sp.context.trace_id
        assert got.shape == (2, 4)
        tracing.enable(False)
        proc.stdin.write("done\n")
        proc.stdin.flush()
        assert "DUMPED" in (proc.stdout.readline() or "")
        proc.wait(timeout=30)
    finally:
        if client is not None:
            client.close()
        if proc.poll() is None:
            proc.kill()
    # client side: test/pull -> ps/client/pull.bin in one trace
    local = _spans()
    ps_client = next(e for e in local if e["name"] == "ps/client/pull.bin")
    assert ps_client["trace"] == parent_trace
    # server side (OTHER PROCESS): http/server joined the client's trace,
    # parented to the client RPC span; the route span nests inside
    with open(child_out) as f:
        remote = tracecrit.parse_jsonl(f.read())
    http = [e for e in remote if e["name"] == "http/server"]
    assert http and http[0]["trace"] == parent_trace
    assert http[0]["parent"] == ps_client["id"]
    route = [e for e in remote if e["name"] == "ps/server/pull.bin"]
    assert route and route[0]["trace"] == parent_trace
    assert route[0]["parent"] == http[0]["id"]


def test_parked_push_replays_under_its_own_trace():
    """A push parked during an endpoint outage must deliver under the
    trace that PRODUCED it, not under whatever newer item happened to be
    draining when the endpoint recovered — the per-record context on the
    replay buffer."""
    import socket

    from deeplearning4j_tpu.parallel.paramserver import (
        EmbeddingParameterServer,
        EmbeddingPSClient,
    )

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    tracing.get_tracer().clear()
    tracing.enable(True)
    client = EmbeddingPSClient([f"http://127.0.0.1:{port}"],
                               timeout=2.0, max_retries=0,
                               retry_backoff=0.01)
    server = None
    try:
        with tracing.span("producer_a") as spa:
            client.push_async("syn0", np.array([1]),
                              np.ones((1, 4), np.float32))
        # let the drain attempt + park it against the dead endpoint
        deadline = time.monotonic() + 10
        while client.pending_pushes() == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert client.pending_pushes() == 1, "push A never parked"
        server = EmbeddingParameterServer(
            {"syn0": np.zeros((8, 4), np.float32)}, port=port)
        server.start()
        with tracing.span("producer_b") as spb:
            client.push_async("syn0", np.array([2]),
                              np.ones((1, 4), np.float32))
        client.flush()
        deadline = time.monotonic() + 10
        while server.pushes_applied < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert server.pushes_applied == 2
    finally:
        tracing.enable(False)
        client.close()
        if server is not None:
            server.stop()
    pushes = [e for e in _spans() if e["name"] == "ps/client/push.bin"]
    traces = {e["trace"] for e in pushes}
    # A's replay reported under A's trace, B's under B's — both present
    assert spa.context.trace_id in traces, "parked push lost its trace"
    assert spb.context.trace_id in traces


# -- satellites: logs, blackbox, analyzer, cli --------------------------------

def test_json_logs_carry_trace_and_span_ids():
    import io
    import logging

    buf = io.StringIO()
    lg = dl4j.configure_logging(level=logging.INFO, json_lines=True,
                                stream=buf)
    try:
        tracing.enable(True)
        with tracing.span("logged") as sp:
            lg.info("inside span")
            ctx = sp.context
        tracing.enable(False)
        lg.info("outside span")
        recs = [json.loads(l) for l in buf.getvalue().strip().splitlines()]
        assert recs[0]["trace_id"] == ctx.trace_id
        assert recs[0]["span_id"] == format(ctx.span_id, "016x")
        assert recs[1]["trace_id"] == "" and recs[1]["span_id"] == ""
    finally:
        for h in list(lg.handlers):
            if getattr(h, "_dl4j_tpu_configured", False):
                lg.removeHandler(h)


def test_blackbox_event_carries_trace_id_and_renders(capsys):
    from deeplearning4j_tpu.utils.blackbox import FlightRecorder, render_dump

    rec = FlightRecorder()
    tracing.enable(True)
    with tracing.span("crashy_request") as sp:
        rec.record_event("replica_evicted", replica=1, reason="test")
        tid = sp.context.trace_id
    tracing.enable(False)
    rec.record_event("untraced_event")
    snap = rec.snapshot(reason="test")
    evs = {e["kind"]: e for e in snap["events"]}
    assert evs["replica_evicted"]["trace_id"] == tid
    assert "trace_id" not in evs["untraced_event"]
    out = render_dump(snap)
    assert f"[trace {tid}]" in out


def test_tracecrit_critical_path_synthetic():
    t = "ab" * 16
    events = [
        {"name": "root", "ph": "X", "ts": 0.0, "dur": 100.0, "id": 1,
         "parent": None, "trace": t, "tid": 1},
        {"name": "early", "ph": "X", "ts": 0.0, "dur": 40.0, "id": 2,
         "parent": 1, "trace": t, "tid": 1},
        {"name": "late", "ph": "X", "ts": 50.0, "dur": 45.0, "id": 3,
         "parent": 1, "trace": t, "tid": 2},
        {"name": "shadowed", "ph": "X", "ts": 52.0, "dur": 10.0, "id": 4,
         "parent": 1, "trace": t, "tid": 3},  # inside `late`'s window
        {"name": "leaf", "ph": "X", "ts": 60.0, "dur": 20.0, "id": 5,
         "parent": 3, "trace": t, "tid": 2},
    ]
    report = tracecrit.analyze(events)
    assert report["n_traces"] == 1
    tr = report["traces"][0]
    path = [s["name"] for s in tr["critical_path"]]
    # the chain walks backward from root's end: late (ends 95) then
    # early (ends 40 <= late's start 50); `shadowed` overlaps late and
    # never gates the end — it must not appear
    assert path == ["root", "early", "late", "leaf"]
    assert "shadowed" not in path
    by_name = {s["name"]: s for s in tr["critical_path"]}
    assert by_name["root"]["self_us"] == pytest.approx(15.0, abs=0.1)
    assert by_name["late"]["self_us"] == pytest.approx(25.0, abs=0.1)
    assert tr["critical_path_us"] == pytest.approx(100.0, abs=0.5)


def test_cli_trace_renders_file_export(tmp_path, capsys):
    from deeplearning4j_tpu.cli import main

    tracing.get_tracer().clear()
    tracing.enable(True)
    with tracing.span("outer"):
        with tracing.span("inner"):
            pass
    tracing.enable(False)
    path = str(tmp_path / "spans.jsonl")
    tracing.get_tracer().write_jsonl(path)
    assert main(["trace", path]) == 0
    out = capsys.readouterr().out
    assert "critical path" in out and "outer" in out
    # --trace-id prefix resolution + --json round-trip
    tid = _spans()[0]["trace"]
    assert main(["trace", path, "--trace-id", tid[:12], "--json", "-"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["traces"][0]["trace_id"] == tid
    # a missing id is a nonzero exit (scriptable resolution check)
    assert main(["trace", path, "--trace-id", "f" * 32]) == 1


def test_cli_chaos_trace_out_links_faults_to_requests(tmp_path, capsys):
    """The serving chaos preset under --trace-out: the run's span export
    is written, and every injected fault's marker sits inside a request
    trace that also carries the serve/* lifecycle spans."""
    from deeplearning4j_tpu.cli import main

    plan_path = str(tmp_path / "plan.json")
    with open(plan_path, "w") as f:
        json.dump({"seed": 5, "rules": [
            {"point": "replica_forward", "kind": "latency",
             "every_nth": 2, "latency_ms": 5.0}]}, f)
    trace_path = str(tmp_path / "chaos_spans.jsonl")
    rc = main(["chaos", "--preset", "serving", "--plan", plan_path,
               "--requests", "12", "--clients", "2",
               "--trace-out", trace_path, "--json", "-"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0, report
    assert not tracing.is_enabled()  # restored after the run
    tr = report["trace"]
    assert tr["path"] == trace_path and os.path.exists(trace_path)
    assert tr["fault_spans"] >= 1, "plan fired no faults"
    assert tr["fault_trace_ok"] is True
    assert tr["fault_spans_linked"] == tr["fault_spans"]
    with open(trace_path) as f:
        events = tracecrit.parse_jsonl(f.read())
    assert any(e["name"] == "fault/injected" for e in events)


def test_device_prefetch_stage_joins_iterating_trace():
    """The prefetch thread handoff keeps parentage: staging spans from
    the background device-prefetch worker land in the trace that is
    consuming the iterator, not in fresh per-worker roots."""
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator
    from deeplearning4j_tpu.data.prefetch import DevicePrefetchIterator

    rng = np.random.default_rng(0)
    sets = [DataSet(rng.standard_normal((4, 3)).astype(np.float32),
                    np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)])
            for _ in range(3)]
    base = ExistingDataSetIterator(sets)
    tracing.get_tracer().clear()
    tracing.enable(True)
    it = DevicePrefetchIterator(base, depth=1,
                                stage="trace_test_prefetch")
    try:
        with tracing.span("epoch") as sp:
            n = sum(1 for _ in it)
        assert n == 3
    finally:
        tracing.enable(False)
        it.close()
    stages = [e for e in _spans() if e["name"] == "prefetch/stage"]
    assert len(stages) == 3
    assert {e["trace"] for e in stages} == {sp.context.trace_id}
    assert {e["parent"] for e in stages} == {sp.context.span_id}
