"""Native C++ corpus pipeline (native/corpus.cpp via ctypes): vocab +
indexing parity with the Python VocabConstructor, and end-to-end word2vec
training through fit_file."""

import numpy as np
import pytest

from deeplearning4j_tpu import native
from deeplearning4j_tpu.nlp.vocab import VocabConstructor

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="no C++ toolchain")

_TEXT = """the quick brown fox jumps over the lazy dog
the dog barks at the fox
a quick fox and a lazy dog
the end
"""


@pytest.fixture
def corpus_file(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text(_TEXT)
    return str(p)


def test_vocab_matches_python_constructor(corpus_file):
    with native.NativeCorpus(corpus_file) as c:
        assert c.num_sentences == 4
        assert c.total_tokens == len(_TEXT.split())
        words, counts = c.vocab(min_count=1)
    py_vocab = VocabConstructor(1).build(
        [line.split() for line in _TEXT.strip().split("\n")])
    py_words = [py_vocab.word_at_index(i)
                for i in range(py_vocab.num_words())]
    py_counts = py_vocab.counts()
    assert words == py_words
    np.testing.assert_array_equal(counts, py_counts)


def test_min_count_filter_and_indexing(corpus_file):
    with native.NativeCorpus(corpus_file) as c:
        words, counts = c.vocab(min_count=2)
        assert all(cc >= 2 for cc in counts)
        sents = c.indexed_sentences(min_count=2)
        words1, _ = c.vocab(min_count=1)
        sents1 = c.indexed_sentences(min_count=1)
    # sentence 1 indexed against the full vocab round-trips to its text
    decoded = [words1[i] for i in sents1[0]]
    assert decoded == "the quick brown fox jumps over the lazy dog".split()
    # with min_count=2: rare words dropped, ids within filtered vocab
    assert all(int(s.max()) < len(words) for s in sents if s.size)
    flat = [words[i] for s in sents for i in s]
    assert "barks" not in flat and "the" in flat


def test_word2vec_fit_file(corpus_file, tmp_path):
    """fit_file trains through the native pipeline and produces usable
    vectors."""
    from deeplearning4j_tpu.nlp.sequencevectors import (
        SequenceVectors,
        VectorsConfiguration,
    )

    # a bigger synthetic corpus so training has signal
    rng = np.random.default_rng(0)
    words_a = [f"a{i}" for i in range(10)]
    words_b = [f"b{i}" for i in range(10)]
    lines = []
    for _ in range(300):
        pool = words_a if rng.random() < 0.5 else words_b
        lines.append(" ".join(rng.choice(pool, size=8)))
    big = tmp_path / "big.txt"
    big.write_text("\n".join(lines) + "\n")

    conf = VectorsConfiguration(layer_size=24, window=3,
                                min_word_frequency=1, epochs=3,
                                negative=4, use_hierarchic_softmax=False,
                                batch_size=512, seed=1)
    sv = SequenceVectors(conf)
    sv.fit_file(str(big))
    # words co-occurring within a pool are closer than across pools
    intra = sv.similarity("a1", "a2")
    inter = sv.similarity("a1", "b2")
    assert intra > inter, (intra, inter)
