"""GravesLSTM character-level RNN — the BASELINE.md "char-rnn tokens/sec"
workload (reference: dl4j-examples GravesLSTMCharModellingExample — two
GravesLSTM layers + RnnOutputLayer(MCXENT), TBPTT; LSTM kernel
nn/layers/recurrent/LSTMHelpers.java:62,291)."""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf import (
    BackpropType,
    GravesLSTM,
    InputType,
    NeuralNetConfiguration,
    RnnOutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def char_lstm_conf(vocab_size: int = 77, hidden: int = 200, layers: int = 2,
                   tbptt_length: int = 50, seed: int = 12345,
                   learning_rate: float = 0.1, precision: str = "f32"):
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Updater.RMSPROP)
        .rms_decay(0.95)
        .learning_rate(learning_rate)
        .weight_init("xavier")
        .precision(precision)
        .list()
    )
    for _ in range(layers):
        b = b.layer(GravesLSTM(n_out=hidden, activation="tanh"))
    return (
        b.layer(RnnOutputLayer(n_out=vocab_size, activation="softmax",
                               loss="mcxent"))
        .backprop_type(BackpropType.TRUNCATED_BPTT)
        .t_bptt_lengths(tbptt_length)
        .set_input_type(InputType.recurrent(vocab_size))
        .build()
    )


def char_lstm_network(vocab_size: int = 77, hidden: int = 200, layers: int = 2,
                      tbptt_length: int = 50, precision: str = "f32",
                      **kw) -> MultiLayerNetwork:
    return MultiLayerNetwork(
        char_lstm_conf(vocab_size, hidden, layers, tbptt_length,
                       precision=precision, **kw)
    ).init()
