"""Concurrency audit tests: the runtime lock-order sanitizer
(utils/locktrace), the merged static+runtime audit
(analysis/concurrency_audit), deadlock forensics in blackbox dumps, and
the lexical CC005/CN002 extensions in analysis/lint.

The off-path contract is load-bearing: with DL4J_LOCKCHECK unset the
whole subsystem must cost one module-global read, so the pins here are
the same 10µs/call bar the metering and ledger hooks carry."""

import os
import queue
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.analysis import concurrency_audit as ca
from deeplearning4j_tpu.analysis import lint
from deeplearning4j_tpu.utils import locktrace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(lint.__file__)))
REPO = os.path.dirname(REPO)
# a tiny, lock-free file for the static half: keeps report() fast in
# tests that only exercise the runtime graph
_SMALL_STATIC = [os.path.join(
    REPO, "deeplearning4j_tpu", "analysis", "findings.py")]


@pytest.fixture
def armed():
    """Arm the sanitizer for one test, restore the stdlib after."""
    was = locktrace.enabled()
    if not was:
        locktrace.install()
    locktrace.reset()
    try:
        yield
    finally:
        if not was:
            locktrace.uninstall()


# -- CN001: reversed acquisition order ----------------------------------------

def test_reversed_order_is_cn001_with_both_witness_stacks(armed):
    """The ISSUE fixture: two threads taking two locks in opposite
    orders — no real contention needed, the order graph alone convicts,
    and BOTH edges carry a stack witness naming this file."""
    a = locktrace.traced_lock("fixA")
    b = locktrace.traced_lock("fixB")

    def forward():
        with a:
            with b:
                pass

    def backward():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=forward, name="dl4j-lockfix-1")
    t1.start()
    t1.join(10)
    t2 = threading.Thread(target=backward, name="dl4j-lockfix-2")
    t2.start()
    t2.join(10)
    assert not t1.is_alive() and not t2.is_alive()

    snap = locktrace.snapshot()
    by_pair = {(e["src"], e["dst"]): e for e in snap["edges"]}
    assert ("fixA", "fixB") in by_pair and ("fixB", "fixA") in by_pair
    for pair in (("fixA", "fixB"), ("fixB", "fixA")):
        witness = by_pair[pair]["witness"]
        assert witness, f"edge {pair} has no witness stack"
        assert any("test_concurrency_audit" in fr for fr in witness)

    doc = ca.report(runtime=True, paths=_SMALL_STATIC, base_dir=REPO)
    cn1 = [f for f in doc["findings"] if f.code == "CN001"
           and "fixA" in f.name and "fixB" in f.name]
    assert len(cn1) == 1
    msg = cn1[0].message
    assert msg.count("witness:") == 2, msg
    assert "test_concurrency_audit" in msg
    assert "[runtime]" in msg
    assert cn1[0].name == "CN001:fixA->fixB"


def test_consistent_order_is_clean(armed):
    a = locktrace.traced_lock("okA")
    b = locktrace.traced_lock("okB")
    for _ in range(3):
        with a:
            with b:
                pass
    doc = ca.report(runtime=True, paths=_SMALL_STATIC, base_dir=REPO)
    assert not [f for f in doc["findings"] if f.code == "CN001"]
    assert any(e["src"] == "okA" and e["dst"] == "okB"
               for e in doc["edges"])


# -- deadlock forensics: the real wedge ---------------------------------------

def test_real_wedge_forensics_named_and_rendered(armed, tmp_path, capsys):
    """The same fixture wedged for REAL (bounded by acquire timeouts so
    the threads always exit): the live wait-graph names the cycle, the
    watchdog's degradation hook captures it, and `cli blackbox` renders
    the DEADLOCK CYCLE section from the dump."""
    a = locktrace.traced_lock("wedgeA")
    b = locktrace.traced_lock("wedgeB")
    a_held = threading.Event()
    b_held = threading.Event()

    def holder_a():
        with a:
            a_held.set()
            b_held.wait(5)
            if b.acquire(timeout=6):
                b.release()

    def holder_b():
        with b:
            b_held.set()
            a_held.wait(5)
            if a.acquire(timeout=6):
                a.release()

    t1 = threading.Thread(target=holder_a, name="dl4j-wedge-1")
    t2 = threading.Thread(target=holder_b, name="dl4j-wedge-2")
    t1.start()
    t2.start()

    cycle = None
    deadline = time.monotonic() + 5.0
    try:
        while time.monotonic() < deadline:
            fx = locktrace.forensics()
            if fx and fx["deadlock_cycles"]:
                cycle = fx["deadlock_cycles"][0]
                break
            time.sleep(0.02)
        assert cycle is not None, "wait-graph never showed the cycle"
        names = {e["thread"] for e in cycle}
        assert names == {"dl4j-wedge-1", "dl4j-wedge-2"}
        for e in cycle:
            assert e["waits_for"] in ("wedgeA", "wedgeB")
            assert e["held_by"] in names

        # the watchdog's first-stall hook sees the same forensics
        from deeplearning4j_tpu.utils import blackbox

        rec = blackbox.get_recorder()
        rec.on_degradation("lock-fixture", 1.0, ["dl4j-wedge-1"])
        assert rec.last_degradation["locks"]["deadlock_cycles"]

        dump = str(tmp_path / "wedge_dump.json")
        rec.dump(dump, reason="deadlock fixture")
    finally:
        t1.join(15)
        t2.join(15)
    assert not t1.is_alive() and not t2.is_alive()

    from deeplearning4j_tpu.cli import main as cli_main

    assert cli_main(["blackbox", dump]) == 0
    out = capsys.readouterr().out
    assert "DEADLOCK CYCLE" in out
    assert "dl4j-wedge-1" in out and "dl4j-wedge-2" in out
    assert "waits for" in out and "held by" in out


# -- CN002/CN003: runtime probes ----------------------------------------------

def test_blocking_probes_fire_under_lock_only(armed):
    lk = locktrace.traced_lock("probeL")
    # no lock held: probes stay silent
    time.sleep(0.001)
    q = queue.Queue()
    q.put(1)
    q.get()
    assert locktrace.snapshot()["blocking"] == []

    with lk:
        time.sleep(0.001)
        with pytest.raises(queue.Empty):
            q.get(timeout=0.01)
        q.put(2)
        locktrace.note_blocking("custom.rpc")
        locktrace.note_dispatch("fixture/step")
    snap = locktrace.snapshot()
    kinds = {b["kind"] for b in snap["blocking"]}
    assert {"time.sleep", "queue.get", "queue.put", "custom.rpc"} <= kinds
    for b in snap["blocking"]:
        assert "probeL" in b["held"]
    assert snap["dispatch"] and snap["dispatch"][0]["what"] == "fixture/step"
    assert "probeL" in snap["dispatch"][0]["held"]

    doc = ca.report(runtime=True, paths=_SMALL_STATIC, base_dir=REPO)
    names = ca.finding_names(doc)
    assert any(n.startswith("CN002:time.sleep:") for n in names)
    assert any(n.startswith("CN003:fixture/step:") for n in names)


def test_condition_wait_exempts_own_lock(armed):
    """`with cond: cond.wait()` is THE pattern — no finding. The same
    wait with ANOTHER traced lock still held is CN002."""
    outer = locktrace.traced_lock("cvOuter")
    cond = threading.Condition()  # raw: constructed from tests/, unwrapped

    def waker():
        time.sleep(0.05)
        with cond:
            cond.notify_all()

    t = threading.Thread(target=waker, name="dl4j-cv-waker")
    t.start()
    with cond:
        cond.wait(2)
    t.join(10)
    assert all(b["kind"] != "condition.wait"
               for b in locktrace.snapshot()["blocking"])

    t = threading.Thread(target=waker, name="dl4j-cv-waker2")
    t.start()
    with outer:
        with cond:
            cond.wait(2)
    t.join(10)
    waits = [b for b in locktrace.snapshot()["blocking"]
             if b["kind"] == "condition.wait"]
    assert waits and "cvOuter" in waits[0]["held"]


# -- the off-path contract ----------------------------------------------------

def test_uninstrumented_paths_under_10us_per_call():
    assert not locktrace.enabled()
    assert threading.Lock is locktrace._ORIG["Lock"]
    assert time.sleep is locktrace._ORIG["sleep"]
    calls = 20_000
    lk = threading.Lock()

    def acquire_release():
        lk.acquire()
        lk.release()

    for fn in (acquire_release,
               lambda: locktrace.note_dispatch("off"),
               lambda: locktrace.note_blocking("off")):
        t0 = time.perf_counter()
        for _ in range(calls):
            fn()
        per_call = (time.perf_counter() - t0) / calls
        assert per_call < 10e-6, f"{fn}: {per_call * 1e6:.2f}µs/call"


def test_uninstall_restores_stdlib():
    locktrace.install()
    assert threading.Lock is not locktrace._ORIG["Lock"]
    traced = threading.Condition  # patched factory while armed
    assert traced is not locktrace._ORIG["Condition"]
    locktrace.uninstall()
    assert threading.Lock is locktrace._ORIG["Lock"]
    assert threading.RLock is locktrace._ORIG["RLock"]
    assert threading.Condition is locktrace._ORIG["Condition"]
    assert time.sleep is locktrace._ORIG["sleep"]
    assert queue.Queue.get is locktrace._ORIG["queue_get"]
    assert threading.Event.wait is locktrace._ORIG["event_wait"]
    with pytest.raises(RuntimeError):
        locktrace.traced_lock("late")


def test_lockcheck_on_fit_is_bit_identical():
    """Arming the sanitizer must not change training numerics: same
    seed, same data, same score with and without DL4J_LOCKCHECK."""
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
        Updater,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    rng = np.random.default_rng(3)
    x = rng.standard_normal((64, 4)).astype(np.float32)
    y = np.zeros((64, 2), np.float32)
    y[np.arange(64), (x.sum(axis=1) > 0).astype(int)] = 1

    def fit_once():
        conf = (NeuralNetConfiguration.builder()
                .seed(42).updater(Updater.SGD).learning_rate(0.1).list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
                .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(x, y, epochs=2, batch_size=32, async_prefetch=False)
        return net.score(x, y)

    baseline = fit_once()
    locktrace.install()
    try:
        checked = fit_once()
    finally:
        locktrace.uninstall()
    assert baseline == pytest.approx(checked, abs=1e-9)


# -- lexical half: CC005 call form + static CN002/CN003 -----------------------

_SRC_ACQUIRE_CYCLE = """\
import threading

class S:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()

    def one(self):
        self.a_lock.acquire()
        try:
            with self.b_lock:
                pass
        finally:
            self.a_lock.release()

    def two(self):
        self.b_lock.acquire()
        try:
            self.a_lock.acquire()
            try:
                pass
            finally:
                self.a_lock.release()
        finally:
            self.b_lock.release()
"""

_SRC_COND = """\
import threading

class W:
    def __init__(self):
        self.state_lock = threading.Lock()
        self.cv = threading.Condition()
        self._step_fn = None

    def bad_wait(self):
        with self.state_lock:
            with self.cv:
                self.cv.wait()

    def good_wait(self):
        with self.cv:
            self.cv.wait()

    def bad_dispatch(self, x):
        with self.state_lock:
            return self._step_fn(x)

    def bad_sleep(self):
        self.state_lock.acquire()
        try:
            import time
            time.sleep(1.0)
        finally:
            self.state_lock.release()
"""


def test_lint_acquire_release_form_feeds_cc005(tmp_path):
    """The PR's CC005 false-negative fix: reversed order expressed via
    acquire()/try/finally — invisible to the `with` pass before — is a
    lock-order cycle."""
    p = tmp_path / "mod_cycle.py"
    p.write_text(_SRC_ACQUIRE_CYCLE)
    findings = lint.lint_paths([str(p)], base_dir=str(tmp_path))
    cc5 = [f for f in findings if f.code == "CC005"]
    assert len(cc5) == 1
    assert "S.a_lock" in cc5[0].name and "S.b_lock" in cc5[0].name


def test_lint_static_cn002_cn003(tmp_path):
    p = tmp_path / "mod_cond.py"
    p.write_text(_SRC_COND)
    findings = lint.lint_paths([str(p)], base_dir=str(tmp_path))
    cn2 = [f for f in findings if f.code == "CN002"]
    # bad_wait (condition.wait with W.mu still held) + bad_sleep
    # (time.sleep inside the acquire/finally scope); good_wait exempt
    assert len(cn2) == 2
    msgs = " | ".join(f.message for f in cn2)
    assert "condition.wait" in msgs and "time.sleep" in msgs
    assert "W.state_lock" in msgs
    cn3 = [f for f in findings if f.code == "CN003"]
    assert len(cn3) == 1 and "_step_fn" in cn3[0].message
    # the construction sites were mapped to lexical keys for the join
    _, edges, ctor_sites = lint.collect([str(p)], base_dir=str(tmp_path))
    assert "W.state_lock" in ctor_sites.values() and "W.cv" in ctor_sites.values()


def test_merged_edges_origin_labels():
    static = {("S.a", "S.b"): "m.py:12", ("S.b", "S.c"): "m.py:20"}
    snap = {"enabled": True, "locks": {}, "blocking": [], "dispatch": [],
            "edges": [
                {"src": "m.py:5", "dst": "m.py:6", "count": 2,
                 "thread": "t0", "witness": ["m.py:12 in one"]},
                {"src": "m.py:6", "dst": "helper.py:9", "count": 1,
                 "thread": "t1", "witness": []},
            ]}
    ctor = {"m.py:5": "S.a", "m.py:6": "S.b"}
    merged = ca.merged_edges(static, snap, ctor)
    assert merged[("S.a", "S.b")]["origin"] == "both"
    assert merged[("S.a", "S.b")]["count"] == 2
    assert merged[("S.b", "helper.py:9")]["origin"] == "runtime"
    assert merged[("S.b", "S.c")]["origin"] == "static"


# -- gate semantics -----------------------------------------------------------

def test_baseline_gate_red_then_green(armed, tmp_path, capsys):
    lk = locktrace.traced_lock("gateL")
    with lk:
        time.sleep(0.001)
    doc = ca.report(runtime=True, paths=_SMALL_STATIC, base_dir=REPO)
    names = [n for n in ca.finding_names(doc) if n.startswith("CN002:")]
    assert names

    empty = tmp_path / "empty_baseline.txt"
    empty.write_text("# nothing allowed\n")
    rc = ca.main(["--quiet", "--baseline", str(empty)] + _SMALL_STATIC)
    assert rc == 1
    err = capsys.readouterr().err
    assert "LOCK AUDIT REGRESSIONS" in err and names[0] in err

    allowed = tmp_path / "baseline.txt"
    allowed.write_text("# fixture sleep, exercised on purpose\n"
                       + "".join(n + "\n" for n in names))
    rc = ca.main(["--quiet", "--baseline", str(allowed)] + _SMALL_STATIC)
    assert rc == 0


def test_cli_locks_static_only(capsys):
    """`cli locks` without the sanitizer armed: static half over the
    repo, which the committed tree keeps clean."""
    from deeplearning4j_tpu.cli import main as cli_main

    assert cli_main(["locks"]) == 0
    out = capsys.readouterr().out
    assert "lock audit:" in out and "runtime=off" in out
