"""TF-IDF / bag-of-words vectorizers (nlp/vectorizers.py), node2vec
biased walks (graph/walkers.py + graph/deepwalk.py), and the LFW fetcher
(data/fetchers.py) — the round-4 NLP completeness sweep."""

import math

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BagOfWordsVectorizer,
    LabelsSource,
    TfidfVectorizer,
)

DOCS = [
    "the cat sat on the mat",
    "the dog sat on the log",
    "cats and dogs and cats",
]


def test_bow_per_document_counts():
    v = BagOfWordsVectorizer().fit(DOCS)
    row = v.transform("the cat and the cat")
    assert row.shape == (1, v.vocab.num_words())
    assert row[0, v.vocab.index_of("the")] == 2.0
    assert row[0, v.vocab.index_of("cat")] == 2.0
    assert row[0, v.vocab.index_of("and")] == 1.0
    assert row[0, v.vocab.index_of("dog")] == 0.0
    # unknown words are simply absent
    assert v.vocab.index_of("zebra") == -1


def test_tfidf_reference_formulas():
    """tf = count/len; idf = log10(totalDocs/docFreq)
    (TfidfVectorizer.java + MathUtils.java:258)."""
    v = TfidfVectorizer().fit(DOCS)
    row = v.transform("cat cat dog mat")  # len 4
    # "cat" appears in 1 of 3 docs; tf = 2/4
    want_cat = (2 / 4) * math.log10(3 / 1)
    np.testing.assert_allclose(
        row[0, v.vocab.index_of("cat")], want_cat, rtol=1e-6)
    # "the" appears in 2 of 3 docs, absent from this doc -> 0
    assert row[0, v.vocab.index_of("the")] == 0.0
    # "sat" in 2/3 docs; absent here
    want_dog = (1 / 4) * math.log10(3 / 1)
    np.testing.assert_allclose(
        row[0, v.vocab.index_of("dog")], want_dog, rtol=1e-6)


def test_tfidf_vectorize_dataset_and_labels():
    v = TfidfVectorizer().fit(DOCS, labels=["pets", "other"])
    ds = v.vectorize("the cat sat", "pets")
    assert ds.features.shape == (1, v.vocab.num_words())
    assert ds.labels.shape[1] == 2 and ds.labels[0, 0] == 1.0
    # label space is fixed at fit: every DataSet has the same width, and
    # an unknown label raises instead of silently widening
    assert v.vectorize("the dog", "other").labels.shape == (1, 2)
    with pytest.raises(ValueError, match="unknown label"):
        v.vectorize("the dog", "vehicles")
    with pytest.raises(ValueError, match="no label space"):
        BagOfWordsVectorizer().fit(DOCS).vectorize("the cat", "pets")
    ls = LabelsSource(["a", "b"])
    assert ls.index_of("b") == 1 and ls.index_of("missing") == -1


def test_min_word_frequency_filters():
    v = BagOfWordsVectorizer(min_word_frequency=2).fit(DOCS)
    assert v.vocab.index_of("the") >= 0       # appears 4x
    assert v.vocab.index_of("log") == -1      # appears once


def test_tfidf_trains_classifier():
    """End-to-end: tf-idf features feed the training stack (the
    reference's vectorizer->DataSet->fit flow)."""
    from deeplearning4j_tpu.nn.conf.layers import OutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    rng = np.random.default_rng(0)
    animals = ["cat", "dog", "horse", "cow", "sheep"]
    tech = ["cpu", "gpu", "ram", "disk", "cache"]
    docs, labels = [], []
    for _ in range(60):
        grp = animals if rng.random() < 0.5 else tech
        docs.append(" ".join(rng.choice(grp, 6)))
        labels.append("animal" if grp is animals else "tech")
    v = TfidfVectorizer().fit(docs, labels=["animal", "tech"])
    X = np.concatenate([v.transform(d) for d in docs])
    y = np.zeros((len(docs), 2), np.float32)
    for i, l in enumerate(labels):
        y[i, v.labels_source.index_of(l)] = 1.0
    conf = (NeuralNetConfiguration.builder().seed(1).updater("adam")
            .learning_rate(0.05).weight_init("xavier").list()
            .layer(OutputLayer(n_in=X.shape[1], n_out=2,
                               activation="softmax", loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    for _ in range(60):
        net.fit(X, y, batch_size=32, epochs=1, async_prefetch=False)
    acc = float(np.mean(
        np.argmax(np.asarray(net.output(X)), -1) == np.argmax(y, -1)))
    assert acc > 0.95, acc


# -- node2vec ----------------------------------------------------------------

def _barbell():
    """Two 6-cliques joined by one bridge edge — communities that biased
    walks should keep separate."""
    from deeplearning4j_tpu.graph import Graph

    g = Graph(12)
    for base in (0, 6):
        for i in range(6):
            for j in range(i + 1, 6):
                g.add_edge(base + i, base + j)
    g.add_edge(5, 6)  # bridge
    return g


def test_node2vec_walk_bias():
    """q >> 1 (BFS-ish) keeps walks near the start; with p=q=1 the walk
    is the uniform random walk."""
    from deeplearning4j_tpu.graph.walkers import Node2VecWalkIterator

    g = _barbell()
    # strongly discourage outward exploration: walks from clique A should
    # almost never spend time deep inside clique B
    it = Node2VecWalkIterator(g, walk_length=20, p=1.0, q=8.0, seed=0)
    crossings = 0
    for _ in range(50):
        walk = it.walk_from(0)
        crossings += sum(1 for v in walk if v > 6)
    it_uniform = Node2VecWalkIterator(g, walk_length=20, p=1.0, q=1.0,
                                      seed=0)
    crossings_uniform = 0
    for _ in range(50):
        walk = it_uniform.walk_from(0)
        crossings_uniform += sum(1 for v in walk if v > 6)
    assert crossings < crossings_uniform, (crossings, crossings_uniform)


def test_node2vec_learns_communities():
    from deeplearning4j_tpu.graph import Node2Vec

    g = _barbell()
    vecs = Node2Vec(vector_size=16, window_size=4, walks_per_vertex=8,
                    p=1.0, q=2.0, seed=3).fit(g, walk_length=12)
    # same-clique similarity beats cross-clique similarity
    same = np.mean([vecs.similarity(0, j) for j in range(1, 5)])
    cross = np.mean([vecs.similarity(0, j) for j in range(7, 11)])
    assert same > cross, (same, cross)
    near = vecs.verts_nearest(1, 4)
    assert all(v <= 6 for v in near), near


# -- LFW ---------------------------------------------------------------------

def test_lfw_synthetic_fallback_shapes_and_determinism():
    from deeplearning4j_tpu.data.fetchers import (
        LFWDataFetcher,
        LFWDataSetIterator,
    )

    it = LFWDataSetIterator(
        16, train=True,
        fetcher=LFWDataFetcher(allow_download=False, synthetic_n=64,
                               num_labels=5, image_size=32))
    assert it.source == "synthetic"
    ds = next(iter(it))
    assert ds.features.shape == (16, 32, 32, 3)
    assert ds.labels.shape == (16, 5)
    # deterministic: same fetcher args -> same bytes
    it2 = LFWDataSetIterator(
        16, train=True,
        fetcher=LFWDataFetcher(allow_download=False, synthetic_n=64,
                               num_labels=5, image_size=32))
    np.testing.assert_array_equal(ds.features,
                                  next(iter(it2)).features)
    # identities are class-consistent: nearest-centroid beats chance
    x, y = LFWDataFetcher(allow_download=False, synthetic_n=200,
                          num_labels=5, image_size=32).load(True)
    labels = np.argmax(y, -1)
    flat = x.reshape(len(x), -1)
    cents = np.stack([flat[labels == c].mean(0) for c in range(5)])
    pred = np.argmin(
        ((flat[:, None, :] - cents[None]) ** 2).sum(-1), axis=1)
    assert (pred == labels).mean() > 0.8
