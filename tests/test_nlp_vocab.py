"""Vocab/Huffman/tokenization unit tests.

Mirrors the reference's NLP test coverage (SURVEY.md §4: 42 test files
under deeplearning4j-nlp; vocab + Huffman invariants are exercised by
models/word2vec tests)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
    Huffman,
    NGramTokenizerFactory,
    VocabConstructor,
)


def test_tokenizer_and_preprocessor():
    tf = DefaultTokenizerFactory()
    assert tf.create("Hello  world").get_tokens() == ["Hello", "world"]
    tf.set_token_pre_processor(CommonPreprocessor())
    assert tf.create("Hello, World! 123").get_tokens() == ["hello", "world"]
    ng = NGramTokenizerFactory(1, 2)
    toks = ng.create("a b c").get_tokens()
    assert "a b" in toks and "b c" in toks and "a" in toks


def test_vocab_construction_min_frequency():
    seqs = [["a", "a", "a", "b", "b", "c"]]
    vocab = VocabConstructor(min_word_frequency=2).build(seqs)
    assert vocab.contains_word("a") and vocab.contains_word("b")
    assert not vocab.contains_word("c")
    # frequency-descending index assignment
    assert vocab.index_of("a") == 0
    assert vocab.word_frequency("a") == 3
    assert vocab.total_word_count == 5


def test_huffman_invariants():
    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(50)]
    seqs = [
        list(rng.choice(words, p=_zipf(50), size=200)) for _ in range(20)
    ]
    vocab = VocabConstructor(1).build(seqs)
    h = Huffman(vocab)
    vws = vocab.vocab_words()
    V = len(vws)
    codes = {"".join(map(str, w.code)) for w in vws}
    assert len(codes) == V  # unique
    for c1 in codes:  # prefix-free
        for c2 in codes:
            if c1 != c2:
                assert not c2.startswith(c1)
    for w in vws:
        assert len(w.code) == len(w.points)
        assert all(0 <= p <= V - 2 for p in w.points)
    # more frequent => shorter-or-equal code
    most = max(vws, key=lambda w: w.count)
    least = min(vws, key=lambda w: w.count)
    assert len(most.code) <= len(least.code)
    # expected code length within 1 bit of the entropy bound
    counts = vocab.counts().astype(float)
    p = counts / counts.sum()
    entropy = -(p * np.log2(p)).sum()
    avg_len = sum(len(w.code) * w.count for w in vws) / counts.sum()
    assert entropy <= avg_len <= entropy + 1.0
    # padded arrays agree with the per-word lists
    codes_a, points_a, lengths = h.arrays()
    for i, w in enumerate(vws):
        n = lengths[i]
        assert list(codes_a[i, :n]) == w.code
        assert list(points_a[i, :n]) == w.points


def _zipf(n):
    w = 1.0 / np.arange(1, n + 1)
    return w / w.sum()


def test_unigram_table_distribution():
    from deeplearning4j_tpu.nlp import InMemoryLookupTable

    vocab = VocabConstructor(1).build([["a"] * 75 + ["b"] * 25])
    lt = InMemoryLookupTable(vocab, 4, negative=1)
    table = lt.unigram_table(10_000)
    frac_a = np.mean(table == vocab.index_of("a"))
    expected = 75**0.75 / (75**0.75 + 25**0.75)
    assert abs(frac_a - expected) < 0.02


def test_cjk_tokenizer_factory():
    """Language plugin on the TokenizerFactory SPI: character-class run
    segmentation with han/hangul bigrams (Lucene CJKAnalyzer strategy in
    place of the reference's bundled Kuromoji/KOMORAN)."""
    from deeplearning4j_tpu.nlp import CJKTokenizerFactory

    tf = CJKTokenizerFactory()
    # Japanese: kanji run -> bigrams, kana runs whole, latin word kept
    toks = tf.create("東京都に住むGPUユーザー").get_tokens()
    assert "東京" in toks and "京都" in toks          # overlapping bigrams
    assert "に" in toks                               # hiragana run
    assert "ユーザー" in toks                          # katakana run
    assert "GPU" in toks
    # Korean hangul bigrams
    toks_ko = tf.create("서울특별시").get_tokens()
    assert "서울" in toks_ko and "울특" in toks_ko
    # document order is preserved
    assert toks.index("東京") < toks.index("に") < toks.index("GPU")
    # run mode (no bigrams) keeps whole runs
    toks_runs = CJKTokenizerFactory(bigrams=False).create(
        "東京都に住む").get_tokens()
    assert "東京都" in toks_runs
    # and the plugin drives SequenceVectors like any TokenizerFactory
    sents = [tf.create(s).get_tokens()
             for s in ("東京の天気", "東京の電車", "大阪の天気")]
    assert all(len(s) >= 2 for s in sents)
