"""Generator for cg_adam_v1.zip / cg_adam_v1_expected.npz — run ONCE and
commit the outputs; tests load the frozen bytes (the reference's
regressiontest discipline, RegressionTest080.java: assertions against
release-era artifacts, never against freshly-built ones).

The zip is hand-assembled in the REFERENCE shape (Jackson WRAPPER_OBJECT
vertices, networkInputs/vertexInputs names, vertices listed OUT of
topological order, coefficients.bin in topo+f-order layout, Adam
updaterState.bin as one [m|v] block) so the fixture pins the parser to
the wire format, not to this framework's own exporter.
"""

import io
import json
import os
import zipfile

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(HERE)))
    from deeplearning4j_tpu.modelimport.dl4j import write_nd4j_array

    rng = np.random.default_rng(42)
    nin, h, classes = 5, 3, 3
    Wa = rng.standard_normal((nin, h)).astype(np.float32)
    ba = rng.standard_normal(h).astype(np.float32)
    Wb = rng.standard_normal((nin, h)).astype(np.float32)
    bb = rng.standard_normal(h).astype(np.float32)
    Wo = rng.standard_normal((2 * h, classes)).astype(np.float32)
    bo = rng.standard_normal(classes).astype(np.float32)

    train = {"updater": "ADAM", "learningRate": 0.01,
             "adamMeanDecay": 0.9, "adamVarDecay": 0.999, "epsilon": 1e-8}
    conf = {
        "networkInputs": ["in"],
        "networkOutputs": ["out"],
        # deliberately NOT in topological order
        "vertices": {
            "out": {"LayerVertex": {"layerConf": {"layer": {"output": {
                "nin": 2 * h, "nout": classes, "activationFn": "softmax",
                "lossFn": "mcxent", **train}}}}},
            "m": {"MergeVertex": {}},
            "b": {"LayerVertex": {"layerConf": {"layer": {"dense": {
                "nin": nin, "nout": h, "activationFn": "tanh",
                **train}}}}},
            "a": {"LayerVertex": {"layerConf": {"layer": {"dense": {
                "nin": nin, "nout": h, "activationFn": "relu",
                **train}}}}},
        },
        "vertexInputs": {"a": ["in"], "b": ["in"], "m": ["a", "b"],
                         "out": ["m"]},
        "iterationCount": 7,
    }
    # reference flat walk is TOPO order with FIFO-Kahn ascending-vertex-
    # number tie-breaks; vertex numbers follow JSON listing order
    # (out=1, m=2, b=3, a=4), so the walk is b, a, out (m has no params)
    flat = np.concatenate([
        Wb.reshape(-1, order="F"), bb, Wa.reshape(-1, order="F"), ba,
        Wo.reshape(-1, order="F"), bo,
    ])
    # Adam updater state: ONE block (uniform config, no BN) = [all m | all v]
    n = flat.size
    m_state = (rng.standard_normal(n) * 0.01).astype(np.float32)
    v_state = np.abs(rng.standard_normal(n) * 1e-4).astype(np.float32)
    upd = np.concatenate([m_state, v_state])

    cbuf, ubuf = io.BytesIO(), io.BytesIO()
    write_nd4j_array(flat, cbuf)
    write_nd4j_array(upd, ubuf)
    zpath = os.path.join(HERE, "cg_adam_v1.zip")
    with zipfile.ZipFile(zpath, "w") as zf:
        zf.writestr("configuration.json", json.dumps(conf))
        zf.writestr("coefficients.bin", cbuf.getvalue())
        zf.writestr("updaterState.bin", ubuf.getvalue())

    # expected outputs, computed here once with plain numpy
    x = rng.standard_normal((4, nin)).astype(np.float32)
    act_a = np.maximum(x @ Wa + ba, 0.0)
    act_b = np.tanh(x @ Wb + bb)
    merged = np.concatenate([act_a, act_b], axis=1)
    logits = merged @ Wo + bo
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    out = e / e.sum(axis=1, keepdims=True)
    np.savez(os.path.join(HERE, "cg_adam_v1_expected.npz"),
             x=x, out=out, updater_state=upd, iteration=np.int64(7))
    print("wrote", zpath)


if __name__ == "__main__":
    main()
