"""Structured static-analysis findings.

Every analysis pass (shapeflow config checker, jaxpr program auditor,
concurrency lint) reports the same record: a short stable code, a
severity, where it happened, what is wrong and how to fix it. The
uniform shape is what lets `cli doctor` / `cli lint` share JSON output,
exit-code policy, and the baseline name-diff gate in scripts/lint.sh
(the same pattern as tests/tier1_baseline_failures.txt).

Finding codes (the stable vocabulary — documented in README "Static
analysis"; tests pin one fixture per code):

shapeflow (config graph, no params built, no tracing):
  SF001  nIn/nOut wiring mismatch (or unset) on a parameterized layer
  SF002  input-family mismatch / missing preprocessor between layers
  SF003  merge-vertex fan-in conflict (mixed kinds, unequal h/w/timesteps)
  SF004  dead or unreachable vertex / unused graph input / cycle
  SF005  vertex shape conflict (elementwise arity, subset out of range)
  SF006  precision promotion point (bf16 compute -> f32 loss head)
  SF007  no trainable loss head (fit() would fail)

jaxpr audit (abstract trace of the train-step loss):
  JX001  float64 value inside the program (TPU runs it 10-100x slow)
  JX002  widening float cast (bf16/f16 -> f32, f32 -> f64) in the graph
  JX003  large constant folded into the program (recompiled per trace,
         resident per executable)
  JX004  host callback inside jit (forces device->host sync per step)
  JX005  parameter with no cotangent path to the loss (dead weight)
  JX006  train-step buffers not donated on a device backend (peak
         memory doubles)

cost model (analysis/costmodel — static device cost of the train step):
  JX007  cost model diverges from XLA cost_analysis beyond tolerance
         (MFU/roofline numbers built on it are untrustworthy)
  JX008  static residency estimate (params + updater + data +
         activation liveness peak) exceeds device HBM — will OOM

SLO rules (analysis/slo evaluated on the run ledger, utils/runledger):
  SLO001 a declarative SLO rule entered `firing` (severity = the
         rule's own: a burning latency objective is an error, an
         MFU-below-roofline drift a warning)

divergence sentinel (train/sentinel judging each optimizer step):
  SN001  a numerically anomalous optimizer step — non-finite loss/grad
         norm, or grad norm > k x the rolling median (warning: the
         step was quarantined; error: training diverged past the
         bounded rollback budget). Collected on the sentinel
         (`DivergenceSentinel.findings`), same record shape as every
         other pass.

concurrency lint (AST over the repo itself):
  CC001  bare `except:`
  CC002  queue put/get without timeout/abort in thread code
  CC003  thread without a name (dl4j-* naming convention)
  CC004  thread neither daemon nor joined
  CC005  lock-order cycle across nested `with <lock>:` scopes
  CC006  stray print() in library code (use the package logger)
  CC007  time.time() in deadline/timeout arithmetic (use monotonic)

concurrency audit (analysis/concurrency_audit: the runtime lock-order
sanitizer in utils/locktrace merged with the lexical lock pass above;
armed by DL4J_LOCKCHECK=1):
  CN001  lock-order cycle in the merged (static + runtime) lock-order
         graph — two code paths acquire the same locks in conflicting
         orders; a runtime cycle carries BOTH witness stacks (error)
  CN002  blocking call while holding a lock — time.sleep, queue
         get/put, Condition/Event wait on another lock, Thread.join,
         socket/HTTP I/O, block_until_ready/device sync (warning;
         gated by name against scripts/lock_baseline.txt in the
         `T1 LOCK AUDIT` step, not by the lint ERROR gate)
  CN003  lock held across a jitted dispatch — the fit step or decode
         engine step entered with a traced lock held (warning)
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable, List

ERROR = "error"
WARNING = "warning"
INFO = "info"

_SEVERITY_RANK = {ERROR: 0, WARNING: 1, INFO: 2}


@dataclasses.dataclass
class Finding:
    code: str        # "SF001" / "JX004" / "CC002" ...
    severity: str    # ERROR | WARNING | INFO
    location: str    # "layer[3]:dense_1" / "vertex:s1b0_add" / "path.py:42"
    message: str     # what is wrong, concretely
    fix_hint: str = ""   # the shortest path to green
    name: str = ""       # stable id for baseline diffs (no line numbers)

    def __post_init__(self):
        if not self.name:
            self.name = f"{self.code}:{self.location}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        hint = f"  [fix: {self.fix_hint}]" if self.fix_hint else ""
        return (f"{self.severity.upper():<7} {self.code} {self.location}: "
                f"{self.message}{hint}")


def sort_findings(findings: Iterable[Finding]) -> List[Finding]:
    """Severity-major order (errors first), then code, then location."""
    return sorted(findings,
                  key=lambda f: (_SEVERITY_RANK.get(f.severity, 3),
                                 f.code, f.location))


def has_errors(findings: Iterable[Finding]) -> bool:
    return any(f.severity == ERROR for f in findings)


def error_names(findings: Iterable[Finding]) -> List[str]:
    return sorted({f.name for f in findings if f.severity == ERROR})


def summarize(findings: Iterable[Finding]) -> dict:
    fs = list(findings)
    by = {ERROR: 0, WARNING: 0, INFO: 0}
    for f in fs:
        by[f.severity] = by.get(f.severity, 0) + 1
    return {
        "ok": by[ERROR] == 0,
        "errors": by[ERROR],
        "warnings": by[WARNING],
        "infos": by[INFO],
        "findings": [f.to_dict() for f in sort_findings(fs)],
    }


def to_json(findings: Iterable[Finding]) -> str:
    return json.dumps(summarize(findings), indent=2)


def format_findings(findings: Iterable[Finding]) -> str:
    fs = sort_findings(findings)
    if not fs:
        return "no findings"
    return "\n".join(f.format() for f in fs)
