"""Input pre-processors — shape adapters between layer families.

Analog of the reference's nn/conf/preprocessor/ (12 classes:
CnnToFeedForwardPreProcessor, FeedForwardToCnnPreProcessor,
RnnToFeedForwardPreProcessor, ...). Here each is a config dataclass with a
pure forward function; the backward direction is free via autodiff, where
the reference hand-writes backprop() per preprocessor.

Layout note: CNN activations are NHWC (TPU-native), so Cnn<->FeedForward is
a plain reshape with channels fastest-varying — different flattening order
from the reference's NCHW, by design. Rnn<->FeedForward merges/splits the
time axis: [batch, time, size] <-> [batch*time, size] (reference:
RnnToFeedForwardPreProcessor.java).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import (
    ConvolutionalInput,
    FeedForwardInput,
    RecurrentInput,
)
from deeplearning4j_tpu.nn.conf.serde import register_config


@dataclasses.dataclass(kw_only=True)
class InputPreProcessor:
    def __call__(self, x, state=None):
        raise NotImplementedError

    def output_type(self, it):
        raise NotImplementedError


@register_config("preproc.cnn_to_ff")
@dataclasses.dataclass(kw_only=True)
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, state=None):
        return x.reshape(x.shape[0], -1)

    def output_type(self, it):
        return FeedForwardInput(it.arity())


@register_config("preproc.ff_to_cnn")
@dataclasses.dataclass(kw_only=True)
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 1

    def __call__(self, x, state=None):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, it):
        return ConvolutionalInput(self.height, self.width, self.channels)


@register_config("preproc.rnn_to_ff")
@dataclasses.dataclass(kw_only=True)
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[batch, time, size] -> [batch*time, size] so dense layers apply
    time-distributed (reference: RnnToFeedForwardPreProcessor.java)."""

    def __call__(self, x, state=None):
        return x.reshape(-1, x.shape[-1])

    def output_type(self, it):
        return FeedForwardInput(it.size)


@register_config("preproc.ff_to_rnn")
@dataclasses.dataclass(kw_only=True)
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """[batch*time, size] -> [batch, time, size]; time length comes from the
    network's current minibatch context (passed via state). Genuinely
    feed-forward input (no prior 3-D activation => no time context) is
    treated as a single timestep, matching the reference
    (FeedForwardToRnnPreProcessor handles 2-D input as t=1)."""

    def __call__(self, x, state=None):
        ts = (state or {}).get("timesteps")
        if ts is None:
            ts = 1
        return x.reshape(-1, ts, x.shape[-1])

    def output_type(self, it):
        return RecurrentInput(it.arity())


@register_config("preproc.cnn_to_rnn")
@dataclasses.dataclass(kw_only=True)
class CnnToRnnPreProcessor(InputPreProcessor):
    """[batch, h, w, c] -> [batch, time=h, size=w*c]
    (reference: CnnToRnnPreProcessor.java, adapted to NHWC)."""

    def __call__(self, x, state=None):
        b, h, w, c = x.shape
        return x.reshape(b, h, w * c)

    def output_type(self, it):
        return RecurrentInput(it.width * it.channels, it.height)


@register_config("preproc.rnn_to_cnn")
@dataclasses.dataclass(kw_only=True)
class RnnToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 1

    def __call__(self, x, state=None):
        b = x.shape[0]
        return x.reshape(b, self.height, self.width, self.channels)

    def output_type(self, it):
        return ConvolutionalInput(self.height, self.width, self.channels)


@register_config("preproc.flat_to_cnn")
@dataclasses.dataclass(kw_only=True)
class FlatToCnnPreProcessor(InputPreProcessor):
    """Flattened image rows -> NHWC image (the reshape behind
    InputType.convolutional_flat, reference: FeedForwardToCnnPreProcessor
    inserted by MultiLayerConfiguration for convolutionalFlat input)."""

    height: int = 0
    width: int = 0
    channels: int = 1

    def __call__(self, x, state=None):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, it):
        return ConvolutionalInput(self.height, self.width, self.channels)


@register_config("preproc.composable")
@dataclasses.dataclass(kw_only=True)
class ComposableInputPreProcessor(InputPreProcessor):
    """Chain of preprocessors (reference: ComposableInputPreProcessor.java)."""

    processors: list = dataclasses.field(default_factory=list)

    def __call__(self, x, state=None):
        for p in self.processors:
            x = p(x, state)
        return x

    def output_type(self, it):
        for p in self.processors:
            it = p.output_type(it)
        return it
