"""InputType — shape metadata for automatic nIn inference and preprocessor
insertion.

Analog of the reference's org.deeplearning4j.nn.conf.inputs.InputType (used
by MultiLayerConfiguration.Builder.setInputType and InputTypeUtil). One
deliberate TPU-first difference: convolutional activations are NHWC
(batch, height, width, channels) — XLA's preferred TPU layout — where the
reference uses NCHW. Keras/DL4J import paths transpose at the boundary.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from deeplearning4j_tpu.nn.conf.serde import register_config


@register_config("input.feedforward")
@dataclasses.dataclass
class FeedForwardInput:
    size: int

    @property
    def kind(self):
        return "ff"

    def arity(self):
        return self.size


@register_config("input.recurrent")
@dataclasses.dataclass
class RecurrentInput:
    size: int
    timesteps: Optional[int] = None  # None = variable length

    @property
    def kind(self):
        return "rnn"

    def arity(self):
        return self.size


@register_config("input.convolutional")
@dataclasses.dataclass
class ConvolutionalInput:
    """NHWC activation shape (height, width, channels)."""

    height: int
    width: int
    channels: int

    @property
    def kind(self):
        return "cnn"

    def arity(self):
        return self.height * self.width * self.channels


@register_config("input.convolutional_flat")
@dataclasses.dataclass
class ConvolutionalFlatInput:
    """Flattened image rows (e.g. MNIST 784) to be reshaped to NHWC.
    Reference: InputType.convolutionalFlat."""

    height: int
    width: int
    channels: int

    @property
    def kind(self):
        return "cnn_flat"

    def arity(self):
        return self.height * self.width * self.channels


class InputType:
    """Factory namespace mirroring the reference's static methods."""

    @staticmethod
    def feed_forward(size: int) -> FeedForwardInput:
        return FeedForwardInput(int(size))

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> RecurrentInput:
        return RecurrentInput(int(size), timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> ConvolutionalInput:
        return ConvolutionalInput(int(height), int(width), int(channels))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> ConvolutionalFlatInput:
        return ConvolutionalFlatInput(int(height), int(width), int(channels))
