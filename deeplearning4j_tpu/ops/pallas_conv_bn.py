"""Pallas conv + BN-statistics epilogue fusion — the CudnnConvolutionHelper/
CudnnBatchNormalizationHelper pair for the ResNet trunk.

Why: PROFILE_resnet50.md shows the train step is bandwidth-bound, with
16.4 ms of a 48.8 ms step spent on batch-norm statistics/normalization
traffic over the residual trunk (`convert_reduce_fusion` = 25.8 ms/step).
XLA materializes each conv output to HBM, then re-reads the full tensor
for the per-channel statistics reduction, then re-reads it AGAIN for the
normalize. This module closes one of those reads: the conv kernel computes
per-channel sum / sum-of-squares in f32 as an epilogue over each output
tile while it is still in VMEM, so the stats cost no extra HBM traffic at
all; a second fused normalize(+ReLU) kernel then performs the one
remaining read.

Two helper slots (ops/helpers.py), mirroring the reference's plugin pair
(CudnnConvolutionHelper.java:345, BatchNormalizationHelper.java:29):

- "conv2d":     `_conv2d_helper` — conv forward with the stats epilogue.
  The stats ride to the downstream BatchNormalization layer through a
  producer→consumer stash keyed by tensor identity: within one trace the
  conv's output object IS the BN layer's input object (compgraph passes
  activations through untouched), so the match is exact and anything in
  between (an activation, a residual add) breaks it safely.
- "batch_norm": `_bn_helper` — fused normalize from the stashed stats,
  with a deferred-ReLU hook: when the very next layer is a ReLU
  ActivationLayer, it swaps in the normalize+ReLU variant of the kernel
  and the plain-normalize pallas_call is dead-code-eliminated by XLA.

Scope (checked by the probes; everything else falls back silently to the
XLA lowering, exactly like the cuDNN checkSupported fallback): NHWC,
bf16 on real TPU, training mode, bias-free identity-activation convs with
SAME padding, no dilation, and kernel/stride in {1x1 (stride 1 or 2),
3x3 (stride 1 or 2), 7x7 (stride 2)} — every conv instance of the
ResNet-50 trunk, stem included (53/53). Structural support is necessary
but not sufficient: `conv_decision` then consults the per-instance
roofline (`analysis/costmodel.instance_roofline`) and DECLINES
compute-bound instances — an MXU-saturating conv gains nothing from the
stats epilogue and must never regress through the helper; only
memory-bound instances route to the kernel.

Backward is a hand-written custom_vjp pair: the conv pullback is the
standard pair of transposed XLA convolutions (jax.linear_transpose of the
reference lowering — already MXU-shaped; Pallas buys nothing there), and
the BN pullback reuses the fused-BN VJP structure of nn/layers/norm.py
(per-channel coefficients in the f32 accumulator dtype, every full-size
tensor in x.dtype). The per-channel reductions of that pullback (sum g,
sum g·x) and the dx normalization are themselves Pallas-fused here — one
reduce pass + one apply pass over the saved activations instead of
XLA's three separate re-reads — registered as a third helper slot
("bn_backward") consumed both by `bn_apply`'s VJP and by
nn/layers/norm.py's built-in `_bn_train` backward, behind the same
kill-switch/auto-disable machinery. The stats outputs are
stop_gradient'ed at the stash: the BN backward's dx is the TOTAL
derivative including the statistics paths (same composite as norm.py's
`_bn_train`), so routing any cotangent through the stats tensors as well
would double-count.
"""

from __future__ import annotations

import logging
import os
from collections import deque
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

logger = logging.getLogger("deeplearning4j_tpu")

# Interpret mode runs the kernels as a jaxpr interpreter on any backend —
# the CPU-correctness/bench configuration (same pattern as pallas_lstm).
# Tests flip the module flag directly; bench flips it via set_interpret;
# DL4J_PALLAS_INTERPRET=1 forces it from the environment.
_INTERPRET = os.environ.get("DL4J_PALLAS_INTERPRET", "0") == "1"


def set_interpret(on: bool) -> None:
    """Run the Pallas kernels in interpret mode (any backend). Used by
    bench.py for the CPU-interpret helper A/B; tests set the module flag
    directly through their fixture."""
    global _INTERPRET
    _INTERPRET = bool(on)

_DIMS2D = ("NHWC", "HWIO", "NHWC")


# -- producer→consumer stashes ----------------------------------------------
#
# Entries are matched by `is` on the traced value, so they can only ever
# connect a conv to the BN (or a BN to the ReLU) that consumes that exact
# tensor inside the same trace. Bounded deques: unmatched entries (a conv
# whose consumer is not a BN, an abandoned trace) age out instead of
# accumulating tracer references.

_STATS_STASH: deque = deque(maxlen=8)
_RELU_STASH: deque = deque(maxlen=8)


def _stash_pop(dq: deque, x):
    """Remove and return the entry whose key tensor IS x. Removal is by
    index — deque.remove would compare entries with ==, which on traced
    arrays of unequal shapes raises instead of answering False."""
    for i, entry in enumerate(dq):
        if entry[0] is x:
            del dq[i]
            return entry
    return None


def _stash_stats(y, s1, s2) -> None:
    _STATS_STASH.append((y, s1, s2))


def take_stats(x):
    """(sum, sum_sq) f32 per-channel stats stashed for exactly this tensor,
    removing the entry; None when x is not a stashed conv output."""
    entry = _stash_pop(_STATS_STASH, x)
    return None if entry is None else (entry[1], entry[2])


def peek_stats(x) -> bool:
    return any(entry[0] is x for entry in _STATS_STASH)


def _stash_relu(y, thunk) -> None:
    _RELU_STASH.append((y, thunk))


def take_fused_relu(x):
    """The normalize+ReLU variant of a stashed BN output, or None. The
    plain-normalize pallas_call that produced x becomes dead code once its
    only consumer switches to the fused variant — XLA eliminates it."""
    entry = _stash_pop(_RELU_STASH, x)
    if entry is None:
        return None
    try:
        return entry[1]()
    except Exception as e:  # never let the fusion shortcut kill a layer
        logger.warning("fused BN+ReLU thunk failed (%s); applying "
                       "plain ReLU instead", e)
        return None


# -- tiling helpers ----------------------------------------------------------

def _row_tile(m: int, cap: int = 512) -> int:
    """Largest power-of-two row tile <= cap dividing m (ResNet row counts
    are highly 2-adic: N*H*W = 128*56*56 etc; tiny test shapes land on a
    smaller divisor, worst case 1)."""
    t = cap
    while t > 1 and m % t:
        t //= 2
    return t


def _acc_dtype(dtype):
    """f32 accumulators, or f64 when the whole check runs f64 (the
    gradient-check configuration) — matches nn/layers/norm.py."""
    return jnp.promote_types(dtype, jnp.float32)


# -- 1x1 conv (pointwise matmul) with stats epilogue -------------------------

def _mm_stats_kernel(x_ref, w_ref, y_ref, s1_ref, s2_ref):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        s1_ref[:] = jnp.zeros_like(s1_ref)
        s2_ref[:] = jnp.zeros_like(s2_ref)

    acc_dt = s1_ref.dtype
    y = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=acc_dt)
    yb = y.astype(y_ref.dtype)
    y_ref[:] = yb
    # Epilogue over the tile while it is still in VMEM. Statistics are of
    # the STORED (rounded) tensor — what the normalize will actually read
    # — not the f32 pre-rounding accumulator.
    yf = yb.astype(acc_dt)
    s1_ref[:] += jnp.sum(yf, axis=0, keepdims=True)
    s2_ref[:] += jnp.sum(yf * yf, axis=0, keepdims=True)


def _mm_stats_call(x2, w2):
    m, cin = x2.shape
    cout = w2.shape[1]
    acc = _acc_dtype(x2.dtype)
    # big-channel shapes get a smaller row tile so weights + double-buffered
    # row tiles stay inside VMEM (probe re-checks the same budget)
    tm = _row_tile(m, 128 if cin * cout >= 1024 * 1024 else 512)
    y2, s1, s2 = pl.pallas_call(
        _mm_stats_kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, cin), lambda t: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((cin, cout), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tm, cout), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, cout), x2.dtype),
            jax.ShapeDtypeStruct((1, cout), acc),
            jax.ShapeDtypeStruct((1, cout), acc),
        ],
        interpret=_INTERPRET,
    )(x2, w2)
    return y2, s1, s2


# -- kxk strided SAME conv with stats epilogue -------------------------------

def _same_out_pad(in_sz: int, k: int, s: int):
    """(out_sz, pad_lo) of one spatial dim under XLA SAME padding (extra
    pad goes on the high side — must match the reference lowering the
    backward transposes and the tests compare against)."""
    out_sz = -(-in_sz // s)
    return out_sz, max((out_sz - 1) * s + k - in_sz, 0) // 2


def _conv_taps(h: int, w: int, kh: int, kw: int, sh: int, sw: int):
    """Static per-tap slice plan for a SAME kxk/s conv: for each kernel
    tap (a, b), the output range where the tap lands inside the image and
    the matching strided input origin. All values are Python ints, so the
    kernel below unrolls to kh*kw clipped dots with static slices."""
    ho, ph = _same_out_pad(h, kh, sh)
    wo, pw = _same_out_pad(w, kw, sw)
    rows = []
    for a in range(kh):
        o0 = max(0, -((a - ph) // sh)) if a < ph else 0
        o1 = min(ho, (h - 1 + ph - a) // sh + 1)
        if o1 > o0:
            rows.append((a, o0, o1, o0 * sh + a - ph))
    cols = []
    for b in range(kw):
        o0 = max(0, -((b - pw) // sw)) if b < pw else 0
        o1 = min(wo, (w - 1 + pw - b) // sw + 1)
        if o1 > o0:
            cols.append((b, o0, o1, o0 * sw + b - pw))
    taps = tuple((ra, rb) for ra in rows for rb in cols)
    return ho, wo, taps


def _ck_stats_kernel(x_ref, w_ref, y_ref, s1_ref, s2_ref, *, taps, sh, sw):
    n = pl.program_id(0)

    @pl.when(n == 0)
    def _():
        s1_ref[:] = jnp.zeros_like(s1_ref)
        s2_ref[:] = jnp.zeros_like(s2_ref)

    acc_dt = s1_ref.dtype
    ho, wo = y_ref.shape[1], y_ref.shape[2]
    cout = y_ref.shape[3]
    cin = x_ref.shape[3]
    acc = jnp.zeros((ho, wo, cout), acc_dt)
    x = x_ref[0]
    # kh*kw shifted whole-image dots accumulated in VMEM. The SAME-padding
    # halo is handled by clipping each tap to its valid output region
    # (static slices) instead of pre-padding the input — a jnp.pad outside
    # the kernel would materialize a full padded copy to HBM, spending the
    # very read the stats epilogue saves. Stride > 1 subsamples the input
    # rows/cols of each tap with a static strided slice.
    for (a, oh0, oh1, ih0), (b, ow0, ow1, iw0) in taps:
        ch, cw = oh1 - oh0, ow1 - ow0
        if sh == 1 and sw == 1:
            xs = x[ih0:ih0 + ch, iw0:iw0 + cw, :]
        else:
            xs = lax.slice(x, (ih0, iw0, 0),
                           (ih0 + (ch - 1) * sh + 1,
                            iw0 + (cw - 1) * sw + 1, cin),
                           (sh, sw, 1))
        part = lax.dot_general(
            xs, w_ref[a, b],
            (((2,), (0,)), ((), ())),
            preferred_element_type=acc_dt,
        )
        # zero-extend the clipped partial back to (ho, wo) and add —
        # in-register pad; .at[...].add would capture index constants
        # the kernel tracer rejects
        acc = acc + lax.pad(
            part, jnp.asarray(0, acc_dt),
            ((oh0, ho - oh1, 0), (ow0, wo - ow1, 0), (0, 0, 0)))
    yb = acc.astype(y_ref.dtype)
    y_ref[0] = yb
    yf = yb.astype(acc_dt).reshape(ho * wo, cout)
    s1_ref[:] += jnp.sum(yf, axis=0, keepdims=True)
    s2_ref[:] += jnp.sum(yf * yf, axis=0, keepdims=True)


def _ck_stats_call(x, w, strides):
    n, h, wd, cin = x.shape
    kh, kw, _, cout = w.shape
    sh, sw = strides
    ho, wo, taps = _conv_taps(h, wd, kh, kw, sh, sw)
    acc = _acc_dtype(x.dtype)
    y, s1, s2 = pl.pallas_call(
        partial(_ck_stats_kernel, taps=taps, sh=sh, sw=sw),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, wd, cin), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kh, kw, cin, cout), lambda i: (0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, ho, wo, cout), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, cout), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, ho, wo, cout), x.dtype),
            jax.ShapeDtypeStruct((1, cout), acc),
            jax.ShapeDtypeStruct((1, cout), acc),
        ],
        interpret=_INTERPRET,
    )(x, w)
    return y, s1, s2


# -- fused conv + stats op (custom_vjp) --------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(2,))
def conv2d_bn_stats(x, w, strides):
    """NHWC conv (SAME, bias-free) returning (y, sum, sum_sq) where the
    per-channel f32 statistics are computed as a VMEM epilogue of the conv
    output tiles — zero extra HBM traffic for the reduction.

    x: [N,H,W,Cin]; w: [kh,kw,Cin,Cout] with (kh,kw)/(sh,sw) in
    {1x1/s1, 1x1/s2, 3x3/s1, 3x3/s2, 7x7/s2}; strides static.

    The statistics outputs carry NO gradient (see module docstring: the
    paired `bn_apply` backward computes the total dx including the stats
    paths). Consume them via the Helper SPI wiring or stop_gradient them.
    """
    y, s1, s2 = _conv_fwd_impl(x, w, strides)
    return y, s1, s2


def _conv_fwd_impl(x, w, strides):
    kh, kw = int(w.shape[0]), int(w.shape[1])
    cout = int(w.shape[3])
    if (kh, kw) == (1, 1):
        sh, sw = strides
        if (sh, sw) != (1, 1):
            # SAME 1x1/s: output pixel (i,j) samples x[i*s, j*s] exactly
            x = x[:, ::sh, ::sw, :]
        n, h, wd, cin = x.shape
        y2, s1, s2 = _mm_stats_call(x.reshape(n * h * wd, cin),
                                    w.reshape(cin, cout))
        return y2.reshape(n, h, wd, cout), s1[0], s2[0]
    # kxk SAME (stride 1 or 2): full image per grid step, halo clipped
    # and stride subsampled in-kernel
    y, s1, s2 = _ck_stats_call(x, w, strides)
    return y, s1[0], s2[0]


def _conv_fwd(x, w, strides):
    out = _conv_fwd_impl(x, w, strides)
    return out, (x, w)


def _conv_bwd(strides, res, cts):
    """Pullback = the two transposed convolutions of the reference XLA
    lowering (linear_transpose instantiates no forward pass). ds1/ds2 are
    structurally zero — the stats are stop_gradient'ed at the stash and
    bn_apply's dx is the total derivative — so they are dropped here."""
    x, w = res
    dy, _, _ = cts

    def conv_x(xx):
        return lax.conv_general_dilated(
            xx, w, window_strides=strides, padding="SAME",
            dimension_numbers=_DIMS2D)

    def conv_w(ww):
        return lax.conv_general_dilated(
            x, ww, window_strides=strides, padding="SAME",
            dimension_numbers=_DIMS2D)

    dx, = jax.linear_transpose(conv_x, x)(dy)
    dw, = jax.linear_transpose(conv_w, w)(dy)
    return dx, dw


conv2d_bn_stats.defvjp(_conv_fwd, _conv_bwd)


# -- fused normalize(+ReLU) consumer (custom_vjp) ----------------------------

def _norm_kernel_relu(x_ref, mb_ref, sc_ref, sh_ref, y_ref):
    xc = x_ref[:] - mb_ref[:]
    y = xc * sc_ref[:].astype(x_ref.dtype) + sh_ref[:].astype(x_ref.dtype)
    y_ref[:] = jnp.maximum(y, jnp.zeros_like(y))


def _norm_kernel(x_ref, mb_ref, sc_ref, sh_ref, y_ref):
    xc = x_ref[:] - mb_ref[:]
    y_ref[:] = xc * sc_ref[:].astype(x_ref.dtype) \
        + sh_ref[:].astype(x_ref.dtype)


def _norm_call(x2, mean_b, scale, shift, relu):
    """y = (x - mean_b)*scale + shift, one fused pass. Centered BEFORE the
    scale exactly like norm.py's `_bn_train`: x - bf16(mean) is exact near
    the mean (Sterbenz), so low-precision rounding applies to the
    deviation, not to mean*scale-sized intermediates."""
    m, c = x2.shape
    tm = _row_tile(m)
    return pl.pallas_call(
        _norm_kernel_relu if relu else _norm_kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, c), lambda t: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda t: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tm, c), lambda t: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, c), x2.dtype),
        interpret=_INTERPRET,
    )(x2, mean_b, scale, shift)


def _col_sums(x2, acc_dt):
    """Column sums of [n, c] with accumulator-dtype accumulation via a dot
    against ones — the MXU form norm.py's `_sum_to_f32` uses, generalized
    to f64 for the gradient-check configuration."""
    ones = jnp.ones((x2.shape[0],), x2.dtype)
    return lax.dot_general(ones, x2, (((0,), (0,)), ((), ())),
                           preferred_element_type=acc_dt)


# -- fused BN-backward epilogue ----------------------------------------------
#
# The fused-BN pullback needs two per-channel reductions over full-size
# tensors (sum g, sum g·x) and then one elementwise pass producing dx.
# XLA lowers the builtin form as three separate reductions/maps that each
# re-read the saved activation from HBM; these two kernels do it in one
# reduce pass (both sums per tile while g and x are in VMEM) plus one
# apply pass — the backward twin of the forward stats epilogue.

def _bnb_reduce_kernel(g_ref, x_ref, sg_ref, sgx_ref):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        sg_ref[:] = jnp.zeros_like(sg_ref)
        sgx_ref[:] = jnp.zeros_like(sgx_ref)

    acc_dt = sg_ref.dtype
    g = g_ref[:].astype(acc_dt)
    sg_ref[:] += jnp.sum(g, axis=0, keepdims=True)
    sgx_ref[:] += jnp.sum(g * x_ref[:].astype(acc_dt), axis=0,
                          keepdims=True)


def _bnb_apply_kernel(g_ref, x_ref, c1_ref, c3_ref, c0_ref, dx_ref):
    dt = dx_ref.dtype
    dx_ref[:] = (c1_ref[:].astype(dt) * g_ref[:]
                 - c3_ref[:].astype(dt) * x_ref[:]
                 + c0_ref[:].astype(dt))


def _bnb_reduce_call(g2, x2):
    m, c = g2.shape
    acc = _acc_dtype(g2.dtype)
    tm = _row_tile(m)
    return pl.pallas_call(
        _bnb_reduce_kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, c), lambda t: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, c), lambda t: (t, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, c), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda t: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, c), acc),
            jax.ShapeDtypeStruct((1, c), acc),
        ],
        interpret=_INTERPRET,
    )(g2, x2)


def _bnb_apply_call(g2, x2, c1, c3, c0):
    m, c = g2.shape
    tm = _row_tile(m)
    return pl.pallas_call(
        _bnb_apply_kernel,
        grid=(m // tm,),
        in_specs=[
            pl.BlockSpec((tm, c), lambda t: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, c), lambda t: (t, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda t: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda t: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tm, c), lambda t: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, c), g2.dtype),
        interpret=_INTERPRET,
    )(g2, x2, c1, c3, c0)


def bn_backward_fused(g, x_for_dx, center, gamma, inv, n):
    """The fused-BN pullback's heavy lifting in two Pallas passes.

    g:        activation-dtype cotangent (already ReLU-gated if fused);
    x_for_dx: the tensor the dx formula is affine in — centered x for the
              bf16 path, raw x for the f32 path (norm.py `_bn_train_bwd`);
    center:   accumulator-dtype per-channel recentering constant — delta
              (mean's rounding error) for bf16, mean for f32 — so
              sum_gx = Σ g·x_for_dx − center·Σ g matches the builtin;
    gamma/inv: per-channel scale and rsqrt(var+eps); n: reduced elements.

    Returns (dx, dgamma, dbeta) with dx in g.dtype and dgamma/dbeta in
    the accumulator dtype (callers cast to the parameter dtype). The
    coefficient algebra is EXACTLY norm.py's `_bn_train_bwd`; only the
    reductions and the elementwise map are fused."""
    c = g.shape[-1]
    acc = _acc_dtype(g.dtype)
    g2 = g.reshape(n, c)
    x2 = x_for_dx.reshape(n, c)
    sg, sgx_raw = _bnb_reduce_call(g2, x2)
    sum_g = sg[0]
    sum_gx = sgx_raw[0] - center.astype(acc) * sum_g
    gamma_f = gamma.astype(acc)
    dgamma = inv * sum_gx
    dbeta = sum_g
    c1 = gamma_f * inv
    c3 = gamma_f * inv * inv * inv * sum_gx / n
    c0 = -(c1 * sum_g / n) + c3 * center.astype(acc)
    dx2 = _bnb_apply_call(g2, x2, c1[None, :], c3[None, :], c0[None, :])
    return dx2.reshape(g.shape), dgamma, dbeta


def _bn_backward_pieces(g, x, mean, inv, gamma, n):
    """(x_for_dx, center) for the dtype-appropriate recentering, then the
    fused backward if the "bn_backward" helper engages, else the builtin
    reductions — shared by `_bn_bwd` below and norm.py's `_bn_train_bwd`.
    Returns (dx, dgamma, dbeta) in (x.dtype, acc, acc)."""
    from deeplearning4j_tpu.ops.helpers import HelperError, get_helper

    c = x.shape[-1]
    acc = _acc_dtype(x.dtype)
    if x.dtype == jnp.bfloat16:
        mean_b = mean.astype(x.dtype)
        center = mean - mean_b.astype(acc)  # delta: mean's rounding error
        x_for_dx = x - jnp.broadcast_to(mean_b, x.shape)
    else:
        center = mean
        x_for_dx = x
    helper = get_helper("bn_backward", x_shape=tuple(x.shape),
                        dtype=x.dtype, training=True)
    if helper is not None:
        try:
            return helper(g, x_for_dx, center, gamma, inv, n)
        except HelperError:
            pass  # helper auto-disabled itself; builtin path below
    g2 = g.astype(acc) if x.dtype != jnp.bfloat16 else g
    g2 = g2.reshape(n, c)
    x2 = (x_for_dx.astype(acc)
          if x.dtype != jnp.bfloat16 else x_for_dx).reshape(n, c)
    if x.dtype == jnp.bfloat16:
        sum_g = _col_sums(g2, acc)
        sum_gx = _col_sums(g2 * x2, acc) - center * sum_g
    else:
        sum_g = jnp.sum(g2, axis=0)
        sum_gx = jnp.sum(g2 * x2, axis=0) - center * sum_g
    gamma_f = gamma.astype(acc)
    dgamma = inv * sum_gx
    dbeta = sum_g
    c1 = gamma_f * inv
    c3 = gamma_f * inv * inv * inv * sum_gx / n
    c0 = -(c1 * sum_g / n) + c3 * center
    dx = (c1.astype(x.dtype) * g - c3.astype(x.dtype) * x_for_dx
          + c0.astype(x.dtype))
    return dx, dgamma, dbeta


@partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def bn_apply(x, s1, s2, gamma, beta, eps, n, relu):
    """Training-mode batch norm from precomputed raw moments: one fused
    read of x (normalize + optional ReLU in a single Pallas pass) instead
    of XLA's reduce-then-normalize double read. Returns (y, mean, var)
    exactly like norm.py's `_bn_train`; mean/var feed the running-EMA
    state only. n = number of reduced elements (x.size / channels);
    eps/n/relu are static."""
    out, _ = _bn_fwd(x, s1, s2, gamma, beta, eps, n, relu)
    return out


def _bn_fwd(x, s1, s2, gamma, beta, eps, n, relu):
    acc = _acc_dtype(x.dtype)
    c = x.shape[-1]
    mean = s1.astype(acc) / n
    var = jnp.maximum(s2.astype(acc) / n - mean * mean, 0.0)
    inv = lax.rsqrt(var + eps)
    scale = gamma.astype(acc) * inv
    # centered application (norm.py's bf16 form): y = (x - bf16(mean))
    # * scale + (beta - delta*scale), with delta the mean's rounding error
    mean_b = mean.astype(x.dtype)
    delta = mean - mean_b.astype(acc)
    shift = beta.astype(acc) - delta * scale
    y2 = _norm_call(x.reshape(n, c), mean_b[None, :], scale[None, :],
                    shift[None, :], relu)
    y = y2.reshape(x.shape)
    return (y, mean, var), (x, gamma, mean, inv, y)


def _bn_bwd(eps, n, relu, res, cts):
    """The fused-BN VJP of nn/layers/norm.py (`_bn_train_bwd`), extended
    with the ReLU gate: per-channel coefficients in the accumulator dtype,
    every full-size tensor in x.dtype; bf16 uses the centered reduction
    (x - bf16(mean), exact by Sterbenz near the mean) so sum_gx never
    cancels catastrophically. mean/var cotangents are dropped — they feed
    the non-trainable running EMA, as in the reference."""
    g, _, _ = cts
    x, gamma, mean, inv, y = res
    g = g.astype(x.dtype)
    if relu:
        g = jnp.where(y > 0, g, jnp.zeros_like(g))
    c = x.shape[-1]
    dx, dgamma, dbeta = _bn_backward_pieces(g, x, mean, inv, gamma, n)
    # dx is the TOTAL derivative (elementwise + both statistics paths);
    # the raw-moment inputs therefore receive zero cotangent.
    zs = jnp.zeros((c,), _acc_dtype(x.dtype))
    return (dx, zs, zs, dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype))


bn_apply.defvjp(_bn_fwd, _bn_bwd)


# -- Helper SPI wiring -------------------------------------------------------

_VMEM_BUDGET = 12 * 1024 * 1024  # leave headroom under the ~16MB/core VMEM

# the structural whitelist: every ResNet-50 trunk conv is one of these
_KERNEL_STRIDES = {
    ((1, 1), (1, 1)): "conv1x1",
    ((1, 1), (2, 2)): "conv1x1s2",
    ((3, 3), (1, 1)): "conv3x3",
    ((3, 3), (2, 2)): "conv3x3s2",
    ((7, 7), (2, 2)): "conv7x7s2",
}


def conv_family(*, kernel=None, stride=None, **_):
    """Bounded kernel-family slug for the helper metrics labels: one of
    the five covered kernel/stride shapes, else "conv_other"."""
    if kernel is None or stride is None:
        return "conv_other"
    return _KERNEL_STRIDES.get((tuple(kernel), tuple(stride)), "conv_other")


def _conv_vmem_ok(kernel, stride, x_shape, n_in, n_out, itemsize) -> bool:
    kh, kw = kernel
    if (kh, kw) == (1, 1):
        wgt = n_in * n_out * itemsize
        tm = 128 if n_in * n_out >= 1024 * 1024 else 512
        tiles = 2 * tm * (n_in + n_out) * itemsize
        return wgt + tiles <= _VMEM_BUDGET
    h, w = x_shape[1], x_shape[2]
    ho = -(-h // stride[0])
    wo = -(-w // stride[1])
    slab = h * w * n_in * itemsize  # one full input image
    out = ho * wo * n_out * itemsize
    accf = ho * wo * n_out * 4
    wgt = kh * kw * n_in * n_out * itemsize
    return 2 * (slab + out) + accf + wgt <= _VMEM_BUDGET


def conv_decision(*, kernel, stride, dilation, same, has_bias, activation,
                  dtype, n_in, n_out, x_shape, training, planning=False,
                  **_):
    """Routing decision for the "conv2d" slot, in two stages:

    1. structural: the kernel must EXIST for the shape (bias-free SAME
       identity conv, kernel/stride in `_KERNEL_STRIDES`, channels that
       tile the 128-lane registers, the whole image inside the VMEM
       budget) — failures are "unsupported", the cuDNN checkSupported
       pattern;
    2. economic: the per-instance roofline verdict
       (analysis/costmodel.instance_roofline). The stats epilogue saves
       an HBM read — worth exactly nothing on an MXU-saturating conv, so
       compute-bound instances are "declined" and keep the XLA lowering:
       a compute-bound shape can never regress through the helper.

    Returns {"status": "covered"|"declined"|"unsupported", "reason",
    "family", "roofline"} — `cli perf`'s coverage table prints exactly
    this. planning=True models the TPU routing decision regardless of
    the local backend/interpret state (used by the coverage table and
    the T1 kernel-coverage smoke on CPU hosts)."""
    fam = conv_family(kernel=kernel, stride=stride)

    def uns(reason):
        return {"status": "unsupported", "reason": reason, "family": fam,
                "roofline": None}

    if not training:
        return uns("inference")
    if has_bias:
        return uns("bias")
    if not same:
        return uns("padding")
    if activation not in (None, "identity"):
        return uns("fused_activation")
    if tuple(dilation) != (1, 1):
        return uns("dilation")
    k, s = tuple(kernel), tuple(stride)
    if (k, s) not in _KERNEL_STRIDES:
        return uns("kernel_shape")
    if planning:
        pass  # model the TPU decision for any local backend/dtype
    elif _INTERPRET:
        # CPU correctness/bench mode: any float dtype, tiny channels
        if not jnp.issubdtype(dtype, jnp.floating):
            return uns("dtype")
    else:
        if jax.default_backend() != "tpu":
            return uns("backend")
        if dtype != jnp.bfloat16:
            return uns("dtype")
    if planning or not _INTERPRET:
        # trunk channel counts tile the 128-lane registers cleanly; the
        # 7x7 stem's 3 input channels ride the (padded) contraction dim
        if (n_in % 64 and not (k == (7, 7) and n_in <= 4)) or n_out % 64:
            return uns("channel_alignment")
        if not _conv_vmem_ok(k, s, x_shape, n_in, n_out,
                             jnp.dtype(dtype).itemsize):
            return uns("vmem")
    from deeplearning4j_tpu.analysis.costmodel import (
        conv_instance_cost,
        instance_roofline,
    )

    cost = conv_instance_cost(kernel=k, stride=s, x_shape=x_shape,
                              n_out=n_out,
                              itemsize=jnp.dtype(dtype).itemsize)
    rf = instance_roofline(cost["flops"], cost["bytes"])
    if rf["verdict"] == "compute-bound":
        return {"status": "declined", "reason": "compute_bound",
                "family": fam, "roofline": rf}
    return {"status": "covered", "reason": "memory_bound", "family": fam,
            "roofline": rf}


def conv_supported(*, kernel, stride, dilation, same, has_bias, activation,
                   dtype, n_in, n_out, x_shape, training, **_):
    """Probe for the "conv2d" slot — thin wrapper over `conv_decision`:
    engage the kernel only when the instance is structurally covered AND
    memory-bound on the roofline."""
    return conv_decision(
        kernel=kernel, stride=stride, dilation=dilation, same=same,
        has_bias=has_bias, activation=activation, dtype=dtype, n_in=n_in,
        n_out=n_out, x_shape=x_shape, training=training,
    )["status"] == "covered"


def bn_supported(*, x, training, **_):
    """Probe for the "batch_norm" slot: only engages when the input IS a
    stashed conv-epilogue output (identity match) — otherwise the built-in
    fused XLA path is already optimal (it needs the stats reduction
    anyway). The normalize pass is a pure streaming map (≈2 FLOP/byte),
    so the per-instance roofline consult can only say memory-bound; it
    runs anyway so the routing stays cost-model-driven by construction."""
    if not training or not hasattr(x, "ndim") or x.ndim != 4:
        return False
    if not _INTERPRET:
        if jax.default_backend() != "tpu" or x.dtype != jnp.bfloat16:
            return False
    if not peek_stats(x):
        return False
    from deeplearning4j_tpu.analysis.costmodel import (
        bn_instance_cost,
        instance_roofline,
    )

    cost = bn_instance_cost(x_shape=tuple(x.shape),
                            itemsize=jnp.dtype(x.dtype).itemsize)
    return instance_roofline(cost["flops"],
                             cost["bytes"])["verdict"] == "memory-bound"


def bn_bwd_supported(*, x_shape, dtype, training, **_):
    """Probe for the "bn_backward" slot (the fused reduce+apply pullback).
    Same backend/dtype scope as the forward kernels; the roofline consult
    prices the pullback's traffic (read g and x twice, write dx once) —
    like the normalize it is structurally memory-bound, and the consult
    keeps that a checked fact rather than an assumption."""
    if not training or len(x_shape) < 2:
        return False
    if not _INTERPRET:
        if jax.default_backend() != "tpu" or dtype != jnp.bfloat16:
            return False
        if x_shape[-1] % 64:
            return False
    elif not jnp.issubdtype(dtype, jnp.floating):
        return False
    from deeplearning4j_tpu.analysis.costmodel import (
        bn_instance_cost,
        instance_roofline,
    )

    cost = bn_instance_cost(x_shape=tuple(x_shape),
                            itemsize=jnp.dtype(dtype).itemsize,
                            n_reads=4, n_writes=1)
    return instance_roofline(cost["flops"],
                             cost["bytes"])["verdict"] == "memory-bound"


def _conv2d_helper(x, w, *, strides):
    y, s1, s2 = conv2d_bn_stats(x, w, tuple(int(s) for s in strides))
    # stop_gradient: the stats must never carry their own cotangent —
    # bn_apply's backward already accounts for them (module docstring)
    _stash_stats(y, lax.stop_gradient(s1), lax.stop_gradient(s2))
    return y


def _bn_helper(x, gamma, beta, eps):
    st = take_stats(x)
    if st is None:  # probe checked peek_stats; defensive
        raise RuntimeError("bn helper called without stashed conv stats")
    s1, s2 = st
    n = x.size // x.shape[-1]
    y, mean, var = bn_apply(x, s1, s2, gamma, beta, float(eps), n, False)
    # deferred ReLU: a downstream relu ActivationLayer swaps in the fused
    # variant; the plain-normalize call above then has no consumers and is
    # dead-code-eliminated at lowering
    _stash_relu(y, lambda: bn_apply(x, s1, s2, gamma, beta,
                                    float(eps), n, True)[0])
    return y, mean, var


def register():
    from deeplearning4j_tpu.ops.helpers import register_helper

    register_helper("conv2d", _conv2d_helper, conv_supported,
                    name="pallas_conv_bn_stats", family=conv_family)
    register_helper("batch_norm", _bn_helper, bn_supported,
                    name="pallas_fused_bn_apply",
                    family=lambda **_: "bn_apply")
    register_helper("bn_backward", bn_backward_fused, bn_bwd_supported,
                    name="pallas_fused_bn_bwd",
                    family=lambda **_: "bn_bwd")


register()
