"""Subprocess half of tests/test_sentinel_rollback.py.

Runs a small deterministic fit with checkpointing and the divergence
sentinel armed, under a seeded NaN-at-step-k fault plan, printing one
flushed line per training step ("STEP <iteration> <score>") and per
sentinel event ("EVENT <kind>") — so the parent can SIGKILL the process
at a moment of its choosing (the mid-rollback kill test holds fire until
"EVENT train_rollback", then the child's own 2s sleep inside the event
hook guarantees the signal lands while the rollback restore is still in
flight). The builders live here and the parent imports them, so the
killed run and the resumed run are the same model on the same batches by
construction.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np  # noqa: E402

N_EXAMPLES = 128
BATCH = 8
N_FEATURES = 8
N_CLASSES = 3
NAN_STEP = 8  # 1-based train_step invocation the plan taints


def build_net(seed: int = 7):
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
        Updater,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Updater.SGD)
            .learning_rate(0.05).weight_init("xavier").list()
            .layer(DenseLayer(n_in=N_FEATURES, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=N_CLASSES,
                               activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def build_iterator(seed: int = 0):
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator

    rng = np.random.default_rng(seed)
    full = DataSet(
        rng.standard_normal((N_EXAMPLES, N_FEATURES)).astype(np.float32),
        np.eye(N_CLASSES, dtype=np.float32)[
            rng.integers(0, N_CLASSES, N_EXAMPLES)])
    return ListDataSetIterator(full, BATCH)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--rollback-hold", type=float, default=0.0,
                    help="seconds the train_rollback event hook sleeps "
                         "(widens the parent's mid-rollback kill window)")
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.train.checkpoint import CheckpointListener
    from deeplearning4j_tpu.train.sentinel import DivergenceSentinel
    from deeplearning4j_tpu.utils import faultpoints as fp

    net = build_net()
    listener = CheckpointListener(
        args.ckpt_dir, every_n_iterations=3, every_n_epochs=None,
        keep_last=5, async_save=False)

    def on_event(kind, payload):
        print(f"EVENT {kind}", flush=True)
        if kind == "train_rollback" and args.rollback_hold > 0:
            time.sleep(args.rollback_hold)

    sentinel = DivergenceSentinel(rollback_after=1, max_rollbacks=2,
                                  on_event=on_event)

    class StepPrinter:
        def iteration_done(self, model, iteration, info):
            print(f"STEP {iteration} {float(np.asarray(info['score']()))}",
                  flush=True)

        def on_epoch_start(self, model, epoch):
            pass

        def on_epoch_end(self, model, epoch):
            pass

    net.set_listeners(listener, StepPrinter())
    net.set_sentinel(sentinel)
    plan = fp.FaultPlan(seed=1).add("train_step", "nan",
                                    between=(NAN_STEP, NAN_STEP))
    with fp.active(plan):
        net.fit(build_iterator(), epochs=1, async_prefetch=False)
    print(f"FIT DONE {float(np.asarray(net._score))}", flush=True)


if __name__ == "__main__":
    main()
