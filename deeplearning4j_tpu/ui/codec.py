"""Compact binary wire format for stats records.

The reference generates SBE (Simple Binary Encoding) codecs for its
listener payloads (ui/stats/sbe/, ~40 generated files;
SbeStatsReport.java). Capability = a compact, versioned, self-describing
binary mechanism — here a small struct-packed format:

  [magic u16][version u16][flags u32][i64 iteration][f64 ts]
  [f32 score][f32 etl_ms][f32 samples_per_sec][u32 n_series]
  then per series: [u16 name_len][name utf8][u32 n][f32 x n]

Scalars that don't fit the fixed header ride in the named-series section
as length-1 series. JSON in, JSON out — the binary layer is invisible to
callers (encode_record/decode_record).
"""

from __future__ import annotations

import struct
import time
from typing import Dict, List

MAGIC = 0xD14C
VERSION = 1

_HEADER = struct.Struct("<HHIqdfffI")


def encode_record(rec: dict) -> bytes:
    """dict -> bytes. Numeric lists become f32 series; scalar floats under
    non-reserved keys become length-1 series; nested dicts are flattened
    with '/' separators."""
    series: List[tuple] = []

    def flatten(prefix: str, obj):
        if isinstance(obj, dict):
            for k, v in obj.items():
                flatten(f"{prefix}/{k}" if prefix else str(k), v)
        elif isinstance(obj, (list, tuple)):
            if all(isinstance(v, (int, float)) for v in obj):
                series.append((prefix, [float(v) for v in obj]))
            else:
                for i, v in enumerate(obj):
                    flatten(f"{prefix}/{i}", v)
        elif isinstance(obj, (int, float)):
            series.append((prefix, [float(obj)]))
        # non-numeric leaves are dropped (strings live in static info)

    reserved = {"iteration", "ts", "score", "etl_ms", "samples_per_sec"}
    flatten("", {k: v for k, v in rec.items() if k not in reserved})

    out = [_HEADER.pack(
        MAGIC, VERSION, 0,
        int(rec.get("iteration", -1)),
        float(rec.get("ts", time.time())),
        float(rec.get("score", float("nan"))),
        float(rec.get("etl_ms", 0.0)),
        float(rec.get("samples_per_sec", 0.0)),
        len(series),
    )]
    for name, vals in series:
        nb = name.encode()
        out.append(struct.pack("<H", len(nb)))
        out.append(nb)
        out.append(struct.pack("<I", len(vals)))
        out.append(struct.pack(f"<{len(vals)}f", *vals))
    return b"".join(out)


def decode_record(data: bytes) -> dict:
    magic, version, _flags, iteration, ts, score, etl, sps, n_series = (
        _HEADER.unpack_from(data, 0))
    if magic != MAGIC:
        raise ValueError(f"bad magic 0x{magic:x}")
    if version != VERSION:
        raise ValueError(f"unsupported stats record version {version}")
    off = _HEADER.size
    series: Dict[str, list] = {}
    for _ in range(n_series):
        (nlen,) = struct.unpack_from("<H", data, off)
        off += 2
        name = data[off:off + nlen].decode()
        off += nlen
        (n,) = struct.unpack_from("<I", data, off)
        off += 4
        vals = list(struct.unpack_from(f"<{n}f", data, off))
        off += 4 * n
        series[name] = vals
    rec = {
        "iteration": iteration,
        "ts": ts,
        "score": score,
        "etl_ms": etl,
        "samples_per_sec": sps,
    }
    # unflatten '/'-separated names back into nested dicts
    for name, vals in series.items():
        parts = name.split("/")
        d = rec
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = vals[0] if len(vals) == 1 else vals
    return rec
