"""Deterministic fault injection — named points, seeded plans, replayable
chaos.

PR 6/7 proved detection (watchdog, flight recorder) and recovery
(eviction/respawn, journal replay, resume) against *hand-thrown* faults:
a kill -9 here, a raising helper there. Those tests are real but ad-hoc
— nobody can re-run "the failure from Tuesday" because the fault
sequence lived in a shell history. This module makes faults data:

* **Fault points** are named places in the code that ask, on every
  invocation, "should I fail right now?" — `fault_point("ckpt_write")`.
  The registered points (each threaded through its real call site):

      device_put        data/prefetch device-staging put
      ckpt_write        train/checkpoint zip serialization
      paramserver_rpc   parallel/paramserver client HTTP round-trip
      etl_worker        data/prefetch multi-worker host ETL
      helper_fn         ops/helpers guarded kernel dispatch
      replica_forward   parallel/inference device forward
      http_handler      utils/jsonhttp request dispatch
      train_step        nn/netbase fit-loop dispatch

  With no plan installed a fault point is one global read and a `None`
  compare — hot-path safe by construction.

* a **FaultPlan** is a seed plus a list of rules. Each rule names a
  point, a fault kind (`error` raises FaultInjected, `oom` raises
  InjectedOOM — a FaultInjected carrying the RESOURCE_EXHAUSTED marker
  so the real OOM-forensics path fires, `latency` sleeps, `hang` blocks
  until released or `hang_seconds` passes — long enough to trip the
  watchdog, bounded so a chaos run can never wedge the harness
  itself; `nan` and `corrupt` are COOPERATIVE kinds: `fault_point`
  returns the kind instead of raising, and the call site applies the
  damage through its real data path — `train_step` taints the batch's
  features with NaN so the divergence sentinel sees a genuine
  non-finite loss, `ckpt_write` byte-flips the written zip entry so
  checkpoint integrity verification sees genuine corruption; at call
  sites that don't honor them they are recorded but inert, which a
  plan author should treat like the vacuously-green rule warning
  above), and a schedule: `every_nth=N` (every Nth invocation
  of the point), `between=(a, b)` (invocation indices a..b inclusive),
  or `p=0.1` (an independent coin per invocation, drawn from a RNG
  seeded by (plan seed, point, rule index) — NOT wall-clock, NOT a
  shared global stream). Because every decision is a pure function of
  (seed, point name, per-point invocation index), the same plan over
  the same workload produces the same fault sequence — chaos runs are
  replayable, and `tests/test_chaos.py` asserts exactly that.

* every fired fault lands in the plan's **event log** (point,
  per-point invocation index, kind, rule) plus the shared metrics
  registry (`fault_injected_total{point,kind}`) and the flight
  recorder, so a chaos run's forensics look like a real incident's.

Event-log ordering: per-point invocation counters are independent, so
two runs with identical per-point sequences may interleave points
differently across threads. `event_log()` therefore returns events
sorted by (point, invocation) — the canonical, thread-schedule-free
order replay equality is defined over.
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

KINDS = ("error", "latency", "hang", "oom", "nan", "corrupt")

# the sanctioned point names — fault_point() accepts any name (a new
# call site should not need a registry edit to exist), but plans naming
# an unknown point are rejected loudly: a typo'd rule that never fires
# would make a chaos run vacuously green
KNOWN_POINTS = (
    "device_put",
    "ckpt_write",
    "paramserver_rpc",
    "etl_worker",
    "helper_fn",
    "replica_forward",
    "http_handler",
    "train_step",
    "decode_step",
)


class FaultInjected(RuntimeError):
    """An `error`-kind fault fired at a fault point. Carries the point
    name so handlers (and test assertions) can tell injected faults from
    organic ones."""

    def __init__(self, point: str, invocation: int,
                 message: Optional[str] = None):
        super().__init__(
            message
            or f"injected fault at {point!r} (invocation {invocation})")
        self.point = point
        self.invocation = invocation


class InjectedOOM(FaultInjected):
    """An `oom`-kind fault: a FaultInjected whose message carries the
    RESOURCE_EXHAUSTED marker, so it takes exactly the code path a real
    device allocator failure takes (utils/devprof.is_oom recognizes it,
    the fit loop / serving dispatcher run their OOM forensics on it) —
    the deterministic way to rehearse an OOM end to end."""

    def __init__(self, point: str, invocation: int):
        super().__init__(
            point, invocation,
            f"RESOURCE_EXHAUSTED: injected oom at {point!r} "
            f"(invocation {invocation}) — out of memory rehearsal")


class FaultRule:
    """One (point, kind, schedule) entry of a plan. Exactly one schedule
    field must be set. Matching is pure in (invocation index, seeded
    coin), so rule evaluation is replay-deterministic."""

    def __init__(self, point: str, kind: str = "error",
                 every_nth: Optional[int] = None,
                 between: Optional[Sequence[int]] = None,
                 p: Optional[float] = None,
                 latency_ms: float = 50.0,
                 hang_seconds: float = 30.0,
                 max_fires: Optional[int] = None):
        if point not in KNOWN_POINTS:
            raise ValueError(
                f"unknown fault point {point!r} (known: {KNOWN_POINTS})")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (known: {KINDS})")
        schedules = [every_nth is not None, between is not None,
                     p is not None]
        if sum(schedules) != 1:
            raise ValueError(
                "exactly one of every_nth / between / p must be set")
        if every_nth is not None and int(every_nth) < 1:
            raise ValueError(f"every_nth must be >= 1, got {every_nth}")
        if between is not None:
            between = (int(between[0]), int(between[1]))
            if between[0] > between[1] or between[0] < 1:
                raise ValueError(f"bad between range {between}")
        if p is not None and not (0.0 <= float(p) <= 1.0):
            raise ValueError(f"p must be in [0, 1], got {p}")
        self.point = point
        self.kind = kind
        self.every_nth = None if every_nth is None else int(every_nth)
        self.between: Optional[Tuple[int, int]] = between
        self.p = None if p is None else float(p)
        self.latency_ms = float(latency_ms)
        self.hang_seconds = float(hang_seconds)
        self.max_fires = None if max_fires is None else int(max_fires)

    def to_dict(self) -> dict:
        out = {"point": self.point, "kind": self.kind}
        if self.every_nth is not None:
            out["every_nth"] = self.every_nth
        if self.between is not None:
            out["between"] = list(self.between)
        if self.p is not None:
            out["p"] = self.p
        if self.kind == "latency":
            out["latency_ms"] = self.latency_ms
        if self.kind == "hang":
            out["hang_seconds"] = self.hang_seconds
        if self.max_fires is not None:
            out["max_fires"] = self.max_fires
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "FaultRule":
        return cls(
            d["point"], d.get("kind", "error"),
            every_nth=d.get("every_nth"), between=d.get("between"),
            p=d.get("p"), latency_ms=d.get("latency_ms", 50.0),
            hang_seconds=d.get("hang_seconds", 30.0),
            max_fires=d.get("max_fires"))


class FaultPlan:
    """A seeded set of FaultRules plus the run's event log. One plan is
    installed process-wide at a time (`install`/`active`); every
    `fault_point()` call consults it under the plan's own lock, so the
    per-point invocation counters — the replay clock — never race."""

    def __init__(self, seed: int = 0,
                 rules: Optional[Sequence[FaultRule]] = None):
        self.seed = int(seed)
        self.rules: List[FaultRule] = list(rules or [])
        self._lock = threading.Lock()
        self._invocations: Dict[str, int] = {}
        self._fires: Dict[int, int] = {}  # rule index -> times fired
        self._events: List[dict] = []
        # hang faults block on this; release() frees every current and
        # future hang at once (scenario teardown / test cleanup)
        self._release = threading.Event()
        # per-(point, rule) coin streams, derived from the seed — NOT
        # shared, so adding a rule never perturbs another rule's draws
        self._rngs: Dict[Tuple[str, int], random.Random] = {}

    # -- construction / serde ------------------------------------------------

    def add(self, point: str, kind: str = "error", **kw) -> "FaultPlan":
        self.rules.append(FaultRule(point, kind, **kw))
        return self

    def to_json(self) -> str:
        return json.dumps({"seed": self.seed,
                           "rules": [r.to_dict() for r in self.rules]},
                          indent=2)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        doc = json.loads(text)
        return cls(doc.get("seed", 0),
                   [FaultRule.from_dict(r) for r in doc.get("rules", [])])

    # -- the decision --------------------------------------------------------

    def _rng(self, point: str, rule_idx: int) -> random.Random:
        key = (point, rule_idx)
        rng = self._rngs.get(key)
        if rng is None:
            rng = random.Random(f"{self.seed}:{point}:{rule_idx}")
            self._rngs[key] = rng
        return rng

    def decide(self, point: str) -> Optional[Tuple[FaultRule, int]]:
        """Count one invocation of `point` and return (rule, invocation)
        if a rule fires, else None. First matching rule wins. p-rules
        draw their coin EVERY invocation (fired or not) so the stream
        stays aligned with the invocation index across replays."""
        with self._lock:
            inv = self._invocations.get(point, 0) + 1
            self._invocations[point] = inv
            fired: Optional[Tuple[FaultRule, int]] = None
            for i, rule in enumerate(self.rules):
                if rule.point != point:
                    continue
                if rule.p is not None:
                    # consume the draw unconditionally (stream alignment)
                    hit = self._rng(point, i).random() < rule.p
                elif rule.every_nth is not None:
                    hit = inv % rule.every_nth == 0
                else:
                    hit = rule.between[0] <= inv <= rule.between[1]
                if not hit or fired is not None:
                    continue
                if (rule.max_fires is not None
                        and self._fires.get(i, 0) >= rule.max_fires):
                    continue
                self._fires[i] = self._fires.get(i, 0) + 1
                self._events.append({
                    "point": point, "invocation": inv,
                    "kind": rule.kind, "rule": i,
                })
                fired = (rule, inv)
            return fired

    # -- readout / lifecycle -------------------------------------------------

    def event_log(self) -> List[dict]:
        """Fired faults in canonical (point, invocation) order — the
        thread-schedule-free sequence replay equality is defined over."""
        with self._lock:
            return sorted(self._events,
                          key=lambda e: (e["point"], e["invocation"]))

    def invocations(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._invocations)

    def release(self):
        """Free every hang fault, current and future (teardown)."""
        self._release.set()

    def reset(self):
        """Zero the counters/log/RNG streams so the SAME plan object can
        replay from scratch (the determinism tests' second run)."""
        with self._lock:
            self._invocations.clear()
            self._fires.clear()
            self._events.clear()
            self._rngs.clear()
            # free anyone still parked on the OLD event before swapping
            # it out — otherwise a hung thread from the previous run
            # outlives every future release()
            self._release.set()
            self._release = threading.Event()


# -- the process-global active plan -------------------------------------------

_PLAN: Optional[FaultPlan] = None
_PLAN_LOCK = threading.Lock()


def install(plan: FaultPlan) -> FaultPlan:
    global _PLAN
    with _PLAN_LOCK:
        _PLAN = plan
    return plan


def clear():
    global _PLAN
    with _PLAN_LOCK:
        if _PLAN is not None:
            _PLAN.release()  # never strand a hung thread behind teardown
        _PLAN = None


def get_plan() -> Optional[FaultPlan]:
    return _PLAN


class active:
    """`with faultpoints.active(plan): ...` — install for a scope,
    always clear (and release hangs) on the way out."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        return install(self.plan)

    def __exit__(self, *exc):
        clear()
        return False


def fault_point(point: str, **ctx) -> Optional[str]:
    """The call-site hook. No plan: one global read, zero cost. With a
    plan: count the invocation, fire the first matching rule — raise
    (error/oom), sleep (latency), block until release/timeout (hang),
    or RETURN the kind for the cooperative kinds (`nan`, `corrupt`) the
    call site applies through its own data path."""
    plan = _PLAN
    if plan is None:
        return None
    decision = plan.decide(point)
    if decision is None:
        return None
    rule, inv = decision
    _observe(point, rule.kind, inv, ctx)
    if rule.kind == "error":
        raise FaultInjected(point, inv)
    if rule.kind == "oom":
        raise InjectedOOM(point, inv)
    if rule.kind == "latency":
        time.sleep(rule.latency_ms / 1e3)
        return None
    if rule.kind in ("nan", "corrupt"):
        return rule.kind
    # hang: block far past any stall budget, but bounded — an injected
    # hang must be able to trip the watchdog without being able to wedge
    # the chaos harness itself
    plan._release.wait(rule.hang_seconds)
    return None


def taint_nan(ds) -> None:
    """Apply a fired `nan` fault to a batch: poison its (first) feature
    array with NaN so the divergence flows through the REAL dispatch —
    forward, loss, backward — exactly as an organic numerical failure
    would (the sentinel then sees a genuinely non-finite loss/grad
    norm, not a synthetic flag). Works on host numpy and staged device
    arrays alike (`x + nan` builds a new array; the DataSet attribute
    is re-pointed, which the fit closure reads)."""
    feats = getattr(ds, "features", None)
    if isinstance(feats, (list, tuple)):  # MultiDataSet
        if not feats:
            return
        ds.features = [feats[0] + float("nan")] + list(feats[1:])
    elif feats is not None:
        ds.features = feats + float("nan")


def _observe(point: str, kind: str, invocation: int, ctx: dict):
    """Injected faults are observable like real ones: a registry series,
    a flight-recorder event, and a span-tree marker in the active trace
    (never fatal — a metrics bug must not change the injected behavior)."""
    try:
        from deeplearning4j_tpu.utils import metrics as _metrics

        _metrics.get_registry().counter(
            "fault_injected_total", "faults fired by the active FaultPlan",
            ("point", "kind")).labels(point, kind).inc()
    except Exception:
        pass
    try:
        from deeplearning4j_tpu.utils import blackbox as _blackbox

        _blackbox.get_recorder().record_event(
            "fault_injected", point=point, kind=kind,
            invocation=invocation, **{k: str(v) for k, v in ctx.items()})
    except Exception:
        pass
    try:
        # with tracing on, the fault lands INSIDE the trace of the
        # request/step it hit (fault points sit inside lifecycle spans,
        # or under an attach()ed context on pipeline threads) — `cli
        # chaos --trace-out` asserts exactly this linkage
        from deeplearning4j_tpu.utils import tracing as _tracing

        _tracing.instant("fault/injected", point=point, kind=kind,
                         invocation=invocation)
    except Exception:
        pass
