"""Core feed-forward layers: dense, output heads, embedding, activation,
dropout, autoencoder.

Reference impls: nn/layers/feedforward/dense/DenseLayer.java (preOutput =
input·W + b then activation, BaseLayer.java), BaseOutputLayer.java,
feedforward/embedding/EmbeddingLayer.java, DropoutLayer, ActivationLayer,
feedforward/autoencoder/AutoEncoder.java.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.registry import LayerContext, register_layer
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import apply_activation

try:  # deferred-ReLU hook of the Pallas conv/BN fusion; pallas may be
    # unavailable on this backend — resolve ONCE, not per forward call
    from deeplearning4j_tpu.ops.pallas_conv_bn import take_fused_relu
except Exception:  # pragma: no cover - pallas unavailable
    take_fused_relu = None


def apply_dropout(x, retain_prob, ctx: LayerContext):
    """Inverted dropout on a layer's *input*, matching the reference
    (BaseLayer.preOutput applies Dropout.applyDropout to the input;
    `dropout` is the retain probability, util/Dropout.java)."""
    if not ctx.training or retain_prob is None or retain_prob <= 0.0 or retain_prob >= 1.0:
        return x
    if ctx.rng is None:
        return x
    keep = jax.random.bernoulli(ctx.rng, retain_prob, x.shape)
    return jnp.where(keep, x / retain_prob, 0.0)


# -- dense -------------------------------------------------------------------

def dense_init(key, conf: L.DenseLayer, dtype):
    kw, _ = jax.random.split(key)
    W = init_weights(kw, (conf.n_in, conf.n_out), conf.n_in, conf.n_out,
                     conf.weight_init, conf.dist, dtype)
    b = jnp.full((conf.n_out,), conf.bias_init or 0.0, dtype)
    return {"W": W, "b": b}


def dense_forward(conf, params, x, ctx: LayerContext):
    x = apply_dropout(x, conf.dropout, ctx)
    z = x @ params["W"] + params["b"]
    return apply_activation(conf.activation, z, key=ctx.rng, training=ctx.training), None


register_layer(L.DenseLayer, dense_init, dense_forward)


# -- output heads ------------------------------------------------------------
# OutputLayer / RnnOutputLayer forward = dense + activation; the loss is
# applied by the network (reference: BaseOutputLayer.computeScore uses the
# layer's preOutput). RnnOutputLayer applies the same W time-distributed.

register_layer(L.OutputLayer, dense_init, dense_forward)


def rnn_output_forward(conf, params, x, ctx: LayerContext):
    # x: [batch, time, nIn] — einsum keeps the time axis batched for the MXU
    z = jnp.einsum("bti,io->bto", x, params["W"]) + params["b"]
    return apply_activation(conf.activation, z, key=ctx.rng, training=ctx.training), None


register_layer(L.RnnOutputLayer, dense_init, rnn_output_forward)


def center_loss_init(key, conf: L.CenterLossOutputLayer, dtype):
    return dense_init(key, conf, dtype)


def center_loss_state(conf: L.CenterLossOutputLayer, dtype):
    # per-class feature centers, EMA-updated outside the gradient
    # (reference: CenterLossOutputLayer / CenterLossParamInitializer 'cL')
    return {"centers": jnp.zeros((conf.n_out, conf.n_in), dtype)}


register_layer(L.CenterLossOutputLayer, center_loss_init, dense_forward,
               state_fn=center_loss_state)


# -- activation / dropout / loss (parameterless) -----------------------------

def _no_params(key, conf, dtype):
    return {}


def activation_forward(conf, params, x, ctx: LayerContext):
    if conf.activation == "relu" and take_fused_relu is not None:
        # deferred-ReLU hook of the Pallas conv/BN epilogue fusion: when x
        # is a stashed fused-BN output, swap in the normalize+ReLU variant
        # of that kernel (the plain-normalize call is then dead code and
        # XLA eliminates it) instead of a separate elementwise pass
        fused = take_fused_relu(x)
        if fused is not None:
            return fused, None
    return apply_activation(conf.activation, x, key=ctx.rng, training=ctx.training), None


register_layer(L.ActivationLayer, _no_params, activation_forward)


def dropout_forward(conf, params, x, ctx: LayerContext):
    return apply_dropout(x, conf.dropout, ctx), None


register_layer(L.DropoutLayer, _no_params, dropout_forward)


def loss_layer_forward(conf, params, x, ctx: LayerContext):
    return apply_activation(conf.activation, x, key=ctx.rng, training=ctx.training), None


register_layer(L.LossLayer, _no_params, loss_layer_forward)


# -- embedding ---------------------------------------------------------------

def embedding_init(key, conf: L.EmbeddingLayer, dtype):
    kw, _ = jax.random.split(key)
    W = init_weights(kw, (conf.n_in, conf.n_out), conf.n_in, conf.n_out,
                     conf.weight_init, conf.dist, dtype)
    out = {"W": W}
    if conf.has_bias:
        out["b"] = jnp.full((conf.n_out,), conf.bias_init or 0.0, dtype)
    return out


def embedding_forward(conf, params, x, ctx: LayerContext):
    """x: integer indices [batch] or [batch, 1] (reference:
    EmbeddingLayer.java — one-hot-equivalent lookup). XLA lowers the gather
    + scatter-add gradient natively on TPU."""
    idx = x.astype(jnp.int32)
    if idx.ndim == 2 and idx.shape[-1] == 1:
        idx = idx[:, 0]
    z = jnp.take(params["W"], idx, axis=0)
    if conf.has_bias:
        z = z + params["b"]
    return apply_activation(conf.activation, z, key=ctx.rng, training=ctx.training), None


def embedding_order(conf):
    return ("W", "b") if conf.has_bias else ("W",)


register_layer(L.EmbeddingLayer, embedding_init, embedding_forward, order_fn=embedding_order)


# -- autoencoder (supervised path) ------------------------------------------

def autoencoder_init(key, conf: L.AutoEncoder, dtype):
    kw, _ = jax.random.split(key)
    W = init_weights(kw, (conf.n_in, conf.n_out), conf.n_in, conf.n_out,
                     conf.weight_init, conf.dist, dtype)
    b = jnp.full((conf.n_out,), conf.bias_init or 0.0, dtype)
    vb = jnp.zeros((conf.n_in,), dtype)  # visible bias for reconstruction
    return {"W": W, "b": b, "vb": vb}


def autoencoder_forward(conf, params, x, ctx: LayerContext):
    x = apply_dropout(x, conf.dropout, ctx)
    z = x @ params["W"] + params["b"]
    return apply_activation(conf.activation, z, key=ctx.rng, training=ctx.training), None


def autoencoder_reconstruct(conf, params, x, ctx: LayerContext, corrupt: bool = True):
    """Unsupervised pass: corrupt -> encode -> decode with tied weights
    (reference: AutoEncoder.java decode uses W^T + visible bias)."""
    h_in = x
    if corrupt and ctx.training and ctx.rng is not None and conf.corruption_level > 0:
        keep = jax.random.bernoulli(ctx.rng, 1.0 - conf.corruption_level, x.shape)
        h_in = jnp.where(keep, x, 0.0)
    h = apply_activation(conf.activation, h_in @ params["W"] + params["b"])
    recon = apply_activation(conf.activation, h @ params["W"].T + params["vb"])
    return recon


def autoencoder_order(conf):
    return ("W", "b", "vb")


register_layer(L.AutoEncoder, autoencoder_init, autoencoder_forward,
               order_fn=autoencoder_order)
