"""Vocabulary construction + Huffman coding.

Analog of the reference's models/word2vec/wordstore/ (VocabCache,
AbstractCache, VocabConstructor — 612 LoC — and Huffman/HuffmanNode):
frequency-thresholded vocab built from a sequence stream, and the Huffman
tree that gives every word its hierarchical-softmax code (bit string) and
points (inner-node indices along the root path).
"""

from __future__ import annotations

import heapq
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np


class VocabWord:
    __slots__ = ("word", "count", "index", "code", "points")

    def __init__(self, word: str, count: int = 0, index: int = -1):
        self.word = word
        self.count = count
        self.index = index
        self.code: Optional[List[int]] = None     # Huffman bits (0/1)
        self.points: Optional[List[int]] = None   # inner-node indices

    def __repr__(self):
        return f"VocabWord({self.word!r}, count={self.count}, index={self.index})"


class VocabCache:
    """Word <-> index store with counts (reference: VocabCache SPI +
    AbstractCache impl)."""

    def __init__(self):
        self._words: List[VocabWord] = []
        self._by_word: Dict[str, VocabWord] = {}
        self.total_word_count = 0

    def add(self, word: str, count: int = 1):
        vw = self._by_word.get(word)
        if vw is None:
            vw = VocabWord(word, 0, len(self._words))
            self._words.append(vw)
            self._by_word[word] = vw
        vw.count += count
        self.total_word_count += count
        return vw

    def contains_word(self, word: str) -> bool:
        return word in self._by_word

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._by_word.get(word)

    def index_of(self, word: str) -> int:
        vw = self._by_word.get(word)
        return -1 if vw is None else vw.index

    def word_at_index(self, index: int) -> str:
        return self._words[index].word

    def word_frequency(self, word: str) -> int:
        vw = self._by_word.get(word)
        return 0 if vw is None else vw.count

    def num_words(self) -> int:
        return len(self._words)

    def words(self) -> List[str]:
        return [w.word for w in self._words]

    def vocab_words(self) -> List[VocabWord]:
        return list(self._words)

    def counts(self) -> np.ndarray:
        return np.asarray([w.count for w in self._words], np.int64)


class VocabConstructor:
    """Build a frequency-filtered vocab from token sequences (reference:
    models/word2vec/wordstore/VocabConstructor.java — parallel counting +
    min-frequency truncation; counting here is a single pass, the
    parallelism the reference needs for JVM-speed counting is unnecessary)."""

    def __init__(self, min_word_frequency: int = 1, limit: Optional[int] = None):
        self.min_word_frequency = int(min_word_frequency)
        self.limit = limit

    def build(self, sequences: Iterable[Sequence[str]]) -> VocabCache:
        counts: Dict[str, int] = {}
        for seq in sequences:
            for tok in seq:
                counts[tok] = counts.get(tok, 0) + 1
        # deterministic ordering: by descending count then word — gives
        # stable indices (the reference sorts by frequency for the Huffman
        # build and index assignment)
        items = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        if self.limit is not None:
            items = items[: self.limit]
        vocab = VocabCache()
        for word, c in items:
            if c >= self.min_word_frequency:
                vocab.add(word, c)
        return vocab


class Huffman:
    """Huffman-code a vocab for hierarchical softmax (reference:
    models/word2vec/Huffman.java): assigns each VocabWord its `code`
    (bits, root->leaf) and `points` (inner-node ids along the path). Inner
    nodes are numbered 0..V-2 and index rows of syn1."""

    MAX_CODE_LENGTH = 40

    def __init__(self, vocab: VocabCache):
        self.vocab = vocab
        self._build()

    def _build(self):
        words = self.vocab.vocab_words()
        V = len(words)
        if V == 0:
            self.max_code_length = 0
            return
        # heap of (count, tie, node_id); leaves are 0..V-1, inner V..2V-2
        heap = [(w.count, i, i) for i, w in enumerate(words)]
        heapq.heapify(heap)
        parent = np.zeros(2 * V - 1, np.int64)
        binary = np.zeros(2 * V - 1, np.int8)
        next_id = V
        tie = V
        while len(heap) > 1:
            c1, _, n1 = heapq.heappop(heap)
            c2, _, n2 = heapq.heappop(heap)
            parent[n1] = next_id
            parent[n2] = next_id
            binary[n2] = 1
            heapq.heappush(heap, (c1 + c2, tie, next_id))
            next_id += 1
            tie += 1
        root = heap[0][2]
        max_len = 0
        for i, w in enumerate(words):
            if V == 1:
                # degenerate single-word vocab: no inner nodes
                w.code, w.points = [], []
                continue
            # chain: leaf -> ... -> root
            chain = [i]
            while chain[-1] != root:
                chain.append(int(parent[chain[-1]]))
            # every node except the root carries the bit that selects it
            # from its parent; root->leaf order is the stored code
            code = [int(binary[n]) for n in chain[:-1]][::-1]
            # the inner nodes visited root->down (excluding the leaf) are
            # the syn1 rows scored at each bit; inner node k maps to row
            # k - V (word2vec.c point[] convention)
            points = [n - V for n in chain[1:][::-1]]
            w.code = code[: self.MAX_CODE_LENGTH]
            w.points = points[: len(w.code)]
            max_len = max(max_len, len(w.code))
        self.max_code_length = max_len

    def arrays(self):
        """(codes [V, L], points [V, L], lengths [V]) padded to the max
        code length — the static-shape form the jitted HS step consumes."""
        words = self.vocab.vocab_words()
        V = len(words)
        L = max(1, self.max_code_length)
        codes = np.zeros((V, L), np.int8)
        points = np.zeros((V, L), np.int64)
        lengths = np.zeros((V,), np.int32)
        for i, w in enumerate(words):
            n = len(w.code)
            codes[i, :n] = w.code
            points[i, :n] = w.points
            lengths[i] = n
        return codes, points, lengths
