"""Early stopping: conditions, savers, score calculators, trainer.

Reference: deeplearning4j-nn/.../earlystopping/ — EarlyStoppingConfiguration
+ termination conditions (termination/), model savers (saver/), score
calculators (scorecalc/), and the trainer loop with per-iteration and
per-epoch checks + exception capture
(trainer/BaseEarlyStoppingTrainer.java:76-131).
"""

from __future__ import annotations

import dataclasses
import io
import os
import time
from typing import Callable, List, Optional

import numpy as np


# -- termination conditions --------------------------------------------------

class EpochTerminationCondition:
    # conditions that read the score are only checked on epochs where a
    # fresh score was computed; score-free conditions (MaxEpochs, custom
    # wall-clock subclasses) run every epoch
    requires_score = True

    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, iteration: int, score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs (reference: MaxEpochsTerminationCondition)."""

    requires_score = False

    def __init__(self, max_epochs: int):
        self.max_epochs = int(max_epochs)

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs

    def __repr__(self):
        return f"MaxEpochsTerminationCondition({self.max_epochs})"


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score is at least this good (reference:
    BestScoreEpochTerminationCondition)."""

    def __init__(self, best_expected: float):
        self.best_expected = float(best_expected)

    def terminate(self, epoch, score):
        return score <= self.best_expected

    def __repr__(self):
        return f"BestScoreEpochTerminationCondition({self.best_expected})"


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs without (sufficient) improvement (reference:
    ScoreImprovementEpochTerminationCondition)."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = int(max_epochs_without_improvement)
        self.min_improvement = float(min_improvement)
        self.initialize()

    def initialize(self):
        self._best = None
        self._since = 0

    def terminate(self, epoch, score):
        if self._best is None or self._best - score > self.min_improvement:
            self._best = score
            self._since = 0
            return False
        self._since += 1
        return self._since >= self.patience

    def __repr__(self):
        return (f"ScoreImprovementEpochTerminationCondition("
                f"{self.patience}, {self.min_improvement})")


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    """Wall-clock budget (reference: MaxTimeIterationTerminationCondition)."""

    def __init__(self, max_seconds: float):
        self.max_seconds = float(max_seconds)
        self.initialize()

    def initialize(self):
        self._t0 = time.monotonic()

    def terminate(self, iteration, score):
        return time.monotonic() - self._t0 >= self.max_seconds

    def __repr__(self):
        return f"MaxTimeIterationTerminationCondition({self.max_seconds}s)"


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort when the score exceeds a bound — divergence guard (reference:
    MaxScoreIterationTerminationCondition)."""

    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def terminate(self, iteration, score):
        return score > self.max_score

    def __repr__(self):
        return f"MaxScoreIterationTerminationCondition({self.max_score})"


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort on NaN/Inf score (reference:
    InvalidScoreIterationTerminationCondition).

    Detection routes through train/sentinel.check_score — the ONE
    non-finite classification path — so a termination here lands in the
    same books as an in-fit sentinel anomaly:
    `train_anomaly_total{kind="nonfinite_loss"}` plus a flight-recorder
    event, instead of a silent ad-hoc isfinite."""

    def terminate(self, iteration, score):
        from deeplearning4j_tpu.train import sentinel as _sentinel

        return _sentinel.check_score(iteration, score,
                                     origin="earlystopping")

    def __repr__(self):
        return "InvalidScoreIterationTerminationCondition()"


# -- model savers ------------------------------------------------------------

class InMemoryModelSaver:
    """Keep the best/latest model cloned in memory (reference:
    saver/InMemoryModelSaver.java)."""

    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, net, score):
        self._best = net.clone()

    def save_latest_model(self, net, score):
        self._latest = net.clone()

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver:
    """Persist best/latest model zips in a directory (reference:
    saver/LocalFileModelSaver.java — bestModel.bin/latestModel.bin)."""

    def __init__(self, directory: str):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.directory, name)

    def save_best_model(self, net, score):
        from deeplearning4j_tpu.utils.model_serializer import save_model

        save_model(net, self._path("bestModel.zip"))

    def save_latest_model(self, net, score):
        from deeplearning4j_tpu.utils.model_serializer import save_model

        save_model(net, self._path("latestModel.zip"))

    def get_best_model(self):
        from deeplearning4j_tpu.utils.model_serializer import load_model

        return load_model(self._path("bestModel.zip"))

    def get_latest_model(self):
        from deeplearning4j_tpu.utils.model_serializer import load_model

        return load_model(self._path("latestModel.zip"))


# -- score calculators -------------------------------------------------------

class DataSetLossCalculator:
    """Average loss over a held-out set (reference:
    scorecalc/DataSetLossCalculator.java). Works for MultiLayerNetwork and
    ComputationGraph (the reference needed a separate CG class)."""

    def __init__(self, data, average: bool = True):
        self.data = data
        self.average = average

    def calculate_score(self, net) -> float:
        from deeplearning4j_tpu.data.iterators import DataSetIterator

        if isinstance(self.data, DataSetIterator):
            total, n = 0.0, 0
            for ds in self.data:
                s = net.score(ds)
                b = ds.num_examples()
                total += s * b
                n += b
            self.data.reset()
            if n == 0:
                return float("nan")
            return total / n if self.average else total
        return net.score(self.data)


# -- configuration + result --------------------------------------------------

@dataclasses.dataclass
class EarlyStoppingConfiguration:
    """Mirrors the reference's EarlyStoppingConfiguration.Builder fields."""

    score_calculator: object
    epoch_termination_conditions: List[EpochTerminationCondition] = dataclasses.field(default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = dataclasses.field(default_factory=list)
    model_saver: object = dataclasses.field(default_factory=InMemoryModelSaver)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


class TerminationReason:
    EPOCH_CONDITION = "epoch_termination_condition"
    ITERATION_CONDITION = "iteration_termination_condition"
    ERROR = "error"


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: str
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    score_vs_epoch: dict
    best_model: object


class _IterationStop(Exception):
    def __init__(self, condition, score):
        self.condition = condition
        self.score = score


class _IterationConditionListener:
    """Fit listener evaluating iteration-level conditions on every step
    (reference: BaseEarlyStoppingTrainer checks inside the fit loop)."""

    def __init__(self, conditions):
        self.conditions = conditions

    def on_epoch_start(self, net, epoch):
        pass

    def on_epoch_end(self, net, epoch):
        pass

    def iteration_done(self, net, iteration, info):
        score = float(np.asarray(info["score"]()))
        for c in self.conditions:
            if c.terminate(iteration, score):
                raise _IterationStop(c, score)


class EarlyStoppingTrainer:
    """Train with early stopping (reference:
    trainer/BaseEarlyStoppingTrainer.java:76-131; works for both network
    types because fit()/score()/clone() are the shared surface)."""

    def __init__(self, config: EarlyStoppingConfiguration, net, train_data,
                 labels=None, batch_size: int = 32):
        self.config = config
        self.net = net
        self.train_data = train_data
        self.labels = labels
        self.batch_size = batch_size

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        listener = (
            _IterationConditionListener(cfg.iteration_termination_conditions)
            if cfg.iteration_termination_conditions else None
        )
        if listener is not None:
            self.net.add_listener(listener)

        score_vs_epoch = {}
        best_score = None
        best_epoch = -1
        epoch = 0
        reason = TerminationReason.EPOCH_CONDITION
        details = ""
        try:
            while True:
                try:
                    self.net.fit(self.train_data, self.labels, epochs=1,
                                 batch_size=self.batch_size,
                                 async_prefetch=False)
                except _IterationStop as stop:
                    reason = TerminationReason.ITERATION_CONDITION
                    details = repr(stop.condition)
                    break
                last_score = None
                if (epoch % max(1, cfg.evaluate_every_n_epochs)) == 0:
                    last_score = float(
                        cfg.score_calculator.calculate_score(self.net)
                    )
                    score_vs_epoch[epoch] = last_score
                    if best_score is None or last_score < best_score:
                        best_score = last_score
                        best_epoch = epoch
                        cfg.model_saver.save_best_model(self.net, last_score)
                    if cfg.save_last_model:
                        cfg.model_saver.save_latest_model(self.net, last_score)
                # score-free epoch conditions run EVERY epoch (so MaxEpochs
                # cannot overshoot when evaluate_every_n_epochs > 1);
                # score-based conditions only where a fresh score exists
                stop_now = None
                for c in cfg.epoch_termination_conditions:
                    if c.requires_score and last_score is None:
                        continue  # don't re-judge a stale score
                    if c.terminate(epoch, last_score):
                        stop_now = c
                        break
                if stop_now is not None:
                    reason = TerminationReason.EPOCH_CONDITION
                    details = repr(stop_now)
                    break
                epoch += 1
        except Exception as e:  # capture, don't crash (reference :113)
            reason = TerminationReason.ERROR
            details = f"{type(e).__name__}: {e}"
        finally:
            if listener is not None and listener in self.net.listeners:
                self.net.listeners.remove(listener)

        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            total_epochs=epoch + 1,
            best_model_epoch=best_epoch,
            best_model_score=best_score if best_score is not None else float("nan"),
            score_vs_epoch=score_vs_epoch,
            best_model=cfg.model_saver.get_best_model(),
        )
