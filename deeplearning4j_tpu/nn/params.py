"""Flattened parameter views.

The reference's load-bearing design: one flat buffer for all params
(MultiLayerNetwork.java:102-104 flattenedParams/flattenedGradients), with
each layer's ParamInitializer defining its slice layout (nn/params/*). Here
parameters natively live as a pytree (list of per-layer dicts) — XLA needs
no flat buffer for fused updates — but the flat view remains the API for
serialization (coefficients.bin), parameter averaging and params()/
setParams() compatibility.

Flattening order: layer index ascending, then the layer's param_order()
names, each tensor row-major. Deterministic across processes and device
counts.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.layers.registry import param_order


def num_params(layer_confs, params_list) -> int:
    return sum(
        int(np.prod(p[name].shape))
        for conf, p in zip(layer_confs, params_list)
        for name in param_order(conf)
        if name in p
    )


def params_to_flat(layer_confs, params_list) -> jnp.ndarray:
    """Concatenate all parameters into one 1-D vector (reference:
    flattenedParams view order)."""
    chunks = []
    for conf, p in zip(layer_confs, params_list):
        for name in param_order(conf):
            if name in p:
                chunks.append(jnp.ravel(p[name]))
    if not chunks:
        return jnp.zeros((0,), jnp.float32)
    return jnp.concatenate(chunks)


def flat_to_params(layer_confs, params_list, flat) -> List[Dict]:
    """Inverse of params_to_flat: scatter a flat vector back into a pytree
    with the same shapes as params_list."""
    out = []
    off = 0
    flat = jnp.asarray(flat)
    for conf, p in zip(layer_confs, params_list):
        new = dict(p)
        for name in param_order(conf):
            if name in p:
                n = int(np.prod(p[name].shape))
                new[name] = flat[off : off + n].reshape(p[name].shape).astype(p[name].dtype)
                off += n
        out.append(new)
    if off != flat.shape[0]:
        raise ValueError(f"flat vector length {flat.shape[0]} != model params {off}")
    return out


def param_table(layer_confs, params_list) -> List[Tuple[str, Tuple[int, ...], int]]:
    """[(qualified_name, shape, size)] in flattening order — the analog of
    the reference's paramTable() keys like '0_W', '1_b'."""
    rows = []
    for i, (conf, p) in enumerate(zip(layer_confs, params_list)):
        for name in param_order(conf):
            if name in p:
                rows.append((f"{i}_{name}", tuple(p[name].shape), int(np.prod(p[name].shape))))
    return rows
