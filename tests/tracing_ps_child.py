"""Subprocess half of the cross-process trace-propagation test
(tests/test_tracing_distributed.py).

Runs an EmbeddingParameterServer with tracing enabled, prints the bound
port, then waits on stdin; any line (or EOF) makes it export its span
ring as JSONL to the path in argv[1] and exit. The parent asserts that
the trace id it minted client-side shows up in THIS process's export
with the client RPC span as the server route span's ancestor — the W3C
traceparent hop across a real process boundary.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    out_path = sys.argv[1]

    import numpy as np

    from deeplearning4j_tpu.parallel.paramserver import (
        EmbeddingParameterServer,
    )
    from deeplearning4j_tpu.utils import tracing

    tracing.enable(True)
    server = EmbeddingParameterServer(
        {"syn0": np.zeros((16, 4), np.float32)})
    port = server.start()
    print(f"PORT {port}", flush=True)
    sys.stdin.readline()  # parent says "done" (or died: EOF)
    server.stop()
    tracing.get_tracer().write_jsonl(out_path)
    print("DUMPED", flush=True)


if __name__ == "__main__":
    main()
