"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

NEW capability beyond the reference (SURVEY §2.4: DL4J ships data
parallelism only — no tensor/pipeline/expert parallelism anywhere). When a
model's layer stack does not fit one chip's HBM, its repeated blocks are
sharded over the "stage" mesh axis: device s permanently holds stage s's
parameters, activations flow stage-to-stage over ICI neighbor links with
`lax.ppermute`, and the batch is split into microbatches so all stages work
concurrently (the GPipe schedule; Huang et al.). The whole schedule is a
`lax.scan` inside one `shard_map` — XLA sees a static loop and overlaps
each tick's permute with the next tick's compute, and autodiff through
scan+ppermute yields the reverse (backward) pipeline for free, so the same
jitted train step the rest of the framework uses works unchanged.

Layout:
  stage params  — every leaf stacked on a leading [S] dim, sharded over
                  the "stage" axis (`shard_stage_params`)
  activations   — microbatch-resident, [mb, ...]; only the ppermute edge
                  crosses devices
  inputs/outputs— replicated [B, ...]; stage 0 feeds microbatch t at tick
                  t, the last stage's outputs are psum-broadcast once at
                  the end

The schedule runs S + M - 1 ticks for M microbatches over S stages
(pipeline bubble = (S-1)/(S+M-1) of the ticks; raise M to amortize).

Equivalence proof vs the sequential stack (values AND gradients) on the
8-device CPU mesh: tests/test_pipeline_parallel.py.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

STAGE_AXIS = "stage"


def pipeline_parallel_mesh(devices=None, axis_name: str = STAGE_AXIS) -> Mesh:
    """1-D mesh over the given (or all) devices with a single "stage" axis."""
    import numpy as np

    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis_name,))


def shard_stage_params(stacked_params, mesh: Mesh,
                       axis_name: str = STAGE_AXIS):
    """Place stage-stacked parameters (every leaf [S, ...]) with their
    leading dim sharded over the stage axis — device s holds only stage
    s's slice, the pipeline analog of tensor.py's `shard_params_tp`."""
    sh = NamedSharding(mesh, PartitionSpec(axis_name))
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh),
                                  stacked_params)


def _pipeline_body(stage_fn, stacked_params, x_mb, *, axis_name: str,
                   n_stages: int):
    """The shard_map body. `stacked_params` leaves arrive as [1, ...] local
    slices (this device's stage); `x_mb` is the full [M, mb, ...]
    microbatch stack, replicated. Returns the pipeline output [M, mb, ...]
    (replicated via one final psum)."""
    S = n_stages
    M = x_mb.shape[0]
    idx = lax.axis_index(axis_name)
    local_params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)

    perm = [(i, i + 1) for i in range(S - 1)]  # stage i -> i+1, no wrap
    zero_state = jnp.zeros(x_mb.shape[1:], x_mb.dtype)

    def tick(state, t):
        # stage 0 ingests microbatch t. Drain ticks (t >= M) re-feed
        # microbatch M-1: its re-processed results can never reach the
        # last stage within the S+M-1-tick window, so they are
        # output-invisible (forward and backward) — deliberate trade-off
        # keeping every tick's ops identical for XLA instead of gating
        # stage-0 compute on t < M
        feed = lax.dynamic_index_in_dim(x_mb, jnp.clip(t, 0, M - 1), 0,
                                        keepdims=False)
        state_in = jnp.where(idx == 0, feed, state)
        out = stage_fn(local_params, state_in)
        # the last stage's result at tick t is final output microbatch
        # t - (S - 1); zero elsewhere so the end-of-scan psum broadcasts it
        y_t = jnp.where(idx == S - 1, out, jnp.zeros_like(out))
        if S > 1:
            nxt = lax.ppermute(out, axis_name, perm)
        else:
            nxt = out
        return nxt, y_t

    if hasattr(lax, "pcast"):
        zero_state = lax.pcast(zero_state, (axis_name,), to="varying")
    elif hasattr(lax, "pvary"):  # pre-0.9 jax
        zero_state = lax.pvary(zero_state, (axis_name,))
    _, ys = lax.scan(tick, zero_state, jnp.arange(S + M - 1))
    ys = ys[S - 1:]                      # drop fill ticks: [M, mb, ...]
    return lax.psum(ys, axis_name)       # only the last stage is nonzero


def pipeline_apply(stage_fn: Callable, stacked_params, x, *, mesh: Mesh,
                   n_microbatches: int, axis_name: str = STAGE_AXIS):
    """Run `x` through S pipelined stages of `stage_fn`.

    Args:
        stage_fn: (params_one_stage, x[mb, ...]) -> y[mb, ...] — must be
            shape-preserving (same in/out shape, as for repeated blocks);
            put embed/head layers outside the pipelined region.
        stacked_params: pytree, every leaf [S, ...] (stage-major), placed
            with `shard_stage_params` (or any layout GSPMD can reshard).
        x: global batch [B, ...], B divisible by n_microbatches.
        mesh: mesh with the stage axis; its size is S.
        n_microbatches: M — higher amortizes the (S-1)-tick bubble.

    Returns [B, ...], replicated. Differentiable: `jax.grad` through this
    yields the reverse pipeline schedule.
    """
    S = int(mesh.shape[axis_name])
    B = x.shape[0]
    M = int(n_microbatches)
    if B % M:
        raise ValueError(f"batch {B} not divisible by n_microbatches {M}")
    x_mb = x.reshape((M, B // M) + x.shape[1:])

    body = partial(_pipeline_body, stage_fn, axis_name=axis_name,
                   n_stages=S)
    p_spec = jax.tree_util.tree_map(
        lambda _: PartitionSpec(axis_name), stacked_params)
    from deeplearning4j_tpu.parallel.mesh import shard_map_fn

    out = shard_map_fn()(
        body, mesh=mesh,
        in_specs=(p_spec, PartitionSpec()),
        out_specs=PartitionSpec(),
    )(stacked_params, x_mb)
    return out.reshape((B,) + out.shape[2:])


def sequential_apply(stage_fn: Callable, stacked_params, x):
    """Single-device reference semantics: the same stages applied in
    order (what the pipeline must exactly reproduce)."""
    S = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    for s in range(S):
        p_s = jax.tree_util.tree_map(lambda a: a[s], stacked_params)
        x = stage_fn(p_s, x)
    return x
