"""Concurrency/robustness lint — AST checkers over the repo itself.

The thread-leak class PR 4 fixed by hand (producers blocked forever on
queues nobody drains, anonymous daemon threads impossible to attribute
in a dump) had no tool preventing its reintroduction. This pass encodes
those conventions as enforceable checks, in the spirit of compile-time
race detection (RacerD, Blackshear et al.) scaled to what an AST can
prove:

  CC001  bare `except:` — swallows KeyboardInterrupt/SystemExit and
         hides real bugs; catch something
  CC002  queue .put/.get without a timeout in a module that runs
         threads — the caller wedges forever when its peer dies
         (data/'s `_put_abortable`/`_get_abortable` and
         utils/concurrency are the sanctioned shapes)
  CC003  thread constructed without a name — undiagnosable in thread
         dumps; the dl4j-* naming convention is enforced
  CC004  thread neither daemon nor joined in its creating scope — can
         hold the interpreter alive on exit
  CC005  lock-order cycle: nested lock scopes — `with <lock>:` AND the
         `acquire()`/`try`/`finally`/`release()` call form, including
         Condition-guarded locks — acquiring locks in conflicting
         orders across the module (static deadlock)
  CC006  print() in library code — the deeplearning4j_tpu logger is the
         only sanctioned channel (cli.py and bench.py are operator
         surfaces and exempt)
  CC007  `time.time()` in deadline/timeout arithmetic — wall-clock
         jumps (NTP slew, manual resets) silently shrink or stretch a
         deadline computed from it; time.monotonic() is the only clock
         deadlines may be built on. Detected when a statement both
         calls `time.time()` and mentions a deadline-ish identifier
         (deadline/timeout/expire/remaining/retry_after...); plain
         timestamping (`"ts": time.time()`) stays legal.

The pass also feeds the concurrency-audit vocabulary (CN codes, see
analysis/concurrency_audit) where a finding is detectable without
running:

  CN002  blocking call lexically inside a held lock scope —
         time.sleep, queue get/put, a Condition/Event wait on *another*
         lock, Thread.join, socket/HTTP I/O, block_until_ready
         (WARNING: the runtime sanitizer is the authority; the lexical
         hit is the early warning)
  CN003  jitted-dispatch-shaped call (step_fn/fit_fn/*_jit) entered
         with a lock held (WARNING)

Findings carry stable names (`CODE:path:scope[#n]`, no line numbers) so
scripts/lint.sh can diff them against the committed
scripts/lint_baseline.txt exactly like tier-1 diffs failing-test names
against tests/tier1_baseline_failures.txt: the gate starts green and
only regressions fail.

Run: python -m deeplearning4j_tpu.analysis.lint [--json -] [paths...]
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

from deeplearning4j_tpu.analysis.findings import (
    ERROR,
    WARNING,
    Finding,
    error_names,
    format_findings,
    summarize,
)

DEFAULT_TARGETS = ("deeplearning4j_tpu", "bench.py")
# operator surfaces whose stdout IS the interface (lint.py's own CLI
# output included — it is what scripts/lint.sh reads)
PRINT_EXEMPT_BASENAMES = ("cli.py", "bench.py", "lint.py",
                          "concurrency_audit.py")
THREAD_NAME_PREFIX = "dl4j-"

# receiver heuristic for queue ops: the last attribute/name segment, sans
# leading underscores, is queue-ish ("q", "queue", "handoff", "*_q", ...)
_QUEUE_NAME = re.compile(r"^_*(q|queue|handoff|.*_q|.*_queue|.*_handoff)$")
_LOCK_NAME = re.compile(r"(^|_)(lock|mutex)s?$", re.IGNORECASE)
# Condition-ish receivers guard a lock: `with self._wake:` acquires the
# underlying lock exactly like `with self._lock:` does, so they join
# the same lock-order graph (and `<cond>.wait()` releases only its OWN
# lock — waiting while another lock is held is a CN002)
_CONDISH = re.compile(
    r"(^|_)(cond|cv|condition|wake|not_empty|not_full|all_tasks_done)s?$",
    re.IGNORECASE)
# Event-ish receivers: `.wait()` on one of these blocks without
# releasing anything — always a CN002 under a held lock
_EVENTISH = re.compile(
    r"(^|_)(event|evt)s?$|(^|_)stop(ped)?$|(^|_)(done|ready)$",
    re.IGNORECASE)
# jitted-dispatch-shaped callables for the static CN003 heuristic
_JIT_FN = re.compile(r"(^|_)(step_fn|fit_fn|train_fn)$|jitted|_jit$")
# identifiers that mark a statement as deadline/timeout arithmetic
# (CC007): a `time.time()` in the same statement is wall-clock math on
# a duration contract
_DEADLINE_NAME = re.compile(
    r"deadline|timeout|expire|expiry|remaining|retry_after|retry_by|"
    r"stall_after|due_at", re.IGNORECASE)


def _is_walltime_call(node: ast.Call) -> bool:
    """`time.time()` — the wall clock. (A bare `time()` from
    `from time import time` is rare in this repo and ambiguous with
    user-defined callables, so only the dotted form is claimed.)"""
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "time"
            and isinstance(f.value, ast.Name) and f.value.id == "time"
            and not node.args and not node.keywords)


def _is_queue_receiver(node: ast.expr) -> bool:
    if isinstance(node, ast.Attribute):
        return bool(_QUEUE_NAME.match(node.attr))
    if isinstance(node, ast.Name):
        return bool(_QUEUE_NAME.match(node.id))
    return False


# receiver names that plausibly hold a thread: `t`, `t0`, anything with
# thread/worker in it, or the `_collect_t`-style `*_t` suffix convention
_THREADISH = re.compile(r"^t\d*$|thread|worker|_t$", re.IGNORECASE)


def _is_threadish_receiver(node: ast.expr) -> bool:
    if isinstance(node, ast.Attribute):
        return bool(_THREADISH.search(node.attr))
    if isinstance(node, ast.Name):
        return bool(_THREADISH.search(node.id))
    return False


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_true(node: Optional[ast.expr]) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


def _blocking_without_timeout(node: ast.Call, is_get: bool) -> bool:
    """Whether a queue .get/.put call can block with no deadline.
    Signatures: get(block=True, timeout=None); put(item, block=True,
    timeout=None). An explicit block=False — keyword OR positional —
    raises Empty/Full immediately and cannot wedge; a present timeout
    (keyword or positional) bounds the block."""
    args = node.args
    if any(isinstance(a, ast.Starred) for a in args):
        return False  # cannot reason statically
    if _kwarg(node, "timeout") is not None:
        return False
    block_kw = _kwarg(node, "block")
    if isinstance(block_kw, ast.Constant) and block_kw.value is False:
        return False
    pos_block = 0 if is_get else 1
    if len(args) > pos_block + 1:
        return False  # timeout passed positionally
    if len(args) > pos_block:
        b = args[pos_block]
        if isinstance(b, ast.Constant) and b.value is False:
            return False  # q.get(False) / q.put(x, False)
        return True  # q.get(True) / q.put(x, True): blocking, no timeout
    if not is_get and len(args) < 1:
        return False  # put() with item passed by keyword — not our shape
    return True


def _lock_source(node: ast.expr) -> Optional[str]:
    """Dotted source of a lock-ish (or Condition-ish — a Condition
    guards a lock) expression, or None."""
    try:
        src = ast.unparse(node)
    except Exception:
        return None
    last = src.split(".")[-1].split("(")[0]
    if _LOCK_NAME.search(last) or _CONDISH.search(last):
        return src
    return None


def _is_eventish_receiver(node: ast.expr) -> bool:
    if isinstance(node, ast.Attribute):
        return bool(_EVENTISH.search(node.attr))
    if isinstance(node, ast.Name):
        return bool(_EVENTISH.search(node.id))
    return False


def _is_nonblocking_qcall(node: ast.Call, is_get: bool) -> bool:
    """block=False (keyword or positional) — raises instead of blocking."""
    block_kw = _kwarg(node, "block")
    if isinstance(block_kw, ast.Constant) and block_kw.value is False:
        return True
    pos = 0 if is_get else 1
    if len(node.args) > pos:
        b = node.args[pos]
        if isinstance(b, ast.Constant) and b.value is False:
            return True
    return False


class _ModuleLinter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, tree: ast.Module):
        self.path = path
        self.rel = rel
        self.tree = tree
        self.findings: List[Finding] = []
        self._scope: List[str] = []          # qualname stack
        self._per_scope_counts: Dict[Tuple[str, str], int] = {}
        self._lock_stack: List[str] = []     # locks held lexically
        self._class_stack: List[str] = []
        # module-wide lock-order edges: (a, b) -> first location
        self.lock_edges: Dict[Tuple[str, str], str] = {}
        # `path:line` of a threading.Lock/RLock/Condition construction
        # -> lexical lock key; lets concurrency_audit join the RUNTIME
        # lock-order graph (keyed by construction site) with this
        # lexical one (keyed by Class.attr)
        self.lock_ctor_sites: Dict[str, str] = {}
        src = ast.dump(tree)
        self.runs_threads = ("Thread" in src) or any(
            isinstance(n, (ast.Import, ast.ImportFrom))
            and "threading" in ast.dump(n)
            for n in tree.body)
        self.print_exempt = os.path.basename(path) in PRINT_EXEMPT_BASENAMES

    # -- helpers -------------------------------------------------------------

    def _qualname(self) -> str:
        return ".".join(self._scope) or "<module>"

    def _emit(self, code: str, severity: str, node: ast.AST, message: str,
              fix_hint: str):
        scope = self._qualname()
        key = (code, scope)
        n = self._per_scope_counts.get(key, 0) + 1
        self._per_scope_counts[key] = n
        suffix = "" if n == 1 else f"#{n}"
        self.findings.append(Finding(
            code, severity, f"{self.rel}:{node.lineno}", message, fix_hint,
            name=f"{code}:{self.rel}:{scope}{suffix}"))

    def _lock_key(self, src: str) -> str:
        # class-attribute locks are keyed by Class.attr WITHOUT the
        # module path, so acquisitions of the same class's locks connect
        # across modules in the repo-wide edge graph; module-level locks
        # stay module-scoped (a bare name means nothing elsewhere)
        if src.startswith("self.") and self._class_stack:
            return f"{self._class_stack[-1]}.{src[5:]}"
        return f"{self.rel}:{src}"

    # -- scope tracking ------------------------------------------------------

    def _visit_scope(self, node, name: str):
        self._scope.append(name)
        held = list(self._lock_stack)
        self._lock_stack = []  # lexical lock nesting does not cross defs
        self.generic_visit(node)
        self._lock_stack = held
        self._scope.pop()

    def visit_FunctionDef(self, node):
        self._visit_scope(node, node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._class_stack.append(node.name)
        self._visit_scope(node, node.name)
        self._class_stack.pop()

    # -- CC007 statement tracking --------------------------------------------

    # the statement currently being visited: CC007 is a statement-level
    # judgment ("this statement does deadline math on the wall clock"),
    # but the trigger is a Call node deep inside it
    _stmt: Optional[ast.stmt] = None

    def visit(self, node):
        if isinstance(node, ast.stmt):
            self._stmt = node
        return super().visit(node)

    # a compound statement's nested suites are separate statements with
    # their own judgment — `if time.time() - last > 60:` must not become
    # a finding just because its BODY mentions a timeout somewhere
    _NESTED_SUITE_FIELDS = ("body", "orelse", "finalbody", "handlers")

    @classmethod
    def _mentions_deadline(cls, stmt: ast.stmt) -> bool:
        """Any identifier in the statement's own expressions — name,
        attribute, parameter, keyword argument — that reads as
        deadline/timeout vocabulary. Nested suites are excluded (each
        inner statement is judged on its own), and string constants
        ('{"ts": time.time()}') deliberately do NOT count: timestamping
        stays legal."""
        roots = []
        for field, value in ast.iter_fields(stmt):
            if field in cls._NESTED_SUITE_FIELDS:
                continue
            for n in (value if isinstance(value, list) else [value]):
                if isinstance(n, ast.AST):
                    roots.append(n)
        for root in roots:
            for sub in ast.walk(root):
                if isinstance(sub, ast.Name) \
                        and _DEADLINE_NAME.search(sub.id):
                    return True
                if isinstance(sub, ast.Attribute) \
                        and _DEADLINE_NAME.search(sub.attr):
                    return True
                if isinstance(sub, (ast.arg, ast.keyword)) \
                        and sub.arg and _DEADLINE_NAME.search(sub.arg):
                    return True
        return False

    # -- CC001 bare except ---------------------------------------------------

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self._emit(
                "CC001", ERROR, node,
                "bare `except:` swallows KeyboardInterrupt/SystemExit",
                "catch Exception (or something narrower) and handle or "
                "log it")
        self.generic_visit(node)

    # -- CC002/CC003/CC004/CC006 via calls -----------------------------------

    def visit_Call(self, node):
        func = node.func
        # CC006: print() in library code
        if (isinstance(func, ast.Name) and func.id == "print"
                and not self.print_exempt):
            self._emit(
                "CC006", ERROR, node,
                "print() in library code",
                'log via logging.getLogger("deeplearning4j_tpu") — or '
                "grandfather the site in scripts/lint_baseline.txt if it "
                "is a real operator surface")
        # CC003/CC004: threading.Thread(...) construction
        is_thread = (isinstance(func, ast.Name) and func.id == "Thread") or \
            (isinstance(func, ast.Attribute) and func.attr == "Thread")
        if is_thread:
            name_kw = _kwarg(node, "name")
            if name_kw is None:
                self._emit(
                    "CC003", ERROR, node,
                    "thread constructed without a name",
                    f'pass name="{THREAD_NAME_PREFIX}<component>-<role>" '
                    "so thread dumps are attributable")
            elif (isinstance(name_kw, ast.Constant)
                  and isinstance(name_kw.value, str)
                  and not name_kw.value.startswith(THREAD_NAME_PREFIX)):
                self._emit(
                    "CC003", ERROR, node,
                    f"thread name {name_kw.value!r} does not follow the "
                    f"{THREAD_NAME_PREFIX}* convention",
                    f"prefix the name with {THREAD_NAME_PREFIX!r}")
            if not _is_true(_kwarg(node, "daemon")) \
                    and not self._daemon_assigned_nearby(node):
                self._emit(
                    "CC004", ERROR, node,
                    "thread is neither daemon=True nor visibly joined",
                    "pass daemon=True (and still close/join it "
                    "deterministically where possible)")
        # CC007: wall-clock deadline arithmetic. time.time() is only a
        # finding when the SAME statement speaks deadline vocabulary —
        # `deadline = time.time() + budget` is the bug (NTP slew moves
        # the deadline), `{"ts": time.time()}` is legal timestamping.
        if isinstance(node, ast.Call) and _is_walltime_call(node) \
                and self._stmt is not None \
                and self._mentions_deadline(self._stmt):
            self._emit(
                "CC007", ERROR, node,
                "time.time() in deadline/timeout arithmetic — wall-clock "
                "jumps silently shrink or stretch the deadline",
                "build deadlines on time.monotonic(); keep time.time() "
                "for human-facing timestamps only")
        # CC002: queue put/get without timeout in thread code
        if (self.runs_threads and isinstance(func, ast.Attribute)
                and func.attr in ("put", "get")
                and _is_queue_receiver(func.value)):
            if _blocking_without_timeout(node, is_get=func.attr == "get"):
                self._emit(
                    "CC002", ERROR, node,
                    f"queue .{func.attr}() without a timeout in thread "
                    "code — wedges forever when the peer thread dies",
                    "use utils/concurrency.put_abortable/get_abortable "
                    "(or pass timeout= in a poll loop)")
        # CC005 (call form): lock.acquire()/release() participate in the
        # same lock-order graph as `with lock:` — the try/finally idiom
        # was invisible to the lexical pass before
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            src = _lock_source(func.value)
            if src is not None:
                key = self._lock_key(src)
                for held in self._lock_stack:
                    if held != key:
                        self.lock_edges.setdefault(
                            (held, key), f"{self.rel}:{node.lineno}")
                self._lock_stack.append(key)
        elif isinstance(func, ast.Attribute) and func.attr == "release":
            src = _lock_source(func.value)
            if src is not None:
                key = self._lock_key(src)
                for i in range(len(self._lock_stack) - 1, -1, -1):
                    if self._lock_stack[i] == key:
                        del self._lock_stack[i]
                        break
        if self._lock_stack:
            self._check_blocking_under_lock(node, func)
        self.generic_visit(node)

    # -- CN002/CN003: blocking calls lexically under a held lock -------------

    def _check_blocking_under_lock(self, node: ast.Call, func):
        """Static half of the CN002/CN003 runtime probes (WARNING: the
        sanitizer is the authority, this is the no-run early warning).
        Waiting on a Condition that is itself on the lock stack is
        exempt for its OWN lock — `with cond: cond.wait()` is THE
        pattern — but still a finding when other locks stay held."""
        held = sorted(set(self._lock_stack))
        blocked = None
        if isinstance(func, ast.Attribute):
            attr = func.attr
            if attr == "sleep" and isinstance(func.value, ast.Name) \
                    and func.value.id == "time":
                blocked = "time.sleep"
            elif attr in ("get", "put") and _is_queue_receiver(func.value):
                if not _is_nonblocking_qcall(node, is_get=attr == "get"):
                    blocked = f"queue.{attr}"
            elif attr == "wait":
                src = _lock_source(func.value)
                if src is not None:
                    key = self._lock_key(src)
                    others = sorted(set(k for k in self._lock_stack
                                        if k != key))
                    if others:
                        blocked = "condition.wait"
                        held = others
                elif _is_eventish_receiver(func.value):
                    blocked = "event.wait"
            elif attr == "join" and _is_threadish_receiver(func.value):
                blocked = "thread.join"
            elif attr == "block_until_ready":
                blocked = "device_sync"
            elif attr in ("urlopen", "create_connection", "getresponse"):
                blocked = "socket/http"
        elif isinstance(func, ast.Name) and func.id == "urlopen":
            blocked = "socket/http"
        if blocked is not None:
            self._emit(
                "CN002", WARNING, node,
                f"{blocked} while holding lock(s) {', '.join(held)} — "
                "every peer contending for the lock stalls behind this "
                "call (and it can deadlock against the thread that "
                "would unblock it)",
                "snapshot state under the lock, release, THEN block; "
                "or baseline it in scripts/lock_baseline.txt with a "
                "comment")
            return
        tgt = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if tgt is not None and _JIT_FN.search(tgt):
            self._emit(
                "CN003", WARNING, node,
                f"jitted dispatch {tgt}() entered while holding lock(s) "
                f"{', '.join(held)} — the lock is held for a whole "
                "device program (and a compile, on the first call)",
                "stage inputs under the lock, dispatch outside it")

    def _daemon_assigned_nearby(self, call: ast.Call) -> bool:
        """True if the enclosing function also assigns `<x>.daemon = True`
        or joins a thread-ish receiver (conservative: any such statement
        counts). `join` is only credited when the receiver NAME looks
        like a thread — otherwise the ubiquitous str.join (`",".join`,
        `sep.join`) would silently disable the whole check."""
        scope = self._enclosing_function
        if scope is None:
            return False
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and tgt.attr == "daemon" \
                            and _is_true(sub.value):
                        return True
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "join" \
                    and _is_threadish_receiver(sub.func.value):
                return True
        return False

    # -- lock construction sites (runtime-graph join points) ------------------

    def visit_Assign(self, node):
        v = node.value
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute) \
                and v.func.attr in ("Lock", "RLock", "Condition") \
                and isinstance(v.func.value, ast.Name) \
                and v.func.value.id == "threading":
            for tgt in node.targets:
                try:
                    src = ast.unparse(tgt)
                except Exception:
                    continue
                self.lock_ctor_sites[f"{self.rel}:{v.lineno}"] = \
                    self._lock_key(src)
                break
        self.generic_visit(node)

    # -- CC005 lock-order edges ----------------------------------------------

    def visit_With(self, node):
        acquired = []
        for item in node.items:
            src = _lock_source(item.context_expr)
            if src is not None:
                key = self._lock_key(src)
                for held in self._lock_stack:
                    if held != key:
                        self.lock_edges.setdefault(
                            (held, key), f"{self.rel}:{node.lineno}")
                acquired.append(key)
                self._lock_stack.append(key)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self._lock_stack.pop()

    visit_AsyncWith = visit_With

    # -- generic visit keeps track of the innermost function -----------------

    _enclosing_function: Optional[ast.AST] = None

    def generic_visit(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            prev = self._enclosing_function
            self._enclosing_function = node
            super().generic_visit(node)
            self._enclosing_function = prev
        else:
            super().generic_visit(node)


def _find_cycles(edges: Dict[Tuple[str, str], str]) -> List[Tuple[List[str], str]]:
    """Cycles in the lock-order graph. Returns (cycle nodes, a location
    of one edge on the cycle)."""
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    cycles: List[Tuple[List[str], str]] = []
    seen_cycles: Set[Tuple[str, ...]] = set()
    state: Dict[str, int] = {}  # 0 visiting, 1 done

    def dfs(n: str, path: List[str]):
        state[n] = 0
        path.append(n)
        for m in sorted(graph.get(n, ())):
            if state.get(m) == 0:
                cycle = path[path.index(m):]
                sig = tuple(sorted(cycle))
                if sig not in seen_cycles:
                    seen_cycles.add(sig)
                    loc = edges.get((n, m)) or edges.get((m, cycle[0]), "?")
                    cycles.append((cycle + [m], loc))
            elif m not in state:
                dfs(m, path)
        path.pop()
        state[n] = 1

    for n in sorted(graph):
        if n not in state:
            dfs(n, [])
    return cycles


def _py_files(paths) -> List[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(root, f)
                           for f in files if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return sorted(out)


def collect(paths=DEFAULT_TARGETS, base_dir: Optional[str] = None):
    """Lint files/directories, returning the full lexical harvest:
    ``(findings, lock_edges, lock_ctor_sites)``. The extra two are what
    analysis/concurrency_audit merges with the runtime lock-order graph
    (edges -> static/runtime/both labels; ctor sites -> joining a
    runtime ``path:line`` lock class to its lexical ``Class.attr``
    key). Finding names are stable relative paths rooted at `base_dir`
    (default: cwd)."""
    base = os.path.abspath(base_dir or os.getcwd())
    findings: List[Finding] = []
    lock_edges: Dict[Tuple[str, str], str] = {}
    lock_ctor_sites: Dict[str, str] = {}
    for path in _py_files(paths):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, base).replace(os.sep, "/")
        try:
            with open(ap, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=ap)
        except (OSError, SyntaxError) as e:
            findings.append(Finding(
                "CC000", ERROR, rel, f"could not parse: {e}",
                "fix the file", name=f"CC000:{rel}"))
            continue
        linter = _ModuleLinter(ap, rel, tree)
        linter.visit(tree)
        findings.extend(linter.findings)
        lock_edges.update(linter.lock_edges)
        lock_ctor_sites.update(linter.lock_ctor_sites)
    for cycle, loc in _find_cycles(lock_edges):
        order = " -> ".join(cycle)
        findings.append(Finding(
            "CC005", ERROR, loc,
            f"lock-order cycle: {order} — two code paths acquire these "
            "locks in conflicting orders (potential deadlock)",
            "pick one global order for these locks and stick to it",
            name="CC005:" + "->".join(sorted(set(cycle)))))
    return findings, lock_edges, lock_ctor_sites


def lint_paths(paths=DEFAULT_TARGETS, base_dir: Optional[str] = None
               ) -> List[Finding]:
    """Lint files/directories; finding names are stable relative paths
    rooted at `base_dir` (default: cwd)."""
    return collect(paths, base_dir)[0]


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="deeplearning4j_tpu.analysis.lint",
        description="concurrency/robustness lint (CC001-CC007)")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: {DEFAULT_TARGETS})")
    ap.add_argument("--json", dest="json_out", default=None, metavar="PATH",
                    help="write the findings summary as JSON ('-' = stdout)")
    ap.add_argument("--errors-out", default=None, metavar="PATH",
                    help="write sorted ERROR finding names (one per line) "
                         "— the artifact scripts/lint.sh diffs against "
                         "scripts/lint_baseline.txt")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="suppress ERROR findings whose names appear in "
                         "this file; exit 1 only on new ones")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the human-readable listing")
    args = ap.parse_args(argv)

    findings = lint_paths(args.paths or DEFAULT_TARGETS)
    names = error_names(findings)

    if args.errors_out:
        with open(args.errors_out, "w") as f:
            f.write("".join(n + "\n" for n in names))
    if args.json_out == "-":
        print(json.dumps(summarize(findings), indent=2))
    elif args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(summarize(findings), f, indent=2)
        print(f"wrote {args.json_out}")
    elif not args.quiet:
        print(format_findings(findings))

    if args.baseline:
        try:
            with open(args.baseline) as f:
                allowed = {ln.strip() for ln in f
                           if ln.strip() and not ln.startswith("#")}
        except OSError as e:
            print(f"lint: cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        new = [n for n in names if n not in allowed]
        if new:
            print("LINT REGRESSIONS — ERROR findings not in "
                  f"{args.baseline}:", file=sys.stderr)
            for n in new:
                print(f"  {n}", file=sys.stderr)
            return 1
        return 0
    return 1 if names else 0


if __name__ == "__main__":
    sys.exit(main())
