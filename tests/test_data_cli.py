"""Dataset fetchers, record-reader bridge, streaming ingestion, CLI,
keras-backend entry point (SURVEY rows 21/30/31/33)."""

import io
import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.data.fetchers import (
    CifarDataFetcher,
    CifarDataSetIterator,
    IrisDataSetIterator,
    iris_data,
)
from deeplearning4j_tpu.data.records import (
    CollectionRecordReader,
    CSVRecordReader,
    RecordReaderDataSetIterator,
)
from deeplearning4j_tpu.data.streaming import StreamingDataSetIterator


def test_cifar_synthetic_fallback_shapes():
    f = CifarDataFetcher(allow_download=False, synthetic_n=128)
    it = CifarDataSetIterator(32, train=True, fetcher=f)
    assert it.source == "synthetic"
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].features.shape == (32, 32, 32, 3)
    assert batches[0].labels.shape == (32, 10)
    # deterministic across constructions
    f2 = CifarDataFetcher(allow_download=False, synthetic_n=128)
    x2, _ = f2.load(train=True)
    np.testing.assert_array_equal(batches[0].features, x2[:32])


def test_cifar_synthetic_is_learnable():
    """The synthetic gratings are class-separable by a small conv net —
    the property that makes the fallback a faithful pipeline stand-in."""
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        ConvolutionLayer, GlobalPoolingLayer, OutputLayer)
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    f = CifarDataFetcher(allow_download=False, synthetic_n=512)
    x, y = f.load(train=True)
    conf = (NeuralNetConfiguration.builder().seed(1).updater("adam")
            .learning_rate(3e-3).weight_init("relu").list()
            .layer(ConvolutionLayer(n_out=24, kernel_size=(5, 5),
                                    stride=(2, 2), activation="relu",
                                    convolution_mode="same"))
            .layer(ConvolutionLayer(n_out=32, kernel_size=(3, 3),
                                    stride=(2, 2), activation="relu",
                                    convolution_mode="same"))
            .layer(GlobalPoolingLayer(pooling_type="avg"))
            .layer(OutputLayer(n_out=10, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(32, 32, 3)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(x, y, batch_size=64, epochs=14, async_prefetch=False)
    acc = net.evaluate(
        __import__("deeplearning4j_tpu.data.iterators",
                   fromlist=["ListDataSetIterator"]).ListDataSetIterator(
            __import__("deeplearning4j_tpu.data.dataset",
                       fromlist=["DataSet"]).DataSet(x, y), 128)).accuracy()
    assert acc > 0.6, acc


def test_iris_iterator():
    it = IrisDataSetIterator(50)
    batches = list(it)
    assert len(batches) == 3
    x, y = iris_data()
    assert x.shape == (150, 4) and y.shape == (150, 3)
    # deterministic + balanced
    assert y.sum(axis=0).tolist() == [50.0, 50.0, 50.0]
    x2, _ = iris_data()
    np.testing.assert_array_equal(x, x2)


def test_csv_record_reader_classification():
    csv_text = "sepal_l,sepal_w,label\n" + "\n".join(
        f"{i / 10:.1f},{(i * 3 % 7) / 10:.1f},{i % 3}" for i in range(10))
    reader = CSVRecordReader(io.StringIO(csv_text), skip_lines=1)
    it = RecordReaderDataSetIterator(reader, batch_size=4, label_index=2,
                                    num_classes=3)
    batches = list(it)
    assert [b.features.shape[0] for b in batches] == [4, 4, 2]
    assert batches[0].features.shape[1] == 2
    assert batches[0].labels.shape == (4, 3)
    np.testing.assert_allclose(batches[0].features[1], [0.1, 0.3])
    assert batches[0].labels[1].argmax() == 1
    # iterating again re-reads the source
    assert len(list(it)) == 3


def test_record_reader_regression_and_validation():
    recs = [[1.0, 2.0, 3.0, 4.0], [5.0, 6.0, 7.0, 8.0]]
    it = RecordReaderDataSetIterator(
        CollectionRecordReader(recs), 2,
        label_index_from=2, label_index_to=3)
    b = next(iter(it))
    np.testing.assert_allclose(b.features, [[1, 2], [5, 6]])
    np.testing.assert_allclose(b.labels, [[3, 4], [7, 8]])
    with pytest.raises(ValueError):
        RecordReaderDataSetIterator(CollectionRecordReader(recs), 2)


def test_streaming_iterator_backpressure_and_training():
    produced = []

    def gen():
        rng = np.random.default_rng(0)
        for _ in range(6):
            x = rng.standard_normal((8, 4)).astype(np.float32)
            y = np.zeros((8, 2), np.float32)
            y[np.arange(8), rng.integers(0, 2, 8)] = 1.0
            produced.append(x)
            yield x, y

    it = StreamingDataSetIterator(gen(), buffer_size=2)
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(0).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=1, async_prefetch=False)
    assert net.iteration == 6
    # a stream has no beginning to rewind to: reuse raises
    with pytest.raises(RuntimeError, match="already consumed"):
        list(it)


def test_streaming_iterator_propagates_source_error():
    def bad():
        yield (np.zeros((2, 4), np.float32), np.zeros((2, 2), np.float32))
        raise OSError("kafka broke")

    it = StreamingDataSetIterator(bad())
    with pytest.raises(OSError, match="kafka broke"):
        list(it)


def test_cli_train_evaluate_round_trip(tmp_path, capsys):
    from deeplearning4j_tpu.cli import main
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.utils.model_serializer import save_model

    conf = (NeuralNetConfiguration.builder().seed(2).updater("adam")
            .learning_rate(0.05).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    model_path = str(tmp_path / "iris_model.zip")
    save_model(MultiLayerNetwork(conf).init(), model_path)

    out_path = str(tmp_path / "trained.zip")
    rc = main(["train", "--model-path", model_path, "--data", "iris",
               "--epochs", "30", "--batch-size", "32",
               "--output", out_path])
    assert rc == 0
    rc = main(["evaluate", "--model-path", out_path, "--data", "iris"])
    assert rc == 0
    stats = capsys.readouterr().out
    acc = float(stats.split("Accuracy:")[1].split()[0])
    assert acc > 0.9, stats


def test_keras_backend_server(tmp_path):
    from deeplearning4j_tpu.keras_backend import KerasBackendServer

    model_config = json.dumps({
        "class_name": "Sequential",
        "config": [
            {"class_name": "Dense",
             "config": {"output_dim": 12, "activation": "tanh",
                        "batch_input_shape": [None, 6], "name": "d1"}},
            {"class_name": "Dense",
             "config": {"output_dim": 2, "activation": "softmax",
                        "name": "d2"}},
        ],
    })
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 6)).astype(np.float32)
    y = np.zeros((64, 2), np.float32)
    y[np.arange(64), (x[:, 0] > 0).astype(int)] = 1.0
    np.save(tmp_path / "x.npy", x)
    np.save(tmp_path / "y.npy", y)

    server = KerasBackendServer(port=0)
    port = server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/fit",
            data=json.dumps({
                "model_config": model_config,
                "features_path": str(tmp_path / "x.npy"),
                "labels_path": str(tmp_path / "y.npy"),
                "batch_size": 16, "nb_epoch": 20,
            }).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            out = json.loads(r.read())
        assert np.isfinite(out["score"])
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/evaluate",
            data=json.dumps({
                "features_path": str(tmp_path / "x.npy"),
                "labels_path": str(tmp_path / "y.npy"),
            }).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            ev = json.loads(r.read())
        assert ev["accuracy"] > 0.8
    finally:
        server.stop()


def test_streaming_fake_kafka_consumer_contract():
    """A Kafka-shaped client (poll/commit, consumer-group offsets) behind
    the callable-source SPI: every record arrives exactly once and in
    order, offsets commit as batches are CONSUMED (at-least-once
    delivery), and the bounded buffer exerts backpressure — the broker
    read-ahead never exceeds buffer + in-flight slack."""
    import threading
    import time

    class FakeKafkaConsumer:
        """In-memory stand-in with the kafka-python surface the adapter
        needs: poll() -> record batch or None, commit(offset)."""

        def __init__(self, records):
            self._records = records
            self.position = 0          # next fetch offset
            self.committed = 0         # consumer-group committed offset
            self.max_lead = 0          # max(position - committed): slack probe
            self._lock = threading.Lock()

        def poll(self):
            with self._lock:
                if self.position >= len(self._records):
                    return None
                rec = self._records[self.position]
                self.position += 1
                self.max_lead = max(self.max_lead,
                                    self.position - self.committed)
                return rec

        def commit(self, offset):
            with self._lock:
                self.committed = max(self.committed, offset)

    rng = np.random.default_rng(1)
    n_records, buffer_size = 40, 3
    records = []
    for i in range(n_records):
        x = np.full((4, 2), float(i), np.float32)  # payload encodes offset
        y = np.zeros((4, 2), np.float32)
        records.append((x, y))
    consumer = FakeKafkaConsumer(records)

    def source():
        return consumer.poll()

    it = StreamingDataSetIterator(source, buffer_size=buffer_size)
    seen = []
    for k, ds in enumerate(it):
        time.sleep(0.002)  # slow consumer: forces the buffer to fill
        seen.append(float(np.asarray(ds.features)[0, 0]))
        consumer.commit(k + 1)  # commit AFTER consumption (at-least-once)
    # exactly once, in order
    assert seen == [float(i) for i in range(n_records)]
    assert consumer.committed == n_records
    # backpressure: the pump can be at most buffer_size queued + 1 being
    # put + 1 handed to the consumer ahead of the commit cursor
    assert consumer.max_lead <= buffer_size + 2, consumer.max_lead
