"""Device-side embedding training steps.

The AggregateSkipGram analog (reference:
models/embeddings/learning/impl/elements/SkipGram.java:271 batches pair
updates into native libnd4j aggregate ops; CBOW.java likewise). Here one
jitted XLA step consumes a BATCH of examples with static shapes:

  hidden  = mean of gathered syn0 rows (skip-gram: the one input word;
            CBOW/DM: the window, mask-padded; DM/DBOW add a doc row)
  outputs = hierarchical-softmax nodes (points/codes, mask-padded to the
            Huffman max code length) and/or negative samples
  update  = sigmoid-gradient scatter-adds into syn0/syn1/syn1neg/doc

All four tables are donated, so training runs in place on device. The
returned loss is the masked mean negative log sigmoid — the same quantity
the reference's inner loop accumulates.

Batching semantics: the reference applies pair updates SEQUENTIALLY (the
native aggregate loop), so a word hit N times in a batch sees N staged
updates of compounding freshness. A batched scatter-ADD applies all N
against the same stale row — equivalent for small lr*N, but a hot row
(small vocab x large batch) can see an effective rate of lr*N and
diverge. Updates are therefore summed and then TRUST-REGION CLIPPED per
destination row (norm cap), which preserves the sequential frequency
signal while bounding any single step's movement.

Design note (TPU): gathers/scatter-adds are HBM-bandwidth-bound; batching
thousands of examples per step amortizes dispatch exactly like the
reference's aggregate batching amortizes JNI, and XLA fuses the gate math
between them.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _build_update(*, use_hs: bool, negative: int, with_doc: bool,
                  train_words: bool, max_row_update: float):
    """The un-jitted update body shared by the single-batch step and the
    scanned multi-batch step."""

    def _scatter_clipped(table, idx, delta, weights):
        """table[idx] += delta (summed over duplicate rows), each row's
        total clipped to max_row_update (weights: 1/0 per slot)."""
        d = delta * weights[:, None]
        acc = jnp.zeros_like(table).at[idx].add(d)
        norm = jnp.linalg.norm(acc, axis=1, keepdims=True)
        scale = jnp.minimum(1.0, max_row_update / jnp.maximum(norm, 1e-12))
        return table + acc * scale

    def step(syn0, syn1, syn1neg, doc, unigram, batch, lr, key):
        h_idx = batch["h_idx"]        # [B, C] rows of syn0
        B = h_idx.shape[0]
        dt = syn0.dtype
        # h_mask may be omitted (skip-gram: always exactly one input row);
        # padded tail rows are already no-ops via row_mask
        if "h_mask" in batch:
            hm = batch["h_mask"].astype(dt)
        else:
            hm = jnp.ones(h_idx.shape, dt)
        rm = batch["row_mask"].astype(dt)  # [B] 0 for padded tail rows

        rows = syn0[h_idx]                              # [B, C, D]
        cnt = jnp.sum(hm, axis=1, keepdims=True)        # [B, 1]
        h = jnp.sum(rows * hm[..., None], axis=1)       # [B, D]
        if with_doc:
            d_idx = batch["doc_idx"]                    # [B]
            h = h + doc[d_idx]
            cnt = cnt + 1.0
        h = h / jnp.maximum(cnt, 1.0)

        neu1e = jnp.zeros_like(h)
        loss = jnp.zeros((), dt)
        denom = jnp.zeros((), dt)

        if use_hs:
            points = batch["points"]                    # [B, L] rows of syn1
            codes = batch["codes"].astype(dt)           # [B, L] 0/1
            om = batch["hs_mask"].astype(dt) * rm[:, None]  # [B, L]
            u = syn1[points]                            # [B, L, D]
            logit = jnp.einsum("bd,bld->bl", h, u)
            label = 1.0 - codes
            p = jax.nn.sigmoid(logit)
            g = (label - p) * om                        # [B, L] raw gradient
            neu1e = neu1e + jnp.einsum("bl,bld->bd", g, u) * lr
            delta = (g * lr)[..., None] * h[:, None, :]  # [B, L, D]
            if train_words:
                syn1 = _scatter_clipped(
                    syn1, points.reshape(-1),
                    delta.reshape(-1, delta.shape[-1]), om.reshape(-1),
                )
            z = (2.0 * label - 1.0) * logit
            loss = loss + jnp.sum(-jax.nn.log_sigmoid(z) * om)
            denom = denom + jnp.sum(om)

        if negative > 0:
            pos = batch["pos"]                          # [B]
            if "neg" in batch:
                neg = batch["neg"]                      # [B, K]
            else:
                # device-side sampling from the resident unigram table —
                # saves shipping K int32 per example over the host link
                r = jax.random.randint(
                    key, (B, negative), 0, unigram.shape[0]
                )
                neg = unigram[r]
            idx = jnp.concatenate([pos[:, None], neg], axis=1)  # [B, 1+K]
            labels = jnp.zeros((B, 1 + negative), dt).at[:, 0].set(1.0)
            # a sampled negative that collides with the target is skipped
            # (word2vec.c: `if (target == word) continue`)
            om = jnp.concatenate(
                [jnp.ones((B, 1), dt),
                 (neg != pos[:, None]).astype(dt)], axis=1,
            ) * rm[:, None]
            u = syn1neg[idx]                            # [B, 1+K, D]
            logit = jnp.einsum("bd,bkd->bk", h, u)
            p = jax.nn.sigmoid(logit)
            g = (labels - p) * om
            neu1e = neu1e + jnp.einsum("bk,bkd->bd", g, u) * lr
            delta = (g * lr)[..., None] * h[:, None, :]
            if train_words:
                syn1neg = _scatter_clipped(
                    syn1neg, idx.reshape(-1),
                    delta.reshape(-1, delta.shape[-1]), om.reshape(-1),
                )
            z = (2.0 * labels - 1.0) * logit
            loss = loss + jnp.sum(-jax.nn.log_sigmoid(z) * om)
            denom = denom + jnp.sum(om)

        if train_words:
            upd = jnp.broadcast_to(
                neu1e[:, None, :], (B, h_idx.shape[1], neu1e.shape[-1])
            )
            syn0 = _scatter_clipped(
                syn0, h_idx.reshape(-1),
                upd.reshape(-1, upd.shape[-1]), hm.reshape(-1),
            )
        if with_doc:
            # doc rows keep SUM semantics (sequential-SGD equivalent): a
            # doc appears at most doc-length times per batch, so the
            # summed update is bounded by lr * len — no hot-row blowup,
            # and the aggregate signal is what makes doc vectors move
            doc = doc.at[batch["doc_idx"]].add(neu1e * rm[:, None])
        return syn0, syn1, syn1neg, doc, loss / jnp.maximum(denom, 1.0)

    return step


def make_embedding_step(*, use_hs: bool, negative: int, with_doc: bool,
                        train_words: bool = True, donate: bool = True,
                        max_row_update: float = 0.25):
    """Jitted single-batch update step. Static config: which output
    objective (HS and/or negative sampling), whether a doc row joins the
    hidden mean, and whether word tables train (False for infer_vector).
    max_row_update caps the 2-norm any single row moves per step."""
    body = _build_update(
        use_hs=use_hs, negative=negative, with_doc=with_doc,
        train_words=train_words, max_row_update=max_row_update,
    )

    def step(syn0, syn1, syn1neg, doc, batch, lr, unigram=None, key=None):
        if unigram is None:
            unigram = jnp.zeros((1,), jnp.int32)
        if key is None:
            key = jax.random.PRNGKey(0)
        return body(syn0, syn1, syn1neg, doc, unigram, batch, lr, key)

    donate_argnums = (0, 1, 2, 3) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)


def make_embedding_scan_step(*, use_hs: bool, negative: int, with_doc: bool,
                             train_words: bool = True, donate: bool = True,
                             max_row_update: float = 0.25):
    """Jitted MULTI-batch step: lax.scan the update over a stacked group
    of batches ([S, B, ...] leading axis) in ONE device call. Dispatch
    latency (the dominant cost through a remote-device tunnel) is paid
    once per group instead of once per batch — the host<->device analog
    of the reference batching JNI calls into aggregate ops."""
    body = _build_update(
        use_hs=use_hs, negative=negative, with_doc=with_doc,
        train_words=train_words, max_row_update=max_row_update,
    )

    def scan_step(syn0, syn1, syn1neg, doc, unigram, batches, lrs, key):
        keys = jax.random.split(key, lrs.shape[0])

        def one(carry, inp):
            s0, s1, s1n, d = carry
            batch, lr, k = inp
            s0, s1, s1n, d, loss = body(s0, s1, s1n, d, unigram, batch, lr, k)
            return (s0, s1, s1n, d), loss

        (syn0, syn1, syn1neg, doc), losses = jax.lax.scan(
            one, (syn0, syn1, syn1neg, doc), (batches, lrs, keys)
        )
        return syn0, syn1, syn1neg, doc, jnp.mean(losses)

    donate_argnums = (0, 1, 2, 3) if donate else ()
    return jax.jit(scan_step, donate_argnums=donate_argnums)
