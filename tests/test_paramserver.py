"""Async embedding parameter server (parallel/paramserver.py) — the
Aeron-PS analog: row-sharded tables, synchronous pulls, fire-and-forget
pushes, two concurrent workers training one skip-gram model."""

import threading

import numpy as np

from deeplearning4j_tpu.parallel.paramserver import (
    EmbeddingParameterServer,
    EmbeddingPSClient,
)


def test_pull_push_round_trip_sharded():
    rng = np.random.default_rng(0)
    t0 = rng.standard_normal((10, 4)).astype(np.float32)
    s1 = EmbeddingParameterServer({"syn0": t0.copy()})
    s2 = EmbeddingParameterServer({"syn0": t0.copy()})
    p1, p2 = s1.start(), s2.start()
    try:
        client = EmbeddingPSClient(
            [f"http://127.0.0.1:{p1}", f"http://127.0.0.1:{p2}"])
        rows = np.array([3, 0, 7, 2])
        got = client.pull("syn0", rows)
        np.testing.assert_allclose(got, t0[rows], rtol=1e-6)

        deltas = np.ones((4, 4), np.float32)
        client.push_async("syn0", rows, deltas)
        client.flush()
        got2 = client.pull("syn0", rows)
        np.testing.assert_allclose(got2, t0[rows] + 1.0, rtol=1e-6)
        # each row landed only on its modulo-owner
        assert s1.pushes_applied >= 1 and s2.pushes_applied >= 1
    finally:
        s1.stop()
        s2.stop()


def test_two_workers_async_sgd_converges():
    """Two workers doing Hogwild-style pulls/pushes against one server
    drive a toy embedding objective down (the reference's async-SGD
    semantics incl. acknowledged nondeterminism, DeepWalk.java:223)."""
    rng = np.random.default_rng(1)
    vocab, dim = 30, 8
    server = EmbeddingParameterServer({
        "syn0": (rng.standard_normal((vocab, dim)) * 0.1).astype(np.float32)})
    port = server.start()
    url = f"http://127.0.0.1:{port}"
    # target: push word vectors of even ids toward +e0, odd toward -e0
    target = np.zeros((vocab, dim), np.float32)
    target[::2, 0] = 1.0
    target[1::2, 0] = -1.0

    def worker(seed):
        client = EmbeddingPSClient([url])
        w_rng = np.random.default_rng(seed)
        for _ in range(60):
            rows = w_rng.choice(vocab, size=8, replace=False)
            vecs = client.pull("syn0", rows)
            grad = vecs - target[rows]
            client.push_async("syn0", rows, -0.3 * grad)
        client.flush()

    threads = [threading.Thread(target=worker, args=(s,)) for s in (7, 8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    final = server.tables["syn0"]
    err = float(np.mean((final - target) ** 2))
    assert err < 0.02, err
    assert server.pushes_applied > 100
