"""Paramserver failover (ISSUE 7): server-side write-ahead journaling +
snapshot/restore, and the client's retry-with-backoff + park-and-replay
buffer — a restarted shard owner converges instead of silently dropping
async gradient mass (and whatever IS lost stays counted)."""

import os
import struct
import time

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.paramserver import (
    EmbeddingParameterServer,
    EmbeddingPSClient,
    _pack_request,
)


def _wait_until(pred, timeout=10.0, every=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every)
    return pred()


# -- server-side durability ---------------------------------------------------


def test_journal_replays_after_crash(tmp_path):
    """Kill a journal-armed server without snapshotting; a new server on
    the same directory replays every journaled push."""
    jdir = str(tmp_path / "j")
    t0 = np.zeros((8, 3), np.float32)
    server = EmbeddingParameterServer({"syn0": t0.copy()}, journal_dir=jdir)
    port = server.start()
    try:
        client = EmbeddingPSClient([f"http://127.0.0.1:{port}"])
        rows = np.array([0, 2, 5])
        client.push_async("syn0", rows, np.ones((3, 3), np.float32))
        client.push_async("syn0", rows, np.ones((3, 3), np.float32))
        client.flush()
        _wait_until(lambda: server.pushes_applied == 2)
        expect = server.tables["syn0"].copy()
        client.close()
    finally:
        server.stop()  # "crash": no snapshot() — only the journal survives

    reborn = EmbeddingParameterServer({"syn0": t0.copy()}, journal_dir=jdir)
    try:
        np.testing.assert_array_equal(reborn.tables["syn0"], expect)
        assert reborn.tables["syn0"][0, 0] == 2.0
    finally:
        reborn.stop()


def test_snapshot_truncates_journal_and_restores(tmp_path):
    jdir = str(tmp_path / "s")
    server = EmbeddingParameterServer(
        {"syn0": np.zeros((4, 2), np.float32)}, journal_dir=jdir)
    server.push("syn0", [1], np.full((1, 2), 3.0, np.float32))
    path = server.snapshot()
    assert os.path.exists(path)
    assert os.path.getsize(os.path.join(jdir, "journal.bin")) == 0
    # post-snapshot pushes land in the fresh journal
    server.push("syn0", [2], np.full((1, 2), 5.0, np.float32))
    expect = server.tables["syn0"].copy()
    server.stop()

    reborn = EmbeddingParameterServer(
        {"syn0": np.zeros((4, 2), np.float32)}, journal_dir=jdir)
    try:
        np.testing.assert_array_equal(reborn.tables["syn0"], expect)
    finally:
        reborn.stop()


def test_torn_journal_tail_discarded(tmp_path):
    """A writer SIGKILLed mid-append leaves a half-record; restore must
    replay everything before it and drop only the tail."""
    jdir = str(tmp_path / "torn")
    server = EmbeddingParameterServer(
        {"syn0": np.zeros((4, 2), np.float32)}, journal_dir=jdir)
    server.push("syn0", [0], np.ones((1, 2), np.float32))
    server.push("syn0", [1], np.ones((1, 2), np.float32))
    expect = server.tables["syn0"].copy()
    server.stop()
    # a torn record: full length prefix, truncated payload
    payload = _pack_request("syn0", np.array([3], np.int64),
                            np.ones((1, 2), np.float32))
    with open(os.path.join(jdir, "journal.bin"), "ab") as f:
        f.write(struct.pack("<I", len(payload)) + payload[: len(payload) // 2])
    reborn = EmbeddingParameterServer(
        {"syn0": np.zeros((4, 2), np.float32)}, journal_dir=jdir)
    try:
        np.testing.assert_array_equal(reborn.tables["syn0"], expect)
        assert reborn.tables["syn0"][3, 0] == 0.0  # torn push NOT applied
    finally:
        reborn.stop()


def test_snapshot_every_auto_truncates(tmp_path):
    jdir = str(tmp_path / "auto")
    server = EmbeddingParameterServer(
        {"syn0": np.zeros((4, 2), np.float32)}, journal_dir=jdir,
        snapshot_every=3)
    for i in range(7):
        server.push("syn0", [i % 4], np.ones((1, 2), np.float32))
    try:
        # 7 pushes, snapshot every 3 -> 2 snapshots; 1 push left journaled
        assert os.path.exists(os.path.join(jdir, "tables.npz"))
        with open(os.path.join(jdir, "journal.bin"), "rb") as f:
            buf = f.read()
        (rec_len,) = struct.unpack_from("<I", buf, 0)
        assert len(buf) == 4 + rec_len  # exactly one record
    finally:
        server.stop()


def test_snapshot_shape_mismatch_rejected(tmp_path):
    jdir = str(tmp_path / "shape")
    server = EmbeddingParameterServer(
        {"syn0": np.zeros((4, 2), np.float32)}, journal_dir=jdir)
    server.push("syn0", [0], np.ones((1, 2), np.float32))
    server.snapshot()
    server.stop()
    with pytest.raises(ValueError, match="shape"):
        EmbeddingParameterServer({"syn0": np.zeros((9, 9), np.float32)},
                                 journal_dir=jdir)


# -- client failover ----------------------------------------------------------


def test_client_parks_and_replays_when_endpoint_returns(tmp_path):
    """The convergence contract: pushes against a down endpoint PARK
    (not drop), and the drain's idle tick replays them once the endpoint
    comes back — a restarted journal-backed server ends up with every
    batch."""
    jdir = str(tmp_path / "replay")
    t0 = np.zeros((6, 2), np.float32)
    server = EmbeddingParameterServer({"syn0": t0.copy()}, journal_dir=jdir)
    port = server.start()
    url = f"http://127.0.0.1:{port}"
    client = EmbeddingPSClient([url], timeout=2.0, max_retries=1,
                               retry_backoff=0.01, replay_capacity=16)
    try:
        rows = np.array([1, 4])
        client.push_async("syn0", rows, np.ones((2, 2), np.float32))
        client.flush()
        _wait_until(lambda: server.pushes_applied == 1)
        server.stop()  # the outage

        for _ in range(3):
            client.push_async("syn0", rows, np.ones((2, 2), np.float32))
        client.flush()
        assert _wait_until(lambda: client.pending_pushes() == 3)
        assert client.dropped_pushes == 0  # parked, not lost

        # the shard owner comes back on the SAME port, journal intact
        reborn = EmbeddingParameterServer({"syn0": t0.copy()},
                                          journal_dir=jdir, port=port)
        reborn.start()
        try:
            # no new traffic needed: the idle tick replays the backlog
            assert _wait_until(lambda: client.pending_pushes() == 0, 15.0)
            _wait_until(lambda: reborn.pushes_applied >= 3)
            np.testing.assert_array_equal(
                reborn.tables["syn0"][1], np.full(2, 4.0, np.float32))
            assert client.dropped_pushes == 0
        finally:
            expect_done = reborn
            client.close()
            expect_done.stop()
    except BaseException:
        client.close()
        raise


def test_replay_overflow_drops_oldest_and_counts(tmp_path):
    """Only replay-buffer OVERFLOW loses pushes, and every loss is
    counted — degradation observable, never silent."""
    client = EmbeddingPSClient(["http://127.0.0.1:1"], timeout=0.5,
                               max_retries=0, retry_backoff=0.01,
                               replay_capacity=2)
    try:
        rows = np.array([0])
        for _ in range(5):
            client.push_async("syn0", rows, np.ones((1, 3), np.float32))
        client.flush()
        assert _wait_until(lambda: client.dropped_pushes >= 3)
        assert client.pending_pushes() <= 2
    finally:
        client.close()
    # close() against a still-dead endpoint accounts the parked remainder
    assert client.pending_pushes() == 0
    assert client.dropped_pushes == 5


def test_pull_retries_through_a_blip(tmp_path):
    """A pull against a server that comes up within the retry window
    succeeds instead of surfacing the transient fault."""
    t0 = np.arange(12, dtype=np.float32).reshape(6, 2)
    server = EmbeddingParameterServer({"syn0": t0.copy()})
    port = server.start()
    server.stop()  # learn a port, then take the server down

    client = EmbeddingPSClient([f"http://127.0.0.1:{port}"], timeout=2.0,
                               max_retries=8, retry_backoff=0.2)
    reborn = EmbeddingParameterServer({"syn0": t0.copy()}, port=port)
    import threading

    def bring_back():
        time.sleep(0.4)
        reborn.start()

    t = threading.Thread(target=bring_back, daemon=True,
                         name="dl4j-test-bringback")
    t.start()
    try:
        got = client.pull("syn0", np.array([2, 5]))
        np.testing.assert_array_equal(got, t0[[2, 5]])
    finally:
        t.join()
        client.close()
        reborn.stop()
