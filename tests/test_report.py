"""Standalone HTML report + component DSL + flow view (ui/components.py,
ui/report.py; reference: deeplearning4j-ui-components standalone
rendering + FlowListenerModule)."""

import json
import urllib.request

import numpy as np

from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import (
    ChartHistogram,
    ChartLine,
    Component,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    FlowGraph,
    InMemoryStatsStorage,
    StatsListener,
    UIServer,
    render_page,
    write_training_report,
)
from deeplearning4j_tpu.ui.stats import model_graph


def _trained_storage(histograms=True):
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater("adam").learning_rate(0.05).list()
            .layer(DenseLayer(n_out=12, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    storage = InMemoryStatsStorage()
    net.set_collect_stats(True)
    net.set_listeners(StatsListener(
        storage, session_id="sess-report",
        histogram_bins=16 if histograms else 0, histogram_frequency=2))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 6)).astype(np.float32)
    y = np.zeros((64, 3), np.float32)
    y[np.arange(64), rng.integers(0, 3, 64)] = 1.0
    net.fit(x, y, batch_size=16, epochs=3, async_prefetch=False)
    return storage


# -- component DSL ------------------------------------------------------------

def test_component_json_round_trip():
    from deeplearning4j_tpu.ui.components import ChartScatter, StyleChart

    custom = StyleChart(width=800, height=300, stroke_color="#000000")
    comps = [
        ComponentText("hello", size=15, bold=True),
        ComponentTable(["a", "b"], [[1, 2], [3, 4]]),
        ChartLine("scores", {"s": [(0, 1.0), (1, 0.5), (2, 0.25)]}),
        ChartLine("styled", {"s": [(0, 1.0), (1, 2.0)]}, style=custom),
        ChartHistogram("w", [0.0, 0.5, 1.0], [3, 7]),
        ChartHistogram("w2", [0.0, 1.0], [5], style=custom),
        ChartScatter("pts", [(0.0, 1.0), (2.0, 3.0)], labels=["a", "b"],
                     style=custom),
        ComponentDiv([ComponentText("inner")], title="box"),
        FlowGraph({"nodes": [{"id": "a", "label": "a"},
                             {"id": "b", "label": "b"}],
                   "edges": [["a", "b"]]}),
    ]
    for c in comps:
        back = Component.from_json(c.to_json())
        assert type(back) is type(c)
        assert back.to_dict() == c.to_dict()


def test_render_page_self_contained():
    html = render_page("t", [
        ComponentText("<script>alert(1)</script>"),  # must be escaped
        ChartLine("s", {"a": [(0, 1.0), (1, 2.0)]}),
    ])
    assert html.startswith("<!doctype html>")
    assert "<script>alert(1)</script>" not in html  # XSS-escaped
    assert "&lt;script&gt;" in html
    assert "<svg" in html
    # no external references — fully standalone
    assert "http://" not in html and "https://" not in html
    assert "src=" not in html


# -- report assembly ----------------------------------------------------------

def test_training_report_artifact(tmp_path):
    storage = _trained_storage()
    out = str(tmp_path / "report.html")
    write_training_report(storage, out, title="run 42")
    html = open(out).read()
    assert "run 42" in html
    assert "score vs iteration" in html
    assert "<svg" in html
    assert "per-layer mean magnitudes" in html
    assert "parameter histograms" in html
    assert "model flow" in html          # the flow graph section
    assert "DenseLayer" in html          # layer boxes carry types
    assert "http" not in html.replace("http-equiv", "")  # standalone


def test_report_empty_storage(tmp_path):
    out = str(tmp_path / "empty.html")
    write_training_report(InMemoryStatsStorage(), out)
    assert "no sessions" in open(out).read()


# -- model graph + flow route -------------------------------------------------

def test_model_graph_mln_chain():
    conf = (NeuralNetConfiguration.builder().list()
            .layer(DenseLayer(n_out=4, activation="relu"))
            .layer(OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(3)).build())
    g = model_graph(MultiLayerNetwork(conf).init())
    ids = [n["id"] for n in g["nodes"]]
    assert ids == ["input", "layer0", "layer1"]
    assert g["edges"] == [["input", "layer0"], ["layer0", "layer1"]]


def test_model_graph_compgraph_dag():
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph
    from deeplearning4j_tpu.nn.conf import MergeVertex

    conf = (NeuralNetConfiguration.builder().graph_builder()
            .add_inputs("in")
            .add_layer("a", DenseLayer(n_out=4, activation="tanh"), "in")
            .add_layer("b", DenseLayer(n_out=4, activation="tanh"), "in")
            .add_vertex("m", MergeVertex(), "a", "b")
            .add_layer("out", OutputLayer(n_out=2, activation="softmax"),
                       "m")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(3)).build())
    g = model_graph(ComputationGraph(conf).init())
    assert ["in", "m"] in g["edges"] or ["a", "m"] in g["edges"]
    assert {"a", "b", "m", "out"} <= {n["id"] for n in g["nodes"]}
    # layer vertices carry the param-list index for stats overlay
    layer_nodes = {n["id"]: n for n in g["nodes"] if "layer_index" in n}
    assert {"a", "b", "out"} <= set(layer_nodes)


def test_flow_route_serves_graph_svg():
    storage = _trained_storage(histograms=False)
    server = UIServer(storage, port=0)
    port = server.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/train/flow/data") as r:
            d = json.loads(r.read())
        assert d["graph"]["edges"] == [["input", "layer0"],
                                       ["layer0", "layer1"]]
        assert d["svg"] and "<svg" in d["svg"]
        assert "DenseLayer" in d["svg"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/train/flow") as r:
            page = r.read().decode()
        assert "flow" in page
    finally:
        server.stop()
