"""Synthetic recsys traffic: zipf-distributed ids over a vocabulary.

Real recommendation id streams are heavy-tailed — a few thousand hot
items absorb most lookups — and that skew is exactly what the sparse
pipeline's hot-id cache (parallel/sparse) exploits. This module is the
workload half: seeded, dependency-free zipf sampling (inverse-CDF over
the normalized 1/k^alpha mass, `np.searchsorted` per draw) plus a batch
stream with deterministic labels, used by `bench.py recsys`, the T1
recsys smoke, and the tests. Everything is reproducible from (seed,
alpha, vocab) — two arms of an A/B run see byte-identical id streams.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np


def zipf_cdf(vocab: int, alpha: float = 1.2) -> np.ndarray:
    """Cumulative mass of p(k) ~ 1/(k+1)^alpha over ids [0, vocab) —
    id 0 is the hottest. float64 so huge vocabularies still sum to 1."""
    if vocab <= 0:
        raise ValueError(f"vocab must be positive, got {vocab}")
    mass = 1.0 / np.power(np.arange(1, vocab + 1, dtype=np.float64), alpha)
    cdf = np.cumsum(mass)
    cdf /= cdf[-1]
    return cdf


def zipf_ids(n: int, vocab: int, alpha: float = 1.2,
             seed: int = 0, cdf: Optional[np.ndarray] = None
             ) -> np.ndarray:
    """`n` zipf-distributed ids in [0, vocab), int64. Pass a
    precomputed `cdf` (zipf_cdf) when sampling many batches — the
    cumsum dominates per-batch cost for multi-hundred-k vocabularies."""
    if cdf is None:
        cdf = zipf_cdf(vocab, alpha)
    rng = np.random.default_rng(seed)
    u = rng.random(n)
    return np.searchsorted(cdf, u, side="right").astype(np.int64)


def zipf_batches(batch: int, vocab: int, alpha: float = 1.2,
                 seed: int = 0) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Endless (ids [batch], labels [batch]) stream. Labels are a
    deterministic function of the id (parity of the id's bit count) so
    the dense tower has something learnable and every arm of an A/B
    bench trains on the identical supervised problem."""
    cdf = zipf_cdf(vocab, alpha)
    step = 0
    while True:
        ids = zipf_ids(batch, vocab, alpha, seed=seed + step, cdf=cdf)
        labels = (_popcount64(ids) & 1).astype(np.int32)
        yield ids, labels
        step += 1


def _popcount64(a: np.ndarray) -> np.ndarray:
    """Vectorized popcount for int64 (no np.bit_count before numpy 2)."""
    v = a.astype(np.uint64)
    out = np.zeros(a.shape, np.int64)
    for _ in range(8):
        # byte-at-a-time bit folding (the classic SWAR popcount)
        b = v & np.uint64(0xFF)
        b = b - ((b >> np.uint64(1)) & np.uint64(0x55))
        b = (b & np.uint64(0x33)) + ((b >> np.uint64(2)) & np.uint64(0x33))
        b = (b + (b >> np.uint64(4))) & np.uint64(0x0F)
        out += b.astype(np.int64)
        v = v >> np.uint64(8)
    return out
