"""Tenant identity + cross-tier chip-budget metering (utils/tenancy,
utils/resourcemeter) — the claims each pinned by a test:

- BOUNDED CARDINALITY: tenant names come from request headers; past the
  registry cap new names collapse into `__other__` instead of exploding
  the metrics registry one curl at a time.
- OFF-PATH COST: an unmetered process pays one module-global read per
  hook — <10µs/call, same contract as the devprof/runledger hooks.
- END-TO-END IDENTITY: a `/generate` with an X-Tenant header books the
  request under that tenant in the decode engine AND tags the span and
  the token-latency exemplar with it; a paramserver pull carries the
  client's tenant across the HTTP boundary next to the traceparent and
  is booked server-side.
- PARITY BY CONSTRUCTION: `cli tenants --ledger` rebuilds the live
  spend table from a recorded run — both parse the same flat
  scalar-values vocabulary.
- PER-TENANT SLO: a tenant outspending its device-seconds allowance
  drives the chip-budget burn rule pending -> firing -> resolved.
- METERING IS CHEAP: a metered fit's wall time stays within noise of an
  unmetered one (the hooks ride devprof's sampled cadence — no new
  sync points).
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils import metrics as metrics_mod
from deeplearning4j_tpu.utils import resourcemeter, tenancy, tracing

N_IN = 12


@pytest.fixture(autouse=True)
def _meter_off_after():
    """The meter and the ambient tenant are process-global — never leak
    an armed meter (or an attached tenant) into other tests."""
    yield
    resourcemeter.disable()
    tenancy.detach(None)


def _mlp_conf(seed=7):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Updater.SGD)
        .learning_rate(0.05)
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_in=N_IN, n_out=16, activation="tanh"))
        .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                           loss="mcxent"))
        .build()
    )


def _xy(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, N_IN)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, n)]
    return x, y


# -- identity -----------------------------------------------------------------

def test_intern_canonicalizes_and_defaults():
    assert tenancy.intern(None) == tenancy.DEFAULT_TENANT
    assert tenancy.intern("   ") == tenancy.DEFAULT_TENANT
    # label-value safety: quotes/spaces/control chars never reach a
    # Prometheus label or a ledger line verbatim
    weird = tenancy.intern('ac me"x')
    assert '"' not in weird and " " not in weird
    assert tenancy.intern("x" * 200) == "x" * 64  # length cap
    # idempotent: a known name round-trips
    assert tenancy.intern(weird) == weird


def test_tenant_cardinality_bounded():
    reg = tenancy.get_tenant_registry()
    try:
        reg.reset(max_tenants=4)
        names = {tenancy.intern(f"cust-{i}") for i in range(20)}
        assert tenancy.OVERFLOW_TENANT in names
        # every name is counted SOMEWHERE; the per-name breakdown
        # saturates at the cap (+ the overflow bucket itself)
        assert len(reg.tenants()) <= 4
        assert reg.overflowed > 0
        # a name interned before the cap keeps resolving to itself
        survivor = next(n for n in names if n != tenancy.OVERFLOW_TENANT)
        assert tenancy.intern(survivor) == survivor
    finally:
        reg.reset(max_tenants=tenancy.DEFAULT_MAX_TENANTS)


def test_header_extraction_case_insensitive():
    assert tenancy.from_headers({"X-Tenant": "acme"}) == "acme"
    assert tenancy.from_headers({"x-tenant": "acme"}) == "acme"
    assert tenancy.from_headers({"Content-Type": "a"}) is None
    assert tenancy.from_headers(None) is None
    # client half: explicit beats ambient, input never mutated
    base = {"Content-Type": "application/json"}
    with tenancy.tenant_scope("ambient"):
        out = tenancy.tenant_headers(base, tenant="explicit")
        assert out["X-Tenant"] == "explicit"
        assert tenancy.tenant_headers(base)["X-Tenant"] == "ambient"
    assert "X-Tenant" not in base


# -- off-path cost ------------------------------------------------------------

def test_unmetered_hooks_under_10us_per_call():
    """The house bar (same as runledger.note_fit_step): a process that
    never enables metering pays one module-global read per hook."""
    resourcemeter.disable()
    calls = 20_000
    for fn in (tenancy.current_tenant,
               lambda: resourcemeter.note_serving_forward(0.0, {}),
               lambda: resourcemeter.note_tokens("a", 1),
               lambda: resourcemeter.note_device_window(None, 0.01)):
        t0 = time.perf_counter()
        for _ in range(calls):
            fn()
        per_call = (time.perf_counter() - t0) / calls
        assert per_call < 10e-6, f"{fn}: {per_call * 1e6:.2f}µs/call"


def test_unmetered_snapshot_is_books_only():
    resourcemeter.disable()
    doc = resourcemeter.snapshot()
    assert "note" in doc  # says WHY spend is empty
    assert doc["conservation"]["ok"] is not None


# -- serving ------------------------------------------------------------------

def test_parallel_inference_books_per_tenant():
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    net = MultiLayerNetwork(_mlp_conf()).init()
    pi = ParallelInference(net, max_batch_size=4, batch_timeout_ms=1.0,
                           component_prefix="tenancy_pi")
    try:
        pi.warmup((N_IN,))
        x = np.zeros((2, N_IN), np.float32)
        for _ in range(3):
            pi.output(x, tenant="acme")
        pi.output(x, tenant="beta")
        with tenancy.tenant_scope("ambient"):
            pi.output(x)  # no explicit tenant -> the thread's ambient one
        m = pi.metrics()
        assert m["tenants"]["acme"]["completed"] == 3
        assert m["tenants"]["beta"]["completed"] == 1
        assert m["tenants"]["ambient"]["completed"] == 1
        assert m["conservation_ok"]
    finally:
        pi.shutdown()


def test_generate_with_header_tags_books_spans_and_exemplars():
    """One `/generate` carrying X-Tenant: the request books under that
    tenant in the engine, the serve/generate span carries it, and the
    token-latency exemplar links it to the trace — the whole identity
    chain from header to flamegraph."""
    from deeplearning4j_tpu.models.charlstm import char_lstm_network
    from deeplearning4j_tpu.serving.inference_server import InferenceServer

    net = char_lstm_network(vocab_size=13, hidden=16, layers=1,
                            tbptt_length=8, seed=12345)
    srv = InferenceServer(net, decode_slots=2, decode_max_tokens=8)
    srv.start()
    tracing.get_tracer().clear()
    tracing.enable(True)
    resourcemeter.enable()
    tok_lat = metrics_mod.get_registry().get(
        "decode_token_seconds").labels()
    with tok_lat._lock:  # a prior test's exemplar must not mask ours
        tok_lat._exemplars.clear()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/generate",
            data=json.dumps({"prompt": [1, 2, 3],
                             "max_tokens": 4}).encode(),
            headers={"Content-Type": "application/json",
                     "X-Tenant": "acme"})
        out = json.loads(urllib.request.urlopen(req, timeout=60).read())
        assert len(out["tokens"]) >= 1
        # books: the engine admitted+completed this under "acme"
        eng = srv.decode.metrics()
        assert eng["tenants"]["acme"]["completed"] >= 1
        # spans: serve/generate (and the engine's admission) carry the
        # tenant arg the header delivered
        spans = [e for e in tracing.get_tracer().recent()
                 if (e.get("args") or {}).get("tenant") == "acme"]
        assert any(e["name"] == "serve/generate" for e in spans), spans
        # exemplars: the per-token latency histogram links value ->
        # trace ->  tenant (the decode loop thread has no ambient
        # tenant — the engine passes the request's explicitly)
        exs = tok_lat.exemplars()
        assert any(ex.get("tenant") == "acme" for ex in exs), exs
        # spend: the decode tier charged device time to "acme"
        snap = resourcemeter.snapshot()
        dev = snap["tenants"]["acme"]["device_seconds"]
        assert dev.get(resourcemeter.TIER_DECODE, 0.0) > 0.0
    finally:
        tracing.enable(False)
        tracing.get_tracer().clear()
        srv.stop()


def test_paramserver_pull_books_tenant_across_boundary():
    """The client's tenant rides X-Tenant next to the traceparent; the
    SERVER books the wire bytes under it — identity crosses the process
    boundary even though the fit thread's TLS cannot."""
    from deeplearning4j_tpu.parallel.paramserver import (
        EmbeddingParameterServer,
        EmbeddingPSClient,
    )

    resourcemeter.enable()
    server = EmbeddingParameterServer(
        {"syn0": np.zeros((10, 4), np.float32)})
    port = server.start()
    try:
        client = EmbeddingPSClient([f"http://127.0.0.1:{port}"],
                                   tenant="acme")
        got = client.pull("syn0", np.array([1, 3]))
        assert got.shape == (2, 4)
        snap = resourcemeter.snapshot()
        wire = snap["tenants"]["acme"]["wire_bytes"]
        assert wire.get(resourcemeter.TIER_PARAMSERVER, 0) > 0
    finally:
        server.stop()


# -- parity: live / ledger replay ---------------------------------------------

def test_cli_tenants_ledger_replay_matches_live(tmp_path, capsys):
    """`cli tenants --ledger` rebuilds the spend table from the
    artifact's final sample; it must equal the live registry's view at
    close time — both parse the same flat vocabulary."""
    from deeplearning4j_tpu.cli import main as cli_main
    from deeplearning4j_tpu.utils.runledger import RunLedger

    resourcemeter.enable()
    path = str(tmp_path / "run.jsonl")
    led = RunLedger(path, sample_every=60.0).start()
    try:
        resourcemeter.note_wire("ledger-a", resourcemeter.TIER_PARAMSERVER,
                                1234)
        resourcemeter.note_tokens("ledger-a", 7)
        resourcemeter.note_serving_forward(0.25, {"ledger-a": 3,
                                                  "ledger-b": 1})
    finally:
        led.close()
    live = resourcemeter.spend_table(
        metrics_mod.get_registry().scalar_values())
    assert cli_main(["tenants", "--ledger", path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    for t in ("ledger-a", "ledger-b"):
        assert doc["tenants"][t] == live[t]
    assert doc["tenants"]["ledger-a"]["wire_bytes"][
        resourcemeter.TIER_PARAMSERVER] >= 1234
    assert doc["conservation"]["spend_ok"]
    # and the human rendering exits 0 too
    assert cli_main(["tenants", "--ledger", path]) == 0
    assert "ledger-a" in capsys.readouterr().out


# -- per-tenant SLO -----------------------------------------------------------

def test_tenant_burn_rule_fires_and_resolves():
    """A tenant burning device time faster than its allowance drives
    the chip-budget rule pending -> firing; the burn stopping resolves
    it — the injected-degradation lifecycle, replayed synthetically."""
    from deeplearning4j_tpu.analysis import slo

    rules = slo.tenant_burn_rules({"acme": 0.5}, sample_every=1.0)
    rs = slo.SLORuleSet(rules)
    key = 'tenant_device_seconds_total{tenant="acme",tier="serving"}'
    transitions = []
    for ts in range(6):  # 2.0 dev-s per wall-s: 4x over allowance
        transitions += rs.evaluate(float(ts), {key: 2.0 * ts})
    assert rs.firing() == ["tenant_chip_budget_burn:acme"]
    assert any(t["to"] == "firing" for t in transitions)
    for ts in range(6, 10):  # burn stops: the rate drops to 0
        transitions += rs.evaluate(float(ts), {key: 10.0})
    assert rs.firing() == []
    assert any(t["from"] == "firing" and t["to"] == "resolved"
               for t in transitions)
    # a tenant with no spend matches nothing and never alerts
    idle = slo.SLORuleSet(slo.tenant_burn_rules({"ghost": 0.1}))
    for ts in range(4):
        assert idle.evaluate(float(ts), {key: 2.0 * ts}) == []


def test_default_rule_pack_includes_tenant_rules():
    from deeplearning4j_tpu.analysis import slo

    names = {r.name for r in slo.default_rule_pack(
        tenants={"gold": 1.0, "free": 0.25})}
    assert "tenant_chip_budget_burn:gold" in names
    assert "tenant_chip_budget_burn:free" in names
    # without the arg the pack is unchanged — no tenant rules appear
    assert not any(n.startswith("tenant_chip_budget_burn")
                   for n in {r.name for r in slo.default_rule_pack()})


# -- metering overhead --------------------------------------------------------

@pytest.mark.slow
def test_metered_fit_within_noise_of_unmetered():
    """Arming the meter must not add a sync point to the fit loop: the
    hooks ride devprof's existing sampled cadence. Median-of-3 A/B with
    a deliberately generous bound — this guards against an accidental
    per-step device sync, not against µs-level drift."""
    x, y = _xy()

    def run_once():
        net = MultiLayerNetwork(_mlp_conf()).init()
        net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
        t0 = time.perf_counter()
        net.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)
        return time.perf_counter() - t0

    resourcemeter.disable()
    base = sorted(run_once() for _ in range(3))[1]
    resourcemeter.enable()
    with tenancy.tenant_scope("trainer"):
        metered = sorted(run_once() for _ in range(3))[1]
    assert metered < base * 3.0 + 0.5, (metered, base)
