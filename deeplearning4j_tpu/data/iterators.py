"""DataSet iterators.

Analog of the reference's iterator framework (datasets/iterator/):
DataSetIterator SPI, ListDataSetIterator, ExistingDataSetIterator,
MultipleEpochsIterator, and AsyncDataSetIterator — the background-prefetch
wrapper MultiLayerNetwork.fit installs automatically
(MultiLayerNetwork.java:1023-1025, prefetch threads feeding a bounded
queue). Here prefetch threads stage host batches while the TPU runs the
previous step, overlapping ETL with compute the same way.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet


class DataSetIterator:
    """SPI: iterable over DataSet minibatches with reset()."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def batch_size(self) -> Optional[int]:
        return None

    def total_examples(self) -> Optional[int]:
        return None


class ListDataSetIterator(DataSetIterator):
    """Minibatches from in-memory arrays (reference:
    ListDataSetIterator / ExistingDataSetIterator)."""

    def __init__(self, dataset: DataSet, batch: int, shuffle: bool = False, seed: int = 0):
        self.dataset = dataset
        self.batch = batch
        self.shuffle = shuffle
        self._epoch = 0
        self.seed = seed

    def __iter__(self):
        n = self.dataset.num_examples()
        idx = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(idx)
        self._epoch += 1
        d = self.dataset
        for i in range(0, n, self.batch):
            sl = idx[i : i + self.batch]
            yield DataSet(
                d.features[sl],
                d.labels[sl],
                None if d.features_mask is None else d.features_mask[sl],
                None if d.labels_mask is None else d.labels_mask[sl],
            )

    def reset(self):
        pass

    def batch_size(self):
        return self.batch

    def total_examples(self):
        return self.dataset.num_examples()


class ExistingDataSetIterator(DataSetIterator):
    """Wraps any iterable of DataSets (reference: ExistingDataSetIterator)."""

    def __init__(self, datasets: Iterable[DataSet]):
        self._list: List[DataSet] = list(datasets)

    def __iter__(self):
        return iter(self._list)

    def total_examples(self):
        return sum(d.num_examples() for d in self._list)


class MultipleEpochsIterator(DataSetIterator):
    """Repeat an underlying iterator n times (reference:
    MultipleEpochsIterator.java)."""

    def __init__(self, epochs: int, base: DataSetIterator):
        self.epochs = epochs
        self.base = base

    def __iter__(self):
        for _ in range(self.epochs):
            self.base.reset()
            yield from self.base

    def batch_size(self):
        return self.base.batch_size()


class MultiDataSetIterator:
    """SPI: iterable over MultiDataSet minibatches with reset()
    (reference: nd4j MultiDataSetIterator, consumed by
    ComputationGraph.fit)."""

    def __iter__(self):
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def batch_size(self) -> Optional[int]:
        return None

    def total_examples(self) -> Optional[int]:
        return None


class StackedDataSetIterator(DataSetIterator):
    """Concatenate k consecutive minibatches into one global batch — how a
    data-parallel trainer turns per-worker batches into one sharded batch
    (reference: ParallelWrapper round-robin dispatch of one minibatch per
    DefaultTrainer, ParallelWrapper.java:389-404)."""

    def __init__(self, base: DataSetIterator, k: int):
        self.base = base
        self.k = max(1, int(k))

    def __iter__(self):
        pending: List[DataSet] = []
        for ds in self.base:
            pending.append(ds)
            if len(pending) == self.k:
                yield DataSet.concat(pending)
                pending = []
        if pending:
            yield DataSet.concat(pending)

    def reset(self):
        self.base.reset()

    def batch_size(self):
        b = self.base.batch_size()
        return None if b is None else b * self.k

    def total_examples(self):
        return self.base.total_examples()


_SENTINEL = object()


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded queue (reference:
    AsyncDataSetIterator, queue capacity = prefetch buffer). The worker
    thread performs ETL while the accelerator computes; exceptions propagate
    to the consumer."""

    def __init__(self, base: DataSetIterator, queue_size: int = 4):
        self.base = base
        self.queue_size = max(1, queue_size)

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        err: List[BaseException] = []

        def worker():
            try:
                for ds in self.base:
                    q.put(ds)
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(_SENTINEL)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item

    def reset(self):
        self.base.reset()

    def batch_size(self):
        return self.base.batch_size()

    def total_examples(self):
        return self.base.total_examples()
