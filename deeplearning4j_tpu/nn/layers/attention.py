"""Multi-head self-attention layer impl (config: SelfAttentionLayer).

Single-device forward uses parallel/sequence.full_attention; the SAME math
runs sequence-parallel over a mesh via ring_self_attention (parallel/
sequence.py) — tests prove block-ring == full. Time masking multiplies
attention scores' keys (masked keys unattendable) and zeroes masked
outputs, matching the framework's RNN masking semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.registry import LayerContext, register_layer
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import apply_activation
from deeplearning4j_tpu.parallel.sequence import full_attention


def attention_init(key, conf: L.SelfAttentionLayer, dtype):
    n_in, n_out = int(conf.n_in), int(conf.n_out)
    if n_out % conf.n_heads != 0:
        raise ValueError(
            f"n_out {n_out} must be divisible by n_heads {conf.n_heads}")
    ks = jax.random.split(key, 4)
    mk = lambda k, i, o: init_weights(k, (i, o), i, o, conf.weight_init,
                                      conf.dist, dtype)
    p = {
        "Wq": mk(ks[0], n_in, n_out),
        "Wk": mk(ks[1], n_in, n_out),
        "Wv": mk(ks[2], n_in, n_out),
        "Wo": mk(ks[3], n_out, n_out),
    }
    if conf.projection_bias:
        p["b"] = jnp.zeros((n_out,), dtype)
    return p


def attention_forward(conf: L.SelfAttentionLayer, params, x,
                      ctx: LayerContext):
    """x: [b, t, nIn] -> [b, t, nOut]."""
    B, T, _ = x.shape
    H = int(conf.n_heads)
    E = int(conf.n_out)
    D = E // H
    dt = x.dtype
    q = (x @ params["Wq"].astype(dt)).reshape(B, T, H, D)
    k = (x @ params["Wk"].astype(dt)).reshape(B, T, H, D)
    v = (x @ params["Wv"].astype(dt)).reshape(B, T, H, D)
    if ctx.mask is not None:
        # masked keys contribute nothing: push their scores to -inf by
        # zeroing v and biasing k is fragile — mask scores directly
        o = _masked_attention(q, k, v, ctx.mask.astype(dt), conf.causal)
    else:
        o = full_attention(q, k, v, causal=conf.causal)
    y = o.reshape(B, T, E) @ params["Wo"].astype(dt)
    if conf.projection_bias:
        y = y + params["b"].astype(dt)
    if ctx.mask is not None:
        y = y * ctx.mask.astype(dt)[..., None]
    return apply_activation(conf.activation or "identity", y,
                            key=ctx.rng, training=ctx.training), None


def _masked_attention(q, k, v, mask, causal):
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    neg = jnp.asarray(-1e30, s.dtype)
    s = jnp.where(mask[:, None, None, :] > 0, s, neg)
    if causal:
        T = q.shape[1]
        tri = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(tri, s, neg)
    a = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", a, v)


def attention_order(conf):
    return ("Wq", "Wk", "Wv", "Wo", "b") if conf.projection_bias else (
        "Wq", "Wk", "Wv", "Wo")


register_layer(L.SelfAttentionLayer, attention_init, attention_forward,
               order_fn=attention_order)
