"""Model serialization — save/restore networks with updater state.

Analog of the reference's util/ModelSerializer.java (:40,79-118): a zip of
  configuration.json  — the full config DSL JSON (the compat surface)
  coefficients.bin    — the flattened parameter vector, little-endian f32
  updaterState.bin    — the updater state, flattened in pytree order
plus two additions the reference keeps implicit:
  layerState.bin      — non-trainable layer state (BN running stats)
  meta.json           — network type tag, format version, iteration/epoch
                        counters (so LR schedules resume correctly)

The flattened parameter order is the deterministic params.py convention
(layer/topo index, then param_order names, row-major) — the same vector
params()/set_params() exposes, so a saved file is also the parameter-
averaging/serving interchange format.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import zipfile
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

FORMAT_VERSION = 2

_CONFIG_JSON = "configuration.json"
_COEFFICIENTS = "coefficients.bin"
_UPDATER_STATE = "updaterState.bin"
_LAYER_STATE = "layerState.bin"
_UPDATER_STATE_NPZ = "updaterState.npz"
_LAYER_STATE_NPZ = "layerState.npz"
_META = "meta.json"
_TRAIN_STATE = "trainState.json"
# per-entry SHA-256 digests, written LAST so it covers every other
# entry — the integrity manifest restore paths verify before trusting
# a checkpoint (zip CRC-32 catches some flips on read; the manifest
# catches them BEFORE deserialization, names the damaged entry, and
# survives format evolution explicitly)
_MANIFEST = "manifest.json"


def _tree_to_npz_bytes(tree) -> bytes:
    """Serialize a pytree's leaves at their NATIVE dtype/shape (npz acts as
    the per-leaf manifest: a shape/dtype mismatch on load is an error, not
    a silent cast — v1's flat-f32 .bin lost f64/int state silently)."""
    leaves = jax.tree_util.tree_leaves(tree)
    arrays = {f"leaf{i:05d}": np.asarray(l) for i, l in enumerate(leaves)}
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _tree_from_npz_bytes(template, data: bytes):
    leaves, treedef = jax.tree_util.tree_flatten(template)
    with np.load(io.BytesIO(data)) as npz:
        keys = sorted(npz.files)
        if len(keys) != len(leaves):
            raise ValueError(
                f"saved state has {len(keys)} leaves, this "
                f"configuration/updater expects {len(leaves)} — file does "
                "not match"
            )
        out = []
        for key, tmpl in zip(keys, leaves):
            arr = npz[key]
            if tuple(arr.shape) != tuple(np.shape(tmpl)):
                raise ValueError(
                    f"saved leaf {key} shape {arr.shape} != expected "
                    f"{np.shape(tmpl)} — leaf-order drift or wrong file"
                )
            tmpl_dtype = np.asarray(tmpl).dtype
            if arr.dtype != tmpl_dtype:
                raise ValueError(
                    f"saved leaf {key} dtype {arr.dtype} != expected "
                    f"{tmpl_dtype} — leaf-order drift or wrong file"
                )
            out.append(jnp.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, out)


def _flatten_tree(tree) -> np.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return np.zeros((0,), np.float32)
    return np.concatenate(
        [np.asarray(l, dtype=np.float32).ravel() for l in leaves]
    )


def _unflatten_tree(template, vec: np.ndarray):
    """v1 compat: scatter a flat f32 vec into template's structure."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    out = []
    off = 0
    for l in leaves:
        n = int(np.prod(np.shape(l)))
        out.append(
            jnp.asarray(vec[off : off + n].reshape(np.shape(l)),
                        dtype=jnp.asarray(l).dtype)
        )
        off += n
    if off != vec.size:
        raise ValueError(
            f"state vector length {vec.size} != expected {off} — saved file "
            "does not match this configuration/updater"
        )
    return jax.tree_util.tree_unflatten(treedef, out)


class ConfigMismatchError(ValueError):
    """A checkpoint was written from a DIFFERENT configuration than the
    net it is being restored into. Deliberately its own type: the
    corruption-fallback restore loop must re-raise this (a changed
    architecture is a user error every candidate will repeat — silently
    'starting fresh' would discard the whole checkpoint history), while
    bit-rot/load failures fall through to the previous candidate."""


class ModelSnapshot:
    """Point-in-time capture of everything a model zip holds, split so
    async checkpointing can separate the two costs: `capture()` grabs
    REFERENCES (jax arrays are immutable and the train step replaces —
    never mutates — the params/state/updater pytrees, so holding the old
    trees IS a consistent snapshot; cost: outer-list copies and ints),
    while `write()` does the device→host pulls, flattening, compression
    and zip IO. The checkpoint listener runs capture() on the fit thread
    (the blocking "snapshot" phase) and write() on its background writer
    (the "write" phase); the synchronous save path runs both back to
    back — same bytes either way."""

    __slots__ = ("conf_json", "network_type", "iteration", "epoch",
                 "save_updater", "layer_confs", "params_list",
                 "state_list", "upd_state", "train_state")

    @classmethod
    def capture(cls, net, save_updater: bool = True,
                train_state: Optional[dict] = None) -> "ModelSnapshot":
        net._require_init()
        snap = cls()
        snap.conf_json = net.conf.to_json()
        snap.network_type = type(net).__name__
        snap.iteration = int(net.iteration)
        snap.epoch = int(net.epoch)
        snap.save_updater = bool(save_updater)
        snap.layer_confs = list(net._ordered_layer_confs())
        snap.params_list = list(net.params_list)
        snap.state_list = list(net.state_list)
        snap.upd_state = net.upd_state if save_updater else None
        snap.train_state = train_state
        return snap

    def write(self, path: Union[str, os.PathLike]) -> None:
        from deeplearning4j_tpu.nn.params import params_to_flat

        coeffs = np.asarray(params_to_flat(self.layer_confs,
                                           self.params_list))
        meta = {
            "format_version": FORMAT_VERSION,
            "network_type": self.network_type,
            "iteration": self.iteration,
            "epoch": self.epoch,
            "save_updater": self.save_updater,
            "coefficients_dtype": coeffs.dtype.str,  # e.g. "<f4", "<f8"
        }
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            digests = {}

            def put(name: str, data):
                # digest the exact bytes the entry stores — the
                # integrity manifest verify_checkpoint() checks on load
                if isinstance(data, str):
                    data = data.encode("utf-8")
                digests[name] = hashlib.sha256(data).hexdigest()
                zf.writestr(name, data)

            put(_CONFIG_JSON, self.conf_json)
            put(_META, json.dumps(meta, indent=2))
            put(_COEFFICIENTS,
                coeffs.astype(coeffs.dtype.newbyteorder("<")).tobytes())
            put(_LAYER_STATE_NPZ, _tree_to_npz_bytes(self.state_list))
            if self.save_updater:
                put(_UPDATER_STATE_NPZ, _tree_to_npz_bytes(self.upd_state))
            if self.train_state is not None:
                put(_TRAIN_STATE, json.dumps(self.train_state))
            zf.writestr(_MANIFEST, json.dumps(
                {"algorithm": "sha256", "entries": digests}, indent=1))


def save_model(net, path: Union[str, os.PathLike], save_updater: bool = True,
               train_state: Optional[dict] = None) -> None:
    """Write a model zip (reference: ModelSerializer.writeModel :79-118).
    `train_state` (a JSON-safe dict, see NetworkBase.train_state()) rides
    along for mid-epoch resume."""
    ModelSnapshot.capture(net, save_updater, train_state).write(path)


def _read_vec(zf: zipfile.ZipFile, name: str, dtype: str = "<f4") -> Optional[np.ndarray]:
    try:
        data = zf.read(name)
    except KeyError:
        return None
    return np.frombuffer(data, dtype=dtype).copy()


def _read_state(zf: zipfile.ZipFile, npz_name: str, bin_name: str):
    """Returns ("npz", bytes) for v2 files, ("vec", ndarray) for v1, or
    None when absent."""
    try:
        return ("npz", zf.read(npz_name))
    except KeyError:
        pass
    vec = _read_vec(zf, bin_name)
    return None if vec is None else ("vec", vec)


def load_model(path: Union[str, os.PathLike], load_updater: bool = True):
    """Restore a network from a model zip; dispatches on the saved config
    type (reference: restoreMultiLayerNetwork/restoreComputationGraph +
    ModelGuesser)."""
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph
    from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.conf.serde import config_from_json
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path, "r") as zf:
        conf = config_from_json(zf.read(_CONFIG_JSON).decode("utf-8"))
        meta = json.loads(zf.read(_META).decode("utf-8"))
        coeffs = _read_vec(
            zf, _COEFFICIENTS, meta.get("coefficients_dtype", "<f4")
        )
        layer_state = _read_state(zf, _LAYER_STATE_NPZ, _LAYER_STATE)
        upd = (
            _read_state(zf, _UPDATER_STATE_NPZ, _UPDATER_STATE)
            if load_updater else None
        )

    if isinstance(conf, MultiLayerConfiguration):
        net = MultiLayerNetwork(conf)
    elif isinstance(conf, ComputationGraphConfiguration):
        net = ComputationGraph(conf)
    else:
        raise ValueError(f"unsupported configuration type {type(conf).__name__}")
    net.init()
    if coeffs is not None:
        net.set_params(coeffs)

    def restore(template, entry):
        kind, payload = entry
        if kind == "npz":
            return _tree_from_npz_bytes(template, payload)
        return _unflatten_tree(template, payload)

    if layer_state is not None and not (
        layer_state[0] == "vec" and layer_state[1].size == 0
    ):
        net.state_list = restore(net.state_list, layer_state)
    if upd is not None and meta.get("save_updater", True):
        net.upd_state = restore(net.upd_state, upd)
    net.iteration = int(meta.get("iteration", 0))
    net.epoch = int(meta.get("epoch", 0))
    return net


def verify_checkpoint(path: Union[str, os.PathLike]) -> dict:
    """Integrity check of a model/checkpoint zip against its per-entry
    SHA-256 manifest. Returns:

        {"ok": bool, "legacy": bool, "algorithm": "sha256"|None,
         "entries": {name: {"status": ..., ...}}}

    Per-entry status: `ok`, `mismatch` (digest differs — a bit flip),
    `unreadable` (the zip layer itself rejects the entry — torn or
    CRC-failing bytes), `missing` (listed in the manifest, absent from
    the zip), `unlisted` (present but never digested — not written by
    this writer). Pre-digest (legacy) zips have no manifest: they report
    `legacy=True` with `ok=True` — graceful, nothing to verify against,
    and the restore paths treat them exactly as before this existed.
    A zip that cannot be opened at all reports ok=False with `error`."""
    out = {"ok": True, "legacy": False, "algorithm": None, "entries": {}}
    try:
        with zipfile.ZipFile(path, "r") as zf:
            names = set(zf.namelist())
            if _MANIFEST not in names:
                out["legacy"] = True
                return out
            try:
                man = json.loads(zf.read(_MANIFEST).decode("utf-8"))
            except Exception as e:
                out["ok"] = False
                out["error"] = (f"manifest unreadable: "
                                f"{type(e).__name__}: {e}")
                return out
            out["algorithm"] = man.get("algorithm", "sha256")
            digests = man.get("entries", {})
            for name, want in digests.items():
                if name not in names:
                    out["entries"][name] = {"status": "missing"}
                    out["ok"] = False
                    continue
                try:
                    got = hashlib.sha256(zf.read(name)).hexdigest()
                except Exception as e:
                    out["entries"][name] = {
                        "status": "unreadable",
                        "error": f"{type(e).__name__}: {e}"}
                    out["ok"] = False
                    continue
                if got != want:
                    out["entries"][name] = {
                        "status": "mismatch",
                        "expected": want[:16], "got": got[:16]}
                    out["ok"] = False
                else:
                    out["entries"][name] = {"status": "ok"}
            for name in sorted(names - set(digests) - {_MANIFEST}):
                out["entries"][name] = {"status": "unlisted"}
                out["ok"] = False
    except Exception as e:
        out["ok"] = False
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def read_train_state(path: Union[str, os.PathLike]) -> Optional[dict]:
    """The TrainState dict a checkpoint carries (None for checkpoints
    written without one — plain save_model calls, pre-resume files)."""
    with zipfile.ZipFile(path, "r") as zf:
        try:
            return json.loads(zf.read(_TRAIN_STATE).decode("utf-8"))
        except KeyError:
            return None


def restore_fit_state(net, path: Union[str, os.PathLike],
                      load_updater: bool = True,
                      ignore_lr: bool = False) -> dict:
    """Load a checkpoint zip INTO an existing (already-configured) net:
    params, layer state, updater state, iteration/epoch counters.
    Returns the zip's meta dict with the saved TrainState (or None)
    under "train_state" — the `fit(resume_from=...)` restore path, which
    continues an existing object instead of constructing a new network
    the way load_model does.

    The checkpoint's configuration must match the net's (compared as
    parsed JSON, so formatting drift is ignored): silently resuming a
    different architecture would train a wrong model. `ignore_lr`
    exempts `net_conf.learning_rate` from the comparison — the
    divergence sentinel's rollback path deliberately backs the rate off
    between the save and the restore, and the backoff must survive the
    restore rather than disqualify every checkpoint."""
    net._require_init()
    with zipfile.ZipFile(path, "r") as zf:
        meta = json.loads(zf.read(_META).decode("utf-8"))
        saved_conf = json.loads(zf.read(_CONFIG_JSON).decode("utf-8"))
        live_conf = json.loads(net.conf.to_json())
        if ignore_lr:
            for doc in (saved_conf, live_conf):
                nc = doc.get("net_conf")
                if isinstance(nc, dict):
                    nc.pop("learning_rate", None)
        if saved_conf != live_conf:
            raise ConfigMismatchError(
                f"checkpoint {path} was written from a different "
                f"configuration than this {type(net).__name__} — resume "
                "into the matching model, or use load_model() to "
                "reconstruct the saved one")
        coeffs = _read_vec(
            zf, _COEFFICIENTS, meta.get("coefficients_dtype", "<f4"))
        layer_state = _read_state(zf, _LAYER_STATE_NPZ, _LAYER_STATE)
        upd = (_read_state(zf, _UPDATER_STATE_NPZ, _UPDATER_STATE)
               if load_updater else None)
        try:
            train_state = json.loads(zf.read(_TRAIN_STATE).decode("utf-8"))
        except KeyError:
            train_state = None

    def restore(template, entry):
        kind, payload = entry
        if kind == "npz":
            return _tree_from_npz_bytes(template, payload)
        return _unflatten_tree(template, payload)

    if coeffs is not None:
        net.set_params(coeffs)
    if layer_state is not None and not (
        layer_state[0] == "vec" and layer_state[1].size == 0
    ):
        net.state_list = restore(net.state_list, layer_state)
    if upd is not None and meta.get("save_updater", True):
        net.upd_state = restore(net.upd_state, upd)
    net.iteration = int(meta.get("iteration", 0))
    net.epoch = int(meta.get("epoch", 0))
    meta["train_state"] = train_state
    return meta


def restore_multi_layer_network(path, load_updater: bool = True):
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    net = load_model(path, load_updater)
    if not isinstance(net, MultiLayerNetwork):
        raise ValueError(f"{path} holds a {type(net).__name__}, not a MultiLayerNetwork")
    return net


def restore_computation_graph(path, load_updater: bool = True):
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph

    net = load_model(path, load_updater)
    if not isinstance(net, ComputationGraph):
        raise ValueError(f"{path} holds a {type(net).__name__}, not a ComputationGraph")
    return net
