"""ResNet — bottleneck residual networks as ComputationGraphs.

The BASELINE.md metric of record is ResNet-50 images/sec/chip (reference
workload: ComputationGraph engine, nn/graph/ComputationGraph.java:1291, with
cuDNN conv helpers, deeplearning4j-cuda/CudnnConvolutionHelper.java:345).
Here the whole train step — every conv, BN, residual add — compiles into
one XLA program; convs run NHWC straight on the MXU, residual adds fuse
into the surrounding elementwise work.

He et al. (2015) v1 bottleneck topology: stem conv7x7/2 + maxpool3x3/2,
stages of [1x1 w, 3x3 w, 1x1 4w] blocks with identity (or 1x1-projection)
shortcuts, global average pool, softmax head. ResNet-50 = blocks (3,4,6,3),
widths (64,128,256,512).
"""

from __future__ import annotations

from typing import Sequence

from deeplearning4j_tpu.nn.conf import (
    ActivationLayer,
    BatchNormalization,
    ConvolutionLayer,
    ElementWiseVertex,
    GlobalPoolingLayer,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    SubsamplingLayer,
    Updater,
)
from deeplearning4j_tpu.nn.compgraph import ComputationGraph


def _conv_bn(gb, name, inp, n_out, k, stride, act="relu"):
    """conv(no bias, SAME) -> BN -> optional relu; returns output vertex
    name. Bias-free convs + BN is the standard ResNet recipe (and what BN
    makes redundant anyway)."""
    gb.add_layer(
        f"{name}_conv",
        ConvolutionLayer(
            kernel_size=(k, k), stride=(stride, stride), n_out=n_out,
            convolution_mode="same", has_bias=False, activation="identity",
        ),
        inp,
    )
    gb.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
    if act is None:
        return f"{name}_bn"
    gb.add_layer(f"{name}_act", ActivationLayer(activation=act), f"{name}_bn")
    return f"{name}_act"


def _bottleneck(gb, name, inp, width, stride, project):
    """[1x1 w, 3x3 w (stride), 1x1 4w] + shortcut -> relu."""
    out_ch = 4 * width
    c = _conv_bn(gb, f"{name}_a", inp, width, 1, 1)
    c = _conv_bn(gb, f"{name}_b", c, width, 3, stride)
    c = _conv_bn(gb, f"{name}_c", c, out_ch, 1, 1, act=None)
    if project:
        sc = _conv_bn(gb, f"{name}_sc", inp, out_ch, 1, stride, act=None)
    else:
        sc = inp
    gb.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), c, sc)
    gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_add")
    return f"{name}_relu"


def resnet_conf(
    blocks: Sequence[int] = (3, 4, 6, 3),
    widths: Sequence[int] = (64, 128, 256, 512),
    num_classes: int = 1000,
    image_size: int = 224,
    channels: int = 3,
    stem_width: int = 64,
    seed: int = 123,
    learning_rate: float = 0.1,
    updater: str = Updater.NESTEROVS,
    precision: str = "f32",
):
    """Parametric bottleneck ResNet as a ComputationGraphConfiguration."""
    gb = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater)
        .learning_rate(learning_rate)
        .momentum(0.9)
        .weight_init("relu")  # He init — the ResNet paper's choice
        .precision(precision)
        .graph_builder()
        .add_inputs("input")
        .set_input_types(InputType.convolutional(image_size, image_size, channels))
    )
    stem = _conv_bn(gb, "stem", "input", stem_width, 7, 2)
    gb.add_layer(
        "stem_pool",
        SubsamplingLayer(pooling_type="max", kernel_size=(3, 3), stride=(2, 2),
                         convolution_mode="same"),
        stem,
    )
    prev = "stem_pool"
    prev_ch = stem_width
    for si, (n_blocks, width) in enumerate(zip(blocks, widths)):
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            project = bi == 0  # channel change (or stride) on stage entry
            prev = _bottleneck(gb, f"s{si}b{bi}", prev, width, stride, project)
        prev_ch = 4 * width
    gb.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), prev)
    gb.add_layer(
        "out",
        OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"),
        "avgpool",
    )
    gb.set_outputs("out")
    return gb.build()


def resnet50_conf(num_classes: int = 1000, image_size: int = 224,
                  precision: str = "f32", **kw):
    return resnet_conf((3, 4, 6, 3), (64, 128, 256, 512),
                       num_classes=num_classes, image_size=image_size,
                       precision=precision, **kw)


def resnet50_network(num_classes: int = 1000, image_size: int = 224,
                     precision: str = "f32", **kw) -> ComputationGraph:
    return ComputationGraph(
        resnet50_conf(num_classes, image_size, precision, **kw)
    ).init()


def tiny_resnet_conf(num_classes: int = 3, image_size: int = 8,
                     precision: str = "f32", seed: int = 7):
    """Two-stage, one-block-per-stage, narrow ResNet for gradient checks
    and CI (the reference's pattern of tiny nets in
    gradientcheck/CNNGradientCheckTest.java)."""
    return resnet_conf(
        blocks=(1, 1), widths=(2, 4), num_classes=num_classes,
        image_size=image_size, channels=3, stem_width=4, seed=seed,
        precision=precision,
    )
