"""Device performance & memory observability (PR 9): the static jaxpr
cost model (analysis/costmodel) cross-checked against XLA's own
cost_analysis, the always-on runtime accounting (utils/devprof), OOM
forensics end to end via the `oom` fault kind, and the satellite
surfaces (bench FLOP-drift, profiler roofline columns, `cli perf`,
flight-recorder memory trajectory)."""

import json
import time

import numpy as np
import pytest

from deeplearning4j_tpu.analysis import costmodel
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator
from deeplearning4j_tpu.models.charlstm import char_lstm_conf
from deeplearning4j_tpu.models.resnet import resnet50_conf, tiny_resnet_conf
from deeplearning4j_tpu.nn.compgraph import ComputationGraph
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils import devprof
from deeplearning4j_tpu.utils import faultpoints as fp


def _dense_net(n_in=8, classes=3, with_input_type=True):
    # ADAM, deliberately: its two moment buffers give the updater a
    # real byte footprint for the device_memory_bytes{kind=updater} gauge
    b = (NeuralNetConfiguration.builder().seed(7).updater(Updater.ADAM)
         .learning_rate(0.05).weight_init("xavier").list()
         .layer(DenseLayer(n_in=n_in, n_out=8, activation="tanh"))
         .layer(OutputLayer(n_in=8, n_out=classes, activation="softmax",
                            loss="mcxent")))
    if with_input_type:
        b = b.set_input_type(InputType.feed_forward(n_in))
    return MultiLayerNetwork(b.build()).init()


def _dense_ds(n=8, n_in=8, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    return DataSet(rng.standard_normal((n, n_in)).astype(np.float32),
                   np.eye(classes, dtype=np.float32)[
                       rng.integers(0, classes, n)])


# -- static model vs XLA (the acceptance cross-check) -------------------------


def _cross_check(net, batch, timesteps, tolerance):
    step, args = costmodel.train_step_args(net, batch_size=batch,
                                           timesteps=timesteps)
    cm = costmodel.cost_fn(step, *args)
    xla = costmodel.xla_cost_analysis(step, *args)
    if xla is None:
        pytest.skip("Compiled.cost_analysis() unavailable on this backend")
    rel = abs(cm.xla_comparable_flops - xla["flops"]) / xla["flops"]
    assert rel <= tolerance, (
        f"cost model {cm.xla_comparable_flops:.4g} vs XLA "
        f"{xla['flops']:.4g} flops: {rel:.1%} > {tolerance:.0%}")
    assert not costmodel.cross_check(cm, xla, tolerance=tolerance)
    return cm, xla


def test_costmodel_matches_xla_resnet50_preset():
    """The acceptance bar: the resnet50 topology's full train step
    within 10% of XLA's own accounting (32px keeps the CPU compile
    tractable; the conv/elementwise mix is the full model's)."""
    net = ComputationGraph(
        resnet50_conf(num_classes=10, image_size=32)).init()
    cm, _ = _cross_check(net, batch=2, timesteps=16, tolerance=0.10)
    fams = cm.families
    assert fams["conv_general_dilated"].flops > 0.5 * cm.flops_total


def test_costmodel_matches_xla_charlstm_preset():
    net = MultiLayerNetwork(
        char_lstm_conf(vocab_size=40, hidden=32, tbptt_length=16)).init()
    cm, _ = _cross_check(net, batch=4, timesteps=16, tolerance=0.10)
    # the scanned LSTM: full-execution flops multiply the body by the
    # trip count, the XLA-comparable view counts it once
    assert cm.flops_total > 1.5 * cm.xla_comparable_flops


def test_costmodel_matches_xla_tiny_resnet_preset():
    """8x8 images are border-dominated: XLA's algebraic simplification
    rewrites the tiny convs past the valid-tap model, so the tiny
    preset gets a looser, documented tolerance (the full-size presets
    above hold the 10% bar)."""
    net = ComputationGraph(tiny_resnet_conf()).init()
    _cross_check(net, batch=4, timesteps=16, tolerance=0.25)


def test_activation_peak_and_residency():
    net = ComputationGraph(tiny_resnet_conf()).init()
    cm = costmodel.train_step_cost(net, batch_size=4)
    assert cm.activation_peak_bytes > 0
    assert cm.largest_activation is not None
    assert cm.activation_peak_bytes >= cm.largest_activation["bytes"]
    assert cm.param_bytes > 0 and cm.updater_bytes > 0
    assert cm.resident_bytes >= (cm.param_bytes + cm.updater_bytes
                                 + cm.activation_peak_bytes)
    # JX008 fires against a ceiling the estimate exceeds, stays quiet
    # against a roomy one, and skips entirely when HBM is unknown (CPU)
    assert not costmodel.residency_findings(cm, hbm_bytes=None)
    assert not costmodel.residency_findings(cm, hbm_bytes=16e9)
    bad = costmodel.residency_findings(cm, hbm_bytes=1024)
    assert bad and bad[0].code == "JX008" and bad[0].severity == "error"


def test_jx007_fires_on_divergence():
    net = ComputationGraph(tiny_resnet_conf()).init()
    cm = costmodel.train_step_cost(net, batch_size=2)
    fake = {"flops": cm.xla_comparable_flops * 2.0, "bytes_accessed": 0.0}
    found = costmodel.cross_check(cm, fake, tolerance=0.10)
    assert found and found[0].code == "JX007" and found[0].severity == "error"
    assert not costmodel.cross_check(cm, None)  # skip-, not fail-silent


def test_roofline_table_and_verdicts():
    net = ComputationGraph(tiny_resnet_conf()).init()
    cm = costmodel.train_step_cost(net, batch_size=4)
    rows = cm.table(peak_flops=197e12, hbm_bandwidth=819e9)
    assert rows[0]["family"] == "conv_general_dilated"  # flops-desc
    assert all(r["verdict"] in ("compute-bound", "memory-bound")
               for r in rows)
    roof = cm.roofline(peak_flops=197e12, hbm_bandwidth=819e9)
    assert roof["step_time_lower_bound_seconds"] > 0
    assert 0 < roof["mfu_ceiling"] <= 1.0
    # a fat matmul IS compute-bound against the same ridge
    import jax
    import jax.numpy as jnp

    # 2048^3: intensity ~ N/6 = 341 FLOP/B, past the v5e ridge (~241)
    big = costmodel.cost_fn(
        lambda a, b: a @ b,
        jax.ShapeDtypeStruct((2048, 2048), jnp.float32),
        jax.ShapeDtypeStruct((2048, 2048), jnp.float32))
    row = big.table(peak_flops=197e12, hbm_bandwidth=819e9)[0]
    assert row["family"] == "dot_general"
    assert row["verdict"] == "compute-bound"


# -- utils/flops demotion -----------------------------------------------------


def test_train_step_flops_for_sources():
    net = ComputationGraph(tiny_resnet_conf()).init()
    v, src = __import__(
        "deeplearning4j_tpu.utils.flops", fromlist=["x"]
    ).train_step_flops_for(net, 4)
    assert src == "costmodel" and v > 0
    # the analytic 3x-forward estimate and the traced MXU flops agree
    # to first order (backward convs ~2x forward, updater adds nothing)
    from deeplearning4j_tpu.utils import flops as F

    per_ex, asrc = F.analytic_step_flops_per_example(net.conf)
    assert asrc == "analytic" and per_ex
    assert 0.4 < v / (per_ex * 4) < 2.5
    # no InputType -> cost model impossible, analytic impossible: None
    bare = _dense_net(with_input_type=False)
    bv, bsrc = F.train_step_flops_for(bare, 4)
    assert bsrc == "analytic"


def test_analytic_refuses_unbounded_recurrent_per_example():
    """A recurrent conf with no fixed timestep count has no honest
    per-example analytic number (the walk prices ONE timestep): the
    lazy MFU path must return None rather than publish a gauge
    ~seq_len x too small, while the explicit per-step wrapper scales by
    the timesteps it is told."""
    from deeplearning4j_tpu.utils import flops as F

    conf = char_lstm_conf(vocab_size=20, hidden=16, tbptt_length=8)
    assert F.analytic_step_flops_per_example(conf) == (None, "analytic")
    net = MultiLayerNetwork(conf).init()
    assert net.model_flops_per_example() == (None, "analytic")
    v16, s = F.train_step_flops_for(net, 4, timesteps=16,
                                    prefer_cost_model=False)
    v32, _ = F.train_step_flops_for(net, 4, timesteps=32,
                                    prefer_cost_model=False)
    assert s == "analytic" and v16 and abs(v32 / v16 - 2.0) < 1e-6


def test_model_flops_per_example_lazy_and_attach():
    net = _dense_net()
    v, src = net.model_flops_per_example()
    assert src == "analytic" and v and v > 0
    cm = costmodel.train_step_cost(net, batch_size=4)
    net.attach_cost_model(cm, batch=4)
    v2, src2 = net.model_flops_per_example()
    assert src2 == "costmodel"
    assert abs(v2 - cm.model_flops / 4) < 1e-6
    assert net._cost_model_meta["activation_peak_bytes"] == \
        cm.activation_peak_bytes


# -- runtime half: devprof ----------------------------------------------------


def test_devprof_gauges_from_sampled_fit():
    from deeplearning4j_tpu.utils.metrics import get_registry

    net = _dense_net()
    cm = costmodel.train_step_cost(net, batch_size=8)
    net.attach_cost_model(cm, batch=8)
    devprof.configure(sample_every=2)
    try:
        net.fit(ExistingDataSetIterator([_dense_ds()] * 8), epochs=1)
    finally:
        devprof.configure(sample_every=0)
    sv = get_registry().scalar_values()
    assert sv.get('step_mfu{source="costmodel"}', 0) > 0
    assert sv.get('step_flops_per_second{source="costmodel"}', 0) > 0
    assert sv.get('device_memory_bytes{kind="params"}', 0) > 0
    assert sv.get('device_memory_bytes{kind="updater"}', 0) > 0
    assert sv.get('device_memory_bytes{kind="activations_est"}', 0) == \
        cm.activation_peak_bytes
    assert sv.get("devprof_samples_total", 0) >= 2
    # the sampling window dies with the fit: a later fit must not open
    # its first window against this fit's last sample timestamp
    assert net._devprof_state is None


def test_devprof_disabled_is_inert():
    net = _dense_net()
    assert devprof.get_profiler().sample_every == 0  # tier-1 default
    devprof.get_profiler().on_step(net, 8, None)
    assert getattr(net, "_devprof_state", None) is None


def test_devprof_step_seconds_counts_optimizer_steps():
    """One fused/TBPTT dispatch advances `iteration` by its whole
    segment count; per-step device time must divide by THAT, not by the
    dispatch count — else a fused-10 fit publishes a step time 10x too
    large next to a correct MFU."""
    from deeplearning4j_tpu.utils.metrics import get_registry

    prof = devprof.DeviceProfiler(sample_every=1)
    net = _dense_net()
    prof.sample_now(net)  # opens the window at iteration 0
    t0 = time.perf_counter()
    time.sleep(0.06)
    net.iteration += 4  # one fused dispatch = 4 optimizer steps
    prof.on_step(net, 32, None)
    dt = time.perf_counter() - t0
    g = get_registry().gauge("step_device_seconds").labels().value
    # divided by the 4 iterations (~dt/4), NOT by the 1 dispatch (~dt)
    assert 0.005 < g < dt / 3.5, (g, dt)


def test_devprof_unsampled_step_cost():
    """The <1%-of-fit-loop overhead guard, PR 6's record_step mechanism:
    the unsampled on_step path is a couple of integer ops — pinned well
    under 10us/call, i.e. <1% of even a 1ms fit step."""
    prof = devprof.DeviceProfiler(sample_every=100_000)
    net = _dense_net()
    prof.on_step(net, 4, None)  # state init off the clock
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        prof.on_step(net, 4, None)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 10e-6, f"on_step cost {per_call * 1e6:.2f}us"


# -- OOM forensics (the acceptance scenario) ----------------------------------


def test_injected_oom_mid_fit_dumps_forensics(capsys):
    from deeplearning4j_tpu.utils import blackbox

    net = _dense_net()
    plan = fp.FaultPlan(seed=3).add("train_step", "oom", every_nth=2,
                                    max_fires=1)
    with fp.active(plan):
        with pytest.raises(fp.InjectedOOM) as ei:
            net.fit(ExistingDataSetIterator([_dense_ds()] * 6), epochs=1)
    assert devprof.is_oom(ei.value)  # the injected error IS oom-shaped
    path = blackbox.get_recorder().last_dump_path
    assert path is not None
    with open(path) as f:
        doc = json.load(f)
    ooms = [e for e in doc["events"] if e.get("kind") == "oom"]
    assert ooms, "no oom event in the flight-recorder dump"
    ev = ooms[-1]
    assert ev["where"] == "fit"
    assert ev["top_buffers"], "dump names no live buffers"
    assert ev["top_buffers"][0]["nbytes"] >= ev["top_buffers"][-1]["nbytes"]
    assert ev["static"].get("activation_peak_bytes"), \
        "dump carries no static activation estimate"
    # rendered by cli blackbox: the OOM forensics section with buffers
    from deeplearning4j_tpu import cli

    rc = cli.main(["blackbox", path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "OOM forensics" in out
    assert "largest live buffers" in out
    assert "RESOURCE_EXHAUSTED" in out


def test_injected_oom_serving_forward():
    from deeplearning4j_tpu.parallel import ParallelInference
    from deeplearning4j_tpu.utils import blackbox

    net = _dense_net()
    pi = ParallelInference(net, max_batch_size=4, batch_timeout_ms=1.0,
                          component_prefix="oomtest")
    try:
        pi.warmup((8,))
        before = len([e for e in blackbox.get_recorder().snapshot()["events"]
                      if e.get("kind") == "oom"])
        plan = fp.FaultPlan(seed=1).add("replica_forward", "oom",
                                        every_nth=1, max_fires=1)
        with fp.active(plan):
            with pytest.raises(Exception) as ei:
                pi.output(np.zeros((2, 8), np.float32))
        assert devprof.is_oom(ei.value)
        events = [e for e in blackbox.get_recorder().snapshot()["events"]
                  if e.get("kind") == "oom"]
        assert len(events) > before
        assert events[-1]["where"] == "serving_forward"
    finally:
        pi.shutdown()


# -- satellites ---------------------------------------------------------------


def test_bench_vs_baseline_flags_flop_model_drift(monkeypatch):
    import bench

    prior = {
        "backend": "cpu",
        "workloads": {
            "lenet": {"value": 100.0, "model_flops_per_step": 1e9,
                      "flops_source": "analytic"},
            "resnet50": {"value": 50.0, "model_flops_per_step": 2e9,
                         "flops_source": "analytic"},
        },
    }
    monkeypatch.setattr(bench, "_prior_bench",
                        lambda: ("BENCH_r99.json", prior))
    current = {
        "lenet": {"value": 110.0, "model_flops_per_step": 0.8e9,
                  "flops_source": "costmodel"},
        "resnet50": {"value": 55.0, "model_flops_per_step": 2e9,
                     "flops_source": "costmodel"},
    }
    vs = bench._vs_baseline(current, "cpu")
    assert vs["speedup"]["lenet"] == 1.1
    drift = vs["flop_model_changed"]
    assert "lenet" in drift and "resnet50" not in drift
    assert drift["lenet"]["ratio"] == 0.8
    assert drift["lenet"]["prior_source"] == "analytic"
    assert drift["lenet"]["current_source"] == "costmodel"
    assert "flop_model_note" in vs
    # within-1% accounting agreement: no warning block at all
    agreeing = {"resnet50": {"value": 55.0, "model_flops_per_step": 2e9}}
    assert "flop_model_changed" not in bench._vs_baseline(agreeing, "cpu")


def test_profiler_roofline_columns(tmp_path):
    from deeplearning4j_tpu.utils.profiler import (
        roofline_columns,
        write_profile_json,
    )

    net = ComputationGraph(tiny_resnet_conf()).init()
    cm = costmodel.train_step_cost(net, batch_size=4).to_dict()
    fams = {"convolution": 25.8, "convert_reduce_fusion": 15.1,
            "dot": 1.2}
    cols = roofline_columns(fams, cm)
    assert cols["convolution"]["flops"] == \
        cm["families"]["conv_general_dilated"]["flops"]
    assert cols["dot"]["cost_model_family"] == "dot_general"
    assert "flops" not in cols["convert_reduce_fusion"]  # fusion: time-only
    assert roofline_columns(fams, None)["convolution"] == {"ms": 25.8}
    # the JSON export carries the cost model + annotated families (no
    # xplane in tmp_path -> measured families empty, context intact)
    out = tmp_path / "profile.json"
    payload = write_profile_json(str(tmp_path), str(out), cost_model=cm)
    assert payload["cost_model"]["model_flops"] > 0
    assert json.loads(out.read_text())["cost_model"]["families"][
        "conv_general_dilated"]["flops"] > 0


def test_cli_perf_json(capsys):
    from deeplearning4j_tpu import cli

    rc = cli.main(["perf", "--preset", "tiny_resnet", "--batch", "2",
                   "--no-vs-prior", "--json", "-"])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert doc["cost_model"]["model_flops"] > 0
    assert doc["cost_model"]["activation_peak_bytes"] > 0
    assert doc["families"][0]["family"] == "conv_general_dilated"
    assert doc["families"][0]["verdict"] in ("compute-bound",
                                             "memory-bound")
    assert doc["roofline"]["mfu_ceiling"] > 0
    assert doc["xla"] is None  # --xla not passed: no compile
    assert doc["findings"] == []


def test_blackbox_memory_trajectory():
    from deeplearning4j_tpu.utils.blackbox import FlightRecorder
    from deeplearning4j_tpu.utils.metrics import get_registry

    gauge = get_registry().gauge(
        "device_memory_bytes",
        "device memory watermarks polled at devprof samples", ("kind",))
    rec = FlightRecorder(metrics_every=1)
    gauge.labels("live").set(1000.0)
    rec.record_metrics_delta()  # baseline capture
    gauge.labels("live").set(2000.0)
    rec.record_metrics_delta()
    gauge.labels("live").set(3000.0)
    rec.record_metrics_delta()
    deltas = rec.snapshot()["metrics_deltas"]
    mems = [d["memory"]['device_memory_bytes{kind="live"}']
            for d in deltas if "memory" in d]
    # ABSOLUTE levels per capture — the trajectory, not just the slope
    assert mems[-2:] == [2000.0, 3000.0]
