"""KD-tree for low-dimensional exact neighbor search (reference:
clustering/kdtree/KDTree.java:129-157 knn(point, threshold); insert/delete
point API).

Host-side axis-median tree over numpy data with vectorized leaf scoring —
the same TPU-first stance as VPTree: trees organize indices, matmuls (or
vectorized numpy for the tiny per-node work) do the arithmetic. KD-trees
only pay off in low dimension; for d ≳ 20 use VPTree or brute force.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _KDNode:
    __slots__ = ("axis", "split", "index", "left", "right", "leaf_indices")

    def __init__(self):
        self.axis = 0
        self.split = 0.0
        self.index = -1
        self.left: Optional["_KDNode"] = None
        self.right: Optional["_KDNode"] = None
        self.leaf_indices: Optional[np.ndarray] = None


class KDTree:
    def __init__(self, points: np.ndarray, leaf_size: int = 32):
        self.points = np.asarray(points, np.float32)
        self.dims = self.points.shape[1]
        self.leaf_size = int(leaf_size)
        self.root = self._build(np.arange(self.points.shape[0]), depth=0)

    def _build(self, idx: np.ndarray, depth: int) -> Optional[_KDNode]:
        if idx.size == 0:
            return None
        node = _KDNode()
        if idx.size <= self.leaf_size:
            node.leaf_indices = idx
            return node
        axis = depth % self.dims
        vals = self.points[idx, axis]
        order = np.argsort(vals, kind="stable")
        mid = idx.size // 2
        node.axis = axis
        node.index = int(idx[order[mid]])
        node.split = float(vals[order[mid]])
        node.left = self._build(idx[order[:mid]], depth + 1)
        node.right = self._build(idx[order[mid + 1:]], depth + 1)
        return node

    def knn(self, point: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
        """k nearest by euclidean distance -> (indices, distances)."""
        q = np.asarray(point, np.float32).reshape(-1)
        k = min(int(k), self.points.shape[0])
        heap: List[Tuple[float, int]] = []  # max-heap via negation

        def consider(indices: np.ndarray):
            d2 = ((self.points[indices] - q[None, :]) ** 2).sum(axis=1)
            for i, di in zip(indices, d2):
                if len(heap) < k:
                    heapq.heappush(heap, (-float(di), int(i)))
                elif -heap[0][0] > di:
                    heapq.heapreplace(heap, (-float(di), int(i)))

        def tau2() -> float:
            return -heap[0][0] if len(heap) == k else np.inf

        def walk(node: Optional[_KDNode]):
            if node is None:
                return
            if node.leaf_indices is not None:
                consider(node.leaf_indices)
                return
            consider(np.array([node.index]))
            delta = q[node.axis] - node.split
            near, far = (node.right, node.left) if delta > 0 else (node.left, node.right)
            walk(near)
            if delta * delta <= tau2():
                walk(far)

        walk(self.root)
        out = sorted((-nd, i) for nd, i in heap)
        idx = np.array([i for _, i in out])
        dist = np.sqrt(np.array([d for d, _ in out]))
        return idx, dist
