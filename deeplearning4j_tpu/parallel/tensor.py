"""Tensor (model) parallelism over the "model" mesh axis.

NEW capability beyond the reference (SURVEY §2.4: DL4J ships data
parallelism only). TPU-native TP is declarative: parameters carry
NamedShardings over the "model" axis and XLA GSPMD inserts the
all-gathers/reduce-scatters — there is no hand-written collective code to
maintain. The canonical pattern (Megatron split):

  layer i   (column-parallel): W1 [E, F] sharded on F -> local activations
  layer i+1 (row-parallel):    W2 [F, E] sharded on F -> psum over "model"

``shard_params_tp`` applies that column/row alternation to a
MultiLayerNetwork's dense stack in place; the jitted train step is
unchanged — GSPMD propagates the shardings through forward, backward and
the updater. Combine with the "data" axis (mesh_2d) for DP+TP.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.parallel.mesh import MODEL_AXIS


def tp_dense_specs(layer_confs: List, axis: str = MODEL_AXIS):
    """PartitionSpec per layer-param for the alternating column/row split
    of consecutive Dense layers; everything else replicated. Output
    layers stay replicated (their nOut is the tiny class count)."""
    specs = []
    col = True  # start column-parallel
    for lc in layer_confs:
        inner = lc.inner if isinstance(lc, L.FrozenLayer) else lc
        if isinstance(inner, L.DenseLayer):
            if col:
                specs.append({"W": PartitionSpec(None, axis),
                              "b": PartitionSpec(axis)})
            else:
                specs.append({"W": PartitionSpec(axis, None),
                              "b": PartitionSpec()})
            col = not col
        else:
            specs.append(None)  # replicated
    return specs


def shard_params_tp(net, mesh: Mesh, axis: str = MODEL_AXIS):
    """Place a network's parameters (and updater state) with TP shardings
    over `mesh`. Training/inference then run tensor-parallel with no
    further code changes (GSPMD). Returns the per-layer specs used."""
    specs = tp_dense_specs(net.layer_confs, axis)
    rep = NamedSharding(mesh, PartitionSpec())

    def place(p, spec):
        out = {}
        for k, v in p.items():
            s = (spec or {}).get(k)
            sh = NamedSharding(mesh, s) if s is not None else rep
            out[k] = jax.device_put(v, sh)
        return out

    net.params_list = [
        place(p, s) for p, s in zip(net.params_list, specs)
    ]

    # updater state mirrors the param tree one level down (per-layer dicts
    # of per-param state pytrees) — shard it identically so moments stay
    # aligned with their parameters
    def place_state(st, spec):
        if st is None:
            return None
        out = {}
        for k, v in st.items():
            s = (spec or {}).get(k)
            sh = NamedSharding(mesh, s) if s is not None else rep
            out[k] = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sh), v)
        return out

    if net.upd_state is not None:
        net.upd_state = [
            place_state(st, s) for st, s in zip(net.upd_state, specs)
        ]
    return specs
