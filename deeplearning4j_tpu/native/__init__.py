"""Native (C++) runtime components, reached over ctypes.

The compute path is JAX/XLA/Pallas; these are the host-side runtime
pieces the reference also kept native (SURVEY §2.11) — currently the
corpus pipeline (corpus.cpp: tokenize + vocab count + index, the
VocabConstructor/text-pipeline hot loop). The shared library is built
from source on first use with g++ and cached next to this file; when no
toolchain exists the callers fall back to their pure-Python paths.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import List, Optional, Tuple

import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libdl4jcorpus.so")
_SRC = os.path.join(_HERE, "corpus.cpp")
_lock = threading.Lock()
_lib = None
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            try:
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     _SRC, "-o", _SO],
                    check=True, capture_output=True, text=True, timeout=120)
            except (OSError, subprocess.SubprocessError) as e:
                logger.warning("native corpus build failed (%s); "
                               "falling back to Python paths", e)
                _build_failed = True
                return None
        lib = ctypes.CDLL(_SO)
        lib.corpus_open.restype = ctypes.c_void_p
        lib.corpus_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.corpus_close.argtypes = [ctypes.c_void_p]
        for fn, ret in (("corpus_total_tokens", ctypes.c_int64),
                        ("corpus_num_sentences", ctypes.c_int64)):
            getattr(lib, fn).restype = ret
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.corpus_vocab_size.restype = ctypes.c_int64
        lib.corpus_vocab_size.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.corpus_vocab_bytes.restype = ctypes.c_int64
        lib.corpus_vocab_bytes.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.corpus_vocab_dump.restype = ctypes.c_int64
        lib.corpus_vocab_dump.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
        lib.corpus_index.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64)]
        lib.corpus_cooc_build.restype = ctypes.c_int64
        lib.corpus_cooc_build.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        lib.corpus_cooc_dump.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float)]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


class NativeCorpus:
    """One tokenized file. Exposes (words, counts) in VocabConstructor
    order and the corpus as vocab-indexed sentences."""

    def __init__(self, path: str, lowercase: bool = False):
        lib = _load()
        if lib is None:
            raise RuntimeError("native corpus library unavailable")
        self._lib = lib
        self._h = lib.corpus_open(path.encode(), int(lowercase))
        if not self._h:
            raise OSError(f"cannot read corpus file {path!r}")

    def close(self):
        if self._h:
            self._lib.corpus_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def total_tokens(self) -> int:
        return int(self._lib.corpus_total_tokens(self._h))

    @property
    def num_sentences(self) -> int:
        return int(self._lib.corpus_num_sentences(self._h))

    def vocab(self, min_count: int = 1) -> Tuple[List[str], np.ndarray]:
        """(words, counts) sorted by (count desc, word asc)."""
        n = self._lib.corpus_vocab_size(self._h, min_count)
        counts = np.zeros(n, np.int64)
        nbytes = self._lib.corpus_vocab_bytes(self._h, min_count)
        buf = ctypes.create_string_buffer(int(nbytes) + 1)
        written = self._lib.corpus_vocab_dump(
            self._h, min_count, buf, nbytes + 1,
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if written < 0:
            raise RuntimeError("vocab dump buffer undersized")
        words = buf.raw[:written].decode().split("\n")[:-1]
        return words, counts

    def cooccurrences(self, min_count: int = 1, window: int = 5,
                      symmetric: bool = True
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """GloVe co-occurrence COO triple (rows, cols, weights) over the
        filtered vocab: forward-window scan, 1/distance weights,
        mirrored when symmetric."""
        n = self._lib.corpus_cooc_build(
            self._h, min_count, window, int(symmetric))
        rows = np.zeros(n, np.int32)
        cols = np.zeros(n, np.int32)
        vals = np.zeros(n, np.float32)
        self._lib.corpus_cooc_dump(
            self._h,
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return rows, cols, vals

    def indexed_sentences(self, min_count: int = 1) -> List[np.ndarray]:
        """Sentences as vocab-index arrays, filtered words dropped —
        the exact shape SequenceVectors.train_indexed consumes."""
        total = self.total_tokens
        n_sent = self.num_sentences
        tokens = np.zeros(total, np.int32)
        offsets = np.zeros(n_sent + 1, np.int64)
        self._lib.corpus_index(
            self._h, min_count,
            tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        out = []
        for s in range(n_sent):
            seg = tokens[offsets[s]:offsets[s + 1]]
            seg = seg[seg >= 0]
            if seg.size:
                out.append(seg.astype(np.int64))
        return out
