"""Meta-checks that documentation claims stay true.

Round-4 verdict finding: a docstring cited an equivalence test that did
not exist ("manufactured verification"). This sweep greps every source
docstring/comment for `tests/<file>.py` citations and fails if any cited
file is missing — a claim of test coverage must point at a real test."""

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PAT = re.compile(r"tests/([A-Za-z0-9_]+\.py)")


def _source_files():
    for root, dirs, files in os.walk(os.path.join(REPO, "deeplearning4j_tpu")):
        dirs[:] = [d for d in dirs if not d.startswith("__pycache__")]
        for f in files:
            if f.endswith(".py"):
                yield os.path.join(root, f)
    for extra in ("bench.py", "__graft_entry__.py"):
        p = os.path.join(REPO, extra)
        if os.path.exists(p):
            yield p


def test_cited_test_files_exist():
    missing = []
    for path in _source_files():
        with open(path, encoding="utf-8") as f:
            text = f.read()
        for m in PAT.finditer(text):
            cited = os.path.join(REPO, "tests", m.group(1))
            if not os.path.exists(cited):
                missing.append(f"{os.path.relpath(path, REPO)} cites "
                               f"{m.group(0)}")
    assert not missing, "dangling test citations:\n" + "\n".join(missing)
