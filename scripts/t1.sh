#!/usr/bin/env bash
# Tier-1 verify — the exact command from ROADMAP.md, wrapped so builders
# and CI invoke ONE entrypoint instead of each re-typing (and drifting
# from) the canonical flags. Prints DOTS_PASSED=<n> after the run; exits
# with pytest's status. Slow-marked tests (serving load, multi-process)
# are excluded — that is what keeps tier-1 fast.
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
