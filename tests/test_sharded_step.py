"""Mainline multi-chip training: the sharded, donated, NamedSharding
train step `fit()` runs by default on multi-device platforms
(nn/netbase.set_mesh + parallel/sharded.MeshPlan).

Runs on the virtual 8-device CPU mesh (tests/conftest.py). The tests
whose names contain "smoke" are ALSO run standalone by scripts/t1.sh
under a forced 2-device platform with DL4J_AUTO_MESH=1 (the production
default), so the auto-engagement path is exercised by the gate at a
device count the suite itself never uses — they size their meshes from
whatever platform they find.
"""

import os

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import (
    BatchNormalization,
    DenseLayer,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import data_parallel_mesh
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS
from deeplearning4j_tpu.train.listeners import IterationListener

needs_8 = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs the 8-device virtual platform (t1's 2-device smoke "
           "interpreter runs only the smoke-named tests)")


def _mlp_conf(updater=Updater.NESTEROVS, with_bn=False, seed=7):
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater)
        .learning_rate(0.05)
        .momentum(0.9)
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_in=12, n_out=16, activation="tanh"))
    )
    if with_bn:
        b = b.layer(BatchNormalization(n_in=16))
    return (
        b.layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                            loss="mcxent"))
        .build()
    )


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 12)).astype(np.float32)
    y = np.zeros((n, 4), np.float32)
    y[np.arange(n), rng.integers(0, 4, n)] = 1.0
    return x, y


class _ScoreTap(IterationListener):
    """Per-iteration score collector (reads the lazy device score)."""

    def __init__(self):
        self.scores = []

    def iteration_done(self, model, iteration, info):
        self.scores.append(float(np.asarray(info["score"]())))


def _sub_mesh(n):
    devs = jax.devices()
    assert len(devs) >= n, f"need {n} devices, have {len(devs)}"
    return data_parallel_mesh(devs[:n])


# -- smoke tests (also run standalone by scripts/t1.sh at 2 devices) ----------


def test_smoke_sharded_fit_matches_single_device(monkeypatch):
    """Per-step scores and final params of a mesh-sharded fit equal the
    single-device run at the same global batch — the acceptance identity,
    sized to whatever platform is available (2 in the t1 smoke
    interpreter, 8 in the suite)."""
    n_dev = min(len(jax.devices()), 8)
    assert n_dev >= 2
    x, y = _data(64)

    monkeypatch.setenv("DL4J_AUTO_MESH", "0")
    net1 = MultiLayerNetwork(_mlp_conf()).init()
    tap1 = _ScoreTap()
    net1.set_listeners(tap1)
    net1.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)

    netN = MultiLayerNetwork(_mlp_conf()).init().set_mesh(_sub_mesh(n_dev))
    tapN = _ScoreTap()
    netN.set_listeners(tapN)
    netN.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)

    np.testing.assert_allclose(tap1.scores, tapN.scores,
                               rtol=2e-5, atol=2e-6)
    for p1, pN in zip(net1.params_list, netN.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(pN[k]), rtol=2e-5, atol=2e-6)
    # the sharded net's params really live on the whole mesh, replicated
    w0 = netN.params_list[0]["W"]
    assert len(w0.sharding.device_set) == n_dev
    assert w0.sharding.is_fully_replicated


def test_smoke_auto_mesh_is_the_multi_device_default(monkeypatch):
    """On a multi-device platform a PLAIN fit() — no wrapper, no
    set_mesh — engages the sharded data-parallel step (the tentpole's
    mainline claim). DL4J_AUTO_MESH=0 opts out."""
    x, y = _data(32)
    monkeypatch.setenv("DL4J_AUTO_MESH", "1")
    net = MultiLayerNetwork(_mlp_conf()).init()
    assert net._mesh_plan is None
    net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    assert net._mesh_plan is not None
    assert net._mesh_plan.n_data_shards == len(jax.devices())
    w0 = net.params_list[0]["W"]
    assert len(w0.sharding.device_set) == len(jax.devices())

    # numerics: identical to the opted-out single-device run
    monkeypatch.setenv("DL4J_AUTO_MESH", "0")
    ref = MultiLayerNetwork(_mlp_conf()).init()
    ref.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    assert ref._mesh_plan is None
    for p1, p2 in zip(ref.params_list, net.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=2e-5, atol=2e-6)


def test_smoke_allreduce_is_in_graph():
    """The gradient reduction is INSIDE the compiled step program (an
    all-reduce over the mesh), not a host-side averaging pass — the
    design point that replaces the reference's ParallelWrapper."""
    import jax.numpy as jnp

    n_dev = min(len(jax.devices()), 8)
    x, y = _data(16)
    net = MultiLayerNetwork(_mlp_conf()).init().set_mesh(_sub_mesh(n_dev))
    ds = net._mesh_plan.shard_batch(DataSet(x, y))
    step = net._build_train_step()
    lowered = step.lower(
        net.params_list, net.state_list, net.upd_state,
        (ds.features, ds.labels, None, ds.labels_mask),
        jnp.float32(0.05), jnp.float32(0.0), jax.random.PRNGKey(0))
    txt = lowered.compile().as_text()
    assert "all-reduce" in txt, "no all-reduce in the compiled step HLO"
    # and the donation rule was recorded for the JX006 audit
    assert net._donate_argnums is not None


# -- full-mesh (8-device) coverage --------------------------------------------


@needs_8
def test_sharded_scores_prefetch_on_off_and_allreduce_books():
    """The staged input pipeline (shard split in the prefetch worker)
    and the inline path produce the same sharded training trajectory
    (PR 4 fold_in determinism), and every sharded step lands in the
    allreduce books (`allreduce_bytes_total` = payload x steps)."""
    from deeplearning4j_tpu.utils.metrics import get_registry

    x, y = _data(64)

    def run(async_prefetch):
        net = MultiLayerNetwork(_mlp_conf()).init().set_mesh()
        tap = _ScoreTap()
        net.set_listeners(tap)
        net.fit(x, y, batch_size=16, epochs=2,
                async_prefetch=async_prefetch)
        return net, tap.scores

    ctr = get_registry().counter(
        "allreduce_bytes_total",
        "gradient bytes all-reduced in-graph by the sharded "
        "train step (logical payload: summed gradient leaf "
        "bytes per optimizer step)").labels()
    before = ctr.value
    net_on, scores_on = run(True)
    net_off, scores_off = run(False)
    np.testing.assert_allclose(scores_on, scores_off, rtol=1e-6, atol=1e-7)
    for p1, p2 in zip(net_on.params_list, net_off.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=1e-6, atol=1e-7)
    payload = net_on._mesh_plan.grad_payload_bytes(net_on)
    # 2 runs x 2 epochs x 4 batches = 16 sharded optimizer steps
    assert ctr.value - before == payload * 16


@needs_8
def test_sharded_batchnorm_global_stats():
    """Batch statistics under the mainline sharded step are GLOBAL-batch
    statistics — the property the reference's per-replica averaging
    could not provide."""
    x, y = _data(64, seed=3)
    net1 = MultiLayerNetwork(_mlp_conf(with_bn=True)).init()
    net8 = MultiLayerNetwork(_mlp_conf(with_bn=True)).init().set_mesh()
    net1.fit(x, y, batch_size=32, epochs=1, async_prefetch=False)
    net8.fit(x, y, batch_size=32, epochs=1, async_prefetch=False)
    for p1, p8 in zip(net1.params_list, net8.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p8[k]), rtol=5e-5, atol=5e-6)
    for s1, s8 in zip(net1.state_list, net8.state_list):
        if s1 is None:
            continue
        for k in s1:
            np.testing.assert_allclose(
                np.asarray(s1[k]), np.asarray(s8[k]), rtol=5e-5, atol=5e-6)


@needs_8
def test_compgraph_sharded_equivalence():
    """The DAG network rides the same sharded step (its jit sites all
    route through netbase._jit_step)."""
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph

    def conf():
        return (
            NeuralNetConfiguration.builder().seed(9).updater(Updater.SGD)
            .learning_rate(0.05).weight_init("xavier")
            .graph_builder()
            .add_inputs("in")
            .add_layer("h", DenseLayer(n_in=12, n_out=16,
                                       activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=16, n_out=4,
                                          activation="softmax",
                                          loss="mcxent"), "h")
            .set_outputs("out")
            .build()
        )

    x, y = _data(64, seed=5)
    g1 = ComputationGraph(conf()).init()
    g8 = ComputationGraph(conf()).init().set_mesh()
    g1.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)
    g8.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)
    for p1, p8 in zip(g1.params_list, g8.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p8[k]), rtol=2e-5, atol=2e-6)
    w0 = g8.params_list[0]["W"]
    assert len(w0.sharding.device_set) == 8


@needs_8
def test_donation_rule_extends_to_sharded_signature(monkeypatch):
    """Off-cpu the sharded step donates params (0) and updater state (2)
    — the ONE `_step_donate_argnums` rule, recorded on the net so the
    JX006 audit checks the value the sharded jit actually got."""
    from deeplearning4j_tpu.analysis.jaxpr_audit import check_donation

    net = MultiLayerNetwork(_mlp_conf()).init().set_mesh()
    # cpu: donation is a no-op and skipped — rule says ()
    step = net._build_train_step()
    assert step is not None
    assert net._donate_argnums == ()
    assert check_donation(net._donate_argnums, backend="cpu") == []

    # device backend: the sharded jit is BUILT with (0, 2) and records it
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    net._reset_step_programs()
    step = net._build_train_step()
    assert step is not None
    assert net._donate_argnums == (0, 2)
    assert check_donation(net._donate_argnums, backend="tpu") == []


@needs_8
def test_sharded_resume_roundtrip(tmp_path):
    """Mid-epoch `resume_from` (PR 7) round-trips through the sharded
    state: crash after k sharded steps, resume into a fresh sharded net,
    land on the same trajectory as the uninterrupted sharded run."""
    from deeplearning4j_tpu.train.checkpoint import CheckpointListener

    x, y = _data(64, seed=11)
    ckpt = str(tmp_path / "ckpt")

    # uninterrupted sharded reference
    ref = MultiLayerNetwork(_mlp_conf()).init().set_mesh()
    ref_tap = _ScoreTap()
    ref.set_listeners(ref_tap)
    ref.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)

    class _CrashAfter(IterationListener):
        def __init__(self, n):
            self.n = n

        def iteration_done(self, model, iteration, info):
            self.n -= 1
            if self.n == 0:
                raise RuntimeError("simulated preemption")

    # crashed run: checkpoint every step, die mid-epoch 2 (iteration 5
    # of 8: epoch 1, batch 1)
    crashed = MultiLayerNetwork(_mlp_conf()).init().set_mesh()
    crashed.set_listeners(
        CheckpointListener(ckpt, every_n_iterations=1, every_n_epochs=None,
                           keep_last=2),
        _CrashAfter(5))
    with pytest.raises(RuntimeError, match="simulated preemption"):
        crashed.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)

    # resumed run: fresh sharded net, same command line + resume_from
    resumed = MultiLayerNetwork(_mlp_conf()).init().set_mesh()
    tap = _ScoreTap()
    resumed.set_listeners(tap)
    resumed.fit(x, y, batch_size=16, epochs=2, async_prefetch=False,
                resume_from=ckpt)
    assert resumed.iteration == ref.iteration == 8
    # the resumed scores are the reference's suffix
    np.testing.assert_allclose(tap.scores, ref_tap.scores[-len(tap.scores):],
                               rtol=2e-5, atol=2e-6)
    for p1, p2 in zip(ref.params_list, resumed.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=2e-5, atol=2e-6)
    # the restored state went back onto the mesh
    w0 = resumed.params_list[0]["W"]
    assert len(w0.sharding.device_set) == 8


@needs_8
def test_shard_batch_no_double_transfer():
    """A batch already committed with the mesh sharding passes through
    shard_batch ZERO-COPY (the `_pipeline_staged` contract extended to
    sharded placement) — the fix that keeps fit_data_wait ~0 when the
    bench pre-stages batches."""
    net = MultiLayerNetwork(_mlp_conf()).init().set_mesh()
    plan = net._mesh_plan
    x, y = _data(32)
    staged = plan.shard_batch(DataSet(x, y))
    again = plan.shard_batch(staged)
    assert again.features is staged.features
    assert again.labels is staged.labels
    assert again.reported_examples == 32

    # a non-divisible tail still pads + masks (the slow path); reset the
    # pad-up-to-largest-seen target first (per-fit state) so the expected
    # shape is the next multiple, not the 32 staged above
    plan.reset_pad_target()
    tail = plan.shard_batch(DataSet(x[:19], y[:19]))
    assert tail.features.shape[0] == 24  # padded to the next multiple of 8
    assert tail.reported_examples == 19
    lm = np.asarray(tail.labels_mask)
    assert lm[:19].all() and not lm[19:].any()


@needs_8
def test_parallel_wrapper_is_a_deprecated_facade():
    """ParallelWrapper deprecates into a shim over set_mesh: no private
    averaging/sharding machinery left, the model IS a sharded net after
    construction, and fit delegates to the model's own loop."""
    x, y = _data(32)
    net = MultiLayerNetwork(_mlp_conf()).init()
    with pytest.warns(DeprecationWarning, match="set_mesh"):
        from deeplearning4j_tpu.parallel import ParallelWrapper

        w = ParallelWrapper(net, data_parallel_mesh())
    assert net._mesh_plan is not None
    assert not hasattr(w, "_shard_batch")
    assert not hasattr(w, "_place_replicated")
    assert net._batch_transform == net._mesh_plan.shard_batch
    w.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    assert np.isfinite(float(np.asarray(net._score)))
    # the plan persists: the net keeps training sharded without the shim
    net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    assert net._mesh_plan is not None


@needs_8
def test_per_chip_accounting():
    """The cost model and devprof divide by the data-axis size so
    multi-chip MFU/memory is per-chip-correct, not over-reported 8x."""
    from deeplearning4j_tpu.analysis.costmodel import train_step_cost
    from deeplearning4j_tpu.nn.conf import InputType
    from deeplearning4j_tpu.utils.devprof import _data_shards_of, _tree_bytes

    def typed_conf():
        return (
            NeuralNetConfiguration.builder().seed(7)
            .updater(Updater.SGD).learning_rate(0.05).weight_init("xavier")
            .list()
            .layer(DenseLayer(n_in=12, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(12))
            .build()
        )

    single = MultiLayerNetwork(typed_conf()).init()
    cm1 = train_step_cost(single, batch_size=16)
    assert cm1.data_axis_shards == 1
    assert cm1.model_flops_per_chip == cm1.model_flops

    net = MultiLayerNetwork(typed_conf()).init().set_mesh()
    assert _data_shards_of(net) == 8
    cm8 = train_step_cost(net, batch_size=16)
    assert cm8.data_axis_shards == 8
    np.testing.assert_allclose(cm8.model_flops_per_chip * 8, cm8.model_flops)

    # per-chip bytes: replicated params count full size, a batch-sharded
    # array counts its shard
    full = _tree_bytes(single.params_list)
    assert _tree_bytes(net.params_list) == full
    sharded = net._mesh_plan.shard_batch(DataSet(*_data(32))).features
    assert _tree_bytes([sharded]) * 8 == int(sharded.nbytes)


@needs_8
def test_sharded_fused_dispatch_equals_per_step():
    """set_fused_steps composes with the mesh: K sharded same-shape
    batches run as ONE stacked jitted dispatch (batch dim 1 sharded over
    "data") with numerics equal to the per-step sharded loop — the
    fusion opt-in survives mesh attachment."""
    x, y = _data(64, seed=13)

    def run(fused):
        net = MultiLayerNetwork(_mlp_conf()).init().set_mesh()
        if fused:
            net.set_fused_steps(2)
        net.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)
        assert net.iteration == 8
        return net

    per_step = run(False)
    fused = run(True)
    for p1, p2 in zip(per_step.params_list, fused.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=2e-5, atol=2e-6)
    w0 = fused.params_list[0]["W"]
    assert len(w0.sharding.device_set) == 8


@needs_8
def test_unset_mesh_returns_to_single_device(monkeypatch):
    """unset_mesh must re-commit state to the default device — leftover
    mesh-committed params would hand the rebuilt un-sharded jit
    arguments on incompatible device sets (review finding)."""
    monkeypatch.setenv("DL4J_AUTO_MESH", "0")
    x, y = _data(32)
    net = MultiLayerNetwork(_mlp_conf()).init().set_mesh()
    net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    net.unset_mesh()
    assert net._mesh_plan is None and net._batch_transform is None
    # trains again, single-device, with no incompatible-devices error
    net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    w0 = net.params_list[0]["W"]
    assert len(w0.sharding.device_set) == 1


@needs_8
def test_tp_placement_survives_auto_mesh(monkeypatch):
    """Auto-mesh must not clobber a deliberate tensor-parallel placement:
    params already committed to a mesh opt the net out of the data-mesh
    default."""
    from deeplearning4j_tpu.parallel import shard_params_tp
    from deeplearning4j_tpu.parallel.mesh import mesh_2d

    monkeypatch.setenv("DL4J_AUTO_MESH", "1")
    conf = (
        NeuralNetConfiguration.builder().seed(11).updater(Updater.ADAM)
        .learning_rate(0.01).weight_init("xavier").list()
        .layer(DenseLayer(n_in=12, n_out=32, activation="tanh"))
        .layer(DenseLayer(n_in=32, n_out=16, activation="tanh"))
        .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                           loss="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    shard_params_tp(net, mesh_2d(1, 8))
    x, y = _data(32, seed=9)
    net.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    assert net._mesh_plan is None  # deferred to the tp decision
    w0 = net.params_list[0]["W"]
    assert w0.sharding.shard_shape(w0.shape) == (12, 4)  # tp layout kept
