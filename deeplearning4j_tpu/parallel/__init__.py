"""Scale-out: data parallelism, sharded inference, mesh utilities.

TPU-native replacement for deeplearning4j-scaleout (SURVEY.md §2.4): the
reference's three data-parallel transports (thread-replica ParallelWrapper,
Aeron parameter server, Spark parameter averaging) collapse into one
mechanism here — sharded global batches + XLA GSPMD gradient allreduce over
ICI/DCN on a `jax.sharding.Mesh`.
"""

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharded,
    data_parallel_mesh,
    data_shards,
    mesh_2d,
    n_devices,
    replicated,
)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.parallel.inference import InferenceMode, ParallelInference
from deeplearning4j_tpu.parallel.tensor import shard_params_tp, tp_dense_specs

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "batch_sharded",
    "data_parallel_mesh",
    "data_shards",
    "mesh_2d",
    "n_devices",
    "replicated",
    "ParallelWrapper",
    "ParallelInference",
    "InferenceMode",
]
