"""Multi-host DCN data parallelism: 2 processes x 4 virtual CPU devices
== 1 process x 8 devices (VERDICT next #9 done-criterion).

The reference's equivalent test tier is BaseSparkTest's local[N] Spark
context (SURVEY §4 "distributed-without-a-cluster"); here the two workers
are REAL separate processes joined by jax.distributed over localhost, so
the cross-process collective path (DCN analog) is genuinely exercised.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_equals_single_process(tmp_path):
    # baseline: this process already runs an 8-device CPU platform
    from tests.multihost_common import build_net, global_data
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator
    from deeplearning4j_tpu.parallel import ParallelWrapper, data_parallel_mesh

    x, y = global_data()
    net1 = build_net()
    dss = [DataSet(x[i:i + 16], y[i:i + 16]) for i in range(0, 32, 16)]
    ParallelWrapper(net1, data_parallel_mesh()).fit(
        ExistingDataSetIterator(dss), epochs=2, async_prefetch=False)

    # two real processes, 4 virtual devices each, same global math
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    out = str(tmp_path / "p0.npz")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    script = os.path.join(REPO, "tests", "multihost_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, script, coordinator, "2", str(i), out],
            env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(2)
    ]
    for i, p in enumerate(procs):
        try:
            _, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"worker {i} timed out")
        assert p.returncode == 0, f"worker {i} failed:\n{err[-3000:]}"

    got = np.load(out)
    for i, p in enumerate(net1.params_list):
        for k, v in p.items():
            np.testing.assert_allclose(
                got[f"{i}/{k}"], np.asarray(v), rtol=2e-5, atol=2e-6,
                err_msg=f"param {i}/{k} diverged across the process boundary")
