"""Persistent run ledger — continuous metrics recording on a daemon,
plus the live side of the SLO rules layer (analysis/slo).

Every signal the observability PRs built (MFU/HBM gauges, shed books,
deadline outcomes, step phases) lives in the process-global
MetricsRegistry — scrape-time-only, dead with the process. This module
is the DL4J persistent-StatsStorage idea rebuilt for that registry: a
`RunLedger` samples `MetricsRegistry.scalar_values()` (the same
mechanism the flight recorder's periodic deltas use, here with
histogram buckets included) every `sample_every` seconds on a
`dl4j-ledger-*` daemon and appends to a per-run JSONL artifact:

    {"kind": "manifest", run_id, ts, pid, argv, devices, sample_every,
     config_hash, flops_source, links, rules: [...]}
    {"kind": "note", ...}          — late manifest enrichment (the first
                                     fit step names the net: config
                                     hash, flops source) — append-only,
                                     readers merge notes into the
                                     manifest
    {"kind": "sample", seq, ts, values: {series: value}}   — DELTA rows:
                                     only series whose value changed
                                     since the previous sample (first
                                     row = everything); readers
                                     reconstruct absolutes by
                                     accumulating
    {"kind": "rollup", t0, t1, n, series: {name: {min, max, mean,
     last}}}                       — n folded raw samples (see
                                     retention below)
    {"kind": "alert", ts, rule, from, to, value, severity, component,
     detail}                       — SLO rule lifecycle transitions

Retention (why a days-long soak stays MBs): the ledger keeps the most
recent `raw_window` samples raw; older samples are folded
oldest-first, `rollup_chunk` at a time, into one min/mean/max/last
rollup row, and the file is compacted in place (tmp + os.replace — the
checkpoint discipline; a reader never sees a half-written artifact).
At the 5 s default a day of soak is 17 280 samples -> 720 raw +
~260 rollups ≈ a few MB regardless of run length.

Overhead contract (same pin as tracing / PR 6 record_step): with no
ledger attached, the fit-loop and serving hooks (`note_fit_step`,
`note_request`) are ONE module-global read — <10 µs/call, tested.
With one attached they are a couple of integer ops; all real work
(sampling, rule evaluation, IO) happens on the ledger's own daemon,
which is heartbeat-registered with the watchdog (`component ledger`)
and abortable like every other dl4j-* worker.

Opting in is one knob: `fit(run_ledger=path_or_ledger)`,
`ParallelInference(run_ledger=...)`, `bench.py parallel_inference
--overload` (always records one), or `attach(RunLedger(path))`
directly. While attached, firing SLO rules emit findings (SLO001),
increment `slo_alerts_total{rule,severity}`, mark the owning component
DEGRADED in utils/health, and drop a flight-recorder event — the
"judged continuously" half the ROADMAP autotune item consumes.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, Iterator, List, Optional, Tuple

from deeplearning4j_tpu.utils import blackbox as _blackbox
from deeplearning4j_tpu.utils import health as _health
from deeplearning4j_tpu.utils import metrics as _metrics

logger = logging.getLogger("deeplearning4j_tpu")

# the module-global attachment point: hooks read this ONCE per call —
# the whole off-path cost when no ledger is recording
_LEDGER: Optional["RunLedger"] = None


def attach(ledger: "RunLedger") -> "RunLedger":
    """Make `ledger` the process's recording ledger (starts it if
    needed). One ledger records at a time — attaching a second replaces
    the first (which keeps running; detach/close it explicitly)."""
    global _LEDGER
    ledger.start()
    _LEDGER = ledger
    return ledger


def detach(ledger: Optional["RunLedger"] = None):
    """Stop routing hooks to the attached ledger (the ledger itself
    stays open). With an argument, only detaches if that ledger is the
    attached one — a scope that attached its own ledger cannot evict a
    replacement installed since."""
    global _LEDGER
    if ledger is None or _LEDGER is ledger:
        _LEDGER = None


def current() -> Optional["RunLedger"]:
    return _LEDGER


# -- the hot-path hooks (one global read when off) ----------------------------

def note_fit_step(net) -> None:
    """Fit-loop hook (netbase._timed_fit): no ledger = one global read.
    Attached: count the step and, once, hand the ledger the net so the
    manifest can be enriched (config hash, flops source) off-thread."""
    led = _LEDGER
    if led is None:
        return
    led._fit_steps += 1
    if led._net is None:
        led._net = net


def note_request() -> None:
    """Serving hook (ParallelInference.output): same contract."""
    led = _LEDGER
    if led is None:
        return
    led._requests += 1


class RunLedger:
    """One training/serving run's persistent metric history + live SLO
    judgment. Context manager; `close()` takes a final sample and
    flushes, so even a run shorter than `sample_every` leaves a
    start/end pair to diff."""

    def __init__(self, path: str, sample_every: float = 5.0,
                 raw_window: int = 720, rollup_chunk: int = 64,
                 rules=None, manifest: Optional[dict] = None,
                 links: Optional[dict] = None):
        from deeplearning4j_tpu.analysis.slo import SLORule, SLORuleSet

        self.path = path
        self.sample_every = max(0.05, float(sample_every))
        self.raw_window = max(2, int(raw_window))
        self.rollup_chunk = max(2, int(rollup_chunk))
        if rules is not None and not isinstance(rules, SLORuleSet):
            rules = SLORuleSet([r if isinstance(r, SLORule)
                                else SLORule.from_dict(r) for r in rules])
        self.rules = rules
        self.run_id = (manifest or {}).get("run_id") \
            or f"{time.strftime('%Y%m%d-%H%M%S')}-{uuid.uuid4().hex[:8]}"
        self._manifest_extra = dict(manifest or {})
        self._links = dict(links or {})
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._hb = None
        self._file = None
        self._started = False
        self._closed = False
        self._seq = 0
        # reconstruction state: last written absolutes (for delta rows)
        self._current: Dict[str, float] = {}
        # retained rows, in file order (manifest/notes/alerts/rollups/
        # samples) — the compaction rewrite source of truth
        self._rows: List[dict] = []
        # absolutes per retained raw sample, aligned with the raw
        # sample rows (rollup math needs per-sample values)
        self._raw_abs: deque = deque()
        self._raw_indices: deque = deque()  # indices into _rows
        self._alerts: deque = deque(maxlen=256)  # recent transitions
        self.findings: List = []  # analysis.findings.Finding, bounded
        # firing-rule count per health component: DEGRADED while > 0
        self._component_firing: Dict[str, int] = {}
        # hook counters (GIL-atomic int adds; no lock on the hot path):
        # the run's OWN share of fit steps / serving requests — written
        # into the artifact as a closing note (the registry families
        # count the whole process lifetime)
        self._fit_steps = 0
        self._requests = 0
        self._net = None  # first fit net seen; manifest enrichment
        self._net_noted = False

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "RunLedger":
        with self._lock:
            if self._started:
                return self
            self._started = True
        if os.path.dirname(self.path):
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._file = open(self.path, "w")
        self._append_row(self._build_manifest())
        self.sample_now()  # t0 baseline: diffs cover the whole run
        # per-run component name: concurrent ledgers (the conftest
        # session ledger + a test's own) must not evict each other's
        # watchdog coverage by re-registering one shared name
        self._hb = _health.get_health().register(
            f"ledger-{self.run_id[-8:]}",
            stall_after=max(60.0, 8.0 * self.sample_every))
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"dl4j-ledger-{self.run_id[-8:]}")
        self._thread.start()
        return self

    def close(self):
        """Final sample, flush, retire the daemon (unregistering its
        heartbeat), detach if attached. Idempotent."""
        with self._lock:
            if self._closed or not self._started:
                self._closed = True
                return
            self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        detach(self)
        try:
            self.sample_now()
        except Exception:
            logger.exception("run ledger final sample failed")
        with self._lock:
            # persist the hook-side activity tally: how many fit steps /
            # serving requests ran through the instrumented paths WHILE
            # this ledger was attached — the registry families are
            # process-lifetime, this is the run's own share (readers
            # merge the note into the manifest)
            self._append_row({
                "kind": "note", "ts": round(time.time(), 3),
                "fit_steps_hooked": self._fit_steps,
                "requests_hooked": self._requests,
            })
            if self._file is not None:
                self._file.close()
                self._file = None
        if self._hb is not None:
            _health.get_health().unregister(self._hb)
        # a closed ledger must leave no condition behind: resolve every
        # component its firing rules degraded
        for comp, n in list(self._component_firing.items()):
            if n > 0:
                _health.get_health().set_condition(
                    comp, _health.OK, reason=f"ledger {self.run_id} closed")
        self._component_firing.clear()

    def __enter__(self) -> "RunLedger":
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False

    # -- the recorder thread --------------------------------------------------

    def _loop(self):
        while not self._stop.wait(self.sample_every):
            try:
                with self._hb.busy():
                    self.sample_now()
            except Exception:  # a sampling bug must not kill recording
                logger.exception("run ledger sample failed")

    def sample_now(self, ts: Optional[float] = None):
        """Take one sample (callable from tests / the closing thread):
        registry scalars + buckets, delta row, rule evaluation with live
        side effects, rollup-based compaction when the raw window
        overflows."""
        ts = time.time() if ts is None else float(ts)
        values = _metrics.get_registry().scalar_values(include_buckets=True)
        with self._lock:
            if self._file is None and self._started:
                return  # closed under us
            if self._net is not None and not self._net_noted:
                self._net_noted = True
                note = self._net_note()
                if note:
                    self._append_row({"kind": "note",
                                      "ts": round(ts, 3), **note})
            delta = {k: v for k, v in values.items()
                     if self._current.get(k) != v}
            self._seq += 1
            row = {"kind": "sample", "seq": self._seq,
                   "ts": round(ts, 3), "values": delta}
            self._current = values
            self._raw_indices.append(len(self._rows))
            self._append_row(row)
            self._raw_abs.append(values)
            if len(self._raw_abs) > self.raw_window + self.rollup_chunk:
                self._compact_locked()
            if self._file is not None:
                self._file.flush()
        if self.rules is not None:
            try:
                transitions = self.rules.evaluate(ts, values)
            except Exception:
                logger.exception("SLO rule evaluation failed")
                transitions = []
            for tr in transitions:
                self._apply_transition(tr)

    # -- persistence ----------------------------------------------------------

    def _append_row(self, row: dict):
        self._rows.append(row)
        if self._file is not None:
            self._file.write(json.dumps(row, default=str) + "\n")

    def _compact_locked(self):
        """Fold the oldest `rollup_chunk` raw samples into one rollup
        row and rewrite the artifact. The rollup carries min/max/mean/
        last for EVERY series live at the span's end, so reconstruction
        seeds exactly (a series untouched within the span has min ==
        max == last)."""
        chunk_n = self.rollup_chunk
        abs_rows = [self._raw_abs.popleft() for _ in range(chunk_n)]
        idxs = [self._raw_indices.popleft() for _ in range(chunk_n)]
        t0 = self._rows[idxs[0]]["ts"]
        t1 = self._rows[idxs[-1]]["ts"]
        series: Dict[str, dict] = {}
        last = abs_rows[-1]
        for key, v_last in last.items():
            vs = [a[key] for a in abs_rows if key in a]
            series[key] = {
                "min": min(vs), "max": max(vs),
                "mean": round(sum(vs) / len(vs), 9), "last": v_last,
            }
        rollup = {"kind": "rollup", "t0": t0, "t1": t1,
                  "n": chunk_n, "series": series}
        # splice: replace the chunk's sample rows with the one rollup,
        # keeping interleaved notes/alerts in place
        drop = set(idxs)
        new_rows: List[dict] = []
        remap: Dict[int, int] = {}
        inserted = False
        for i, r in enumerate(self._rows):
            if i in drop:
                if not inserted:
                    new_rows.append(rollup)
                    inserted = True
                continue
            remap[i] = len(new_rows)
            new_rows.append(r)
        self._raw_indices = deque(remap[i] for i in self._raw_indices)
        self._rows = new_rows
        # the delta of the first surviving sample row is relative to the
        # rollup's `last` values — reconstruction is exact; rewrite the
        # whole artifact atomically
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for r in self._rows:
                f.write(json.dumps(r, default=str) + "\n")
        os.replace(tmp, self.path)
        if self._file is not None:
            self._file.close()
            self._file = open(self.path, "a")

    def _build_manifest(self) -> dict:
        devices = {}
        try:
            import jax

            devs = jax.devices()
            devices = {"platform": devs[0].platform,
                       "device_count": len(devs),
                       "device_kind": getattr(devs[0], "device_kind", "")}
        except Exception:
            pass
        import sys

        man = {
            "kind": "manifest",
            "run_id": self.run_id,
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "sample_every": self.sample_every,
            "raw_window": self.raw_window,
            "rollup_chunk": self.rollup_chunk,
            "devices": devices,
            "config_hash": None,
            "flops_source": None,
            "links": self._links,
            "rules": self.rules.to_dicts() if self.rules is not None
            else [],
        }
        extra = {k: v for k, v in self._manifest_extra.items()
                 if k not in ("kind", "run_id")}
        man.update(extra)
        return man

    def _net_note(self) -> dict:
        """Manifest enrichment from the first fit net the hooks saw —
        computed on the recorder thread, never on the fit hot path."""
        net = self._net
        note = {}
        try:
            conf_json = net.conf.to_json()
        except Exception:
            conf_json = repr(getattr(net, "conf", None))
        try:
            import hashlib

            note["config_hash"] = hashlib.sha256(
                conf_json.encode()).hexdigest()[:16]
        except Exception:
            pass
        try:
            _, source = net.model_flops_per_example()
            note["flops_source"] = source
        except Exception:
            pass
        note["network_type"] = type(net).__name__
        return note

    def add_link(self, name: str, target: str):
        """Link a sibling artifact (bench JSON, trace export, blackbox
        dump) into the run's record — an append-only note."""
        with self._lock:
            self._links[name] = target
            self._append_row({"kind": "note", "ts": round(time.time(), 3),
                              "links": {name: target}})
            if self._file is not None:
                self._file.flush()

    # -- live alert side effects ----------------------------------------------

    def _apply_transition(self, tr: dict):
        """One rule lifecycle transition: persist it, then the live
        surfaces — slo_alerts_total, health condition on the owning
        component, flight-recorder event, and a structured finding."""
        with self._lock:
            self._alerts.append(tr)
            self._append_row({"kind": "alert", **tr})
            if self._file is not None:
                self._file.flush()
        comp = tr["component"]
        firing = tr["to"] == "firing"
        if firing:
            _metrics.get_registry().counter(
                "slo_alerts_total",
                "SLO rule firings (analysis/slo via the run ledger)",
                ("rule", "severity")).labels(tr["rule"],
                                             tr["severity"]).inc()
            n = self._component_firing.get(comp, 0) + 1
            self._component_firing[comp] = n
            _health.get_health().set_condition(
                comp, _health.DEGRADED,
                reason=f"SLO rule {tr['rule']} firing: {tr['detail']}")
            try:
                from deeplearning4j_tpu.analysis.findings import Finding

                self.findings.append(Finding(
                    "SLO001", tr["severity"], f"rule:{tr['rule']}",
                    f"SLO rule firing (value {tr['value']}): "
                    f"{tr['detail']}",
                    "inspect the ledger around this timestamp "
                    f"(cli slo --ledger {self.path})"))
                del self.findings[:-64]  # bounded
            except Exception:
                logger.exception("SLO finding emission failed")
        else:
            n = max(0, self._component_firing.get(comp, 1) - 1)
            self._component_firing[comp] = n
            if n == 0:
                _health.get_health().set_condition(
                    comp, _health.OK,
                    reason=f"SLO rule {tr['rule']} resolved")
        _blackbox.get_recorder().record_event(
            "slo_alert", rule=tr["rule"], to=tr["to"],
            severity=tr["severity"], component=comp,
            value=tr["value"])
        logger.warning("SLO rule %r %s (value %s): %s", tr["rule"],
                       tr["to"], tr["value"], tr["detail"])

    # -- readout --------------------------------------------------------------

    def alert_status(self) -> dict:
        """The live /alerts payload: per-rule states + recent
        transitions."""
        with self._lock:
            recent = list(self._alerts)
        return {
            "run_id": self.run_id,
            "ledger": self.path,
            "rules": self.rules.status() if self.rules is not None else [],
            "firing": self.rules.firing() if self.rules is not None
            else [],
            "transitions": recent,
        }


# -- reading ledger artifacts (cli slo / runs / metrics --ledger) -------------

def read_ledger(path: str) -> dict:
    """Parse a ledger artifact into {manifest, rows}. Notes merge into
    the manifest (late enrichment is part of the run's identity); a torn
    final line (the process died mid-append) is dropped, not fatal."""
    manifest: dict = {}
    rows: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                logger.warning("ledger %s: dropping torn row", path)
                continue
            kind = row.get("kind")
            if kind == "manifest":
                manifest = row
            elif kind == "note":
                links = row.get("links")
                if links:
                    manifest.setdefault("links", {}).update(links)
                for k, v in row.items():
                    if k not in ("kind", "ts", "links"):
                        manifest[k] = v
            else:
                rows.append(row)
    return {"manifest": manifest, "rows": rows, "path": path}


def iter_samples(doc: dict) -> Iterator[Tuple[float, Dict[str, float]]]:
    """Reconstruct the absolute sample stream (ts, {series: value})
    from a parsed ledger: rollups seed the accumulator with their
    `last` values, delta sample rows update it."""
    acc: Dict[str, float] = {}
    for row in doc["rows"]:
        kind = row.get("kind")
        if kind == "rollup":
            for k, st in row.get("series", {}).items():
                acc[k] = st["last"]
        elif kind == "sample":
            acc.update(row.get("values", {}))
            yield float(row["ts"]), dict(acc)


def iter_alerts(doc: dict) -> Iterator[dict]:
    for row in doc["rows"]:
        if row.get("kind") == "alert":
            yield row


# -- cross-run summary & regression analysis ----------------------------------

def summarize_run(doc: dict) -> dict:
    """Per-series stats over a run — the vs_baseline idea generalized
    from bench one-shots to whole runs. Counters (and histogram
    count/sum facets) report their RATE over the run (delta/duration);
    gauges report mean/min/max/last over the samples."""
    first: Dict[str, float] = {}
    last: Dict[str, float] = {}
    agg: Dict[str, dict] = {}
    t0 = t1 = None
    n = 0
    for ts, values in iter_samples(doc):
        n += 1
        t0 = ts if t0 is None else t0
        t1 = ts
        for k, v in values.items():
            if ":bucket:" in k:
                continue
            if k not in first:
                first[k] = v
                agg[k] = {"min": v, "max": v, "sum": 0.0, "n": 0}
            a = agg[k]
            a["min"] = min(a["min"], v)
            a["max"] = max(a["max"], v)
            a["sum"] += v
            a["n"] += 1
            last[k] = v
    duration = max(1e-9, (t1 or 0.0) - (t0 or 0.0))
    series: Dict[str, dict] = {}
    for k, v_last in last.items():
        a = agg[k]
        counterish = (k.endswith(":count") or k.endswith(":sum")
                      or k.split("{")[0].endswith("_total"))
        entry = {
            "first": first[k], "last": v_last,
            "mean": round(a["sum"] / max(1, a["n"]), 9),
            "min": a["min"], "max": a["max"],
        }
        if counterish:
            entry["delta"] = round(v_last - first[k], 9)
            entry["rate_per_sec"] = round(entry["delta"] / duration, 9)
        series[k] = entry
    # derived histogram means (latency family headline): delta sum /
    # delta count per family+labels
    for k in list(series):
        if k.endswith(":count"):
            base = k[:-len(":count")]
            sk = base + ":sum"
            if sk in series:
                dc = series[k].get("delta", 0.0)
                dsum = series[sk].get("delta", 0.0)
                if dc and dc > 0:
                    series[base + ":mean"] = {
                        "mean": round(dsum / dc, 9),
                        "derived": True,
                    }
    return {
        "run_id": doc["manifest"].get("run_id"),
        "path": doc.get("path"),
        "samples": n,
        "duration_seconds": round(duration, 3),
        "series": series,
    }


def _family(key: str) -> str:
    base = key.split("{")[0]
    for sfx in (":count", ":sum", ":mean"):
        if key.endswith(sfx):
            return base + sfx
    return base


def compare_runs(reference: dict, candidate: dict,
                 threshold: float = 0.25,
                 min_magnitude: float = 1e-9) -> dict:
    """Per-metric regression deltas of `candidate` vs `reference` (two
    summarize_run outputs): for counter-ish series the RATE ratio, for
    gauges (and derived histogram means) the MEAN ratio. A series is
    flagged when |ratio - 1| > threshold — direction-agnostic (the
    ledger cannot know which way is "worse" for every series; the
    verdict names the family, the operator knows the sign). Only
    series present in BOTH runs compare; `only_in_*` lists the rest."""
    ref_s, cand_s = reference["series"], candidate["series"]
    rows: List[dict] = []
    flagged: List[dict] = []
    for k in sorted(set(ref_s) & set(cand_s)):
        r, c = ref_s[k], cand_s[k]
        if "rate_per_sec" in r and "rate_per_sec" in c:
            rv, cv, basis = r["rate_per_sec"], c["rate_per_sec"], "rate"
        else:
            rv, cv, basis = r["mean"], c["mean"], "mean"
        if abs(rv) < min_magnitude and abs(cv) < min_magnitude:
            continue
        ratio = None if abs(rv) < min_magnitude else round(cv / rv, 4)
        row = {"series": k, "family": _family(k), "basis": basis,
               "reference": rv, "candidate": cv, "ratio": ratio}
        rows.append(row)
        if ratio is None or abs(ratio - 1.0) > threshold:
            flagged.append(row)
    flagged.sort(key=lambda r: -abs((r["ratio"] or 1e9) - 1.0))
    families = sorted({r["family"] for r in flagged})
    return {
        "reference": {"run_id": reference.get("run_id"),
                      "path": reference.get("path"),
                      "duration_seconds":
                          reference.get("duration_seconds")},
        "candidate": {"run_id": candidate.get("run_id"),
                      "path": candidate.get("path"),
                      "duration_seconds":
                          candidate.get("duration_seconds")},
        "threshold": threshold,
        "series": rows,
        "regressions": flagged,
        "regression_families": families,
        "ok": not flagged,
    }


def list_ledgers(directory: str) -> List[dict]:
    """Manifest summaries of every ledger artifact in a directory —
    `cli runs`. A file that does not parse as a ledger is skipped."""
    out = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not name.endswith((".jsonl", ".ledger")):
            continue
        path = os.path.join(directory, name)
        try:
            with open(path) as f:
                head = json.loads(f.readline())
        except (OSError, json.JSONDecodeError, ValueError):
            continue
        if head.get("kind") != "manifest":
            continue
        out.append({
            "path": path,
            "run_id": head.get("run_id"),
            "ts": head.get("ts"),
            "devices": head.get("devices"),
            "rules": len(head.get("rules") or []),
            "links": head.get("links") or {},
        })
    return out
