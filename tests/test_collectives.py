"""Cheap collectives (PR 16): bucketed in-graph gradient all-reduce,
opt-in bf16 wire payload, and the measured-collective probe.

The contract under test: the BUCKETED f32 reduce is bit-identical to
the monolithic tail-end all-reduce (same sum, different schedule), the
bf16 wire halves the all-reduce books while staying inside a pinned
trajectory tolerance and NEVER becoming the default, and mid-epoch
resume round-trips through a bucketed mesh. Runs on the virtual
8-device CPU mesh (tests/conftest.py); the smoke-named test also runs
in scripts/t1.sh's forced 2-device interpreter, which additionally
pins DL4J_GRAD_BUCKET_BYTES=512 so even ~1 KB smoke grads split into
multiple buckets.
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import data_parallel_mesh
from deeplearning4j_tpu.train.listeners import IterationListener
from deeplearning4j_tpu.utils.metrics import get_registry

needs_8 = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs the 8-device virtual platform (t1's 2-device smoke "
           "interpreter runs only the smoke-named tests)")


def _mlp_conf(seed=7):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Updater.NESTEROVS)
        .learning_rate(0.05)
        .momentum(0.9)
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_in=12, n_out=16, activation="tanh"))
        .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                           loss="mcxent"))
        .build()
    )


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 12)).astype(np.float32)
    y = np.zeros((n, 4), np.float32)
    y[np.arange(n), rng.integers(0, 4, n)] = 1.0
    return x, y


class _ScoreTap(IterationListener):
    def __init__(self):
        self.scores = []

    def iteration_done(self, model, iteration, info):
        self.scores.append(float(np.asarray(info["score"]())))


def _sub_mesh(n):
    devs = jax.devices()
    assert len(devs) >= n, f"need {n} devices, have {len(devs)}"
    return data_parallel_mesh(devs[:n])


def _fit_sharded(n_dev, *, bucket_bytes, grad_dtype=None, fused_steps=1,
                 seed=7):
    net = MultiLayerNetwork(_mlp_conf(seed)).init().set_mesh(
        _sub_mesh(n_dev), bucket_bytes=bucket_bytes, grad_dtype=grad_dtype)
    if fused_steps > 1:
        net.set_fused_steps(fused_steps)
    tap = _ScoreTap()
    net.set_listeners(tap)
    x, y = _data(64, seed=3)
    net.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)
    return net, tap


def _assert_params_equal(a, b, **tol):
    for p1, p2 in zip(a.params_list, b.params_list):
        for k in p1:
            if tol:
                np.testing.assert_allclose(
                    np.asarray(p1[k]), np.asarray(p2[k]), **tol)
            else:
                np.testing.assert_array_equal(
                    np.asarray(p1[k]), np.asarray(p2[k]))


# -- smoke (also run standalone by scripts/t1.sh at 2 devices) ----------------


def test_smoke_bucketed_reduce_matches_monolithic():
    """The bucketed f32 schedule is a re-bracketing of the same sum:
    per-step scores and final params must be BIT-identical to the
    monolithic all-reduce, at whatever device count the platform has."""
    n_dev = min(len(jax.devices()), 8)
    if n_dev < 2:
        pytest.skip("needs >=2 devices")
    mono, mono_tap = _fit_sharded(n_dev, bucket_bytes=0)
    buck, buck_tap = _fit_sharded(n_dev, bucket_bytes=512)
    assert mono_tap.scores == buck_tap.scores
    _assert_params_equal(mono, buck)
    # the bucketed plan actually split: >1 bucket at 512B on ~1 KB grads
    desc = buck._mesh_plan.collective_describe(buck)
    assert desc["mode"] == "bucketed" and desc["n_buckets"] > 1


# -- 8-device suite -----------------------------------------------------------


@needs_8
def test_bucketed_bit_identical_across_bucket_sizes():
    """Bucket size is a SCHEDULE knob, never a numerics knob: 0 (mono),
    tiny (many buckets), and the 4 MiB default (one bucket here) all
    land on identical trajectories."""
    runs = [_fit_sharded(8, bucket_bytes=bb) for bb in (0, 512, None)]
    (ref, ref_tap), rest = runs[0], runs[1:]
    for net, tap in rest:
        assert tap.scores == ref_tap.scores
        _assert_params_equal(ref, net)


@needs_8
def test_bucketed_fused_dispatch_bit_identical():
    """set_fused_steps composes with the bucketed schedule: K stacked
    steps with per-bucket reduces still match the monolithic fused
    run bit for bit."""
    mono, _ = _fit_sharded(8, bucket_bytes=0, fused_steps=2)
    buck, _ = _fit_sharded(8, bucket_bytes=512, fused_steps=2)
    _assert_params_equal(mono, buck)


@needs_8
def test_bucketed_tbptt_bit_identical():
    """The truncated-BPTT step (3-D grads, recurrent state threading)
    reduces through the same bucket path: bucketed == monolithic."""
    from deeplearning4j_tpu.models.charlstm import char_lstm_conf

    vocab, seq = 11, 8
    rng = np.random.default_rng(5)
    idx = rng.integers(0, vocab, (16, seq))
    x = np.eye(vocab, dtype=np.float32)[idx]
    yidx = rng.integers(0, vocab, (16, seq))
    y = np.eye(vocab, dtype=np.float32)[yidx]

    def run(bb):
        conf = char_lstm_conf(vocab_size=vocab, hidden=8, tbptt_length=4)
        net = MultiLayerNetwork(conf).init().set_mesh(
            _sub_mesh(8), bucket_bytes=bb)
        net.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)
        return net

    _assert_params_equal(run(0), run(256))


@needs_8
def test_bf16_wire_halves_books_and_stays_in_tolerance():
    """grad_dtype="bf16" halves the all-reduce wire bytes (the books
    the bench artifact commits) while the trajectory stays inside a
    pinned tolerance of the f32 run — and the knob is OPT-IN: a plain
    set_mesh stays f32."""
    reg = get_registry()
    ar = reg.counter(
        "allreduce_bytes_total",
        "gradient bytes all-reduced in-graph by the sharded "
        "train step (logical payload: summed gradient leaf "
        "bytes per optimizer step)").labels()

    a0 = ar.value
    f32, f32_tap = _fit_sharded(8, bucket_bytes=None)
    f32_bytes = ar.value - a0
    a0 = ar.value
    bf16, bf16_tap = _fit_sharded(8, bucket_bytes=None, grad_dtype="bf16")
    bf16_bytes = ar.value - a0

    assert f32_bytes > 0 and bf16_bytes * 2 == f32_bytes
    assert f32._mesh_plan.collective_describe(f32)["grad_dtype"] == "f32"
    assert bf16._mesh_plan.collective_describe(bf16)["grad_dtype"] == "bf16"
    # pinned trajectory tolerance: bf16 rounds the WIRE payload only
    # (f32 accumulate), so after 8 tiny-lr steps the drift stays small
    np.testing.assert_allclose(bf16_tap.scores, f32_tap.scores,
                               rtol=5e-2, atol=5e-3)
    _assert_params_equal(f32, bf16, rtol=5e-2, atol=5e-3)


@needs_8
def test_resume_from_through_bucketed_mesh(tmp_path):
    """Mid-epoch resume_from round-trips through a bucketed mesh: crash
    after k bucketed sharded steps, resume into a fresh bucketed net,
    land on the uninterrupted run's trajectory."""
    from deeplearning4j_tpu.train.checkpoint import CheckpointListener

    x, y = _data(64, seed=11)
    ckpt = str(tmp_path / "ckpt")

    def mk():
        return MultiLayerNetwork(_mlp_conf()).init().set_mesh(
            _sub_mesh(8), bucket_bytes=512)

    ref = mk()
    ref_tap = _ScoreTap()
    ref.set_listeners(ref_tap)
    ref.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)

    class _CrashAfter(IterationListener):
        def __init__(self, n):
            self.n = n

        def iteration_done(self, model, iteration, info):
            self.n -= 1
            if self.n == 0:
                raise RuntimeError("simulated preemption")

    crashed = mk()
    crashed.set_listeners(
        CheckpointListener(ckpt, every_n_iterations=1, every_n_epochs=None,
                           keep_last=2),
        _CrashAfter(5))
    with pytest.raises(RuntimeError, match="simulated preemption"):
        crashed.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)

    resumed = mk()
    tap = _ScoreTap()
    resumed.set_listeners(tap)
    resumed.fit(x, y, batch_size=16, epochs=2, async_prefetch=False,
                resume_from=ckpt)
    assert resumed.iteration == ref.iteration == 8
    np.testing.assert_allclose(tap.scores, ref_tap.scores[-len(tap.scores):],
                               rtol=2e-5, atol=2e-6)
    _assert_params_equal(ref, resumed, rtol=2e-5, atol=2e-6)


@needs_8
def test_measured_collective_counter_moves_when_sampled():
    """train_step_collective_seconds{source="measured"} — the estimate's
    falsifier — accumulates when devprof sampling is on, and stays put
    under tier-1's sample_every=0 (the default this suite runs with)."""
    from deeplearning4j_tpu.utils import devprof

    reg = get_registry()
    measured = reg.counter(
        "train_step_collective_seconds",
        "time attributed to the train step's gradient all-reduce, "
        "by accounting source", ("source",)).labels("measured")
    estimate = reg.counter(
        "train_step_collective_seconds",
        "time attributed to the train step's gradient all-reduce, "
        "by accounting source", ("source",)).labels("estimate")

    m0, e0 = measured.value, estimate.value
    _fit_sharded(8, bucket_bytes=512)
    assert estimate.value > e0  # the ring model always accrues
    assert measured.value == m0  # sampling off -> no blocking probe

    prev = devprof.get_profiler().sample_every
    devprof.configure(1)
    try:
        m0 = measured.value
        _fit_sharded(8, bucket_bytes=512)
        assert measured.value > m0
    finally:
        devprof.configure(prev)


@needs_8
def test_set_mesh_rejects_knobs_with_explicit_plan():
    """bucket_bytes/grad_dtype are plan-construction knobs: passing them
    alongside a prebuilt plan= would silently ignore one of the two —
    refuse instead."""
    from deeplearning4j_tpu.parallel.sharded import MeshPlan

    plan = MeshPlan(_sub_mesh(2))
    net = MultiLayerNetwork(_mlp_conf()).init()
    with pytest.raises(ValueError):
        net.set_mesh(plan=plan, bucket_bytes=512)
