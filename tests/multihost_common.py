"""Shared model/data builders for the multi-host equivalence test — the
single-process baseline and each worker process must construct bit-identical
nets and data."""

import numpy as np


def build_net():
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(42)
            .updater("nesterovs").learning_rate(0.05).momentum(0.9)
            .weight_init("xavier").list()
            .layer(DenseLayer(n_out=10, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def global_data(n=32, seed=9):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 6)).astype(np.float32)
    y = np.zeros((n, 3), np.float32)
    y[np.arange(n), rng.integers(0, 3, n)] = 1.0
    return x, y
