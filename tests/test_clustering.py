"""Clustering stack: k-means, VPTree, KDTree, t-SNE, k-NN server.

Mirrors the reference's test approach (deeplearning4j-core clustering
tests): correctness vs brute force on random data, convergence on
separable blobs.
"""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import KDTree, KMeansClustering, Tsne, VPTree
from deeplearning4j_tpu.clustering.distances import brute_force_knn
from deeplearning4j_tpu.serving.knnserver import NearestNeighborsServer


def _blobs(n_per=60, centers=((0, 0, 0), (8, 8, 8), (-8, 8, -8)), seed=0):
    rng = np.random.default_rng(seed)
    xs, ys = [], []
    for ci, c in enumerate(centers):
        xs.append(rng.normal(loc=c, scale=1.0, size=(n_per, len(c))))
        ys.append(np.full(n_per, ci))
    return np.concatenate(xs).astype(np.float32), np.concatenate(ys)


# -- k-means -----------------------------------------------------------------

def test_kmeans_recovers_blobs():
    x, y = _blobs()
    cs = KMeansClustering.setup(3, 50, "euclidean", seed=3).apply_to(x)
    # each true blob maps to exactly one cluster
    mapping = {}
    for ci in range(3):
        assigned = cs.assignments[y == ci]
        top = np.bincount(assigned, minlength=3).argmax()
        assert np.mean(assigned == top) > 0.95
        mapping[ci] = top
    assert len(set(mapping.values())) == 3
    assert cs.iterations <= 50
    assert len(cs.clusters) == 3
    assert sum(c.count for c in cs.clusters) == x.shape[0]


def test_kmeans_cosine_spherical():
    """cosinesimilarity k-means clusters by direction, not magnitude."""
    rng = np.random.default_rng(7)
    dirs = np.array([[1.0, 0.0], [0.0, 1.0], [-1.0, 0.0]])
    x = np.concatenate([
        d[None, :] * rng.uniform(0.5, 5.0, (40, 1))
        + 0.02 * rng.standard_normal((40, 2))
        for d in dirs
    ]).astype(np.float32)
    cs = KMeansClustering.setup(
        3, 50, "cosinesimilarity", seed=1).apply_to(x)
    labels = np.repeat(np.arange(3), 40)
    for ci in range(3):
        assigned = cs.assignments[labels == ci]
        assert np.mean(assigned == np.bincount(
            assigned, minlength=3).argmax()) > 0.95
    # centers are unit-normalized (spherical k-means)
    np.testing.assert_allclose(
        np.linalg.norm(cs.centers, axis=1), 1.0, atol=1e-5)
    with pytest.raises(ValueError):
        KMeansClustering(3, distance_function="dot")


def test_vptree_invert_flips_ranking():
    rng = np.random.default_rng(8)
    pts = rng.standard_normal((200, 4)).astype(np.float32)
    near = VPTree(pts, "euclidean").search(pts[0], 200)[0]
    far = VPTree(pts, "euclidean", invert=True).search(pts[0], 200)[0]
    assert near[0] == 0 and far[-1] == 0
    assert list(near) == list(far[::-1])


def test_kmeans_nearest_cluster_and_validation():
    x, _ = _blobs(n_per=20)
    cs = KMeansClustering.setup(3, 30).apply_to(x)
    c = cs.nearest_cluster(x[0])
    assert c == cs.assignments[0]
    with pytest.raises(ValueError):
        KMeansClustering.setup(99, 5).apply_to(x[:10])
    with pytest.raises(ValueError):
        KMeansClustering(3, distance_function="nope")


# -- trees vs brute force ----------------------------------------------------

@pytest.mark.parametrize("distance", ["euclidean", "manhattan",
                                      "cosinesimilarity", "dot"])
def test_vptree_matches_brute_force(distance):
    rng = np.random.default_rng(1)
    # above the brute_force_threshold so the tree path is exercised
    pts = rng.standard_normal((3000, 16)).astype(np.float32)
    tree = VPTree(pts, distance, brute_force_threshold=100)
    for qi in (0, 57, 2999):
        idx, dist = tree.search(pts[qi], 10)
        bidx, bdist = brute_force_knn(pts, pts[qi][None, :], 10, distance)
        # atol covers the f32 cancellation in the matmul distance form
        # (||x||^2 + ||y||^2 - 2xy): sqrt of ~eps*||x||^2 is ~1e-3
        np.testing.assert_allclose(
            np.sort(dist), np.sort(bdist[0]), rtol=2e-4, atol=5e-3)
        if distance != "dot":  # under dot, self is not necessarily top-1
            assert idx[0] == qi  # the point itself is its own 1-NN


def test_vptree_brute_path_small_set():
    rng = np.random.default_rng(2)
    pts = rng.standard_normal((100, 8)).astype(np.float32)
    tree = VPTree(pts, "euclidean")  # below threshold -> flat device path
    assert tree.brute
    idx, dist = tree.search(pts[5], 4)
    bidx, _ = brute_force_knn(pts, pts[5][None, :], 4, "euclidean")
    assert set(idx.tolist()) == set(bidx[0].tolist())


def test_kdtree_matches_brute_force():
    rng = np.random.default_rng(3)
    pts = rng.standard_normal((2000, 3)).astype(np.float32)
    tree = KDTree(pts)
    for qi in (1, 500, 1999):
        idx, dist = tree.knn(pts[qi], 8)
        bidx, bdist = brute_force_knn(pts, pts[qi][None, :], 8, "euclidean")
        np.testing.assert_allclose(np.sort(dist), np.sort(bdist[0]),
                                   rtol=1e-4, atol=1e-5)


# -- t-SNE -------------------------------------------------------------------

def test_tsne_separates_blobs():
    x, y = _blobs(n_per=40)
    ts = Tsne(perplexity=15, n_iter=500, stop_lying_iteration=100,
              momentum_switch_iteration=100, seed=4)
    emb = ts.fit_transform(x)
    assert emb.shape == (x.shape[0], 2)
    assert np.isfinite(emb).all()
    assert np.isfinite(ts.kl_)
    # blob centroids in embedding space separate from their spreads
    cents = np.stack([emb[y == c].mean(axis=0) for c in range(3)])
    spread = max(float(emb[y == c].std()) for c in range(3))
    min_sep = min(
        float(np.linalg.norm(cents[i] - cents[j]))
        for i in range(3) for j in range(i + 1, 3))
    assert min_sep > 2.0 * spread


# -- k-NN server -------------------------------------------------------------

def _post(port, route, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{route}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_knn_server_round_trip():
    rng = np.random.default_rng(5)
    pts = rng.standard_normal((300, 8)).astype(np.float32)
    server = NearestNeighborsServer(pts, port=0)
    port = server.start()
    try:
        out = _post(port, "/knn", {"k": 5, "inputIndex": 17})
        got = [r["index"] for r in out["results"]]
        bidx, _ = brute_force_knn(pts, pts[17][None, :], 5, "euclidean")
        assert set(got) == set(bidx[0].tolist())
        assert got[0] == 17

        out = _post(port, "/knnvector",
                    {"k": 3, "vector": pts[42].tolist()})
        assert out["results"][0]["index"] == 42

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health") as r:
            health = json.loads(r.read())
        assert health["points"] == 300
    finally:
        server.stop()
