"""Fused Pallas LSTM kernel vs the scan path (ops/pallas_lstm.py).

Runs the kernel in interpreter mode on the CPU test backend (the real
lowering is exercised on TPU by bench.py); correctness = forward AND
gradient equality against the lax.scan reference implementation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import pallas_lstm
from deeplearning4j_tpu.ops.helpers import (
    get_helper,
    helper_names,
    set_helper_enabled,
)


@pytest.fixture(autouse=True)
def _interpret_mode():
    old = pallas_lstm._INTERPRET
    pallas_lstm._INTERPRET = True
    yield
    pallas_lstm._INTERPRET = old


def _scan_reference(xg_t, rw, pI, pF, pO, h0, c0):
    def step(carry, g_in):
        h, c = carry
        g = g_in + h @ rw
        H = h.shape[-1]
        i = jax.nn.sigmoid(g[:, :H] + c * pI)
        f = jax.nn.sigmoid(g[:, H:2 * H] + c * pF)
        gg = jnp.tanh(g[:, 2 * H:3 * H])
        c_new = f * c + i * gg
        o = jax.nn.sigmoid(g[:, 3 * H:] + c_new * pO)
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    (hF, cF), ys = jax.lax.scan(step, (h0, c0), xg_t)
    return ys, hF, cF


@pytest.mark.parametrize("with_peepholes", [False, True])
def test_kernel_matches_scan_forward_and_grad(with_peepholes):
    rng = np.random.default_rng(0)
    T, B, H = 5, 8, 16
    xg = jnp.asarray(rng.standard_normal((T, B, 4 * H)), jnp.float32)
    rw = jnp.asarray(rng.standard_normal((H, 4 * H)) * 0.2, jnp.float32)
    if with_peepholes:
        pI, pF, pO = (jnp.asarray(rng.standard_normal(H) * 0.3, jnp.float32)
                      for _ in range(3))
    else:
        pI = pF = pO = jnp.zeros((H,), jnp.float32)
    h0 = jnp.asarray(rng.standard_normal((B, H)) * 0.1, jnp.float32)
    c0 = jnp.asarray(rng.standard_normal((B, H)) * 0.1, jnp.float32)

    y1, hF1, cF1 = pallas_lstm.lstm_sequence(xg, rw, pI, pF, pO, h0, c0)
    y2, hF2, cF2 = _scan_reference(xg, rw, pI, pF, pO, h0, c0)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(cF1), np.asarray(cF2),
                               rtol=1e-5, atol=1e-5)

    def loss_k(*a):
        y, hF, cF = pallas_lstm.lstm_sequence(*a)
        return (jnp.sum(y * y) + jnp.sum(jnp.sin(hF))
                + jnp.sum(jnp.cos(cF)))

    def loss_s(*a):
        y, hF, cF = _scan_reference(*a)
        return (jnp.sum(y * y) + jnp.sum(jnp.sin(hF))
                + jnp.sum(jnp.cos(cF)))

    args = (xg, rw, pI, pF, pO, h0, c0)
    g1 = jax.grad(loss_k, argnums=tuple(range(7)))(*args)
    g2 = jax.grad(loss_s, argnums=tuple(range(7)))(*args)
    names = ("dxg", "drw", "dpI", "dpF", "dpO", "dh0", "dc0")
    for a, b, name in zip(g1, g2, names):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
            err_msg=f"gradient mismatch in {name}")


def test_helper_registered_and_probed():
    assert helper_names().get("lstm_sequence") == "pallas_fused_lstm"
    # supported in interpret mode with the standard config
    assert get_helper("lstm_sequence", peephole=False, mask=None,
                      gate_act="sigmoid", cell_act="tanh",
                      reverse=False) is not None
    # peepholes ARE supported (GravesLSTM, the char-rnn baseline model)
    assert get_helper("lstm_sequence", peephole=True, mask=None,
                      gate_act="sigmoid", cell_act="tanh",
                      reverse=False) is not None
    # fallback cases
    for ctx in (dict(mask=np.ones((2, 3))),
                dict(gate_act="hardsigmoid"), dict(cell_act="relu"),
                dict(reverse=True)):
        base = dict(peephole=False, mask=None, gate_act="sigmoid",
                    cell_act="tanh", reverse=False)
        base.update(ctx)
        assert get_helper("lstm_sequence", **base) is None, ctx
    # kill switch
    set_helper_enabled("lstm_sequence", False)
    try:
        assert get_helper("lstm_sequence", peephole=False, mask=None,
                          gate_act="sigmoid", cell_act="tanh",
                          reverse=False) is None
    finally:
        set_helper_enabled("lstm_sequence", True)


def test_network_lstm_uses_helper_and_matches_scan():
    """End to end: an LSTM net trained one step with the helper enabled
    equals the scan path (kernel swapped in via the SPI, not by calling
    it directly)."""
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def build():
        conf = (NeuralNetConfiguration.builder().seed(4)
                .weight_init("xavier").learning_rate(0.1).list()
                .layer(LSTM(n_out=12, activation="tanh"))
                .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(6)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(1)
    x = rng.standard_normal((4, 7, 6)).astype(np.float32)
    y = np.zeros((4, 7, 3), np.float32)
    y[..., 0] = 1.0

    net_helper = build()
    net_helper.fit(x, y, batch_size=4, epochs=1, async_prefetch=False)
    out_helper = np.asarray(net_helper.output(x))

    set_helper_enabled("lstm_sequence", False)
    try:
        net_scan = build()
        net_scan.fit(x, y, batch_size=4, epochs=1, async_prefetch=False)
        out_scan = np.asarray(net_scan.output(x))
    finally:
        set_helper_enabled("lstm_sequence", True)

    np.testing.assert_allclose(out_helper, out_scan, rtol=2e-4, atol=2e-5)
    for p1, p2 in zip(net_helper.params_list, net_scan.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=2e-4, atol=2e-5,
                err_msg=f"param {k}")


def test_network_tbptt_uses_helper_and_matches_scan():
    """TBPTT segment training with a GravesLSTM (peepholes — the char-rnn
    bench model) through the fused kernel equals the scan path — state
    carry (h0/c0 in, hF/cF out) crosses the kernel boundary correctly."""
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def build():
        conf = (NeuralNetConfiguration.builder().seed(9)
                .weight_init("xavier").learning_rate(0.1)
                .list()
                .backprop_type("tbptt")
                .t_bptt_lengths(8)  # 2 segments over T=16
                .layer(GravesLSTM(n_out=10, activation="tanh"))
                .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(5)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(2)
    x = rng.standard_normal((4, 16, 5)).astype(np.float32)
    y = np.zeros((4, 16, 3), np.float32)
    y[..., 1] = 1.0

    net_h = build()
    net_h.fit(x, y, batch_size=4, epochs=1, async_prefetch=False)
    assert net_h.iteration == 2  # 2 TBPTT segments = 2 optimizer steps

    set_helper_enabled("lstm_sequence", False)
    try:
        net_s = build()
        net_s.fit(x, y, batch_size=4, epochs=1, async_prefetch=False)
    finally:
        set_helper_enabled("lstm_sequence", True)
    for p1, p2 in zip(net_h.params_list, net_s.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=2e-4, atol=2e-5,
                err_msg=f"TBPTT param {k}")


@pytest.mark.parametrize("with_peepholes", [False, True])
def test_step_kernel_matches_scan_single_step(with_peepholes):
    """The inference-only decode step kernel (lstm_step — no VJP
    stashes) computes exactly one scan step."""
    rng = np.random.default_rng(3)
    B, H = 8, 16
    xg = jnp.asarray(rng.standard_normal((B, 4 * H)), jnp.float32)
    rw = jnp.asarray(rng.standard_normal((H, 4 * H)) * 0.2, jnp.float32)
    if with_peepholes:
        pI, pF, pO = (jnp.asarray(rng.standard_normal(H) * 0.3, jnp.float32)
                      for _ in range(3))
    else:
        pI = pF = pO = jnp.zeros((H,), jnp.float32)
    h0 = jnp.asarray(rng.standard_normal((B, H)) * 0.1, jnp.float32)
    c0 = jnp.asarray(rng.standard_normal((B, H)) * 0.1, jnp.float32)
    h1, c1 = pallas_lstm.lstm_step(xg, rw, pI, pF, pO, h0, c0)
    ys, hF, cF = _scan_reference(xg[None], rw, pI, pF, pO, h0, c0)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(hF), atol=1e-6)
    np.testing.assert_allclose(np.asarray(c1), np.asarray(cF), atol=1e-6)


def test_decode_fast_path_matches_builtin_scan():
    """The layer-level wiring: a stateful single-timestep GravesLSTM
    forward (the decode engine's / rnn_time_step's shape) routed through
    the lstm_decode_step helper equals the built-in scan path. Two
    fresh same-seed nets so each traces its own jit cache with the
    helper in a different state."""
    from deeplearning4j_tpu.models.charlstm import char_lstm_network
    from deeplearning4j_tpu.ops.helpers import set_helper_enabled

    vocab = 9
    x = np.zeros((2, vocab), np.float32)
    x[0, 3] = 1.0
    x[1, 5] = 1.0
    net_on = char_lstm_network(vocab_size=vocab, hidden=16, layers=1,
                               tbptt_length=8)
    net_off = char_lstm_network(vocab_size=vocab, hidden=16, layers=1,
                                tbptt_length=8)
    set_helper_enabled("lstm_decode_step", True)
    out_on = np.asarray(net_on.rnn_time_step(x))
    out_on2 = np.asarray(net_on.rnn_time_step(x))  # carried state step
    set_helper_enabled("lstm_decode_step", False)
    try:
        out_off = np.asarray(net_off.rnn_time_step(x))
        out_off2 = np.asarray(net_off.rnn_time_step(x))
    finally:
        set_helper_enabled("lstm_decode_step", True)
    np.testing.assert_allclose(out_on, out_off, atol=1e-6)
    np.testing.assert_allclose(out_on2, out_off2, atol=1e-6)
