"""Static analysis: model doctor, jaxpr auditor, concurrency lint.

Three passes over three artifact kinds, unified behind structured
findings (analysis/findings.py) and surfaced as `cli doctor` /
`cli lint`:

1. shapeflow  — symbolic InputType propagation over nn/conf
   configurations (no params, no tracing): nIn/nOut wiring, missing
   preprocessors, merge conflicts, dead vertices. SF*** codes.
2. jaxpr_audit — one abstract trace of the train-step loss, walked for
   TPU hazards: f64, widening casts, folded constants, host callbacks,
   dead weights, non-donated buffers. JX*** codes.
3. lint — AST checks over the repo's own source for the concurrency
   conventions (bare except, timeout-less queue ops, unnamed/non-daemon
   threads, lock-order cycles, stray print). CC*** codes, gated in
   scripts/lint.sh against scripts/lint_baseline.txt.

The DL4J lineage: the reference's config DSL validated nIn/nOut wiring
before any compute ran (InputTypeUtil; MIGRATION.md "config
validation") — this package is that idea extended to the jaxpr program
and to the codebase itself.
"""

from __future__ import annotations

import logging
from typing import List

from deeplearning4j_tpu.analysis.findings import (  # noqa: F401
    ERROR,
    INFO,
    WARNING,
    Finding,
    error_names,
    format_findings,
    has_errors,
    sort_findings,
    summarize,
    to_json,
)

logger = logging.getLogger("deeplearning4j_tpu")


def doctor_network(net, *, batch_size: int = 2, timesteps: int = 8,
                   jaxpr: bool = True) -> List[Finding]:
    """The model doctor: shapeflow over the net's configuration, then —
    when the config is sound — one abstract trace of the train-step loss
    audited for TPU hazards. Returns findings; raises nothing on a bad
    model (that is the point)."""
    from deeplearning4j_tpu.analysis import jaxpr_audit, shapeflow

    findings = shapeflow.check_configuration(net.conf)
    if jaxpr and not has_errors(findings):
        # a config with ERRORs would abstract-trace into the same wreck
        # it describes; report the config layer first. The trace can
        # still fail on warning-grade configs (e.g. SF007 no loss head
        # -> _loss raises) — that failure becomes a finding, never a
        # doctor crash
        try:
            findings = findings + jaxpr_audit.audit_network(
                net, batch_size=batch_size, timesteps=timesteps)
        except Exception as e:
            findings = findings + [Finding(
                "JX000", WARNING, "jaxpr:train_loss",
                f"could not abstract-trace the train-step loss: "
                f"{type(e).__name__}: {e}",
                "resolve the config findings above (a missing loss head "
                "or broken wiring usually explains this)")]
    return findings


def doctor_errors(conf) -> List[Finding]:
    """ERROR-severity shapeflow findings for a configuration — the cheap
    gate bench.py consults before headlining a workload."""
    from deeplearning4j_tpu.analysis import shapeflow

    return [f for f in shapeflow.check_configuration(conf)
            if f.severity == ERROR]


def preflight_report(conf, origin: str = "") -> List[Finding]:
    """Free pre-flight check on an imported model configuration
    (keras/dl4j import paths): run shapeflow, log what it finds, return
    the findings. Never raises — an analysis bug must not sink an
    import that would otherwise succeed."""
    from deeplearning4j_tpu.analysis import shapeflow

    try:
        findings = shapeflow.check_configuration(conf)
    except Exception as e:
        logger.debug("import preflight skipped (%s): %s", origin, e)
        return []
    src = f" [{origin}]" if origin else ""
    for f in sort_findings(findings):
        level = logging.WARNING if f.severity == ERROR else (
            logging.INFO if f.severity == WARNING else logging.DEBUG)
        logger.log(level, "import preflight%s: %s", src, f.format())
    return findings
