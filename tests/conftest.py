"""Test harness configuration.

Mirrors the reference's test-backend strategy (SURVEY.md §4): tests run on
the CPU backend with a virtual 8-device mesh so data-parallel equivalence
tests (n-device == 1-device) run without TPU hardware — the analog of the
reference's local[N] Spark contexts and thread-based ParallelWrapper tests.

Must set env vars before jax is imported anywhere.
"""

import os
import sys

# Note: this image's axon sitecustomize imports jax at interpreter start, so
# env vars set here are read too late; the config updates below are what
# actually select the CPU backend (backends initialize lazily). XLA_FLAGS is
# still read at first backend init, so setting it here works.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Numeric parity tests assume true-f32 matmuls/convs (the TPU bench path
# deliberately runs bf16 — that is a PrecisionPolicy choice, not a default).
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests (multi-process, large fits)")


def pytest_sessionfinish(session, exitstatus):
    # Opt-in observability artifact (scripts/t1.sh T1_METRICS_DUMP=1):
    # dump the process-global metrics registry after the run so compile
    # counts / helper events can be diffed across PRs.
    if not os.environ.get("T1_METRICS_DUMP"):
        return
    import json

    from deeplearning4j_tpu.utils.metrics import get_registry

    path = os.environ.get("T1_METRICS_ARTIFACT", "/tmp/_t1_metrics.json")
    try:
        with open(path, "w") as f:
            json.dump(get_registry().snapshot(), f, indent=2, sort_keys=True)
    except Exception as e:  # an artifact failure must not fail the suite
        print(f"[conftest] metrics dump failed: {e}", file=sys.stderr)


@pytest.fixture
def rng_key():
    import jax

    return jax.random.PRNGKey(12345)
