"""RNN network tests: sequence classification, TBPTT, rnn_time_step
(reference: MultiLayerTestRNN, TestVariableLengthTS)."""

import numpy as np

from deeplearning4j_tpu.data import DataSet
from deeplearning4j_tpu.nn.conf import (
    GravesLSTM,
    InputType,
    LSTM,
    NeuralNetConfiguration,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.conf.network import BackpropType
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def seq_data(n=64, t=12, seed=0):
    """Predict sign of running sum of inputs (time-distributed 2-class)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, t, 1)).astype(np.float32)
    cs = np.cumsum(x[..., 0], axis=1)
    y = np.zeros((n, t, 2), np.float32)
    y[..., 0] = (cs <= 0).astype(np.float32)
    y[..., 1] = (cs > 0).astype(np.float32)
    return x, y


def rnn_conf(cell=LSTM, tbptt=False):
    lb = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .updater("adam")
        .learning_rate(0.02)
        .list()
        .layer(cell(n_out=16, activation="tanh"))
        .layer(RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(1))
    )
    if tbptt:
        lb = lb.backprop_type(BackpropType.TRUNCATED_BPTT).t_bptt_lengths(4)
    return lb.build()


def test_lstm_sequence_classification_learns():
    x, y = seq_data()
    net = MultiLayerNetwork(rnn_conf()).init()
    s0 = net.score(x, y)
    net.fit(x, y, epochs=20, batch_size=32, async_prefetch=False)
    s1 = net.score(x, y)
    assert s1 < s0 * 0.8
    ev = net.evaluate(x, y)
    assert ev.accuracy() > 0.7


def test_graves_lstm_learns():
    x, y = seq_data(48, 8)
    net = MultiLayerNetwork(rnn_conf(cell=GravesLSTM)).init()
    s0 = net.score(x, y)
    net.fit(x, y, epochs=10, batch_size=48, async_prefetch=False)
    assert net.score(x, y) < s0


def test_tbptt_training_runs_and_learns():
    x, y = seq_data(32, 16)
    net = MultiLayerNetwork(rnn_conf(tbptt=True)).init()
    s0 = net.score(x, y)
    net.fit(x, y, epochs=10, batch_size=32, async_prefetch=False)
    assert net.score(x, y) < s0
    # 16 timesteps / tbptt 4 = 4 optimizer steps per batch
    assert net.iteration == 10 * 4


def test_rnn_time_step_matches_full_forward():
    x, y = seq_data(4, 6)
    net = MultiLayerNetwork(rnn_conf()).init()
    full = np.asarray(net.output(x))
    net.rnn_clear_previous_state()
    step1 = np.asarray(net.rnn_time_step(x[:, :3]))
    step2 = np.asarray(net.rnn_time_step(x[:, 3:]))
    streamed = np.concatenate([step1, step2], axis=1)
    np.testing.assert_allclose(full, streamed, atol=1e-5)
    # single-step 2d input
    net.rnn_clear_previous_state()
    s = np.asarray(net.rnn_time_step(x[:, 0]))
    np.testing.assert_allclose(s, full[:, 0], atol=1e-5)


def test_variable_length_masking():
    x, y = seq_data(16, 10)
    mask = np.ones((16, 10), np.float32)
    mask[:, 7:] = 0  # last 3 steps padding
    ds = DataSet(x, y, features_mask=mask, labels_mask=mask)
    net = MultiLayerNetwork(rnn_conf()).init()
    s0 = net.score(ds)
    net.fit(ds, epochs=5, batch_size=16, async_prefetch=False)
    assert net.score(ds) < s0
    # masked-out steps must not influence the loss: perturbing padded input
    # leaves the score unchanged
    x2 = x.copy()
    x2[:, 7:] += 100.0
    ds2 = DataSet(x2, y, features_mask=mask, labels_mask=mask)
    assert abs(net.score(ds2) - net.score(ds)) < 1e-5


def test_rnn_time_step_shape_keyed_compile_cache():
    """The streaming step is jitted with a shape-keyed cache: repeated
    same-shape calls cost ZERO new traces (a serving decode loop must
    not retrace per call), and each distinct (batch, time) shape costs
    exactly one."""
    x, _ = seq_data(4, 6)
    net = MultiLayerNetwork(rnn_conf()).init()
    net.rnn_time_step(x[:, :3])
    c0 = net.output_compile_count
    net.rnn_time_step(x[:, 3:])  # same [4, 3, 1] shape: cached
    for _ in range(5):
        net.clear_rnn_state()
        net.rnn_time_step(x[:, :3])
    assert net.output_compile_count == c0
    net.rnn_time_step(x)  # new time length: exactly one new trace
    assert net.output_compile_count == c0 + 1


def test_rnn_time_step_batch_change_starts_fresh_stream():
    """Regression: a batch-size change used to crash (or silently leak)
    against the previous caller's carried h/c. Now it starts a NEW
    stream — identical to calling clear_rnn_state() first."""
    x, _ = seq_data(4, 6)
    net = MultiLayerNetwork(rnn_conf()).init()
    net.rnn_time_step(x)  # carry now holds batch-4 state
    out = np.asarray(net.rnn_time_step(x[:2]))  # batch 2: new stream
    net.clear_rnn_state()
    fresh = np.asarray(net.rnn_time_step(x[:2]))
    np.testing.assert_array_equal(out, fresh)


def test_clear_rnn_state_resets_stream():
    """clear_rnn_state() regression: without it, carried state makes a
    repeat call differ; with it, the repeat is bit-identical."""
    x, _ = seq_data(4, 6)
    net = MultiLayerNetwork(rnn_conf()).init()
    a = np.asarray(net.rnn_time_step(x))
    b = np.asarray(net.rnn_time_step(x))  # carried h/c: different output
    assert not np.allclose(a, b)
    net.clear_rnn_state()
    c = np.asarray(net.rnn_time_step(x))
    np.testing.assert_array_equal(a, c)
