"""Tensor-op surface: activations, losses, conv primitives, Pallas kernels.

This package is the analog of the reference's ND4J op surface (the external
libnd4j engine every layer calls into) re-expressed as jax.numpy / lax /
Pallas functions that XLA fuses into whole-step programs.
"""

from deeplearning4j_tpu.ops.activations import Activation, activation_fn, register_activation
from deeplearning4j_tpu.ops.losses import LossFunction, loss_value, register_loss
from deeplearning4j_tpu.ops.helpers import (
    HelperError,
    get_helper,
    helper_names,
    register_helper,
    set_helper_enabled,
)

try:  # vendor kernels register themselves; absence must never break ops/
    from deeplearning4j_tpu.ops import pallas_lstm  # noqa: F401
except Exception:  # pragma: no cover - pallas unavailable on this backend
    pass

try:
    from deeplearning4j_tpu.ops import pallas_conv_bn  # noqa: F401
except Exception:  # pragma: no cover - pallas unavailable on this backend
    pass
