"""Bucketed, pipelined inference-serving tests: compile-cache stability
(the shape-keyed output cache + retrace counter), correctness of fused
mixed-size dispatch, the two ParallelInference admission races, and the
REST InferenceServer (reference: ParallelInferenceTest.java +
inference/observers/BatchedInferenceObservable tests — extended with the
trace-count assertions the reference had no equivalent of)."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (
    InferenceMode,
    ParallelInference,
    data_parallel_mesh,
    data_shards,
    power_of_two_buckets,
)
from deeplearning4j_tpu.serving import InferenceServer


def _mlp_conf(seed=7, n_in=12):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(Updater.SGD)
        .learning_rate(0.05)
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_in=n_in, n_out=16, activation="tanh"))
        .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                           loss="mcxent"))
        .build()
    )


def _requests(sizes, n_in=12, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((s, n_in)).astype(np.float32)
            for s in sizes]


def _expected_traces(buckets, n_shards):
    """Distinct jit shapes: each bucket is padded up to a multiple of the
    shard count before dispatch, so buckets below n_shards collapse."""
    return len({b + (-b) % n_shards for b in buckets})


# -- bucket policy ----------------------------------------------------------

def test_default_bucket_set():
    assert power_of_two_buckets(64) == [1, 2, 4, 8, 16, 32, 64]
    assert power_of_two_buckets(48) == [1, 2, 4, 8, 16, 32, 48]
    assert power_of_two_buckets(1) == [1]


def test_custom_buckets_validated():
    net = MultiLayerNetwork(_mlp_conf()).init()
    with pytest.raises(ValueError, match="bucket"):
        ParallelInference(net, data_parallel_mesh(), max_batch_size=32,
                          buckets=[4, 8])  # largest < max_batch_size
    with pytest.raises(ValueError, match="max_batch_size"):
        ParallelInference(net, data_parallel_mesh(), max_batch_size=0)
    pi = ParallelInference(net, data_parallel_mesh(), max_batch_size=32,
                           buckets=[8, 32, 16],
                           inference_mode=InferenceMode.SEQUENTIAL)
    assert pi.buckets == [8, 16, 32]


def test_empty_request_rejected():
    """A 0-row request must be rejected at admission: 0 is a multiple of
    every bucket, so it would otherwise compile a fresh 0-shape trace."""
    net = MultiLayerNetwork(_mlp_conf()).init()
    pi = ParallelInference(net, data_parallel_mesh(), max_batch_size=8)
    try:
        compiles = net.output_compile_count
        with pytest.raises(ValueError, match="empty"):
            pi.output(np.zeros((0, 12), np.float32))
        assert net.output_compile_count == compiles
    finally:
        pi.shutdown()


# -- compile-cache stability (the tentpole claim) ---------------------------

def test_mixed_sizes_bounded_compiles_and_exact_results():
    """≥6 distinct concurrent request sizes through BATCHED mode: the
    number of forward compiles equals the number of distinct bucket
    shapes (NOT the number of distinct request/group sizes), warmup
    precompiles all of them so traffic itself compiles nothing, and every
    caller gets byte-identical rows to a per-request model.output."""
    net = MultiLayerNetwork(_mlp_conf()).init()
    mesh = data_parallel_mesh()
    pi = ParallelInference(net, mesh, max_batch_size=16)
    try:
        assert pi.buckets == [1, 2, 4, 8, 16]
        pi.warmup((12,))
        compiles_warm = net.output_compile_count
        assert compiles_warm == _expected_traces(pi.buckets,
                                                 data_shards(mesh))
        assert compiles_warm <= len(pi.buckets)

        sizes = [1, 2, 3, 5, 8, 11, 16, 4, 7, 13]  # 10 distinct sizes
        xs = _requests(sizes)
        results = {}

        def call(i):
            results[i] = np.asarray(pi.output(xs[i]))

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(xs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # traffic with 10 distinct request sizes compiled NOTHING new
        assert net.output_compile_count == compiles_warm
        m = pi.metrics()
        assert m["requests"] == len(sizes)
        assert m["examples"] == sum(sizes)
        assert m["oversized"] == 0
        assert sum(m["bucket_hits"].values()) == m["batches"] > 0
    finally:
        pi.shutdown()
    # byte-identical to per-request output (row results are independent of
    # the fused batch around them; pad rows are sliced off) — computed
    # after the counter assertions since these calls add new trace shapes
    for i, x in enumerate(xs):
        np.testing.assert_array_equal(results[i], np.asarray(net.output(x)))


def test_sequential_mode_is_bucketed_too():
    net = MultiLayerNetwork(_mlp_conf()).init()
    ref = MultiLayerNetwork(_mlp_conf()).init()  # same seed: same params
    pi = ParallelInference(net, data_parallel_mesh(), max_batch_size=16,
                           inference_mode=InferenceMode.SEQUENTIAL)
    pi.warmup((12,))
    compiles_warm = net.output_compile_count
    for x in _requests([3, 5, 9, 13, 16, 1]):
        np.testing.assert_array_equal(
            np.asarray(pi.output(x)), np.asarray(ref.output(x)))
    assert net.output_compile_count == compiles_warm


def test_oversized_request_served_alone():
    net = MultiLayerNetwork(_mlp_conf()).init()
    pi = ParallelInference(net, data_parallel_mesh(), max_batch_size=8)
    try:
        x = _requests([24])[0]
        out = np.asarray(pi.output(x))
        assert out.shape == (24, 4)
        assert pi.metrics()["oversized"] == 1
    finally:
        pi.shutdown()


def test_output_cache_is_shape_keyed_multilayer():
    net = MultiLayerNetwork(_mlp_conf()).init()
    assert net.output_compile_count == 0
    x8, x16 = _requests([8, 16])
    net.output(x8)
    net.output(x8)  # same shape: cache hit
    assert net.output_compile_count == 1
    net.output(x16)
    assert net.output_compile_count == 2
    net.output(x8, training=True)  # distinct trace per training flag
    assert net.output_compile_count == 3


def test_output_cache_is_shape_keyed_compgraph():
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph

    conf = (NeuralNetConfiguration.builder()
            .seed(7).updater(Updater.SGD).learning_rate(0.05)
            .weight_init("xavier").graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=12, n_out=16, activation="tanh"),
                       "in")
            .add_layer("out", OutputLayer(n_in=16, n_out=4,
                                          activation="softmax",
                                          loss="mcxent"),
                       "d")
            .set_outputs("out")
            .build())
    g = ComputationGraph(conf).init()
    x8, x16 = _requests([8, 16])
    g.output(x8)
    g.output(x8)
    assert g.output_compile_count == 1
    g.output(x16)
    assert g.output_compile_count == 2


def _two_head_graph():
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph

    conf = (NeuralNetConfiguration.builder()
            .seed(7).updater(Updater.SGD).learning_rate(0.05)
            .weight_init("xavier").graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=12, n_out=16, activation="tanh"),
                       "in")
            .add_layer("outA", OutputLayer(n_in=16, n_out=4,
                                           activation="softmax",
                                           loss="mcxent"), "d")
            .add_layer("outB", OutputLayer(n_in=16, n_out=2,
                                           activation="softmax",
                                           loss="mcxent"), "d")
            .set_outputs("outA", "outB")
            .build())
    return ComputationGraph(conf).init()


def test_multi_output_graph_through_parallel_inference():
    """A multi-output ComputationGraph returns a LIST from output(); the
    batch slice/scatter must apply per output array, not to the list."""
    g = _two_head_graph()
    ref = _two_head_graph()  # same seed: same params
    pi = ParallelInference(g, data_parallel_mesh(), max_batch_size=8)
    try:
        results = {}
        xs = _requests([3, 5, 2])

        def call(i):
            results[i] = pi.output(xs[i])

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(xs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, x in enumerate(xs):
            out = results[i]
            assert isinstance(out, list) and len(out) == 2
            assert out[0].shape == (x.shape[0], 4)
            assert out[1].shape == (x.shape[0], 2)
            ref_a, ref_b = ref.output(x)
            # ULP-tolerance, not byte-equality: XLA does not guarantee
            # bitwise row-position invariance for the fused two-head
            # graph (the second head drifts 1 ULP when the request sits
            # at a nonzero row offset inside the fused batch)
            np.testing.assert_allclose(out[0], np.asarray(ref_a),
                                       rtol=2e-6, atol=1e-7)
            np.testing.assert_allclose(out[1], np.asarray(ref_b),
                                       rtol=2e-6, atol=1e-7)
    finally:
        pi.shutdown()


def test_multi_output_graph_through_inference_server():
    """/predict on a multi-output graph returns one predictions entry per
    output head instead of a mis-stacked tensor or a spurious 400."""
    g = _two_head_graph()
    server = InferenceServer(g, max_batch_size=8, warmup_shape=(12,))
    port = server.start()
    try:
        x = _requests([3])[0]
        preds = _http(f"http://127.0.0.1:{port}/predict",
                      {"features": x.tolist()})["predictions"]
        assert len(preds) == 2
        ref_a, ref_b = g.output(x)
        np.testing.assert_allclose(np.asarray(preds[0], np.float32),
                                   np.asarray(ref_a), rtol=2e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(preds[1], np.float32),
                                   np.asarray(ref_b), rtol=2e-6, atol=1e-7)
    finally:
        server.stop()


# -- admission races (satellite regressions) --------------------------------

def test_first_request_shape_race():
    """Two shapes racing to be the first request: exactly ONE wins (the
    admission lock fixes `_expected_shape` atomically) and every loser is
    rejected at admission with ValueError — mismatched shapes can never
    share a fused group. Before the fix, two concurrent first callers
    could both see None, co-admit, and fail the whole fused group with
    collateral errors for correctly-shaped callers."""
    for attempt in range(4):
        net = MultiLayerNetwork(_mlp_conf()).init()
        pi = ParallelInference(net, data_parallel_mesh(), max_batch_size=32)
        try:
            n_each = 6
            xs = (_requests([4] * n_each, n_in=12)
                  + _requests([4] * n_each, n_in=7, seed=1))
            start = threading.Barrier(2 * n_each)
            outcomes = {}

            def call(i):
                start.wait()
                try:
                    outcomes[i] = np.asarray(pi.output(xs[i])).shape
                except ValueError:
                    outcomes[i] = "rejected"  # lost the admission race
                except Exception as e:  # model-level failure (winner != 12)
                    outcomes[i] = ("failed", type(e).__name__)

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(len(xs))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert not any(t.is_alive() for t in threads)
            for i, x in enumerate(xs):
                o = outcomes[i]
                if x.shape[1:] == (12,):
                    # a model-compatible request either lost an admission
                    # race (clean reject) or got a CORRECT result — never
                    # collateral failure from the other shape in its group
                    assert o in ("rejected", (4, 4)), (i, o)
                else:
                    # the model-incompatible shape can win the pin (and
                    # then fail at the model, unpinning) but must never
                    # produce a result
                    assert o == "rejected" or (
                        isinstance(o, tuple) and o[0] == "failed"), (i, o)
            # at least one caller was served or cleanly rejected — and if
            # the bad shape won the provisional pin, its forward failure
            # unpinned it, so the endpoint is never poisoned:
            x_ok = _requests([4])[0]
            assert np.asarray(pi.output(x_ok)).shape == (4, 4)
        finally:
            pi.shutdown()


def test_bad_first_request_does_not_poison_endpoint():
    """A malformed FIRST request (feature width the model rejects) pins
    the expected shape only provisionally: its forward failure unpins,
    so later well-formed requests are served instead of being rejected
    forever."""
    net = MultiLayerNetwork(_mlp_conf()).init()
    pi = ParallelInference(net, data_parallel_mesh(), max_batch_size=8)
    try:
        with pytest.raises(Exception):
            pi.output(np.zeros((2, 7), np.float32))  # model wants n_in=12
        x = _requests([3])[0]
        out = np.asarray(pi.output(x))
        assert out.shape == (3, 4)
        np.testing.assert_array_equal(out, np.asarray(net.output(x)))
    finally:
        pi.shutdown()


def test_shutdown_under_load_no_hung_futures():
    """Requests racing shutdown(): every caller either gets a result or a
    fast RuntimeError — the enqueue-after-drain window that used to leave
    a Future unresolved forever is closed by the admission lock."""
    net = MultiLayerNetwork(_mlp_conf()).init()
    pi = ParallelInference(net, data_parallel_mesh(), max_batch_size=8,
                           batch_timeout_ms=1.0)
    pi.warmup((12,))
    x = _requests([2])[0]
    served, rejected, hung = [], [], []

    def client(i):
        try:
            out = pi.output(x)
            assert np.asarray(out).shape == (2, 4)
            served.append(i)
        except RuntimeError:
            rejected.append(i)
        except BaseException:
            hung.append(i)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(32)]
    for j, t in enumerate(threads):
        t.start()
        if j == 12:  # shut down mid-stream
            killer = threading.Thread(target=pi.shutdown)
            killer.start()
    killer.join(timeout=15)
    for t in threads:
        t.join(timeout=15)
    assert not any(t.is_alive() for t in threads), "caller hung on shutdown"
    assert not hung
    assert len(served) + len(rejected) == 32
    with pytest.raises(RuntimeError, match="shut down"):
        pi.output(x)


# -- REST server ------------------------------------------------------------

def _http(url, payload=None, timeout=15):
    if payload is None:
        resp = urllib.request.urlopen(url, timeout=timeout)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=timeout)
    return json.loads(resp.read())


def test_inference_server_routes():
    net = MultiLayerNetwork(_mlp_conf()).init()
    server = InferenceServer(net, max_batch_size=8, warmup_shape=(12,))
    port = server.start()
    base = f"http://127.0.0.1:{port}"
    try:
        h = _http(f"{base}/health")
        assert h["status"] == "ok"
        assert h["model"] == "MultiLayerNetwork"
        assert h["feature_shape"] == [12]

        x = _requests([3])[0]
        preds = np.asarray(
            _http(f"{base}/predict", {"features": x.tolist()})["predictions"],
            np.float32)
        np.testing.assert_allclose(preds, np.asarray(net.output(x)),
                                   rtol=1e-5, atol=1e-6)
        # single flat example: one row back
        single = np.asarray(
            _http(f"{base}/predict",
                  {"features": x[0].tolist()})["predictions"], np.float32)
        np.testing.assert_allclose(single, preds[0], rtol=1e-5, atol=1e-6)

        m = _http(f"{base}/metrics")
        assert m["requests"] == 2
        assert m["latency_ms"]["count"] == 2
        assert m["latency_ms"]["p50_ms"] is not None
        assert m["latency_ms"]["p99_ms"] is not None
        assert set(m["bucket_hits"]) == {"1", "2", "4", "8"}
        assert m["forward_compiles"] >= 1
        assert m["queue_depth"] == 0

        # client errors are 4xx with a JSON body, and the server survives
        for payload in ({"features": [[1.0, 2.0]]},  # wrong width
                        {"features": 3.5},           # scalar
                        {"features": []},            # empty
                        {}):                         # missing key
            bad = urllib.request.Request(
                f"{base}/predict", data=json.dumps(payload).encode())
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(bad, timeout=15)
            assert ei.value.code == 400, payload
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/nosuch", timeout=15)
        assert ei.value.code == 404
        assert _http(f"{base}/health")["status"] == "ok"

        # server-side faults are 5xx (retryable), not mislabeled 400s:
        # kill the inference engine under the still-serving HTTP layer
        server.inference.shutdown()
        good = urllib.request.Request(
            f"{base}/predict", data=json.dumps({"features": x.tolist()}).encode())
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(good, timeout=15)
        assert ei.value.code == 500
    finally:
        server.stop()


@pytest.mark.slow
def test_inference_server_concurrent_load():
    """Serving load test: many clients, mixed sizes, through the full
    REST + fused-dispatch + bucket-padding stack; all responses correct,
    no compiles after warmup, metrics consistent."""
    net = MultiLayerNetwork(_mlp_conf()).init()
    server = InferenceServer(net, max_batch_size=16, warmup_shape=(12,),
                             batch_timeout_ms=1.0)
    port = server.start()
    base = f"http://127.0.0.1:{port}"
    compiles_warm = net.output_compile_count
    rng = np.random.default_rng(0)
    sizes = [int(s) for s in rng.integers(1, 17, size=64)]
    xs = _requests(sizes)
    errors = []

    def client(i):
        try:
            preds = np.asarray(
                _http(f"{base}/predict",
                      {"features": xs[i].tolist()})["predictions"],
                np.float32)
            if preds.shape != (sizes[i], 4):
                errors.append((i, preds.shape))
        except BaseException as e:
            errors.append((i, repr(e)))

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(xs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    dt = time.perf_counter() - t0
    try:
        assert not errors, errors[:5]
        assert net.output_compile_count == compiles_warm
        m = _http(f"{base}/metrics")
        assert m["requests"] == len(xs)
        assert m["examples"] == sum(sizes)
        assert m["latency_ms"]["count"] == len(xs)
        assert dt < 60
    finally:
        server.stop()
