"""REST k-NN server over a VPTree (reference:
nearestneighbor/server/NearestNeighborsServer.java:29,70 — loads a stored
points NDArray, builds a VPTree with --similarityFunction/--invert, and
serves POST /knn with {"k": int, "inputIndex": int} ->
{"results": [{"index": i}, ...]}; DTOs in nearestneighbor/model/).

Extensions beyond the reference API (same shape, additive):
- POST /knnvector {"k": int, "vector": [floats]} — query by raw vector
  instead of stored-point index.
- GET /health — liveness.
Distances are included in each result row (the reference computes them
but only returns indices).
"""

from __future__ import annotations

import argparse
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree


class NearestNeighborsServer:
    def __init__(self, points: np.ndarray,
                 similarity_function: str = "euclidean",
                 invert: bool = False, port: int = 9000):
        self.points = np.asarray(points, np.float32)
        self.tree = VPTree(self.points, similarity_function, invert)
        self.port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- request handling ---------------------------------------------------

    def _handle(self, route: str, body: dict) -> tuple:
        if route == "/knn":
            k = int(body["k"])
            idx = int(body["inputIndex"])
            if not (0 <= idx < self.points.shape[0]):
                return 400, {"error": f"inputIndex {idx} out of range"}
            target = self.points[idx]
        elif route == "/knnvector":
            k = int(body["k"])
            target = np.asarray(body["vector"], np.float32)
            if target.shape != (self.points.shape[1],):
                return 400, {
                    "error": f"vector must have dim {self.points.shape[1]}"
                }
        else:
            return 404, {"error": f"no route {route}"}
        indices, distances = self.tree.search(target, k)
        return 200, {
            "results": [
                {"index": int(i), "distance": float(d)}
                for i, d in zip(indices, distances)
            ]
        }

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> int:
        """Start serving on a background thread; returns the bound port
        (useful with port=0 for tests)."""
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code: int, payload: dict):
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/health":
                    self._send(200, {"status": "ok",
                                     "points": outer.points.shape[0]})
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    code, payload = outer._handle(self.path, body)
                except (ValueError, KeyError, TypeError) as e:
                    code, payload = 400, {"error": str(e)}
                self._send(code, payload)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def main(argv=None):
    """CLI matching the reference's flags (NearestNeighborsServer.java):
    --ndarrayPath (a .npy file), --nearestNeighborsPort,
    --similarityFunction, --invert."""
    ap = argparse.ArgumentParser(description="k-NN REST server")
    ap.add_argument("--ndarrayPath", required=True)
    ap.add_argument("--nearestNeighborsPort", type=int, default=9000)
    ap.add_argument("--similarityFunction", default="euclidean")
    ap.add_argument("--invert", action="store_true")
    args = ap.parse_args(argv)
    points = np.load(args.ndarrayPath)
    server = NearestNeighborsServer(points, args.similarityFunction,
                                    args.invert, args.nearestNeighborsPort)
    port = server.start()
    print(f"nearest-neighbors server listening on :{port}")
    try:
        server._thread.join()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
