"""Device-side skip-gram example generation — train from the CORPUS, not
from shipped pair batches.

Why: the host->device link is the word2vec bottleneck on a remote-tunnel
TPU. Shipping (input, target, mask) pair batches costs ~50 bytes/word
(measured ~2.8 MB/s effective through the tunnel -> a hard ~45k words/s
ceiling regardless of device speed); shipping the INDEXED CORPUS costs 4
bytes/word. So the host uploads each epoch's subsampled corpus once (one
int32 per surviving word, sentences separated by `window` sentinel
tokens) and the device does everything the reference's
VectorCalculationsThread workers did host-side
(SequenceVectors.java:285-289, SkipGram.java:271): dynamic windowing,
pair extraction, negative sampling, and the table updates — one jitted
dispatch per epoch.

Semantics preserved (word2vec.c / reference parity):
- dynamic window: per center, effective window = window - b with
  b ~ U[0, window) — pairs at distance 1 are always trained.
- skip-gram trains input = CONTEXT word, output = center word.
- sentence boundaries: a `window`-wide sentinel gap guarantees any
  (center, context) pair within `window` distance that crosses a
  boundary touches a sentinel and is masked out.
- lr decays linearly over PAIRS ACTUALLY TRAINED (carried through the
  scan) toward min_lr — word2vec.c's decay-by-progress, measured on
  true pair counts instead of the host path's expected-pairs estimate.

The update math is learning.py's `_build_update` body (same trust-region
scatter updates, same device-side negative sampling), fed from in-kernel
generated batches.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.learning import _build_update

SENTINEL = -1


def pack_corpus(sentences: List[np.ndarray], window: int,
                bucket: int = 8192) -> np.ndarray:
    """Concatenate indexed sentences into one int32 array with `window`
    SENTINEL tokens between (and after) them, padded with SENTINEL up to
    the next power-of-two multiple of `bucket`: corpora within 2x of each
    other share one compiled program (per-epoch subsampling jitter never
    recompiles; a growing corpus recompiles only on doubling)."""
    gap = np.full(window, SENTINEL, np.int32)
    parts = []
    for s in sentences:
        if s.size == 0:
            continue
        parts.append(s.astype(np.int32))
        parts.append(gap)
    flat = (np.concatenate(parts) if parts
            else np.zeros(0, np.int32))
    size = int(bucket)
    while size < flat.size:
        size *= 2
    if size != flat.size:
        flat = np.concatenate(
            [flat, np.full(size - flat.size, SENTINEL, np.int32)])
    return flat


def _chunk_pairs(corpus, start, n_centers, window, key):
    """Extract the (input=context, target=center, valid) pair block for
    centers at positions [start, start+n_centers). Shapes are static:
    [n_centers * 2 * window] flattened pairs."""
    T = corpus.shape[0]
    c_pos = start + jnp.arange(n_centers)
    center = corpus[jnp.clip(c_pos, 0, T - 1)]
    # dynamic window (word2vec.c: b = next_random % window)
    b = jax.random.randint(key, (n_centers,), 0, window)
    w_eff = window - b                                   # [n_centers]
    offsets = jnp.concatenate(
        [jnp.arange(-window, 0), jnp.arange(1, window + 1)])  # [2W]
    ctx_pos = c_pos[:, None] + offsets[None, :]          # [n_centers, 2W]
    in_bounds = (ctx_pos >= 0) & (ctx_pos < T)
    ctx = corpus[jnp.clip(ctx_pos, 0, T - 1)]
    valid = (
        in_bounds
        & (center[:, None] >= 0)
        & (ctx >= 0)
        & (jnp.abs(offsets)[None, :] <= w_eff[:, None])
    )
    return (ctx.reshape(-1), jnp.repeat(center, 2 * window),
            valid.reshape(-1))


def corpus_pairs_debug(corpus, window, key, n_centers=None):
    """Test hook: the full pair list one chunk would generate (host
    array outputs)."""
    n = int(n_centers if n_centers is not None else corpus.shape[0])
    ins, tgt, valid = _chunk_pairs(jnp.asarray(corpus, jnp.int32), 0, n,
                                   int(window), key)
    return (np.asarray(ins), np.asarray(tgt),
            np.asarray(valid).astype(bool))


def make_corpus_skipgram_step(*, negative: int, window: int,
                              pairs_per_batch: int = 8192,
                              max_row_update: float = 0.25):
    """Jitted one-dispatch-per-epoch skip-gram trainer.

    step(syn0, syn1neg, unigram, corpus, lr0, min_lr, total_pairs,
         seen0, key) -> (syn0, syn1neg, mean_loss, seen)

    The scan walks the corpus in center chunks of
    pairs_per_batch // (2*window) positions; each chunk trains its
    (<= pairs_per_batch) generated pairs through learning.py's update
    body with the lr for the pairs seen so far.
    """
    body = _build_update(use_hs=False, negative=negative, with_doc=False,
                         train_words=True, max_row_update=max_row_update)
    n_centers = max(1, pairs_per_batch // (2 * window))

    def step(syn0, syn1neg, unigram, corpus, lr0, min_lr, total_pairs,
             seen0, key):
        T = corpus.shape[0]
        n_chunks = -(-T // n_centers)
        dummy_syn1 = jnp.zeros((1, syn0.shape[1]), syn0.dtype)
        dummy_doc = jnp.zeros((1, syn0.shape[1]), syn0.dtype)

        def one(carry, inp):
            s0, s1n, seen = carry
            i, k = inp
            k_win, k_neg = jax.random.split(k)
            ins, tgt, valid = _chunk_pairs(
                corpus, i * n_centers, n_centers, window, k_win)
            batch = {
                "h_idx": jnp.maximum(ins, 0)[:, None].astype(jnp.int32),
                "row_mask": valid,
                "pos": jnp.maximum(tgt, 0).astype(jnp.int32),
            }
            lr = jnp.maximum(lr0 * (1.0 - seen / total_pairs), min_lr)
            s0, _, s1n, _, loss = body(
                s0, dummy_syn1, s1n, dummy_doc, unigram, batch, lr, k_neg)
            # seen carried in f32: still exact (+<=8192 per chunk) far past
            # int32 range, and it only feeds the lr ramp
            n_valid = jnp.sum(valid.astype(jnp.float32))
            seen = seen + n_valid
            return (s0, s1n, seen), (loss, n_valid)

        keys = jax.random.split(key, n_chunks)
        (syn0, syn1neg, seen), (losses, weights) = jax.lax.scan(
            one, (syn0, syn1neg, seen0),
            (jnp.arange(n_chunks), keys))
        # pair-weighted mean: bucket-padding chunks (0 valid pairs, loss 0)
        # must not dilute the reported epoch loss
        mean_loss = (jnp.sum(losses * weights)
                     / jnp.maximum(jnp.sum(weights), 1.0))
        return syn0, syn1neg, mean_loss, seen

    return jax.jit(step, donate_argnums=(0, 1))
