"""Transfer learning: builder + featurizing helper.

Reference: nn/transferlearning/TransferLearning.java (808 LoC) —
fineTuneConfiguration (hyperparameter overrides), setFeatureExtractor
(freeze up to a boundary via FrozenLayer), nOutReplace (swap a layer's
width + reinitialize it and its consumer), removeOutputLayer/addLayer; and
TransferLearningHelper (featurize a dataset through the frozen front so
repeated fine-tune epochs skip recomputing it).

Functional design: the builder never mutates the source network — it
produces a NEW MultiLayerNetwork whose configs are deep copies and whose
parameter arrays are shared (jax arrays are immutable, so sharing is safe)
except where a replace/add forces re-initialization.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional

import jax
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
from deeplearning4j_tpu.nn.layers.registry import init_layer_params, init_layer_state
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _copy_tree(tree):
    """Deep-copy a param/state pytree's device arrays. Transferred nets
    must own their buffers: jitted train steps donate params on TPU/GPU,
    so a shared array would be deleted under the source network."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True), tree)


class TransferLearning:
    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            net._require_init()
            self._src = net
            self._fine_tune: Dict = {}
            self._freeze_until: Optional[int] = None
            self._replacements: Dict[int, dict] = {}
            self._removed_from_output = 0
            self._added: List[L.LayerConf] = []

        def fine_tune_configuration(self, **overrides) -> "TransferLearning.Builder":
            """Override global hyperparameters (learning_rate, updater,
            momentum, ... — reference: FineTuneConfiguration)."""
            self._fine_tune.update(overrides)
            return self

        def set_feature_extractor(self, layer_idx: int) -> "TransferLearning.Builder":
            """Freeze layers 0..layer_idx inclusive (reference:
            setFeatureExtractor — wraps in FrozenLayer)."""
            self._freeze_until = int(layer_idx)
            return self

        def n_out_replace(self, layer_idx: int, n_out: int,
                          weight_init: Optional[str] = None) -> "TransferLearning.Builder":
            """Change layer_idx's n_out and reinitialize it + the next
            parameterized layer's n_in (reference: nOutReplace)."""
            self._replacements[int(layer_idx)] = {
                "n_out": int(n_out), "weight_init": weight_init,
            }
            return self

        def remove_output_layer(self) -> "TransferLearning.Builder":
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, n: int) -> "TransferLearning.Builder":
            self._removed_from_output += int(n)
            return self

        def add_layer(self, layer_conf: L.LayerConf) -> "TransferLearning.Builder":
            self._added.append(layer_conf)
            return self

        def build(self) -> MultiLayerNetwork:
            src = self._src
            confs = [copy.deepcopy(c) for c in src.layer_confs]
            keep = len(confs) - self._removed_from_output
            if keep < 0:
                raise ValueError("removed more layers than the network has")
            confs = confs[:keep]
            reinit = set()

            # nOutReplace: new width + downstream n_in rewiring
            for idx, spec in sorted(self._replacements.items()):
                if idx >= len(confs):
                    raise ValueError(f"n_out_replace index {idx} out of range")
                inner = confs[idx].inner if isinstance(confs[idx], L.FrozenLayer) else confs[idx]
                inner.n_out = spec["n_out"]
                if spec["weight_init"]:
                    inner.weight_init = spec["weight_init"]
                reinit.add(idx)
                for j in range(idx + 1, len(confs)):
                    nxt = confs[j].inner if isinstance(confs[j], L.FrozenLayer) else confs[j]
                    if isinstance(nxt, L.BatchNormalization):
                        nxt.n_in = spec["n_out"]
                        reinit.add(j)
                        continue
                    if isinstance(nxt, L.FeedForwardLayerConf):
                        nxt.n_in = spec["n_out"]
                        reinit.add(j)
                        break
                    if nxt.has_params():
                        break

            # added layers: wire n_in from the previous feed-forward width
            prev_out = None
            for c in reversed(confs):
                inner = c.inner if isinstance(c, L.FrozenLayer) else c
                if isinstance(inner, L.FeedForwardLayerConf):
                    prev_out = inner.n_out
                    break
            for lc in self._added:
                inner = lc.inner if isinstance(lc, L.FrozenLayer) else lc
                if isinstance(inner, L.FeedForwardLayerConf) and inner.n_in is None:
                    inner.n_in = prev_out
                if isinstance(inner, L.FeedForwardLayerConf):
                    prev_out = inner.n_out
                reinit.add(len(confs))
                confs.append(lc)

            # freeze the feature extractor
            if self._freeze_until is not None:
                for i in range(min(self._freeze_until + 1, len(confs))):
                    if not isinstance(confs[i], L.FrozenLayer):
                        confs[i] = L.FrozenLayer(inner=confs[i])

            net_conf = copy.deepcopy(src.net_conf)
            for k, v in self._fine_tune.items():
                if not hasattr(net_conf, k):
                    raise ValueError(f"unknown fine-tune hyperparameter {k!r}")
                setattr(net_conf, k, v)

            # added layers inherit network defaults exactly as the
            # ListBuilder does for an original build
            from deeplearning4j_tpu.nn.conf.network import _apply_defaults

            for lc in self._added:
                _apply_defaults(lc, net_conf)

            new_conf = MultiLayerConfiguration(
                net_conf=net_conf,
                layers=confs,
                preprocessors=copy.deepcopy(src.conf.preprocessors),
                backprop_type=src.conf.backprop_type,
                tbptt_fwd_length=src.conf.tbptt_fwd_length,
                tbptt_bwd_length=src.conf.tbptt_bwd_length,
                input_type=copy.deepcopy(src.conf.input_type),
            )
            new_net = MultiLayerNetwork(new_conf).init()
            # parameter transfer: COPY surviving layers' arrays (the train
            # step donates its param buffers on TPU/GPU — sharing would let
            # new_net.fit() invalidate the source network's arrays)
            for i in range(len(confs)):
                if i < len(src.params_list) and i not in reinit:
                    new_net.params_list[i] = _copy_tree(src.params_list[i])
                    new_net.state_list[i] = _copy_tree(src.state_list[i])
            return new_net


class GraphTransferLearning:
    """Transfer learning for ComputationGraph (reference:
    TransferLearning.GraphBuilder in nn/transferlearning/
    TransferLearning.java): fineTune, setFeatureExtractor (freeze every
    ancestor of the named vertices, inclusive), removeVertexAndConnections,
    addLayer/addVertex, nOutReplace, setOutputs. Exposed as
    TransferLearning.GraphBuilder for API parity."""

    def __init__(self, net):
        net._require_init()
        self._src = net
        self._fine_tune: Dict = {}
        self._freeze_at: List[str] = []
        self._removed: List[str] = []
        self._added_layers: List[tuple] = []  # (name, conf, inputs, pp)
        self._added_vertices: List[tuple] = []  # (name, vertex, inputs)
        self._replacements: Dict[str, dict] = {}
        self._new_outputs: Optional[List[str]] = None

    def fine_tune_configuration(self, **overrides) -> "GraphTransferLearning":
        self._fine_tune.update(overrides)
        return self

    def set_feature_extractor(self, *vertex_names: str) -> "GraphTransferLearning":
        """Freeze the named vertices and all their ancestors (reference:
        GraphBuilder.setFeatureExtractor)."""
        self._freeze_at.extend(vertex_names)
        return self

    def remove_vertex_and_connections(self, name: str) -> "GraphTransferLearning":
        self._removed.append(name)
        return self

    def n_out_replace(self, layer_name: str, n_out: int,
                      weight_init: Optional[str] = None) -> "GraphTransferLearning":
        self._replacements[layer_name] = {
            "n_out": int(n_out), "weight_init": weight_init,
        }
        return self

    def add_layer(self, name: str, layer_conf, *inputs: str,
                  preprocessor=None) -> "GraphTransferLearning":
        self._added_layers.append((name, layer_conf, list(inputs), preprocessor))
        return self

    def add_vertex(self, name: str, vertex, *inputs: str) -> "GraphTransferLearning":
        self._added_vertices.append((name, vertex, list(inputs)))
        return self

    def set_outputs(self, *names: str) -> "GraphTransferLearning":
        self._new_outputs = list(names)
        return self

    def build(self):
        from deeplearning4j_tpu.nn.compgraph import ComputationGraph
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration,
            LayerVertex,
        )
        from deeplearning4j_tpu.nn.conf.network import _apply_defaults

        src = self._src
        conf = src.conf
        vertices = {k: copy.deepcopy(v) for k, v in conf.vertices.items()}
        vertex_inputs = {k: list(v) for k, v in conf.vertex_inputs.items()}
        outputs = list(self._new_outputs or conf.outputs)

        # removals: vertex + every edge pointing at it
        for name in self._removed:
            if name not in vertices:
                raise ValueError(f"cannot remove unknown vertex {name!r}")
            del vertices[name]
            del vertex_inputs[name]
            for k, ins in vertex_inputs.items():
                if name in ins:
                    raise ValueError(
                        f"vertex {k!r} still consumes removed vertex "
                        f"{name!r}; remove or rewire it first"
                    )
            outputs = [o for o in outputs if o != name]

        reinit = set()

        # nOutReplace: change width + rewire direct consumers' n_in
        for lname, spec in self._replacements.items():
            v = vertices.get(lname)
            if not isinstance(v, LayerVertex):
                raise ValueError(f"{lname!r} is not a layer vertex")
            inner = v.layer.inner if isinstance(v.layer, L.FrozenLayer) else v.layer
            inner.n_out = spec["n_out"]
            if spec["weight_init"]:
                inner.weight_init = spec["weight_init"]
            reinit.add(lname)
            for cname, ins in vertex_inputs.items():
                if lname not in ins:
                    continue
                cv_obj = vertices.get(cname)
                if isinstance(cv_obj, LayerVertex):
                    cv = cv_obj.layer
                    c_inner = cv.inner if isinstance(cv, L.FrozenLayer) else cv
                    if hasattr(c_inner, "n_in"):
                        c_inner.n_in = spec["n_out"]
                        reinit.add(cname)
                else:
                    # a non-layer consumer (Merge/ElementWise/...) changes
                    # how the new width propagates — refuse loudly instead
                    # of leaving stale n_in deeper in the graph (the
                    # reference's GraphBuilder errors here too)
                    raise ValueError(
                        f"n_out_replace({lname!r}) feeds non-layer vertex "
                        f"{cname!r}; rewire downstream widths explicitly "
                        "(remove_vertex_and_connections + add_layer)"
                    )

        # additions
        net_conf = copy.deepcopy(src.net_conf)
        for k, val in self._fine_tune.items():
            if not hasattr(net_conf, k):
                raise ValueError(f"unknown fine-tune hyperparameter {k!r}")
            setattr(net_conf, k, val)
        for name, vertex, ins in self._added_vertices:
            if name in vertices:
                raise ValueError(f"duplicate vertex name {name!r}")
            vertices[name] = copy.deepcopy(vertex)
            vertex_inputs[name] = list(ins)
        for name, lc, ins, pp in self._added_layers:
            if name in vertices:
                raise ValueError(f"duplicate vertex name {name!r}")
            lc = copy.deepcopy(lc)
            _apply_defaults(lc, net_conf)
            vertices[name] = LayerVertex(layer=lc, preprocessor=pp)
            vertex_inputs[name] = list(ins)
            reinit.add(name)

        # freeze: named vertices + all ancestors
        if self._freeze_at:
            frozen = set()
            stack = list(self._freeze_at)
            while stack:
                n = stack.pop()
                if n in frozen or n in conf.inputs:
                    continue
                frozen.add(n)
                stack.extend(vertex_inputs.get(n, []))
            for n in frozen:
                v = vertices.get(n)
                if isinstance(v, LayerVertex) and not isinstance(
                    v.layer, L.FrozenLayer
                ):
                    v.layer = L.FrozenLayer(inner=v.layer)

        new_conf = ComputationGraphConfiguration(
            net_conf=net_conf,
            inputs=list(conf.inputs),
            outputs=outputs,
            vertices=vertices,
            vertex_inputs=vertex_inputs,
            backprop_type=conf.backprop_type,
            tbptt_fwd_length=conf.tbptt_fwd_length,
            tbptt_bwd_length=conf.tbptt_bwd_length,
            input_types=copy.deepcopy(conf.input_types),
        )
        new_net = ComputationGraph(new_conf).init()
        # parameter transfer by vertex name (topo order may have changed);
        # arrays are COPIED so donation in new_net's train step cannot
        # invalidate the source network's buffers
        for name, new_idx in new_net._pidx.items():
            if name in src._pidx and name not in reinit:
                old_idx = src._pidx[name]
                new_net.params_list[new_idx] = _copy_tree(src.params_list[old_idx])
                new_net.state_list[new_idx] = _copy_tree(src.state_list[old_idx])
        return new_net


TransferLearning.GraphBuilder = GraphTransferLearning


class TransferLearningHelper:
    """Featurize through the frozen front once, then fine-tune the
    unfrozen tail on cached features (reference:
    nn/transferlearning/TransferLearningHelper.java)."""

    def __init__(self, net: MultiLayerNetwork):
        net._require_init()
        self.net = net
        self.boundary = 0
        for i, c in enumerate(net.layer_confs):
            if isinstance(c, L.FrozenLayer):
                self.boundary = i + 1
        if self.boundary == 0:
            raise ValueError("network has no frozen layers to featurize through")
        self._feed = jax.jit(
            lambda params, states, x: net._forward(
                params, states, net.policy.cast_input(x),
                training=False, rng=None, to_layer=self.boundary,
            )[0]
        )
        # the unfrozen tail as its own network sharing parameter arrays
        tail_confs = [copy.deepcopy(c) for c in net.layer_confs[self.boundary:]]
        tail_conf = MultiLayerConfiguration(
            net_conf=copy.deepcopy(net.net_conf),
            layers=tail_confs,
            preprocessors={
                str(int(k) - self.boundary): v
                for k, v in net.conf.preprocessors.items()
                if int(k) >= self.boundary
            },
        )
        self.tail = MultiLayerNetwork(tail_conf).init()
        # copies, not shares: tail.fit() donates its param buffers
        self.tail.params_list = [
            _copy_tree(p) for p in net.params_list[self.boundary:]
        ]
        self.tail.state_list = [
            _copy_tree(s) for s in net.state_list[self.boundary:]
        ]

    def featurize(self, ds: DataSet) -> DataSet:
        feats = self._feed(self.net.params_list, self.net.state_list,
                           np.asarray(ds.features))
        return DataSet(np.asarray(feats), ds.labels, None, ds.labels_mask)

    def fit_featurized(self, data, labels=None, *, epochs: int = 1,
                       batch_size: int = 32):
        """Train the unfrozen tail on featurized data, then write the
        updated parameters back into the full network."""
        self.tail.fit(data, labels, epochs=epochs, batch_size=batch_size,
                      async_prefetch=False)
        for i, p in enumerate(self.tail.params_list):
            self.net.params_list[self.boundary + i] = p
        for i, s in enumerate(self.tail.state_list):
            self.net.state_list[self.boundary + i] = s
        return self.net

    def unfrozen_network(self) -> MultiLayerNetwork:
        return self.tail
