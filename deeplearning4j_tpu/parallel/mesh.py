"""Device-mesh helpers — the TPU-native replacement for the reference's
device-affinity machinery (AffinityManager / thread-per-device replicas,
deeplearning4j-scaleout/.../parallelism/ParallelWrapper.java:133-134).

On TPU, "workers" are mesh axes, not threads: a `jax.sharding.Mesh` names
the device grid and `PartitionSpec`s say how each array maps onto it. XLA
GSPMD then inserts the ICI collectives (psum/all-gather) that the reference
performed by explicit parameter copies between worker threads.

Axis vocabulary used throughout the framework:
    "data"  — data parallelism (batch axis sharding)
    "model" — tensor/model parallelism (feature axis sharding)
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map_fn():
    """The shard_map entry point across jax versions: top-level
    `jax.shard_map` where the installed jax exposes it, else
    `jax.experimental.shard_map.shard_map` (the only home in the 0.4.x
    line installed here — the bare `jax.shard_map` access was what kept
    the whole sequence/pipeline parallel stack import-broken on this
    container, 11 of the seed baseline failures)."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    from jax.experimental.shard_map import shard_map

    return shard_map


def data_parallel_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over all (or the given) devices with a single "data" axis —
    the topology of the reference's ParallelWrapper (one replica per
    device)."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices), (DATA_AXIS,))


def mesh_2d(data: int, model: int, devices: Optional[Sequence] = None) -> Mesh:
    """data × model mesh for combined DP+TP. `data * model` must equal the
    device count."""
    devices = list(devices) if devices is not None else jax.devices()
    if data * model != len(devices):
        raise ValueError(
            f"mesh {data}x{model} needs {data * model} devices, have {len(devices)}"
        )
    return Mesh(np.array(devices).reshape(data, model), (DATA_AXIS, MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully replicated sharding (parameters, updater state)."""
    return NamedSharding(mesh, PartitionSpec())


def batch_sharded(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard dim 0 (the batch) across the data axis."""
    return NamedSharding(mesh, PartitionSpec(axis))


def n_devices() -> int:
    return jax.device_count()


def data_shards(mesh: Mesh) -> int:
    """Number of shards along the data axis (NOT the total device count —
    on a 2-D data×model mesh only the data axis splits the batch)."""
    return int(mesh.shape[DATA_AXIS])


def placement_for_batch(mesh: Mesh, n_examples: int) -> NamedSharding:
    """Placement policy for a batch of n examples: shard dim 0 over the
    data axis when divisible, otherwise fall back to replicated (the tail
    batch of an epoch) — still correct, just not distributed. The single
    source of truth for training AND serving paths."""
    if n_examples % data_shards(mesh) == 0:
        return batch_sharded(mesh)
    return replicated(mesh)


def pad_wrap(a: np.ndarray, multiple: int) -> np.ndarray:
    """Pad dim 0 up to the next multiple by cyclically repeating examples
    (np.resize wraps, correct even when the pad exceeds the batch). Used
    by every pad-and-slice serving/training path so the policy lives in
    one place."""
    n = a.shape[0]
    pad = (-n) % multiple
    return np.resize(a, (n + pad,) + a.shape[1:]) if pad else a
