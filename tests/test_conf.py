"""Config DSL + JSON serde tests (reference: nn/conf serde + regression
tests for configuration.json round trips)."""

import dataclasses

import pytest

from deeplearning4j_tpu.nn.conf import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    GravesLSTM,
    InputType,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
    Updater,
)
from deeplearning4j_tpu.nn.conf.inputs import ConvolutionalInput, FeedForwardInput
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor,
    FlatToCnnPreProcessor,
    RnnToFeedForwardPreProcessor,
)


def lenet_conf():
    return (
        NeuralNetConfiguration.builder()
        .seed(123)
        .updater(Updater.NESTEROVS)
        .learning_rate(0.01)
        .momentum(0.9)
        .weight_init("xavier")
        .list()
        .layer(ConvolutionLayer(kernel_size=(5, 5), n_out=20, activation="identity"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(ConvolutionLayer(kernel_size=(5, 5), n_out=50, activation="identity"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=500, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.convolutional_flat(28, 28, 1))
        .build()
    )


def test_global_defaults_inherited():
    conf = lenet_conf()
    # dense layer had explicit activation; weight_init inherited
    assert conf.layers[4].weight_init == "xavier"
    assert conf.layers[4].activation == "relu"
    assert conf.layers[0].activation == "identity"
    assert conf.net_conf.updater == "nesterovs"
    assert conf.net_conf.momentum == 0.9


def test_shape_inference_lenet():
    conf = lenet_conf()
    # conv1: 28 -> 24, pool -> 12, conv2 -> 8, pool -> 4
    assert conf.layers[0].n_in == 1
    assert conf.layers[2].n_in == 20
    assert conf.layers[4].n_in == 4 * 4 * 50
    assert conf.layers[5].n_in == 500
    # automatic preprocessors: flat->cnn at 0, cnn->ff at 4
    assert isinstance(conf.preprocessors["0"], FlatToCnnPreProcessor)
    assert isinstance(conf.preprocessors["4"], CnnToFeedForwardPreProcessor)


def test_input_types_per_layer():
    conf = lenet_conf()
    its = conf.input_types_per_layer()
    assert isinstance(its[0], ConvolutionalInput)
    assert (its[0].height, its[0].width, its[0].channels) == (28, 28, 1)
    assert isinstance(its[4], FeedForwardInput)
    assert its[4].size == 800


def test_json_round_trip():
    conf = lenet_conf()
    s = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(s)
    assert conf2.to_json() == s
    assert len(conf2.layers) == 6
    assert isinstance(conf2.layers[0], ConvolutionLayer)
    assert conf2.layers[0].n_out == 20
    assert list(conf2.layers[0].kernel_size) == [5, 5]
    assert conf2.net_conf.learning_rate == 0.01
    assert isinstance(conf2.preprocessors["0"], FlatToCnnPreProcessor)


def test_rnn_conf_inference():
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(GravesLSTM(n_out=64, activation="tanh"))
        .layer(RnnOutputLayer(n_out=10, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.recurrent(32))
        .build()
    )
    assert conf.layers[0].n_in == 32
    assert conf.layers[1].n_in == 64


def test_rnn_to_dense_preprocessor():
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(GravesLSTM(n_out=8))
        .layer(DenseLayer(n_out=4))
        .set_input_type(InputType.recurrent(5))
        .build()
    )
    assert isinstance(conf.preprocessors["1"], RnnToFeedForwardPreProcessor)


def test_manual_n_in_wiring_without_input_type():
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(DenseLayer(n_in=10, n_out=20))
        .layer(DenseLayer(n_out=5))
        .layer(OutputLayer(n_out=3))
        .build()
    )
    assert conf.layers[1].n_in == 20
    assert conf.layers[2].n_in == 5


def test_batchnorm_n_in_from_cnn():
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=8))
        .layer(BatchNormalization())
        .layer(OutputLayer(n_out=2, activation="softmax"))
        .set_input_type(InputType.convolutional(10, 10, 3))
        .build()
    )
    assert conf.layers[1].n_in == 8


def test_same_mode_shapes():
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(ConvolutionLayer(kernel_size=(3, 3), stride=(2, 2), n_out=4,
                                convolution_mode="same"))
        .layer(OutputLayer(n_out=2, activation="softmax"))
        .set_input_type(InputType.convolutional(9, 9, 1))
        .build()
    )
    its = conf.input_types_per_layer()
    # ceil(9/2) = 5
    assert (its[1].size) == 5 * 5 * 4


def test_unknown_type_tag_raises():
    with pytest.raises(ValueError):
        MultiLayerConfiguration.from_json('{"type": "layer.bogus_thing"}')


def test_yaml_round_trip():
    """reference: NeuralNetConfiguration toYaml/fromYaml."""
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration

    conf = (NeuralNetConfiguration.builder().seed(9).updater("adam")
            .learning_rate(0.01).list()
            .layer(DenseLayer(n_out=7, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(5)).build())
    text = conf.to_yaml()
    assert "layer.dense" in text
    back = type(conf).from_yaml(text)
    assert back.to_json() == conf.to_json()
