"""Graph package: Graph/walkers/DeepWalk (reference: deeplearning4j-graph
tests — walk validity, embedding quality on a clustered graph)."""

import numpy as np
import pytest

from deeplearning4j_tpu.graph import DeepWalk, Graph, RandomWalkIterator
from deeplearning4j_tpu.graph.walkers import NoEdgeHandling


def _two_cliques(k=6, bridge=True):
    """Two k-cliques joined by one bridge edge."""
    g = Graph(2 * k)
    for base in (0, k):
        for i in range(k):
            for j in range(i + 1, k):
                g.add_edge(base + i, base + j)
    if bridge:
        g.add_edge(k - 1, k)
    return g


def test_graph_and_walks():
    g = _two_cliques()
    assert g.degree(0) == 5
    walks = list(RandomWalkIterator(g, walk_length=10, seed=1))
    assert len(walks) == 12
    for w in walks:
        assert len(w) == 11
        for a, b in zip(w, w[1:]):
            assert b in g.neighbors(a), f"invalid hop {a}->{b}"


def test_dead_end_handling():
    g = Graph(2, directed=True)
    g.add_edge(0, 1)  # vertex 1 has no outgoing edge
    it = RandomWalkIterator(g, 4, seed=0,
                            no_edge_handling=NoEdgeHandling.SELF_LOOP)
    w = it.walk_from(0)
    assert len(w) == 5 and w[-1] == 1  # parked at the sink
    it = RandomWalkIterator(g, 4, seed=0,
                            no_edge_handling=NoEdgeHandling.CUTOFF)
    assert it.walk_from(0) == [0, 1]
    it = RandomWalkIterator(g, 4, seed=0,
                            no_edge_handling=NoEdgeHandling.EXCEPTION)
    with pytest.raises(RuntimeError):
        it.walk_from(0)


def test_deepwalk_separates_cliques():
    g = _two_cliques(k=6)
    dw = DeepWalk(vector_size=16, window_size=4, walks_per_vertex=8,
                  learning_rate=0.05, seed=3, batch_size=512)
    vectors = dw.fit(g, walk_length=20)
    # intra-clique similarity dominates inter-clique (skip the bridge
    # endpoints, whose walks straddle both cliques)
    intra, inter = [], []
    for a in range(0, 4):
        for b in range(1, 4):
            if a != b:
                intra.append(vectors.similarity(a, b))
        for b in range(6, 10):
            inter.append(vectors.similarity(a, b))
    assert np.mean(intra) > np.mean(inter) + 0.2, (
        np.mean(intra), np.mean(inter))
    # nearest neighbors of a clique-0 vertex are in clique 0
    near = vectors.verts_nearest(1, top_n=3)
    assert all(v < 6 for v in near), near
    assert vectors.vertex_vector(0).shape == (16,)
