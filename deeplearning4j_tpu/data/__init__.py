"""Data pipeline: DataSet container, iterators, async prefetch, dataset
fetchers.

Analog of the reference's DataSet/DataSetIterator framework
(deeplearning4j-nn datasets/ + deeplearning4j-core datasets/iterator/impl/).
"""

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.data.iterators import (
    AsyncDataSetIterator,
    DataSetIterator,
    ExistingDataSetIterator,
    ListDataSetIterator,
    MultipleEpochsIterator,
)
from deeplearning4j_tpu.data.fetchers import (
    CifarDataSetIterator,
    IrisDataSetIterator,
    LFWDataSetIterator,
)
