"""Random-walk sequence generators (reference: graph/iterator/
RandomWalkIterator.java + WeightedWalkIterator — fixed-length walks
starting from every vertex, with a NoEdgeHandling policy for dead ends)."""

from __future__ import annotations

from typing import Iterator, List

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph


class NoEdgeHandling:
    SELF_LOOP = "self_loop"          # stay at the vertex
    EXCEPTION = "exception"
    CUTOFF = "cutoff"                # end the walk early


class RandomWalkIterator:
    """Yields one fixed-length walk per start vertex per epoch, in
    shuffled vertex order (reference semantics)."""

    def __init__(self, graph: Graph, walk_length: int,
                 weighted: bool = False, seed: int = 0,
                 no_edge_handling: str = NoEdgeHandling.SELF_LOOP):
        self.graph = graph
        self.walk_length = int(walk_length)
        self.weighted = weighted
        self.no_edge = no_edge_handling
        self._rng = np.random.default_rng(seed)

    def walk_from(self, start: int) -> List[int]:
        walk = [start]
        v = start
        for _ in range(self.walk_length):
            nxt = self.graph.random_neighbor(v, self._rng, self.weighted)
            if nxt is None:
                if self.no_edge == NoEdgeHandling.EXCEPTION:
                    raise RuntimeError(f"vertex {v} has no outgoing edges")
                if self.no_edge == NoEdgeHandling.CUTOFF:
                    break
                nxt = v  # self loop
            walk.append(nxt)
            v = nxt
        return walk

    def __iter__(self) -> Iterator[List[int]]:
        order = self._rng.permutation(self.graph.num_vertices)
        for start in order:
            yield self.walk_from(int(start))


class Node2VecWalkIterator:
    """Biased 2nd-order walks (Grover & Leskovec node2vec; the reference's
    models/node2vec/Node2Vec.java is a deprecated stub — this is the real
    algorithm the stub pointed at). From edge (prev -> cur), the next hop
    x is drawn with unnormalized probability
        1/p  if x == prev        (return)
        1    if x ~ prev         (BFS-ish: stays near)
        1/q  otherwise           (DFS-ish: explores outward)
    times the edge weight when `weighted`. p == q == 1 degenerates to
    RandomWalkIterator's uniform walks."""

    def __init__(self, graph: Graph, walk_length: int, *, p: float = 1.0,
                 q: float = 1.0, weighted: bool = False, seed: int = 0,
                 no_edge_handling: str = NoEdgeHandling.SELF_LOOP):
        self.graph = graph
        self.walk_length = int(walk_length)
        self.p = float(p)
        self.q = float(q)
        self.weighted = weighted
        self.no_edge = no_edge_handling
        self._rng = np.random.default_rng(seed)
        # adjacency sets for the O(1) "is x a neighbor of prev" probe
        self._nbr_sets = [set(graph.neighbors(v))
                          for v in range(graph.num_vertices)]

    def walk_from(self, start: int) -> List[int]:
        walk = [start]
        prev, cur = None, start
        for _ in range(self.walk_length):
            nbrs = self.graph.neighbors(cur)
            if not nbrs:
                if self.no_edge == NoEdgeHandling.EXCEPTION:
                    raise RuntimeError(f"vertex {cur} has no outgoing edges")
                if self.no_edge == NoEdgeHandling.CUTOFF:
                    break
                walk.append(cur)  # self loop
                prev = cur
                continue
            w = (np.asarray(self.graph.weights(cur), np.float64)
                 if self.weighted else np.ones(len(nbrs)))
            if prev is not None:
                prev_nbrs = self._nbr_sets[prev]
                bias = np.asarray(
                    [1.0 / self.p if x == prev
                     else (1.0 if x in prev_nbrs else 1.0 / self.q)
                     for x in nbrs])
                w = w * bias
            w = w / w.sum()
            nxt = int(self._rng.choice(len(nbrs), p=w))
            nxt = nbrs[nxt]
            walk.append(nxt)
            prev, cur = cur, nxt
        return walk

    def __iter__(self) -> Iterator[List[int]]:
        order = self._rng.permutation(self.graph.num_vertices)
        for start in order:
            yield self.walk_from(int(start))
