"""Tokenization SPI.

Analog of the reference's text/tokenization/ (TokenizerFactory SPI,
DefaultTokenizerFactory, NGramTokenizerFactory, CommonPreprocessor —
deeplearning4j-nlp/.../text/tokenization/tokenizerfactory/). Language
plugins ride the same SPI: CJKTokenizerFactory below (dictionary-free
char-class runs + bigrams) and the Japanese lattice segmenter in
nlp/japanese.py (the deeplearning4j-nlp-japanese slot); see README "CJK
tokenization" for the scope rationale.
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional

# single source of the CJK/word character classes: the run tokenizer and
# the Japanese lattice's per-char classifier must never drift apart
CJK_CHAR_RANGES = (
    ("han", "㐀-䶿一-鿿豈-﫿"),
    ("hiragana", "぀-ゟ"),
    ("katakana", "゠-ヿㇰ-ㇿ"),
    ("hangul", "가-힯ᄀ-ᇿ"),
    ("word", "A-Za-z0-9_"),
)


class TokenPreProcess:
    """Per-token normalization hook (reference: TokenPreProcess)."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits-preserving (reference:
    text/tokenization/tokenizer/preprocessor/CommonPreprocessor.java)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token).lower()


class Tokenizer:
    def __init__(self, tokens: List[str]):
        self._tokens = tokens

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    def count_tokens(self) -> int:
        return len(self._tokens)

    def __iter__(self):
        return iter(self._tokens)


class TokenizerFactory:
    """SPI: create(text) -> Tokenizer (reference: TokenizerFactory)."""

    def __init__(self):
        self._pre: Optional[TokenPreProcess] = None

    def set_token_pre_processor(self, pre: TokenPreProcess) -> "TokenizerFactory":
        self._pre = pre
        return self

    def _apply_pre(self, tokens: List[str]) -> List[str]:
        if self._pre is None:
            return tokens
        out = [self._pre.pre_process(t) for t in tokens]
        return [t for t in out if t]

    def create(self, text: str) -> Tokenizer:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    """Whitespace tokenization (reference: DefaultTokenizerFactory wraps
    Java's StreamTokenizer; whitespace split is the effective behavior)."""

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(self._apply_pre(text.split()))


class NGramTokenizerFactory(TokenizerFactory):
    """Emit all n-grams for n in [min_n, max_n] joined by spaces
    (reference: NGramTokenizerFactory)."""

    def __init__(self, min_n: int = 1, max_n: int = 1):
        super().__init__()
        self.min_n = int(min_n)
        self.max_n = int(max_n)

    def create(self, text: str) -> Tokenizer:
        base = self._apply_pre(text.split())
        out: List[str] = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(base) - n + 1):
                out.append(" ".join(base[i : i + n]))
        return Tokenizer(out)


class CJKTokenizerFactory(TokenizerFactory):
    """Language plugin for unsegmented CJK text (reference:
    deeplearning4j-nlp-japanese bundles a Kuromoji fork, -korean a KOMORAN
    wrapper — 81 main files of bundled morphological analyzers; this
    framework ships a dictionary-free analyzer on the same SPI instead,
    and a full morphological analyzer plugs into the identical slot).

    Segmentation: text is split into runs by character class (han,
    hiragana, katakana, hangul, latin/digit words); han and hangul runs
    are additionally emitted as overlapping bigrams (the Lucene
    CJKAnalyzer strategy — robust retrieval/embedding units without a
    lexicon), kana runs and latin words as whole tokens.

    ``bigrams=False`` keeps whole runs (closer to word2vec preprocessing
    for pre-segmented corpora)."""

    _CLASSES = tuple(
        (name, re.compile(f"[{body}]+"))
        for name, body in CJK_CHAR_RANGES
    )

    def __init__(self, bigrams: bool = True):
        super().__init__()
        self.bigrams = bool(bigrams)

    def create(self, text: str) -> Tokenizer:
        spans: List[tuple] = []  # (start, kind, run)
        for kind, pat in self._CLASSES:
            for m in pat.finditer(text):
                spans.append((m.start(), kind, m.group()))
        spans.sort()
        out: List[str] = []
        for _, kind, run in spans:
            if (self.bigrams and kind in ("han", "hangul")
                    and len(run) > 1):
                out.extend(run[i:i + 2] for i in range(len(run) - 1))
            else:
                out.append(run)
        return Tokenizer(self._apply_pre(out))


class SentenceIterator:
    """Stream of sentences/documents (reference: text/sentenceiterator/).
    Any iterable of strings works; this wrapper adds reset()."""

    def __init__(self, sentences):
        self._sentences = list(sentences)

    def __iter__(self):
        return iter(self._sentences)

    def reset(self):
        pass


class LabelAwareSentenceIterator(SentenceIterator):
    """Sentences with document labels, for ParagraphVectors (reference:
    text/documentiterator/LabelAwareIterator)."""

    def __init__(self, sentences, labels):
        super().__init__(sentences)
        self.labels = list(labels)
        if len(self.labels) != len(self._sentences):
            raise ValueError("labels and sentences must align")

    def labeled(self):
        return zip(self._sentences, self.labels)
