"""Benchmark entry point — prints ONE JSON line.

Headline: ResNet-50 training images/sec/chip (BASELINE.md metric of
record) with an analytic-MFU estimate; the `workloads` field carries the
full table (LeNet-MNIST images/sec, GravesLSTM char-rnn tokens/sec, each
with its own MFU, plus `parallel_inference` serving requests/sec/chip
with p50/p99 latency).

Protocol (BASELINE.md): synthetic data (BenchmarkDataSetIterator
equivalent) to exclude ETL; public fit() API drives every workload;
steady-state steps timed after a warmup fit that includes compilation;
bf16 compute policy on TPU, f32 on CPU. The reference publishes no numbers
(BASELINE.json published={}), so vs_baseline is null — an honest "no
published baseline", not a self-graded 1.0.

Wedge-proofing: the device tunnel on this box can wedge indefinitely (a
bare backend touch hangs, no error). The orchestrator therefore never
touches the jax backend itself; it runs (a) a watchdog probe subprocess
(tiny matmul + scalar readback) under a hard deadline, then (b) each
workload in its own subprocess with a per-workload timeout and an overall
deadline. One hung workload costs its timeout, not the round: the
headline JSON is always printed, with per-workload errors for whatever
did not finish ("timeout", "rc=N ...", or "skipped: ...") and
`infra_error: tunnel_wedged` when the probe itself never comes back.
"""

import json
import os
import subprocess
import sys
import time

import jax  # import alone is safe; only backend *use* can wedge
import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ExistingDataSetIterator
from deeplearning4j_tpu.utils.flops import (
    peak_flops_per_chip,
    train_step_flops_for,
)


def _onehot(rng, n, k):
    y = np.zeros((n, k), np.float32)
    y[np.arange(n), rng.integers(0, k, n)] = 1.0
    return y


def _device_dataset(x, y) -> DataSet:
    """Pre-stage the synthetic batch in HBM — the benchmark protocol
    excludes ETL (BenchmarkDataSetIterator equivalent), and re-uploading
    the same batch every step would measure the host link, not the chip."""
    import jax

    return DataSet(jax.device_put(x), jax.device_put(y))


def _step_flops(net_factory, batch, timesteps: int = 16):
    """Model FLOPs of one optimizer step for a workload's MFU, sourced
    from the jaxpr cost model of the REAL step program (helpers
    disabled during the trace — model FLOPs are implementation-
    independent), falling back to the analytic per-layer estimate.
    Returns (flops_per_step, source); the source is recorded next to
    every MFU so a FLOP-accounting change can never masquerade as a
    speedup (the vs_baseline drift check reads it)."""
    net = net_factory()
    try:
        return train_step_flops_for(net, batch, timesteps=timesteps)
    finally:
        del net  # free the throwaway params before the timed runs


def _doctor_refusal(conf, unit):
    """Honesty mechanism (the PR-2 A/B precedent, applied to model
    validity): a workload whose model config fails the static doctor at
    ERROR severity must not headline a throughput number — a broken
    graph can trace into something fast and wrong. Returns the refusal
    dict to emit instead of benching, or None when the model is sound."""
    from deeplearning4j_tpu.analysis import doctor_errors

    errs = doctor_errors(conf)
    if not errs:
        return None
    return {
        "value": None,
        "unit": unit,
        "doctor_errors": [f"{f.name}: {f.message}" for f in errs],
        "note": "model failed `cli doctor` at ERROR severity; refusing "
                "to headline a broken model's throughput",
    }


def _sync(net):
    """Force completion. block_until_ready does not actually block through
    the axon tunnel, so synchronize with a host readback of the last
    step's score (a scalar — negligible transfer)."""
    if net._score is not None:
        float(np.asarray(net._score))
    else:
        jax.block_until_ready(net.params_list)


def _time_fit(net, make_iter, steps, warmup=True, reps=3):
    """Latency-cancelling timing: warmup (compile), then time fits of N and
    2N steps and report t(2N) - t(N) — the constant dispatch/readback
    overhead of the device tunnel cancels out. The warmup runs a full
    `steps`-length fit so every program the timed runs will use (fused
    multi-batch chunks AND any per-batch tail) is compiled before t1;
    pass warmup=False on repeat measurements of an already-warm net.

    The marginal difference is taken as the MEDIAN of `reps` t-pairs:
    tunnel latency varies run to run by more than some workloads' whole
    measurement window (a single pair measured resnet50 anywhere between
    28% and 42% MFU)."""

    def timed(k):
        it = make_iter(k)
        before = net.iteration
        t0 = time.perf_counter()
        net.fit(it, epochs=1, async_prefetch=True)
        _sync(net)
        dt = time.perf_counter() - t0
        return dt, net.iteration - before

    if warmup:  # same chunking pattern as the timed run
        timed(steps)
    trials = []
    for _ in range(max(1, reps)):
        t1, n1 = timed(steps)
        t2, n2 = timed(2 * steps)
        assert n2 == 2 * n1, (n1, n2)
        trials.append((max(t2 - t1, 1e-9), n1))
    trials.sort()
    return trials[len(trials) // 2]


def _run_ab(run, variants, ops):
    """Shared A/B harness for helper-vs-builtin workloads: snapshots and
    restores the helper kill-switch state, runs each (name, helpers_on)
    variant, and detects a MID-RUN auto-disable — a helper fn that raised
    was disabled by the SPI and the layers fell back, so that variant
    measured builtin throughput and must not be reported under the
    kernel's name (the availability lie the A/B exists to prevent).
    Returns (results, errors)."""
    from deeplearning4j_tpu.ops.helpers import (
        helper_enabled,
        set_helper_enabled,
    )

    results, errors = {}, {}
    saved = {op: helper_enabled(op) for op in ops}
    try:
        for name, on in variants:
            try:
                results[name] = run(on)
            except Exception as e:  # e.g. pallas lowering failure
                import traceback

                traceback.print_exc(file=sys.stderr)
                errors[name] = f"{type(e).__name__}: {e}"
                continue
            if on and any(helper_enabled(op) is False for op in ops):
                results.pop(name, None)
                errors[name] = ("helper disabled mid-run (fn raised; see "
                                "log) — measured value was the builtin "
                                "fallback and is not reported as the kernel")
                for op in ops:
                    set_helper_enabled(op, True)
    finally:
        # restore the caller's kill-switch state, don't force-enable
        for op, enabled in saved.items():
            if enabled is not None:
                set_helper_enabled(op, enabled)
    return results, errors


def bench_resnet50(batch=128, steps=8, image_size=224, classes=1000):
    """images/sec/chip for the headline workload. A/B-measures BOTH conv/BN
    paths in the same run — the Pallas conv+BN-stats epilogue fusion
    registered in the conv2d/batch_norm Helper slots
    (ops/pallas_conv_bn.py) and the default XLA lowering — the headline is
    the faster, the loser is reported under `vs_alternate`: the same
    honesty mechanism the char-LSTM workload uses (a kernel that
    compiles-but-loses stays visible instead of silently winning on
    availability)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.resnet import resnet50_conf
    from deeplearning4j_tpu.nn.compgraph import ComputationGraph
    from deeplearning4j_tpu.ops.helpers import get_helper, set_helper_enabled

    on_tpu = jax.default_backend() not in ("cpu",)
    if not on_tpu:  # CPU smoke config — full ResNet-50 on CPU is pointless
        batch, steps, image_size, classes = 8, 4, 64, 10
        # CPU-interpret A/B: run the Pallas kernels through the pallas
        # interpreter so helper-on vs helper-off measures the SAME two
        # code paths the TPU round A/Bs (stash wiring, custom VJPs,
        # fused BN backward) — correctness + not-worse evidence off-TPU,
        # never reported as silicon perf (mfu stays null on cpu)
        from deeplearning4j_tpu.ops import pallas_conv_bn as _pcb

        _pcb.set_interpret(True)
    conf = resnet50_conf(num_classes=classes, image_size=image_size,
                         precision="bf16" if on_tpu else "f32")
    refusal = _doctor_refusal(conf, "images/sec/chip")
    if refusal is not None:
        return refusal
    # NO fused multi-batch dispatch here: profiled 98.2 vs 48.8 ms/step
    # device time (PROFILE_resnet50.md) — the scan-carried params defeat
    # XLA's layout/fusion choices on this compute-bound model, while
    # dispatch overhead (the thing fusing removes) is ~5ms/step noise
    rng = np.random.default_rng(0)
    x = rng.random((batch, image_size, image_size, 3), np.float32)
    ds = _device_dataset(x, _onehot(rng, batch, classes))
    step_flops, flops_source = _step_flops(
        lambda: ComputationGraph(conf).init(), batch)

    def run(helpers_on):
        for op in ("conv2d", "batch_norm", "bn_backward"):
            set_helper_enabled(op, helpers_on)
        net = ComputationGraph(conf).init()  # fresh net => fresh trace
        if step_flops:  # devprof's live MFU gauges ride the same model
            net.set_model_flops_per_example(step_flops / batch,
                                            flops_source)
        dt, n_steps = _time_fit(
            net, lambda k: ExistingDataSetIterator([ds] * k), steps,
            reps=3 if on_tpu else 1)
        return batch * n_steps / dt, dt, n_steps

    # a representative stage-2 trunk shape; the probe says whether the
    # Pallas path exists at all on this backend (CPU: never)
    probe = get_helper(
        "conv2d", kernel=(1, 1), stride=(1, 1), dilation=(1, 1), same=True,
        has_bias=False, activation="identity", dtype=jnp.bfloat16,
        n_in=64, n_out=256, x_shape=(batch, 56, 56, 64), training=True)
    variants = [("xla_builtin", False)]
    if probe is not None:
        variants.insert(0, ("pallas_conv_bn_stats", True))
    results, errors = _run_ab(run, variants,
                              ("conv2d", "batch_norm", "bn_backward"))
    if not on_tpu:
        from deeplearning4j_tpu.ops import pallas_conv_bn as _pcb

        _pcb.set_interpret(False)
    if not results:
        raise RuntimeError(f"both conv/BN paths failed: {errors}")
    kernel = max(results, key=lambda k: results[k][0])
    ips, dt, n_steps = results[kernel]
    mfu = ((step_flops * n_steps / dt) / peak_flops_per_chip()
           if on_tpu and step_flops else None)
    alternates = {k: round(v[0], 2) for k, v in results.items() if k != kernel}
    return {
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "batch": batch,
        "steps": steps,
        "image_size": image_size,
        "classes": classes,
        # fit(async_prefetch=True) routes through the staged input
        # pipeline: batches flow via DevicePrefetchIterator (the protocol
        # still pre-stages them in HBM, so the device_put the prefetch
        # worker issues is a same-device no-op — ETL stays excluded)
        "input_pipeline": "device_prefetch(depth=2, pre-staged batches)",
        "kernel": kernel,
        # pallas_interpret marks a CPU round whose kernel arm ran the
        # interpreter, so the A/B is read as correctness/not-worse
        # evidence and never as silicon perf
        **({"pallas_interpret": True} if not on_tpu else {}),
        "vs_alternate": alternates,
        **({"kernel_errors": errors} if errors else {}),
        "seconds": round(dt, 3),
        "model_flops_per_step": step_flops,
        "flops_source": flops_source,
        "mfu": None if mfu is None else round(mfu, 4),
    }


def bench_lenet(batch=512, steps=30):
    from deeplearning4j_tpu.models.lenet import lenet_conf
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    on_tpu = jax.default_backend() not in ("cpu",)
    conf = lenet_conf(precision="bf16" if on_tpu else "f32")
    net = MultiLayerNetwork(conf).init().set_fused_steps(10)
    step_flops, flops_source = train_step_flops_for(net, batch)
    if step_flops:
        net.set_model_flops_per_example(step_flops / batch, flops_source)
    rng = np.random.default_rng(0)
    ds = _device_dataset(rng.random((batch, 784), np.float32),
                         _onehot(rng, batch, 10))
    dt, n_steps = _time_fit(net, lambda k: ExistingDataSetIterator([ds] * k), steps,
                            reps=3 if on_tpu else 1)
    ips = batch * n_steps / dt
    mfu = ((step_flops * n_steps / dt) / peak_flops_per_chip()
           if on_tpu and step_flops else None)
    return {
        "value": round(ips, 1),
        "unit": "images/sec/chip",
        "batch": batch,
        "steps": steps,
        "seconds": round(dt, 3),
        "model_flops_per_step": step_flops,
        "flops_source": flops_source,
        "mfu": None if mfu is None else round(mfu, 4),
    }


def bench_char_lstm(batch=64, seq_len=200, tbptt=50, vocab=77, hidden=200,
                    steps=96, fused=24, reps=3):
    """tokens/sec through the TBPTT fit path (each fit batch = seq_len/tbptt
    optimizer steps, all segments + `steps` consecutive batches in one
    jitted dispatch via set_fused_steps). A/B-measures BOTH kernels —
    the fused Pallas LSTM helper and the default `lax.scan` path — in the
    same run; the headline is the faster, the loser is reported under
    `vs_alternate` so a kernel that compiles-but-loses is visible
    (round-4 lesson: availability-based selection hid a regression).
    Ground truth when wall-clock ties through the tunnel: the xplane
    profile (PROFILE_char_lstm.md) — pallas 31.7ms vs scan 58.4ms device
    time over 20 identical batches."""
    from deeplearning4j_tpu.models.charlstm import char_lstm_conf
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.ops.helpers import get_helper, set_helper_enabled

    on_tpu = jax.default_backend() not in ("cpu",)
    if not on_tpu:
        batch, seq_len, steps, hidden = 16, 100, 3, 64
        # reps=3 even on CPU: the first TIMED fit can pay a compile the
        # warmup does not cover, driving t(2N)-t(N) ≤ 0 (clamped to the
        # 1e-9 floor = an absurd headline); the median over 3 t-pairs is
        # the designed defense and the post-warmup pairs are cheap here
        fused, reps = 3, 3
        # CPU-interpret A/B — same rationale as bench_resnet50: both
        # kernel arms measurable off-TPU, reported as pallas_interpret
        from deeplearning4j_tpu.ops import pallas_lstm as _plstm

        _plstm._INTERPRET = True

    rng = np.random.default_rng(0)
    idx = rng.integers(0, vocab, (batch, seq_len))
    x = np.eye(vocab, dtype=np.float32)[idx]
    yidx = rng.integers(0, vocab, (batch, seq_len))
    y = np.eye(vocab, dtype=np.float32)[yidx]
    ds = _device_dataset(x, y)
    segments = -(-seq_len // tbptt)
    conf0 = char_lstm_conf(vocab_size=vocab, hidden=hidden,
                           tbptt_length=tbptt,
                           precision="bf16" if on_tpu else "f32")
    refusal = _doctor_refusal(conf0, "tokens/sec/chip")
    if refusal is not None:
        return refusal
    # full-sequence step FLOPs (the TBPTT segmentation splits the same
    # matmuls across dispatches; it does not change their count)
    step_flops, flops_source = _step_flops(
        lambda: MultiLayerNetwork(conf0).init(), batch, timesteps=seq_len)

    def run(kernel_on):
        set_helper_enabled("lstm_sequence", kernel_on)
        conf = char_lstm_conf(vocab_size=vocab, hidden=hidden,
                              tbptt_length=tbptt,
                              precision="bf16" if on_tpu else "f32")
        net = MultiLayerNetwork(conf).init().set_fused_steps(fused)
        if step_flops:
            net.set_model_flops_per_example(step_flops / batch,
                                            flops_source)
        dt, n_steps = _time_fit(
            net, lambda k: ExistingDataSetIterator([ds] * k), steps,
            reps=reps)
        fit_batches = n_steps / segments
        return conf, batch * seq_len * fit_batches / dt, dt, fit_batches

    probe = get_helper("lstm_sequence", peephole=True, mask=None,
                       gate_act="sigmoid", cell_act="tanh", reverse=False)
    variants = [("lax_scan", False)]
    if probe is not None:
        variants.insert(0, ("pallas_fused_lstm", True))
    results, errors = _run_ab(run, variants, ("lstm_sequence",))
    if not on_tpu:
        from deeplearning4j_tpu.ops import pallas_lstm as _plstm

        _plstm._INTERPRET = False
    if not results:
        raise RuntimeError(f"both kernels failed: {errors}")
    kernel = max(results, key=lambda k: results[k][1])
    conf, tokens, dt, fit_batches = results[kernel]
    mfu = (step_flops * fit_batches / dt / peak_flops_per_chip()
           if on_tpu and step_flops else None)
    alternates = {k: round(v[1], 1) for k, v in results.items()
                  if k != kernel}
    return {
        "value": round(tokens, 1),
        "unit": "tokens/sec/chip",
        "batch": batch,
        "seq_len": seq_len,
        "tbptt": tbptt,
        "vocab": vocab,
        "hidden": hidden,
        "kernel": kernel,
        **({"pallas_interpret": True} if not on_tpu else {}),
        "vs_alternate": alternates,
        **({"kernel_errors": errors} if errors else {}),
        "seconds": round(dt, 3),
        "model_flops_per_step": step_flops,
        "flops_source": flops_source,
        "mfu": None if mfu is None else round(mfu, 4),
        # what "good" is: cuDNN-era fused LSTM training lands ~5-15% MFU
        # at these small-cell shapes; the round-2 scan path measured 0.007
        "mfu_reference": "cudnn-era fused LSTM ~0.05-0.15 at small cells",
    }


def bench_vgg16(batch=32, steps=6, image_size=224, classes=1000):
    """VGG16-via-Keras-import (BASELINE.md workload 5): the conf is built
    THROUGH the Keras 1.x importer (modelimport/keras.py), then trained on
    synthetic data — import path + training measured together."""
    from deeplearning4j_tpu.models.vgg16 import vgg16_conf
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    on_tpu = jax.default_backend() not in ("cpu",)
    if not on_tpu:
        batch, steps, image_size, classes = 4, 3, 32, 10
    conf = vgg16_conf(num_classes=classes, image_size=image_size,
                      precision="bf16" if on_tpu else "f32")
    net = MultiLayerNetwork(conf).init().set_fused_steps(3)
    step_flops, flops_source = train_step_flops_for(net, batch)
    if step_flops:
        net.set_model_flops_per_example(step_flops / batch, flops_source)
    rng = np.random.default_rng(0)
    x = rng.random((batch, image_size, image_size, 3), np.float32)
    ds = _device_dataset(x, _onehot(rng, batch, classes))
    dt, n_steps = _time_fit(net, lambda k: ExistingDataSetIterator([ds] * k), steps,
                            reps=3 if on_tpu else 1)
    ips = batch * n_steps / dt
    mfu = ((step_flops * n_steps / dt) / peak_flops_per_chip()
           if on_tpu and step_flops else None)
    return {
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "batch": batch,
        "image_size": image_size,
        "seconds": round(dt, 3),
        "model_flops_per_step": step_flops,
        "flops_source": flops_source,
        "mfu": None if mfu is None else round(mfu, 4),
    }


def bench_word2vec(vocab=10_000, n_sents=2_000, sent_len=40, batch=8192,
                   layer_size=128, negative=5):
    """Word2Vec skip-gram words/sec (BASELINE.md Word2Vec workload;
    reference hot loop: SkipGram.java:271 native aggregate ops). Synthetic
    Zipf corpus; measures the device update path + host batching, i.e.
    exactly what SequenceVectors.fit does after vocab construction."""
    from deeplearning4j_tpu.nlp.sequencevectors import (
        SequenceVectors,
        VectorsConfiguration,
    )

    on_tpu = jax.default_backend() not in ("cpu",)
    if not on_tpu:
        vocab, n_sents, batch, layer_size = 1_000, 200, 1024, 32
    rng = np.random.default_rng(0)
    p = 1.0 / np.arange(1, vocab + 1)
    p /= p.sum()
    words = [f"w{i}" for i in range(vocab)]
    sents = [
        [words[j] for j in rng.choice(vocab, p=p, size=sent_len)]
        for i in range(n_sents)
    ]
    conf = VectorsConfiguration(
        layer_size=layer_size, window=5, min_word_frequency=1, epochs=1,
        negative=negative, use_hierarchic_softmax=False, batch_size=batch,
        sampling=1e-3,
    )
    sv = SequenceVectors(conf, sents)
    sv.build_vocab()
    indexed = sv._index_sentences(sents)
    total_words = sum(int(s.size) for s in indexed)
    # warmup on the FULL corpus: the corpus-resident device path compiles
    # per corpus-size bucket, so a small-prefix warmup would leave the
    # full-size compile inside the timed region. Median of 3 timed runs —
    # the corpus upload rides the tunnel, whose latency varies run to run.
    sv.train_indexed(indexed)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        sv.train_indexed(indexed)
        float(np.asarray(sv.lookup.syn0[0, 0]))  # sync
        times.append(time.perf_counter() - t0)
    times.sort()
    dt = times[1]
    return {
        "value": round(total_words / dt, 1),
        "unit": "words/sec/chip",
        "vocab": vocab,
        "layer_size": layer_size,
        "negative": negative,
        "total_words": total_words,
        "seconds": round(dt, 3),
        # what "good" is: the original word2vec.c does ~0.1-1M words/sec
        # on a multicore host at this config; the reference's native
        # AggregateSkipGram path is the same order of magnitude
        "reference_point": "word2vec.c ~1e5-1e6 words/sec multicore",
    }


def bench_parallel_inference(max_batch=64, n_requests=512, clients=16,
                             n_in=128, hidden=256, classes=16):
    """Serving throughput/latency through the bucketed BATCHED
    ParallelInference path (the InferenceServer's engine): `clients`
    threads submit a mixed-size request stream — sizes 1..max_batch drawn
    zipf-ish (weight 1/size), the small-request-heavy profile of real
    serving traffic — and the workload reports requests/sec/chip plus
    p50/p99 request latency. warmup() precompiles every bucket first, so
    `forward_compiles_after_warmup` staying at 0 IS the bucketing win
    (before this path, every distinct fused group size was a fresh trace).
    Each latency sample ends at the caller's numpy readback (the dispatch
    thread materializes results host-side — the honest sync on this box,
    where block_until_ready does not block through the tunnel)."""
    import threading

    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
        Updater,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import (
        ParallelInference,
        data_parallel_mesh,
    )
    from deeplearning4j_tpu.utils.latency import LatencyTracker

    on_tpu = jax.default_backend() not in ("cpu",)
    if not on_tpu:
        n_requests, clients, hidden = 96, 8, 64
    conf = (
        NeuralNetConfiguration.builder().seed(7).updater(Updater.SGD)
        .learning_rate(0.05).weight_init("xavier")
        .precision("bf16" if on_tpu else "f32").list()
        .layer(DenseLayer(n_in=n_in, n_out=hidden, activation="relu"))
        .layer(DenseLayer(n_in=hidden, n_out=hidden, activation="relu"))
        .layer(OutputLayer(n_in=hidden, n_out=classes,
                           activation="softmax", loss="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    pi = ParallelInference(net, data_parallel_mesh(),
                           max_batch_size=max_batch, batch_timeout_ms=2.0)
    pi.warmup((n_in,))
    compiles_warm = int(net.output_compile_count)

    rng = np.random.default_rng(0)
    sizes = np.arange(1, max_batch + 1)
    p = 1.0 / sizes
    p /= p.sum()
    req_sizes = rng.choice(sizes, size=n_requests, p=p)
    reqs = [rng.standard_normal((int(s), n_in)).astype(np.float32)
            for s in req_sizes]

    lat = LatencyTracker(window=n_requests)
    next_idx = [0]
    idx_lock = threading.Lock()
    client_errors = []

    def client():
        try:
            while True:
                with idx_lock:
                    i = next_idx[0]
                    if i >= len(reqs):
                        return
                    next_idx[0] = i + 1
                t0 = time.perf_counter()
                out = pi.output(reqs[i])
                assert out.shape[0] == reqs[i].shape[0]
                lat.record(time.perf_counter() - t0)
        except BaseException as e:
            client_errors.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, name=f"dl4j-bench-client-{i}")
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if client_errors or lat.count != n_requests:
        # a silently-dead client would otherwise leave requests/sec counting
        # requests that were never served
        raise RuntimeError(
            f"served {lat.count}/{n_requests}; errors: {client_errors[:3]}")
    m = pi.metrics()
    pi.shutdown()
    snap = lat.snapshot()
    return {
        "value": round(n_requests / dt, 1),
        "unit": "requests/sec/chip",
        "examples_per_sec": round(int(req_sizes.sum()) / dt, 1),
        "p50_ms": snap["p50_ms"],
        "p99_ms": snap["p99_ms"],
        "clients": clients,
        "n_requests": n_requests,
        "distinct_request_sizes": int(len(set(req_sizes.tolist()))),
        "max_batch_size": max_batch,
        "buckets": m["buckets"],
        "batches": m["batches"],
        "bucket_hits": {str(k): v for k, v in m["bucket_hits"].items()},
        "forward_compiles_warmup": compiles_warm,
        "forward_compiles_after_warmup":
            m["forward_compiles"] - compiles_warm,
        "seconds": round(dt, 3),
    }


def bench_parallel_inference_overload(duration=3.0, n_in=64, hidden=64,
                                      classes=8, max_batch=4,
                                      queue_capacity=None, slo_ms=100.0,
                                      ledger_path=None):
    """Graceful degradation under sustained ~2x overload — the numbers
    the admission-control/load-shedding tier is graded on, recorded next
    to the throughput benches instead of only living in a slow test.
    Phase 1 saturates the pipeline with few enough closed-loop clients
    that nothing sheds (the measured capacity); phase 2 keeps ~2x the
    pipeline+queue's absorbable outstanding work in flight, so admission
    MUST shed the excess. Reported: shed rate, p99 latency of ADMITTED
    requests vs the SLO (overload must turn into fast 429s, not
    universal lateness), max queue depth vs capacity (boundedness), and
    the conservation law admitted == completed + shed + failed.

    The run additionally records a persistent run ledger
    (utils/runledger) with the default SLO rule pack derived from this
    workload's serving config — the soak gate: the verdict embeds which
    rules fired, `slo_ok` must stay True at the committed operating
    point, and the artifact replays offline via `cli slo --ledger
    <path> --check` / `cli metrics --ledger <path>`."""
    import threading

    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
        Updater,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import (
        ParallelInference,
        data_parallel_mesh,
    )
    from deeplearning4j_tpu.parallel.inference import (
        DeadlineExceeded,
        RequestRejected,
    )
    from deeplearning4j_tpu.utils import health as _health
    from deeplearning4j_tpu.utils import resourcemeter
    from deeplearning4j_tpu.utils.latency import LatencyTracker
    from deeplearning4j_tpu.utils.metrics import get_registry

    # two tenants ride the overload so the shed/books verdict is
    # per-customer, not just aggregate; metering attributes the forward
    # device time each tenant actually got
    resourcemeter.enable()
    tenants = ("gold", "free")

    on_tpu = jax.default_backend() not in ("cpu",)
    # queue_capacity=None → per-backend preset: a small CPU box needs a
    # shorter queue (and net) or GIL contention between the closed-loop
    # clients starves the dispatcher into shedding EVERYTHING, measuring
    # contention instead of admission control; an explicit value wins
    if queue_capacity is None:
        queue_capacity = 8 if on_tpu else 4
    if not on_tpu:
        hidden = 48
    # "2x overload" means outstanding work, not offered rate (closed-loop
    # clients self-throttle): the pipeline absorbs ~2 groups in flight
    # plus the queue, so 2x that many 1-row closed-loop clients keeps
    # admission permanently oversubscribed — the client count is DERIVED
    # from that, not a knob
    absorbable = 2 * max_batch + queue_capacity
    clients = 2 * absorbable
    conf = (
        NeuralNetConfiguration.builder().seed(7).updater(Updater.SGD)
        .learning_rate(0.05).weight_init("xavier")
        .precision("bf16" if on_tpu else "f32").list()
        .layer(DenseLayer(n_in=n_in, n_out=hidden, activation="relu"))
        .layer(OutputLayer(n_in=hidden, n_out=classes,
                           activation="softmax", loss="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    pi = ParallelInference(net, data_parallel_mesh(),
                           max_batch_size=max_batch, batch_timeout_ms=1.0,
                           queue_capacity=queue_capacity,
                           handoff_capacity=1, default_deadline_ms=slo_ms,
                           component_prefix="bench_overload")
    pi.warmup((n_in,))
    # the soak ledger: continuous samples + the default rule pack for
    # THIS serving config, judged live on the recorder thread. Attached
    # AFTER warmup so the objective only grades traffic.
    import tempfile

    from deeplearning4j_tpu.analysis.slo import default_rule_pack
    from deeplearning4j_tpu.utils import runledger as _runledger

    if ledger_path is None:
        ledger_path = os.path.join(
            tempfile.gettempdir(),
            f"BENCH_overload_ledger_{os.getpid()}.jsonl")
    ledger = _runledger.RunLedger(
        ledger_path, sample_every=max(0.25, duration / 8.0),
        rules=default_rule_pack(
            serving={"default_deadline_ms": slo_ms,
                     "queue_capacity": queue_capacity,
                     "handoff_capacity": 1,
                     "component": "bench_overload"},
            sample_every=max(0.25, duration / 8.0),
            # per-tenant chip-budget burn rules ride the same ledger; a
            # whole chip per tenant is a generous bar this single-host
            # soak must stay under
            tenants={t: 1.0 for t in tenants}))
    _runledger.attach(ledger)
    rng = np.random.default_rng(0)
    reqs = [rng.standard_normal((1, n_in)).astype(np.float32)
            for _ in range(64)]
    lat = LatencyTracker(window=100_000)
    stop = threading.Event()
    max_depth = [0]
    client_errors = []

    def client(i, track):
        j = 0
        try:
            while not stop.is_set():
                j += 1
                t0 = time.perf_counter()
                try:
                    pi.output(reqs[(i * 31 + j) % len(reqs)],
                              tenant=tenants[i % len(tenants)])
                    if track:
                        lat.record(time.perf_counter() - t0)
                except (DeadlineExceeded, RequestRejected) as e:
                    # shed totals come from the metrics deltas; honor the
                    # server's Retry-After hint (bounded: a bench client
                    # must keep offering load)
                    stop.wait(min(getattr(e, "retry_after", 0.0), 0.005))
        except BaseException as e:  # noqa: BLE001 - reported, fails run
            client_errors.append(f"{type(e).__name__}: {e}")

    def run_phase(n_clients, seconds, track):
        stop.clear()
        threads = [threading.Thread(target=client, args=(i, track),
                                    daemon=True,
                                    name=f"dl4j-bench-ovl-{i}")
                   for i in range(n_clients)]
        before = pi.metrics()
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        while time.perf_counter() - t0 < seconds:
            max_depth[0] = max(max_depth[0], pi._q.qsize())
            time.sleep(0.005)
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
            if t.is_alive():
                # a wedged client would otherwise surface as a bogus
                # "conservation violated" (its request stays admitted
                # but unresolved when the books are read)
                client_errors.append(f"{t.name}: wedged past join budget")
        dt = time.perf_counter() - t0
        after = pi.metrics()
        return dt, {k: after[k] - before[k]
                    for k in ("admitted", "completed", "shed", "failed",
                              "rejected", "requests")}

    # phase 1: measured capacity — few clients, nothing sheds
    spend0 = resourcemeter.spend_table(get_registry().scalar_values())
    base_dt, base = run_phase(4, duration * 0.5, track=False)
    # phase 2: ~2x the absorbable outstanding work, shedding expected
    max_depth[0] = 0
    over_dt, over = run_phase(clients, duration, track=True)
    m = pi.metrics()
    spend1 = resourcemeter.spend_table(get_registry().scalar_values())
    tenant_cons = resourcemeter.conservation(
        get_registry().scalar_values())
    comps = _health.get_health().status()["components"]
    stalled = [k for k, v in comps.items()
               if k.startswith("bench_overload")
               and v.get("status") != "ok"]
    # close the ledger (final sample) BEFORE reading the verdict: the
    # rule states are part of the committed operating point — an
    # ERROR-severity firing here fails the soak gate
    ledger.close()
    slo_fired = ledger.rules.ever_fired()
    slo_fired_errors = ledger.rules.ever_fired("error")
    pi.shutdown()
    if client_errors:
        raise RuntimeError(f"overload client died: {client_errors[:3]}")
    if m["admitted"] != m["completed"] + m["shed"] + m["failed"]:
        # the books MUST balance — a leak here is a correctness bug, not
        # a perf number
        raise RuntimeError(f"conservation violated: {m}")
    bad_tenants = {t: b for t, b in m["tenants"].items()
                   if not b["conservation_ok"]}
    if bad_tenants or not tenant_cons["ok"]:
        # the PER-TENANT law and the spend sum-to-process-total check:
        # aggregate books can balance while one tenant leaks into
        # another — multi-tenant hosting is graded on the exact split
        raise RuntimeError(
            f"per-tenant conservation violated: books={bad_tenants} "
            f"spend={tenant_cons}")
    snap = lat.snapshot()
    capacity_rps = base["completed"] / base_dt
    offered = (over["requests"] or 1) / over_dt
    shed_total = over["shed"] + over["rejected"]
    return {
        "value": snap["p99_ms"],
        "unit": "p99_ms_admitted_under_overload",
        "slo_ms": slo_ms,
        "slo_met_p99": bool(snap["p99_ms"] is not None
                            and snap["p99_ms"] <= slo_ms),
        "capacity_requests_per_sec": round(capacity_rps, 1),
        "offered_requests_per_sec": round(offered, 1),
        "overdrive_outstanding": round(clients / absorbable, 2),
        "completed_per_sec": round(over["completed"] / over_dt, 1),
        "shed_total": shed_total,
        "shed_rate": round(shed_total / max(over["requests"], 1), 4),
        "shed_by": m["shed_by"],
        "max_queue_depth": max_depth[0],
        "queue_capacity": queue_capacity,
        "queue_bounded": bool(max_depth[0] <= queue_capacity),
        "watchdog_stalled_components": stalled,
        "clients": clients,
        "p50_ms": snap["p50_ms"],
        "seconds": round(base_dt + over_dt, 3),
        # the continuous-judgment half: rule verdicts from the run
        # ledger (replay: cli slo --ledger <path> --check)
        "slo": {
            "ledger": ledger_path,
            "run_id": ledger.run_id,
            "rules": [r.name for r in ledger.rules.rules],
            "fired": slo_fired,
            "fired_errors": slo_fired_errors,
        },
        "slo_ok": not slo_fired_errors,
        # per-tenant half of the verdict: exact books per customer plus
        # the serving device-seconds each one actually received
        "tenants": m["tenants"],
        "tenant_spend": {
            t: round(
                spend1.get(t, {}).get("device_seconds", {}).get(
                    resourcemeter.TIER_SERVING, 0.0)
                - spend0.get(t, {}).get("device_seconds", {}).get(
                    resourcemeter.TIER_SERVING, 0.0), 4)
            for t in tenants},
        "tenant_conservation": tenant_cons,
    }


def bench_decode(n_slots=8, duration=6.0, vocab=32, hidden=64,
                 slo_ms=None, seed=0):
    """Continuous-batching autoregressive decode (serving/decode.py):
    a sustained soak of zipf-length char-LSTM generate requests from two
    tenants (weighted 3:1) against one DecodeEngine, with a LIVE weight
    swap fired mid-soak. Reported: tokens/sec/chip, per-token latency
    (inter-token p50/p99, time-to-first-token separately — first tokens
    carry queue wait by design), mean/max slot occupancy, and the swap
    verdict: the inter-token p99 inside the swap window must meet the
    same SLO as the whole soak (the no-blip claim), with zero failed
    requests and exact per-tenant conservation books.

    `vs_alternate` is the honesty arm: the same request shapes served by
    the naive per-request loop (sequential `rnn_time_step`, batch=1 —
    what a server without continuous batching would do), so the headline
    is engine-vs-loop, not engine-vs-nothing."""
    import threading

    from deeplearning4j_tpu.models.charlstm import char_lstm_network
    from deeplearning4j_tpu.serving.decode import DecodeEngine
    from deeplearning4j_tpu.utils import resourcemeter
    from deeplearning4j_tpu.utils.latency import LatencyTracker
    from deeplearning4j_tpu.utils.metrics import get_registry

    # arm tenant spend metering: the verdict embeds per-tenant
    # device-seconds, and the fairness probe judges the split
    resourcemeter.enable()

    def _dec_sec(table, tenant):
        return table.get(tenant, {}).get(
            "device_seconds", {}).get(resourcemeter.TIER_DECODE, 0.0)

    on_tpu = jax.default_backend() not in ("cpu",)
    if slo_ms is None:
        # per-token SLO: measured steady-state ITL p99 is ~1 ms on the
        # 2-core CPU box (~2.5 ms inside the swap window) — 50 ms gives
        # box-contention headroom while still catching a real blip
        slo_ms = 20.0 if on_tpu else 50.0
    net = char_lstm_network(vocab_size=vocab, hidden=hidden, layers=1,
                            tbptt_length=16,
                            precision="bf16" if on_tpu else "f32")
    engine = DecodeEngine(net, n_slots=n_slots,
                          tenant_weights={"gold": 3.0, "std": 1.0},
                          default_max_tokens=32, queue_capacity=256,
                          component_prefix="bench_decode")
    rng = np.random.default_rng(seed)

    def make_req(i):
        # zipf-ish request mix: mostly short, a heavy tail
        p_len = int(min(1 + rng.zipf(1.6), 12))
        n_new = int(min(2 + rng.zipf(1.4), 24))
        prompt = rng.integers(0, vocab, size=p_len).tolist()
        tenant = "gold" if i % 2 == 0 else "std"
        return prompt, n_new, tenant

    # ITL (inter-token) and TTFT trackers, plus a timeline of
    # (wall_time, itl_seconds) so the swap window is auditable
    itl = LatencyTracker(window=200_000)
    ttft = LatencyTracker(window=50_000)
    timeline = []
    tl_lock = threading.Lock()
    stop = threading.Event()
    client_errors = []

    def client(ci):
        j = 0
        try:
            while not stop.is_set():
                j += 1
                prompt, n_new, tenant = make_req(ci * 7919 + j)
                t_sub = time.perf_counter()
                last = [None]

                def on_token(_tok, _last=last, _t_sub=t_sub):
                    now = time.perf_counter()
                    if _last[0] is None:
                        ttft.record(now - _t_sub)
                    else:
                        gap = now - _last[0]
                        itl.record(gap)
                        with tl_lock:
                            timeline.append((now, gap))
                    _last[0] = now

                fut = engine.generate(prompt, max_new_tokens=n_new,
                                      tenant=tenant, on_token=on_token)
                fut.result(timeout=120)
        except BaseException as e:  # noqa: BLE001 - reported, fails run
            client_errors.append(f"{type(e).__name__}: {e}")

    # warmup: compile the step + reset programs before the clock starts
    engine.generate([1, 2, 3], max_new_tokens=2, tenant="gold").result(120)
    warm_cache = engine.program_cache_size()
    # the soak ledger: per-tenant spend series recorded like any other,
    # with the per-tenant chip-budget burn rules judged live (a whole
    # chip per tenant is the generous single-host bar). Attached AFTER
    # warmup so the rules only grade traffic.
    import tempfile

    from deeplearning4j_tpu.analysis.slo import default_rule_pack
    from deeplearning4j_tpu.utils import runledger as _runledger

    ledger_path = os.path.join(tempfile.gettempdir(),
                               f"BENCH_decode_ledger_{os.getpid()}.jsonl")
    se = max(0.25, duration / 8.0)
    ledger = _runledger.RunLedger(
        ledger_path, sample_every=se,
        rules=default_rule_pack(
            sample_every=se,
            tenants={"gold": 1.0, "std": 1.0}))
    _runledger.attach(ledger)
    before = engine.metrics()
    spend0 = resourcemeter.spend_table(get_registry().scalar_values())
    clients = n_slots + 2  # keep the pool saturated, the queue shallow
    threads = [threading.Thread(target=client, args=(i,), daemon=True,
                                name=f"dl4j-bench-dec-{i}")
               for i in range(clients)]
    occupancy = []
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    swap_at = duration / 2.0
    swap_t = None
    swap_version = None
    while time.perf_counter() - t0 < duration:
        occupancy.append(engine.metrics()["slots_in_use"])
        if swap_t is None and time.perf_counter() - t0 >= swap_at:
            # the live swap: v+1 committed beside v on THIS thread, the
            # engine flips between steps — traffic never pauses
            perturbed = jax.tree_util.tree_map(
                lambda a: a * 1.001, net.params_list)
            swap_version = engine.load_version(perturbed)
            swap_t = time.perf_counter()
        time.sleep(0.02)
    stop.set()
    for t in threads:
        t.join(timeout=60.0)
        if t.is_alive():
            client_errors.append(f"{t.name}: wedged past join budget")
    dt = time.perf_counter() - t0
    after = engine.metrics()
    final_cache = engine.program_cache_size()
    spend1 = resourcemeter.spend_table(get_registry().scalar_values())
    # close (final sample) BEFORE the verdict: the replayable artifact
    # must hold everything the live verdict judged
    ledger.close()
    slo_fired = ledger.rules.ever_fired()
    slo_fired_errors = ledger.rules.ever_fired("error")
    engine.shutdown()
    if client_errors:
        raise RuntimeError(f"decode client died: {client_errors[:3]}")
    if not after["conservation_ok"]:
        raise RuntimeError(f"decode books violated: {after['tenants']}")
    tokens = after["tokens"] - before["tokens"]
    completed = after["completed"] - before["completed"]
    # the swap window: inter-token gaps landing just after the flip —
    # a blip would show up as a p99 spike HERE even if the whole-soak
    # p99 hides it
    with tl_lock:
        window = [g for (ts, g) in timeline
                  if swap_t is not None and swap_t - 0.5 <= ts <= swap_t + 1.0]
    swap_p99_ms = (None if len(window) < 10 else
                   round(sorted(window)[int(0.99 * (len(window) - 1))]
                         * 1e3, 3))
    itl_snap = itl.snapshot()
    slo_met = bool(itl_snap["p99_ms"] is not None
                   and itl_snap["p99_ms"] <= slo_ms
                   and (swap_p99_ms is None or swap_p99_ms <= slo_ms)
                   and after["failed"] == 0)

    # -- vs_alternate: the naive per-request loop -----------------------------
    def naive_tokens_per_sec(n_reqs=12):
        net.clear_rnn_state()
        reqs = [make_req(10_000 + i) for i in range(n_reqs)]
        # warmup the batch-1 streaming traces
        oh = np.zeros((1, vocab), np.float32)
        oh[0, 1] = 1.0
        net.rnn_time_step(oh)
        net.clear_rnn_state()
        n_tok = 0
        t0 = time.perf_counter()
        for prompt, n_new, _ in reqs:
            net.clear_rnn_state()
            out = None
            for t in prompt:
                oh = np.zeros((1, vocab), np.float32)
                oh[0, t] = 1.0
                out = np.asarray(net.rnn_time_step(oh))
            for _ in range(n_new):
                g = int(np.argmax(out[0]))
                n_tok += 1
                oh = np.zeros((1, vocab), np.float32)
                oh[0, g] = 1.0
                out = np.asarray(net.rnn_time_step(oh))
        return n_tok / (time.perf_counter() - t0)

    # -- fused decode steps: K steps scanned into ONE jitted dispatch --------
    def fused_probe(k, n_reqs=16):
        """Mini-soak at fused_steps=k on a fresh engine (own metric
        prefix — the books above must not be polluted): a FIXED request
        set, client-side ITL tracking, tokens/sec from the engine's own
        counters. K=1 is the per-step dispatch baseline; the K>1 arm
        shows what amortizing host dispatch overhead buys."""
        eng = DecodeEngine(net, n_slots=n_slots,
                           tenant_weights={"gold": 3.0, "std": 1.0},
                           default_max_tokens=32, queue_capacity=256,
                           component_prefix=f"bench_decode_f{k}")
        try:
            eng.set_fused_steps(k)
            eng.generate([1, 2, 3], max_new_tokens=2,
                         tenant="gold").result(120)
            tr = LatencyTracker(window=50_000)
            last = {}

            def mk_cb(i):
                def cb(_tok):
                    now = time.perf_counter()
                    if i in last:
                        tr.record(now - last[i])
                    last[i] = now
                return cb

            reqs = [make_req(50_000 + i) for i in range(n_reqs)]
            tok0 = eng.metrics()["tokens"]
            tp0 = time.perf_counter()
            futs = [eng.generate(p, max_new_tokens=nn, tenant=ten,
                                 on_token=mk_cb(i))
                    for i, (p, nn, ten) in enumerate(reqs)]
            for f in futs:
                f.result(timeout=120)
            dtp = time.perf_counter() - tp0
            n_tok = eng.metrics()["tokens"] - tok0
        finally:
            eng.shutdown()
        snap = tr.snapshot()
        return {"tokens_per_sec": round(n_tok / dtp, 1),
                "itl_p50_ms": snap["p50_ms"],
                "itl_p99_ms": snap["p99_ms"]}

    # -- weighted-fair spend probe: both tenants fully backlogged ------------
    def fairness_probe(secs=2.5):
        """The main soak's clients pick a tenant per request, so neither
        tenant stays backlogged and stride scheduling has nothing to
        arbitrate. Here each tenant keeps n_slots clients outstanding on
        a fresh engine — under dual backlog the 3:1 weights must show up
        as a ~3:1 decode device-seconds split in the resource meter."""
        eng = DecodeEngine(net, n_slots=n_slots,
                           tenant_weights={"gold": 3.0, "std": 1.0},
                           default_max_tokens=32, queue_capacity=256,
                           component_prefix="bench_decode_fair")
        errs = []
        try:
            eng.generate([1, 2, 3], max_new_tokens=2,
                         tenant="gold").result(120)
            s0 = resourcemeter.spend_table(get_registry().scalar_values())
            stop_f = threading.Event()

            def fclient(tenant, ci):
                j = 0
                try:
                    while not stop_f.is_set():
                        j += 1
                        prompt, n_new, _ = make_req(90_000 + ci * 7919 + j)
                        eng.generate(prompt, max_new_tokens=n_new,
                                     tenant=tenant).result(timeout=120)
                except BaseException as e:  # noqa: BLE001 - reported
                    errs.append(f"{type(e).__name__}: {e}")

            ths = [threading.Thread(target=fclient, args=(ten, i),
                                    daemon=True,
                                    name=f"dl4j-bench-fair-{ten}-{i}")
                   for ten in ("gold", "std") for i in range(n_slots)]
            for th in ths:
                th.start()
            time.sleep(secs)
            stop_f.set()
            for th in ths:
                th.join(timeout=60.0)
        finally:
            eng.shutdown()
        if errs:
            raise RuntimeError(f"fairness client died: {errs[:3]}")
        s1 = resourcemeter.spend_table(get_registry().scalar_values())
        gold = _dec_sec(s1, "gold") - _dec_sec(s0, "gold")
        std = _dec_sec(s1, "std") - _dec_sec(s0, "std")
        ratio = gold / max(std, 1e-9)
        want = 3.0  # the engine's gold:std weight ratio
        return {
            "device_seconds": {"gold": round(gold, 4),
                               "std": round(std, 4)},
            "ratio": round(ratio, 2),
            "want_ratio": want,
            # generous 2x band: stride scheduling is exact on admissions
            # but request lengths are zipf, so spend only approximates it
            "ok": bool(std > 0 and want / 2 <= ratio <= want * 2),
        }

    fair = fairness_probe()
    if not fair["ok"]:
        raise RuntimeError(
            f"weighted-fair spend violated: gold:std device-seconds "
            f"ratio {fair['ratio']} (want ~{fair['want_ratio']}): {fair}")

    fused_k = 4
    f_base = fused_probe(1)
    f_fused = fused_probe(fused_k)

    naive_tps = naive_tokens_per_sec()
    engine_tps = tokens / dt
    return {
        "value": round(engine_tps, 1),
        "unit": "tokens/sec/chip",
        "devices": 1,
        "slots": n_slots,
        "clients": clients,
        "seconds": round(dt, 3),
        "tokens": tokens,
        "requests_completed": completed,
        "itl_p50_ms": itl_snap["p50_ms"],
        "itl_p99_ms": itl_snap["p99_ms"],
        "ttft_p50_ms": ttft.snapshot()["p50_ms"],
        "ttft_p99_ms": ttft.snapshot()["p99_ms"],
        "slot_occupancy_mean": round(float(np.mean(occupancy)), 2)
        if occupancy else None,
        "slot_occupancy_max": int(max(occupancy)) if occupancy else None,
        "slo_ms_per_token": slo_ms,
        "slo_met_through_swap": slo_met,
        "swap": {
            "fired": swap_t is not None,
            "version": swap_version,
            "itl_p99_ms_in_window": swap_p99_ms,
            "window_samples": len(window),
            "swaps_counted": after["swaps"] - before["swaps"],
        },
        "zero_retraces": bool(final_cache == warm_cache),
        # K decode steps per dispatch (serving/decode.set_fused_steps):
        # same fixed request set both arms, fresh engine each
        "fused_steps": {
            "k": fused_k,
            "tokens_per_sec": f_fused["tokens_per_sec"],
            "itl_p50_ms": f_fused["itl_p50_ms"],
            "itl_p99_ms": f_fused["itl_p99_ms"],
            "unfused_tokens_per_sec": f_base["tokens_per_sec"],
            "unfused_itl_p50_ms": f_base["itl_p50_ms"],
            "unfused_itl_p99_ms": f_base["itl_p99_ms"],
            "speedup": round(f_fused["tokens_per_sec"]
                             / max(f_base["tokens_per_sec"], 1e-9), 2),
        },
        "books": {k: after[k] for k in ("admitted", "completed", "shed",
                                        "failed", "rejected")},
        "tenants": after["tenants"],
        # per-tenant chip spend over the soak (utils/resourcemeter) and
        # the dual-backlog weighted-fair verdict
        "tenant_spend": {
            t: {"decode_device_seconds":
                round(_dec_sec(spend1, t) - _dec_sec(spend0, t), 4),
                "tokens": round(
                    spend1.get(t, {}).get("tokens", 0.0)
                    - spend0.get(t, {}).get("tokens", 0.0))}
            for t in ("gold", "std")},
        "weighted_fair": fair,
        # the recorded half: per-tenant series + burn rules in a
        # replayable artifact (cli tenants --ledger <path> reproduces
        # tenant_spend; cli slo --ledger <path> --check re-judges it)
        "slo": {
            "ledger": ledger_path,
            "run_id": ledger.run_id,
            "rules": [r.name for r in ledger.rules.rules],
            "fired": slo_fired,
            "fired_errors": slo_fired_errors,
        },
        "slo_ok": not slo_fired_errors,
        "vs_alternate": {
            "alternate": "naive_per_request_rnn_time_step_loop",
            "alternate_tokens_per_sec": round(naive_tps, 1),
            "speedup": round(engine_tps / max(naive_tps, 1e-9), 2),
        },
    }


def bench_input_pipeline(n_batches=48, batch=64, img=24, classes=10,
                         workers=4, io_ms=12.0):
    """Input-bound training, the one workload where ETL is deliberately ON
    the books (every other workload excludes it per the BASELINE.md
    protocol): each record batch costs a simulated storage/codec latency
    (the I/O wait a real decode pays) plus genuine per-pixel host math,
    then normalization + random flip augmentation. A/Bs the staged
    pipeline against the same logical work run synchronously:

      off — decode + normalize + augment inline on the fit thread,
            async_prefetch=False (no overlap anywhere);
      on  — ParallelDataSetIterator(workers) decodes concurrently,
            DevicePrefetchIterator stages batches to the device ahead of
            the step, and normalize+flip run as a jitted on-device
            DeviceBatchTransform in the prefetch worker.

    The acceptance bar is speedup >= 2x on CPU: the pipeline must hide
    ETL behind compute, not just shave it."""
    from deeplearning4j_tpu.data.iterators import DataSetIterator
    from deeplearning4j_tpu.data.prefetch import ParallelDataSetIterator
    from deeplearning4j_tpu.data.transforms import DeviceBatchTransform
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        InputType,
        NeuralNetConfiguration,
        OutputLayer,
        SubsamplingLayer,
        Updater,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    on_tpu = jax.default_backend() not in ("cpu",)
    mean, std = 0.48, 0.27
    rng = np.random.default_rng(0)
    # a small pool of distinct "encoded" records, cycled to n_batches —
    # decode cost is per-batch, so aliasing the raw bytes is free
    pool = [(rng.integers(0, 256, (batch, img, img, 3), dtype=np.uint8),
             _onehot(rng, batch, classes)) for _ in range(8)]
    records = [pool[i % len(pool)] for i in range(n_batches)]

    def decode(item):
        raw, y = item
        time.sleep(io_ms / 1e3)  # storage/codec latency (releases the GIL)
        x = np.sqrt(raw.astype(np.float32) / 255.0)  # gamma-ish host work
        return DataSet(x, y)

    def host_augment(ds, step):
        x = (ds.features - mean) / std
        r = np.random.default_rng(step)
        flip = r.random(x.shape[0]) < 0.5
        x = np.where(flip[:, None, None, None], x[:, :, ::-1, :], x)
        return DataSet(x.astype(np.float32), ds.labels)

    class SyncEtlIterator(DataSetIterator):
        """Pipeline off: the full ETL chain inline on the fit thread."""

        def __iter__(self):
            for step, item in enumerate(records):
                yield host_augment(decode(item), step)

    def make_net():
        # deliberately tiny model: the workload measures the INPUT
        # pipeline, so compute must not be the bottleneck (pool + dense —
        # a conv here would be compute-bound on a 2-core CPU smoke box)
        conf = (
            NeuralNetConfiguration.builder().seed(7).updater(Updater.SGD)
            .learning_rate(0.01).weight_init("xavier")
            .precision("bf16" if on_tpu else "f32").list()
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=classes, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.convolutional(img, img, 3)).build()
        )
        return MultiLayerNetwork(conf).init()

    from deeplearning4j_tpu.utils.metrics import get_registry

    data_wait = get_registry().histogram(
        "fit_data_wait_seconds",
        "time blocked on the data iterator (ETL) before a "
        "dispatch").labels()

    def timed(fit_once):
        fit_once()  # warmup: compile every program the timed pass uses
        times = []
        c0, s0 = data_wait.count, data_wait.sum
        for _ in range(3):
            t0 = time.perf_counter()
            net = fit_once()
            _sync(net)
            times.append(time.perf_counter() - t0)
        times.sort()
        # per-variant slice of the process-global data-wait histogram:
        # the A/B shares one registry, so deltas are the honest per-arm
        # numbers (the snapshot's merged histogram is both arms at once)
        wait_ms = (data_wait.sum - s0) / max(1, data_wait.count - c0) * 1e3
        return batch * n_batches / times[1], wait_ms

    net_off = make_net()
    ips_off, wait_off = timed(lambda: net_off.fit(
        SyncEtlIterator(), epochs=1, async_prefetch=False))

    net_on = make_net().set_input_transform(DeviceBatchTransform(
        normalize=(mean, std), random_flip=True, seed=0))
    make_it = lambda: ParallelDataSetIterator(
        records, transform=decode, workers=workers, queue_size=2 * workers)
    ips_on, wait_on = timed(lambda: net_on.fit(
        make_it(), epochs=1, async_prefetch=True))
    return {
        "value": round(ips_on, 1),
        "unit": "images/sec/chip",
        "pipeline_off": round(ips_off, 1),
        "speedup_vs_sync": round(ips_on / ips_off, 2),
        "fit_data_wait_mean_ms": {"pipeline_off": round(wait_off, 3),
                                  "pipeline_on": round(wait_on, 3)},
        "batch": batch,
        "n_batches": n_batches,
        "image_size": img,
        "etl_workers": workers,
        "simulated_io_ms": io_ms,
        "stages": "ParallelDataSetIterator -> DevicePrefetchIterator -> "
                  "DeviceBatchTransform(normalize+flip)",
    }


# -- multi-chip mode ----------------------------------------------------------


def _n_multichip_devices() -> int:
    return int(os.environ.get("BENCH_MULTICHIP_DEVICES", "8"))


def _legacy_param_averaging_fit(make_net, shard_datasets, steps):
    """The vs_alternate arm: DL4J ParallelWrapper semantics — each
    "worker" trains a full replica step on its own shard from the same
    start params (the real `_fit_dataset` machinery, so TBPTT nets run
    their real segment dispatch), then parameters + updater state are
    averaged THROUGH THE HOST every interval
    (ParallelWrapper.java:417-424, frequency 1). This is exactly the
    per-interval params-to-host round-trip the in-graph all-reduce
    removes; measuring it next to the sharded step is the honesty
    mechanism. Replicas dispatch sequentially — what a GIL-bound host
    orchestrator does on one box — so the arm is a mechanism A/B, not a
    tuned rival."""
    import jax.numpy as jnp

    net = make_net()
    # REAL buffer copies, not aliases: on device backends the step jit
    # donates argnums (0, 2), so each replica must dispatch its OWN
    # copy of the start params/updater — an aliased p0 would be deleted
    # by the first replica's donation (and the legacy semantics DO copy
    # the source model into every replica)
    copy_tree = lambda t: jax.tree_util.tree_map(jnp.copy, t)
    avg = lambda trees: jax.tree_util.tree_map(
        lambda *xs: np.mean([np.asarray(x) for x in xs], axis=0), *trees)
    t_total = None
    for _ in range(2):  # warmup pass (compile), then the timed pass
        t0 = time.perf_counter()
        for _ in range(steps):
            p0 = copy_tree(net.params_list)
            u0 = copy_tree(net.upd_state)
            s0 = list(net.state_list)
            it0 = net.iteration
            outs = []
            for ds in shard_datasets:
                net.params_list = copy_tree(p0)
                net.upd_state = copy_tree(u0)
                net.state_list, net.iteration = list(s0), it0
                net._fit_dataset(ds)
                outs.append((net.params_list, net.upd_state))
            # the legacy averaging interval: every replica's params and
            # updater state round-trip to host numpy, mean, re-upload
            net.params_list = avg([o[0] for o in outs])
            net.upd_state = avg([o[1] for o in outs])
        _sync(net)
        t_total = time.perf_counter() - t0
    return t_total


def _bench_multichip(workload: str):
    """Multi-chip training A/B on an n-device mesh (CPU boxes force the
    host-platform device count — the same virtual-mesh strategy as the
    MULTICHIP_r0x dryruns; the numbers are mechanism evidence there, not
    silicon claims — `backend` says which). Three arms per workload:

      sharded         — the mainline path: fit() with set_mesh, global
                        batch = n × per-chip batch, ONE jitted SPMD step,
                        in-graph gradient all-reduce.
      single_chip     — the same per-chip batch on one device: the
                        scaling-efficiency denominator.
      param_averaging — the legacy DL4J semantics (vs_alternate): per-
                        replica steps + host-side parameter averaging.

    Reported: per-chip throughput, scaling efficiency (sharded per-chip
    / single-chip), and the legacy arm under `vs_alternate` — the same
    A/B honesty mechanism as the kernel benches. MFU is per-chip-correct:
    model FLOPs divide by the data-axis size (`flops_source` recorded)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
    from deeplearning4j_tpu.utils.metrics import get_registry

    n = jax.device_count()
    on_tpu = jax.default_backend() not in ("cpu",)
    rng = np.random.default_rng(0)

    if workload == "resnet50":
        from deeplearning4j_tpu.models.resnet import resnet50_conf
        from deeplearning4j_tpu.nn.compgraph import ComputationGraph

        per_chip, steps, image_size, classes = (
            (128, 8, 224, 1000) if on_tpu else (4, 2, 64, 10))
        conf = resnet50_conf(num_classes=classes, image_size=image_size,
                             precision="bf16" if on_tpu else "f32")
        refusal = _doctor_refusal(conf, "images/sec/chip")
        if refusal is not None:
            return refusal
        make_net = lambda: ComputationGraph(conf).init()
        gb = per_chip * n
        x = rng.random((gb, image_size, image_size, 3), np.float32)
        ds = DataSet(x, _onehot(rng, gb, classes))
        unit, per_step_examples, timesteps = "images/sec/chip", gb, 16
    elif workload == "char_lstm":
        from deeplearning4j_tpu.models.charlstm import char_lstm_conf
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        vocab = 77
        per_chip, seq_len, tbptt, hidden, steps = (
            (64, 200, 50, 200, 8) if on_tpu else (2, 32, 16, 48, 2))
        conf = char_lstm_conf(vocab_size=vocab, hidden=hidden,
                              tbptt_length=tbptt,
                              precision="bf16" if on_tpu else "f32")
        refusal = _doctor_refusal(conf, "tokens/sec/chip")
        if refusal is not None:
            return refusal
        make_net = lambda: MultiLayerNetwork(conf).init()
        gb = per_chip * n
        idx = rng.integers(0, vocab, (gb, seq_len))
        x = np.eye(vocab, dtype=np.float32)[idx]
        yidx = rng.integers(0, vocab, (gb, seq_len))
        ds = DataSet(x, np.eye(vocab, dtype=np.float32)[yidx])
        unit = "tokens/sec/chip"
        per_step_examples = gb * seq_len
        timesteps = seq_len
    else:
        raise SystemExit(f"unknown multichip workload {workload!r}")

    # model FLOPs from an unsharded throwaway trace (mesh-independent);
    # the PER-CHIP figure divides by the data-axis size — the accounting
    # fix that keeps multi-chip MFU honest
    step_flops, flops_source = _step_flops(make_net, gb,
                                           timesteps=timesteps)
    per_chip_flops = step_flops / n if step_flops else None

    reg = get_registry()

    def timed_sharded(bucket_bytes=None, grad_dtype=None, block_scan=None):
        """One sharded arm under explicit collective knobs. Reports the
        throughput AND the per-arm evidence: allreduce wire-byte delta,
        the chosen bucket schedule, `graph_block` body-trace count and
        the first dispatch's trace+compile wall time (where the
        scan-over-blocks collapse shows up)."""
        mesh = data_parallel_mesh()
        net = make_net().set_mesh(mesh, bucket_bytes=bucket_bytes,
                                  grad_dtype=grad_dtype)
        if block_scan is not None and hasattr(net, "set_block_scan"):
            net.set_block_scan(block_scan)
        if per_chip_flops:
            net.set_model_flops_per_example(step_flops / gb, flops_source)
        plan = net._mesh_plan
        # pre-shard ONCE onto the mesh: the prefetch placement then
        # detects the committed sharding and passes through zero-copy
        # (the contract tests/test_sharded_step.py pins; the measured
        # fit_data_wait_mean_ms is REPORTED in the artifact — on a
        # contended CPU box per-epoch thread spin-up keeps it nonzero)
        staged = plan.shard_batch(ds)
        wait = reg.histogram(
            "fit_data_wait_seconds",
            "time blocked on the data iterator (ETL) before a "
            "dispatch").labels()
        gb_notes = reg.counter(
            "compile_total", "jit cache insertions (fresh traces)",
            ("kind",)).labels("graph_block")
        ar = reg.counter(
            "allreduce_bytes_total",
            "gradient bytes all-reduced in-graph by the sharded "
            "train step (logical payload: summed gradient leaf "
            "bytes per optimizer step)").labels()
        c0, s0, ar0, gb0 = wait.count, wait.sum, ar.value, gb_notes.value
        # first fit = trace + compile + one step: the compile-collapse
        # measurement (latency-cancelled throughput timing comes after)
        t0 = time.perf_counter()
        net.fit(ExistingDataSetIterator([staged]), epochs=1,
                async_prefetch=False)
        _sync(net)
        first_s = time.perf_counter() - t0
        dt, n_steps = _time_fit(
            net, lambda k: ExistingDataSetIterator([staged] * k), steps,
            reps=3 if on_tpu else 1)
        wait_ms = ((wait.sum - s0) / max(1, wait.count - c0)) * 1e3
        steps_total = net.iteration
        return {
            "dt": dt,
            "n_steps": n_steps,
            "wait_ms": wait_ms,
            "allreduce_bytes": int(ar.value - ar0),
            "allreduce_bytes_per_step": int(
                round((ar.value - ar0) / max(1, steps_total))),
            "graph_block_body_traces": int(gb_notes.value - gb0),
            "first_dispatch_seconds": round(first_s, 3),
            "collective": plan.collective_describe(net),
        }

    def timed_single():
        net = make_net()
        shard_ds = DataSet(
            jax.device_put(np.asarray(ds.features)[:per_chip]),
            jax.device_put(np.asarray(ds.labels)[:per_chip]))
        dt, n_steps = _time_fit(
            net, lambda k: ExistingDataSetIterator([shard_ds] * k), steps,
            reps=3 if on_tpu else 1)
        return dt, n_steps

    # Three collective arms (the A/B the bucketed path must win or tie):
    #   bucketed        — headline: default bucket schedule, and on graph
    #                     nets the scan-over-identical-blocks compile
    #                     collapse switched on.
    #   monolithic      — bucket_bytes=0 (single tail-end all-reduce) with
    #                     block runs force-unrolled: the old mainline.
    #   bucketed_bf16   — bucketed schedule + opt-in bf16 wire payload
    #                     (f32 accumulate): halves allreduce bytes.
    # (block_scan is hasattr-gated inside timed_sharded: MultiLayerNetwork
    # has no graph topology to scan, so the knob is a no-op there.)
    # Monolithic runs FIRST: the first arm absorbs one-time process
    # warmup (allocator growth, op registries) into its
    # first_dispatch_seconds, and charging that to the headline arm
    # would fake a compile-collapse regression — or hide a real one.
    arm_mono = timed_sharded(bucket_bytes=0, block_scan="unroll")
    arm_bucketed = timed_sharded(block_scan=True)
    arm_bf16 = timed_sharded(grad_dtype="bf16", block_scan=True)
    sh_dt, sh_steps = arm_bucketed["dt"], arm_bucketed["n_steps"]
    sh_wait_ms = arm_bucketed["wait_ms"]
    allreduce_bytes = arm_bucketed["allreduce_bytes"]
    si_dt, si_steps = timed_single()

    # legacy arm: per-shard device-resident batches, host averaging
    shards = []
    for s in range(n):
        sl = slice(s * per_chip, (s + 1) * per_chip)
        shards.append(DataSet(
            jnp.asarray(np.asarray(ds.features)[sl]),
            jnp.asarray(np.asarray(ds.labels)[sl])))
    vs_alt_err = None
    try:
        avg_dt = _legacy_param_averaging_fit(make_net, shards, steps)
    except Exception as e:
        avg_dt, vs_alt_err = None, f"{type(e).__name__}: {e}"

    # per-chip throughput: the sharded arm consumed gb examples/step
    def per_chip_rate(arm):
        return per_step_examples / n * arm["n_steps"] / arm["dt"]

    def arm_summary(arm):
        return {
            "value": round(per_chip_rate(arm), 2),
            "allreduce_bytes": arm["allreduce_bytes"],
            "allreduce_bytes_per_step": arm["allreduce_bytes_per_step"],
            "graph_block_body_traces": arm["graph_block_body_traces"],
            "first_dispatch_seconds": arm["first_dispatch_seconds"],
            "collective": arm["collective"],
        }

    sharded_per_chip = per_chip_rate(arm_bucketed)
    single_chip = per_step_examples / n * si_steps / si_dt
    efficiency = sharded_per_chip / single_chip if single_chip else None
    mfu = (per_chip_flops * sh_steps / sh_dt / peak_flops_per_chip()
           if on_tpu and per_chip_flops else None)
    vs_alt = {
        "collective_monolithic": round(per_chip_rate(arm_mono), 2),
        "collective_bucketed_bf16": round(per_chip_rate(arm_bf16), 2),
    }
    if avg_dt is not None:
        vs_alt["param_averaging_host"] = round(
            per_step_examples / n * steps / avg_dt, 2)
    out = {
        "value": round(sharded_per_chip, 2),
        "unit": unit,
        "devices": n,
        "per_chip_batch": per_chip,
        "global_batch": gb,
        "steps_timed": sh_steps,
        "single_chip_value": round(single_chip, 2),
        "scaling_efficiency": (None if efficiency is None
                               else round(efficiency, 3)),
        "kernel": "sharded_step_allreduce",
        "vs_alternate": vs_alt,
        **({"vs_alternate_errors": {"param_averaging_host": vs_alt_err}}
           if vs_alt_err else {}),
        # the three-arm collective A/B: bucketed is the headline arm
        # above; the per-arm evidence (wire bytes, bucket schedule,
        # graph_block trace counts, first-dispatch trace+compile wall)
        # is what makes the bucketed/bf16/scan claims falsifiable
        "collective_ab": {
            "bucketed": arm_summary(arm_bucketed),
            "monolithic": arm_summary(arm_mono),
            "bucketed_bf16": arm_summary(arm_bf16),
        },
        "fit_data_wait_mean_ms": round(sh_wait_ms, 3),
        "allreduce_bytes_total": allreduce_bytes,
        "model_flops_per_step": step_flops,
        "model_flops_per_chip": per_chip_flops,
        "flops_source": flops_source,
        "mfu": None if mfu is None else round(mfu, 4),
        "seconds": round(
            arm_bucketed["dt"] + arm_mono["dt"] + arm_bf16["dt"]
            + si_dt + (avg_dt or 0.0), 3),
    }
    return out


def bench_recsys(vocab=800_000, dim=64, hidden=192, batch=1024,
                 steps=40, warmup=6, endpoints=2, cache_rows=65536,
                 alpha=1.1, lr=0.05, seed=0, ledger_path=None):
    """Sparse-embedding recsys training over the sharded paramserver
    (parallel/sparse.SparseEmbeddingPipeline): a jitted dense tower
    (pure-jax step — runs unchanged under set_mesh, the embeddings are
    a plain [batch, dim] input) over a host-sharded multi-hundred-MB
    embedding table split across N in-process endpoints, fed synthetic
    zipf id traffic. Pull latency is INJECTED via the `paramserver_rpc`
    faultpoint (calibrated to the measured dense-step time, identical
    in both arms) so the overlap claim is about hiding the wire, not
    about localhost being fast.

    `vs_alternate` is the honesty arm: the SAME step, id stream, and
    injected latency run synchronously — no prefetch, no cache — so the
    pipelined/synchronous examples/sec ratio is the measured value of
    the overlap + hot-id cache. Coherence is graded too: both arms must
    finish with BYTE-IDENTICAL dense-tower params (the pipeline's
    write-through/dirty protocol makes cache + prefetch transparent),
    the cache books must conserve exactly (pull_rows == cache_hit +
    cache_miss), and the pull spend books per tenant under the
    paramserver tier with the process-total conservation check."""
    import tempfile
    import threading

    import jax.numpy as jnp

    from deeplearning4j_tpu.analysis.slo import (
        ERROR,
        SLORule,
        default_rule_pack,
    )
    from deeplearning4j_tpu.data.recsys import zipf_cdf, zipf_ids
    from deeplearning4j_tpu.parallel.paramserver import (
        EmbeddingParameterServer,
        EmbeddingPSClient,
    )
    from deeplearning4j_tpu.parallel.sparse import (
        SPARSE_THREAD_PREFIX,
        SparseEmbeddingPipeline,
    )
    from deeplearning4j_tpu.utils import faultpoints as _faults
    from deeplearning4j_tpu.utils import resourcemeter
    from deeplearning4j_tpu.utils import runledger as _runledger
    from deeplearning4j_tpu.utils.metrics import get_registry

    tenant = "recsys"
    if not resourcemeter.is_enabled():
        resourcemeter.enable()

    # -- the dense tower ------------------------------------------------------
    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 3)
    params0 = {
        "w1": jax.random.normal(ks[0], (dim, hidden), jnp.float32)
        * np.sqrt(2.0 / dim),
        "b1": jnp.zeros((hidden,), jnp.float32),
        "w2": jax.random.normal(ks[1], (hidden, hidden), jnp.float32)
        * np.sqrt(2.0 / hidden),
        "b2": jnp.zeros((hidden,), jnp.float32),
        "w3": jax.random.normal(ks[2], (hidden, 2), jnp.float32)
        * np.sqrt(2.0 / hidden),
        "b3": jnp.zeros((2,), jnp.float32),
    }

    def _loss(p, emb, y):
        h = jnp.maximum(emb @ p["w1"] + p["b1"], 0.0)
        h = jnp.maximum(h @ p["w2"] + p["b2"], 0.0)
        logits = h @ p["w3"] + p["b3"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    @jax.jit
    def _step(p, emb, y):
        loss, (gp, gemb) = jax.value_and_grad(
            _loss, argnums=(0, 1))(p, emb, y)
        new_p = jax.tree_util.tree_map(lambda a, g: a - lr * g, p, gp)
        return new_p, (-lr) * gemb, loss

    # calibrate: the injected pull latency tracks the measured dense
    # step so the overlap is a real hiding problem on ANY box (too-fast
    # compute would make both arms wire-bound; too-slow would hide the
    # wire for free in the synchronous arm too)
    emb_d = jnp.zeros((batch, dim), jnp.float32)
    y_d = jnp.zeros((batch,), jnp.int32)
    p_c, g_c, _ = _step(params0, emb_d, y_d)
    jax.block_until_ready(g_c)
    t0 = time.perf_counter()
    for _ in range(5):
        p_c, g_c, _ = _step(params0, emb_d, y_d)
    jax.block_until_ready(g_c)
    compute_ms = (time.perf_counter() - t0) / 5 * 1e3
    lat_ms = float(min(60.0, max(10.0, compute_ms)))

    # -- ledger + SLO rule pack ----------------------------------------------
    if ledger_path is None:
        ledger_path = os.path.join(
            tempfile.gettempdir(), f"BENCH_recsys_ledger_{os.getpid()}.jsonl")
    sample_every = 0.5
    rules = default_rule_pack(sample_every=sample_every,
                              tenants={tenant: 1.0})
    rules.append(SLORule(
        name="paramserver_push_dropped",
        kind="rate_of_change",
        series="paramserver_client_push_dropped_total",
        op=">", value=0.0, severity=ERROR,
        component="paramserver", for_seconds=0.0))
    rules.append(SLORule(
        name="sparse_prefetch_unhealthy",
        kind="threshold",
        series='component_health{component="sparse_prefetch"}',
        op=">=", value=2.0, severity=ERROR,
        component="sparse_prefetch", for_seconds=0.0))
    ledger = _runledger.RunLedger(ledger_path, sample_every=sample_every,
                                  rules=rules)
    _runledger.attach(ledger)

    # identical id/label streams for both arms (seeded zipf)
    cdf = zipf_cdf(vocab, alpha)
    n_batches = warmup + steps + 1
    batches = [zipf_ids(batch, vocab, alpha, seed=seed * 1000 + k, cdf=cdf)
               for k in range(n_batches)]
    labels = [jnp.asarray((ids & 1).astype(np.int32)) for ids in batches]

    def run_arm(prefetch, arm_cache_rows):
        servers = [EmbeddingParameterServer(
            {"emb": np.zeros((vocab, dim), np.float32)})
            for _ in range(endpoints)]
        ports = [s.start() for s in servers]
        client = EmbeddingPSClient(
            [f"http://127.0.0.1:{pt}" for pt in ports], tenant=tenant)
        try:
            pipe = SparseEmbeddingPipeline(
                client, "emb", cache_rows=arm_cache_rows,
                prefetch=prefetch)
            p = params0
            dt = None
            rows_seen = 0
            with pipe:
                if prefetch:
                    pipe.prefetch(batches[0])
                t_start = time.perf_counter()
                for k in range(warmup + steps):
                    if k == warmup:
                        t_start = time.perf_counter()
                    emb = pipe.lookup(batches[k])
                    if prefetch:
                        pipe.prefetch(batches[k + 1])
                    p, delta, _ = _step(p, jnp.asarray(emb), labels[k])
                    delta = np.asarray(delta)  # blocks: compute is in dt
                    pipe.push(batches[k], delta)
                    if k >= warmup:
                        rows_seen += batches[k].size
                dt = time.perf_counter() - t_start
                stats = pipe.stats()
                pulls = sorted(pipe.pull_seconds)
            if not client.flush(timeout=60.0):
                raise RuntimeError("recsys arm: paramserver flush "
                                   "timed out")
            p = jax.tree_util.tree_map(np.asarray, p)
        finally:
            client.close()
            for s in servers:
                s.stop()
        pull_p50 = (float(np.percentile(pulls, 50)) * 1e3) if pulls else None
        pull_p99 = (float(np.percentile(pulls, 99)) * 1e3) if pulls else None
        if stats["pull_rows"] != stats["cache_hit"] + stats["cache_miss"]:
            raise RuntimeError(f"cache books violated: {stats}")
        return {
            "examples_per_sec": round(rows_seen / dt, 1),
            "step_ms": round(dt / steps * 1e3, 3),
            "pull_p50_ms": None if pull_p50 is None else round(pull_p50, 3),
            "pull_p99_ms": None if pull_p99 is None else round(pull_p99, 3),
            "cache_hit_rate": round(stats["hit_rate"], 4),
            "stats": stats,
        }, p

    plan = _faults.FaultPlan(seed=seed, rules=[_faults.FaultRule(
        point="paramserver_rpc", kind="latency", p=1.0,
        latency_ms=lat_ms)])
    spend0 = resourcemeter.spend_table(get_registry().scalar_values())
    with _faults.active(plan):
        piped, p_piped = run_arm(True, cache_rows)
        sync, p_sync = run_arm(False, 0)
    spend1 = resourcemeter.spend_table(get_registry().scalar_values())
    tenant_cons = resourcemeter.conservation(get_registry().scalar_values())
    ledger.close()
    slo_fired = ledger.rules.ever_fired()
    slo_fired_errors = ledger.rules.ever_fired("error")
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith(SPARSE_THREAD_PREFIX)]
    if leaked:
        raise RuntimeError(f"leaked sparse threads: {leaked}")
    if not tenant_cons["ok"]:
        # the per-tenant spend must sum to the process totals per tier —
        # a leak is a correctness bug, not a perf number
        raise RuntimeError(f"tenant spend conservation violated: "
                           f"{tenant_cons}")
    identical = all(
        np.asarray(a).tobytes() == np.asarray(b).tobytes()
        for a, b in zip(jax.tree_util.tree_leaves(p_piped),
                        jax.tree_util.tree_leaves(p_sync)))
    speedup = (piped["examples_per_sec"]
               / max(sync["examples_per_sec"], 1e-9))
    return {
        "value": piped["examples_per_sec"],
        "unit": "examples_per_sec_pipelined",
        "vocab": vocab,
        "dim": dim,
        "table_mb": round(vocab * dim * 4 / 2**20, 1),
        "endpoints": endpoints,
        "batch": batch,
        "steps": steps,
        "cache_rows": cache_rows,
        "zipf_alpha": alpha,
        "compute_ms": round(compute_ms, 3),
        "injected_pull_latency_ms": round(lat_ms, 3),
        "pipelined": piped,
        "vs_alternate": {
            "alternate": "synchronous_pull_no_prefetch_no_cache",
            **sync,
        },
        "speedup_vs_synchronous": round(speedup, 2),
        "overlap_win": bool(speedup >= 2.0),
        "trajectory_identical": bool(identical),
        "slo": {
            "ledger": ledger_path,
            "run_id": ledger.run_id,
            "rules": [r.name for r in ledger.rules.rules],
            "fired": slo_fired,
            "fired_errors": slo_fired_errors,
        },
        "slo_ok": not slo_fired_errors,
        "tenant_spend_paramserver_s": round(
            spend1.get(tenant, {}).get("device_seconds", {}).get(
                resourcemeter.TIER_PARAMSERVER, 0.0)
            - spend0.get(tenant, {}).get("device_seconds", {}).get(
                resourcemeter.TIER_PARAMSERVER, 0.0), 4),
        "tenant_conservation": tenant_cons,
    }


WORKLOADS = {
    "resnet50": bench_resnet50,
    "lenet": bench_lenet,
    "char_lstm": bench_char_lstm,
    "word2vec": bench_word2vec,
    "vgg16_keras_import": bench_vgg16,
    "parallel_inference": bench_parallel_inference,
    "parallel_inference_overload": bench_parallel_inference_overload,
    "input_pipeline": bench_input_pipeline,
    "decode": bench_decode,
    "recsys": bench_recsys,
}

# Per-workload subprocess timeouts (seconds). First compile through the
# tunnel is 20-40s; the big convnets get headroom for two compiles
# (warmup shape + timed shape share one, but bf16 ResNet-50 compiles are
# the slowest thing we run).
TIMEOUTS = {
    "resnet50": 600,
    "lenet": 420,
    "char_lstm": 600,
    "word2vec": 600,
    "vgg16_keras_import": 600,
    "parallel_inference": 420,
    "parallel_inference_overload": 240,
    "input_pipeline": 300,
    "decode": 300,
    "recsys": 420,
}
PROBE_TIMEOUT = 120  # tiny matmul + readback; generous for backend init
OVERALL_DEADLINE = float(os.environ.get("BENCH_DEADLINE_SEC", 1500))


def _child_env():
    env = dict(os.environ)
    # Persistent compilation cache: repeated subprocess runs (and bench
    # re-runs while tuning) skip recompiles of unchanged programs.
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".jax_cache"))
    return env


def _run_child(args, timeout, extra_env=None):
    """Run `python bench.py <args>` with a hard timeout; return
    (parsed-last-json-line | None, error | None)."""
    cmd = [sys.executable, os.path.abspath(__file__)] + args
    env = _child_env()
    if extra_env:
        env.update(extra_env)
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        return None, "timeout"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return None, f"rc={proc.returncode}: " + " | ".join(tail)[-400:]
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, "no JSON on stdout"


def _prior_bench():
    """Newest committed BENCH_r*.json next to this file — the perf
    trajectory's previous point. The committed files are driver-wrapped
    ({"n", "cmd", "rc", "tail"}) with this script's final JSON line inside
    "tail"; a bare bench result (this script's own output saved directly)
    is accepted too. Returns (basename, result) or (None, None)."""
    import glob
    import re

    def round_no(p):  # numeric, not lexicographic: r6 < r10 < r100
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    here = os.path.dirname(os.path.abspath(__file__))
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json")),
                       key=round_no, reverse=True):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(doc, dict):
            continue
        if "workloads" in doc:
            return os.path.basename(path), doc
        for line in reversed(str(doc.get("tail", "")).strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    result = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "workloads" in result:
                    return os.path.basename(path), result
    return None, None


def _vs_baseline(workloads, backend):
    """Per-workload speedup vs the newest prior BENCH_r*.json, so the
    trajectory is self-reporting. (The reference itself publishes no
    numbers — BASELINE.md — so the prior round is the only honest
    baseline there is; `source` names it.) Ratios are only computed
    against a prior run on the SAME backend — a CPU smoke run vs a TPU
    round would report nonsense 0.00x "slowdowns"."""
    prior_name, prior = _prior_bench()
    if not prior:
        return None
    prior_backend = prior.get("backend")
    if backend != prior_backend:
        result = {"source": prior_name,
                  "note": f"backend mismatch ({backend} vs prior "
                          f"{prior_backend}): ratios omitted"}
        # Speedup ratios are backend-bound, but the FLOP-accounting
        # question is not: "does today's cost model price the PRIOR
        # round's dims the way that round recorded?" is answerable on
        # any host by recomputing the static model at the prior dims
        # (cli perf's vs-prior check). Without this, a pending
        # accounting change could hide behind a backend switch and
        # resurface as a phantom MFU jump later.
        drift = _flop_drift_at_prior_dims(prior, workloads)
        if drift:
            result["flop_model_changed"] = drift
            result["flop_model_note"] = (
                "model_flops_per_step of the prior round differs from "
                "the current cost model evaluated at the prior round's "
                "own dims — MFU is not comparable across the two "
                "accountings")
            _ack_known_repricing(result, drift)
        return result
    ratios = {}
    flop_drift = {}
    for name, out in workloads.items():
        prior_wl = (prior.get("workloads") or {}).get(name) or {}
        pv = prior_wl.get("value")
        cv = out.get("value")
        if pv and cv:
            ratios[name] = round(cv / pv, 3)
        # FLOP-model drift (non-fatal warning): a speedup ratio is only
        # meaningful when both rounds agree on what a step COSTS — an
        # MFU "improvement" caused by a FLOP-accounting change must
        # surface as accounting, never as performance
        pf = prior_wl.get("model_flops_per_step")
        cf = out.get("model_flops_per_step")
        if pf and cf and abs(cf / pf - 1.0) > 0.01:
            flop_drift[name] = {
                "prior": pf,
                "current": cf,
                "ratio": round(cf / pf, 4),
                "prior_source": prior_wl.get("flops_source", "analytic"),
                "current_source": out.get("flops_source"),
            }
    result = {
        "source": prior_name,
        "headline": ratios.get("resnet50"),
        "speedup": ratios,
    }
    if flop_drift:
        result["flop_model_changed"] = flop_drift
        result["flop_model_note"] = (
            "model_flops_per_step differs from the prior round for these "
            "workloads — their MFU numbers are not comparable across "
            "rounds until the accounting change is acknowledged")
        _ack_known_repricing(result, flop_drift)
    return result


def _flop_drift_at_prior_dims(prior, workloads):
    """Cross-backend FLOP-drift detail for `_vs_baseline`: for each
    workload measured THIS run that the prior round priced, recompute the
    static cost model at the prior round's recorded dims and compare with
    what it recorded. Only runs when the current round actually carries
    model FLOPs (a bare unit test poking _vs_baseline shouldn't trigger
    a full ResNet trace)."""
    if not any((out or {}).get("model_flops_per_step")
               for out in workloads.values()):
        return {}
    from deeplearning4j_tpu.cli import _perf_vs_prior

    drift = {}
    for name, preset in (("resnet50", "resnet50"),
                         ("char_lstm", "charlstm")):
        if name not in workloads:
            continue
        if not ((prior.get("workloads") or {}).get(name) or {}).get(
                "model_flops_per_step"):
            continue
        try:
            vp = _perf_vs_prior(preset)
        except Exception as e:  # the drift check must never kill a round
            drift[name] = {"note": f"recompute failed: "
                                   f"{type(e).__name__}: {e}"}
            continue
        if vp and vp.get("drifted"):
            drift[name] = {
                "prior": vp["prior_model_flops_per_step"],
                "current_at_prior_dims": vp["costmodel_flops_per_step"],
                "ratio": vp["ratio"],
                "prior_source": vp.get("prior_flops_source", "analytic"),
                "current_source": "costmodel",
            }
    return drift


def _ack_known_repricing(result, drift):
    """Acknowledge the one known accounting change in the artifact
    itself: every drifted workload moved from the analytic per-layer
    estimate to the costmodel jaxpr trace (the PR 9 switch). The flag
    still fires — this note rides NEXT to it so the committed round
    records both the drift and its cause, and the chain is clean from
    the next round on (both sides costmodel => no drift)."""
    entries = [d for d in drift.values() if "ratio" in d]
    if entries and all(d.get("prior_source") in (None, "analytic")
                       and d.get("current_source") == "costmodel"
                       for d in entries):
        result["flop_model_ack"] = (
            "expected one-time repricing: the prior round recorded the "
            "analytic per-layer FLOP estimate; model FLOPs are now the "
            "cost-model jaxpr trace (HLO valid-pair conv accounting). "
            "MFU baselines reset at this round and are comparable again "
            "from the next round on.")


def _prior_multichip():
    """Newest committed MULTICHIP_r*.json next to this file — the
    multi-chip trajectory's previous point. Same tolerance as
    _prior_bench: driver-wrapped ({"tail": ...}) or bare result JSON.
    Returns (basename, result) or (None, None)."""
    import glob
    import re

    def round_no(p):
        m = re.search(r"MULTICHIP_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    here = os.path.dirname(os.path.abspath(__file__))
    for path in sorted(glob.glob(os.path.join(here, "MULTICHIP_r*.json")),
                       key=round_no, reverse=True):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(doc, dict):
            continue
        if "workloads" in doc:
            return os.path.basename(path), doc
        for line in reversed(str(doc.get("tail", "")).strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    result = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "workloads" in result:
                    return os.path.basename(path), result
    return None, None


def _vs_multichip_baseline(workloads, backend, devices):
    """Multi-chip analogue of _vs_baseline: the comparable number across
    MULTICHIP rounds is `scaling_efficiency` (a within-round ratio, so it
    survives box-speed noise that raw img/s does not); the raw per-chip
    `value` ratio rides along as secondary evidence. Ratios only against
    a prior round on the SAME backend and device count, with the same
    FLOP-drift tripwire as the kernel benches."""
    prior_name, prior = _prior_multichip()
    if not prior:
        return None
    prior_backend = prior.get("backend")
    prior_devices = prior.get("devices")
    if backend != prior_backend or devices != prior_devices:
        return {"source": prior_name,
                "note": f"setup mismatch ({backend}/{devices}dev vs prior "
                        f"{prior_backend}/{prior_devices}dev): "
                        "ratios omitted"}
    eff_ratios, val_ratios, flop_drift = {}, {}, {}
    for name, out in workloads.items():
        prior_wl = (prior.get("workloads") or {}).get(name) or {}
        pe, ce = prior_wl.get("scaling_efficiency"), out.get(
            "scaling_efficiency")
        if pe and ce:
            eff_ratios[name] = round(ce / pe, 3)
        pv, cv = prior_wl.get("value"), out.get("value")
        if pv and cv:
            val_ratios[name] = round(cv / pv, 3)
        pf = prior_wl.get("model_flops_per_step")
        cf = out.get("model_flops_per_step")
        if pf and cf and abs(cf / pf - 1.0) > 0.01:
            flop_drift[name] = {
                "prior": pf, "current": cf, "ratio": round(cf / pf, 4),
                "prior_source": prior_wl.get("flops_source", "analytic"),
                "current_source": out.get("flops_source"),
            }
    result = {
        "source": prior_name,
        "headline": eff_ratios.get("resnet50"),
        "efficiency_ratio": eff_ratios,
        "value_ratio": val_ratios,
    }
    if flop_drift:
        result["flop_model_changed"] = flop_drift
        result["flop_model_note"] = (
            "model_flops_per_step differs from the prior round for these "
            "workloads — an accounting change, never a speedup")
    return result


def _probe():
    """Child mode: prove the device path is alive. Tiny matmul + scalar
    readback (block_until_ready does not block through the tunnel)."""
    import jax.numpy as jnp

    x = jnp.ones((256, 256), jnp.float32)
    y = x @ x
    val = float(np.asarray(y[0, 0]))
    print(json.dumps({
        "ok": val == 256.0,
        "backend": jax.default_backend(),
        "device": jax.devices()[0].device_kind,
    }))


def _workload_multichip(name):
    """Child mode: one multi-chip workload on this process's full device
    set (the orchestrator forced the virtual device count on CPU boxes).
    Auto-mesh is pinned OFF here because the A/B needs all three arms
    explicit — the sharded arm calls set_mesh itself, and the single-chip
    baseline must NOT silently shard over the forced mesh (the t1.sh
    smoke covers the auto-engagement default)."""
    os.environ["DL4J_AUTO_MESH"] = "0"
    out = _bench_multichip(name)
    out["backend"] = jax.default_backend()
    print(json.dumps(out))


def main_multichip(devices=None):
    """Multi-chip orchestrator: per-workload subprocesses like main(),
    with the host-platform device count forced on CPU boxes (a TPU box
    uses its real chips). Prints ONE JSON line — the committed
    MULTICHIP_r0x artifact format."""
    devices = devices or _n_multichip_devices()
    probe, perr = _run_child(["--probe"], PROBE_TIMEOUT)
    if probe is None or not probe.get("ok"):
        print(json.dumps({"mode": "multichip",
                          "infra_error": f"probe failed: {perr}"}))
        return
    backend = probe.get("backend")
    extra = {}
    if backend == "cpu":
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={devices}")
        extra["XLA_FLAGS"] = " ".join(flags)
    workloads, errors = {}, {}
    for name in ("resnet50", "char_lstm"):
        # 1500s: the three-arm collective A/B compiles three distinct
        # SPMD programs per workload; on a 1-core box forcing 8 virtual
        # devices the resnet50 child alone measures ~800s
        out, err = _run_child(["--workload-multichip", name], 1500,
                              extra_env=extra)
        if out is not None:
            child_backend = out.pop("backend", None)
            if child_backend != backend:
                errors[name] = (f"backend mismatch: child ran on "
                                f"{child_backend}, probe saw {backend}")
                continue
            workloads[name] = out
            print(f"[bench] multichip {name}: {json.dumps(out)}",
                  file=sys.stderr)
        else:
            errors[name] = err
            print(f"[bench] multichip {name}: ERROR {err}", file=sys.stderr)
    # report the device count the workloads ACTUALLY ran on: off-cpu no
    # forcing happens, so a 4-chip box must not headline "devices": 8
    ran_on = {wl.get("devices") for wl in workloads.values()
              if wl.get("devices")}
    result = {
        "metric": "multichip_scaling_efficiency",
        "mode": "multichip",
        "devices": ran_on.pop() if len(ran_on) == 1 else devices,
        "backend": backend,
        "device": probe.get("device"),
        "note": ("cpu backend = virtual host-platform devices (mechanism "
                 "evidence, not silicon perf)" if backend == "cpu"
                 else None),
        "workloads": workloads,
    }
    vs = _vs_multichip_baseline(workloads, backend, result["devices"])
    if vs is not None:
        result["vs_baseline"] = vs
    if errors:
        result["errors"] = errors
    print(json.dumps(result))


def _workload(name):
    """Child mode: run one workload, print its JSON dict. The shared
    metrics-registry snapshot rides along so compile counts, helper
    hit/fallback/auto-disable events, and step-phase histograms land in
    the committed BENCH_r*.json next to the perf numbers they explain."""
    out = WORKLOADS[name]()
    out["backend"] = jax.default_backend()
    try:
        from deeplearning4j_tpu.utils.metrics import get_registry

        out["metrics_registry"] = get_registry().snapshot()
    except Exception as e:  # a metrics bug must never sink a bench run
        out["metrics_registry"] = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps(out))


def main():
    # monotonic: the budget must not move when NTP slews the wall clock
    # mid-run (lint CC007)
    t0 = time.monotonic()
    remaining = lambda: OVERALL_DEADLINE - (time.monotonic() - t0)

    workloads, errors = {}, {}
    backend = device = None
    infra_error = None
    # --only a,b runs a subset (regenerating one round's artifact without
    # paying for every workload); unknown names fail loudly, not silently
    selected = dict(WORKLOADS)
    if "--only" in sys.argv:
        only = sys.argv[sys.argv.index("--only") + 1].split(",")
        unknown = [n for n in only if n not in WORKLOADS]
        if unknown:
            raise SystemExit(f"--only: unknown workloads {unknown}; "
                             f"known: {sorted(WORKLOADS)}")
        selected = {n: WORKLOADS[n] for n in only}

    probe, perr = _run_child(["--probe"], min(PROBE_TIMEOUT, remaining()))
    if probe is None:  # one retry: transient tunnel hiccups do recover
        probe, perr = _run_child(["--probe"], min(PROBE_TIMEOUT, max(remaining(), 1)))
    if probe is not None and not probe.get("ok"):
        probe, perr = None, "probe computed a wrong matmul result"
    if probe is None:
        infra_error = ("tunnel_wedged" if perr == "timeout"
                       else f"probe_failed: {perr}")
        for name in selected:
            errors[name] = f"skipped: {infra_error}"
    else:
        backend, device = probe.get("backend"), probe.get("device")
        for name in selected:
            budget = min(TIMEOUTS[name], remaining())
            if budget < 60:
                errors[name] = "skipped: overall deadline"
                continue
            t_wl = time.time()
            out, err = _run_child(["--workload", name], budget)
            if out is not None:
                out["elapsed_sec"] = round(time.time() - t_wl, 1)
                child_backend = out.pop("backend", None)
                if child_backend != backend:
                    # a child that silently fell back (e.g. tunnel dropped
                    # after the probe) must not pass off CPU numbers
                    errors[name] = (f"backend mismatch: child ran on "
                                    f"{child_backend}, probe saw {backend}")
                    print(f"[bench] {name}: ERROR {errors[name]}",
                          file=sys.stderr)
                    continue
                workloads[name] = out
                print(f"[bench] {name}: {json.dumps(out)}", file=sys.stderr)
            else:
                errors[name] = err
                print(f"[bench] {name}: ERROR {err}", file=sys.stderr)

    head = workloads.get("resnet50", {})
    result = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": head.get("value"),
        "unit": head.get("unit", "images/sec/chip"),
        # per-workload speedup vs the newest prior BENCH_r*.json; the
        # reference itself publishes no numbers (BASELINE.md), hence the
        # explicit null vs_reference rather than a self-graded 1.0
        "vs_baseline": _vs_baseline(workloads, backend),
        "vs_reference": None,
        "mfu": head.get("mfu"),
        "backend": backend,
        "device": device,
        "workloads": workloads,
    }
    if errors:
        result["errors"] = errors
    if infra_error:
        result["infra_error"] = infra_error
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--multichip":
        n_dev = None
        if "--devices" in sys.argv:
            n_dev = int(sys.argv[sys.argv.index("--devices") + 1])
        main_multichip(n_dev)
    elif len(sys.argv) > 1 and sys.argv[1] in ("--probe", "--workload",
                                               "--workload-multichip"):
        # The image's sitecustomize initializes the axon platform at
        # interpreter start, which ignores JAX_PLATFORMS from the env; a
        # config update before first backend *use* still wins.
        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            jax.config.update("jax_platforms", plat)
        if sys.argv[1] == "--probe":
            _probe()
        elif sys.argv[1] == "--workload-multichip":
            _workload_multichip(sys.argv[2])
        else:
            name = sys.argv[2]
            if "--overload" in sys.argv[3:]:
                # `bench.py --workload parallel_inference --overload` is
                # the graceful-degradation variant of a serving workload
                name = f"{name}_overload"
            _workload(name)
    else:
        main()
