"""DataSet containers.

Analog of ND4J's DataSet/MultiDataSet (features, labels, optional masks) —
the unit every iterator yields and fit() consumes. Arrays are host numpy
until the train step moves them to HBM; the async iterator can pre-stage
device transfers (reference: AsyncDataSetIterator device callbacks).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    @staticmethod
    def concat(batches: List["DataSet"]) -> "DataSet":
        """Concatenate along the example axis (masks must be uniformly
        present or absent)."""
        if len(batches) == 1:
            return batches[0]

        def _cat(attr):
            vals = [getattr(b, attr) for b in batches]
            if all(v is None for v in vals):
                return None
            if any(v is None for v in vals):
                raise ValueError(f"mixed None/{attr} across concatenated batches")
            return np.concatenate(vals, axis=0)

        return DataSet(
            np.concatenate([b.features for b in batches], axis=0),
            np.concatenate([b.labels for b in batches], axis=0),
            _cat("features_mask"),
            _cat("labels_mask"),
        )

    def split_batches(self, batch_size: int) -> List["DataSet"]:
        out = []
        n = self.num_examples()
        for i in range(0, n, batch_size):
            sl = slice(i, min(i + batch_size, n))
            out.append(
                DataSet(
                    self.features[sl],
                    self.labels[sl],
                    None if self.features_mask is None else self.features_mask[sl],
                    None if self.labels_mask is None else self.labels_mask[sl],
                )
            )
        return out


@dataclasses.dataclass
class MultiDataSet:
    """Multiple inputs / multiple outputs (reference: ND4J MultiDataSet,
    consumed by ComputationGraph.fit)."""

    features: List[np.ndarray]
    labels: List[np.ndarray]
    features_masks: Optional[List[Optional[np.ndarray]]] = None
    labels_masks: Optional[List[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])
