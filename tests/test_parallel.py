"""Data-parallel training tests on the virtual 8-device CPU mesh.

Mirrors the reference's scale-out test strategy (SURVEY.md §4): the
ParallelWrapper tests run N worker threads on the CPU backend
(deeplearning4j-scaleout-parallelwrapper/src/test/.../ParallelWrapperTest.java);
here "N workers" is an 8-device host-platform mesh and the assertions are
numeric equivalence between sharded and single-device training.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    BatchNormalization,
    DenseLayer,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (
    InferenceMode,
    ParallelInference,
    ParallelWrapper,
    data_parallel_mesh,
    mesh_2d,
)


def _mlp_conf(updater=Updater.NESTEROVS, with_bn=False, seed=7):
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .updater(updater)
        .learning_rate(0.05)
        .momentum(0.9)
        .weight_init("xavier")
        .list()
        .layer(DenseLayer(n_in=12, n_out=16, activation="tanh"))
    )
    if with_bn:
        b = b.layer(BatchNormalization(n_in=16))
    return (
        b.layer(OutputLayer(n_in=16, n_out=4, activation="softmax", loss="mcxent"))
        .build()
    )


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 12)).astype(np.float32)
    y = np.zeros((n, 4), np.float32)
    y[np.arange(n), rng.integers(0, 4, n)] = 1.0
    return x, y


def test_mesh_has_8_devices():
    mesh = data_parallel_mesh()
    assert mesh.devices.size == 8


def test_mesh_2d_shape():
    mesh = mesh_2d(4, 2)
    assert mesh.devices.shape == (4, 2)
    assert mesh.axis_names == ("data", "model")


def test_dp_equivalence_8_vs_1_device():
    """8-device sharded training == single-device training at the same
    global batch (SURVEY.md §7 stage 7 exit criterion)."""
    x, y = _data(64)
    net1 = MultiLayerNetwork(_mlp_conf()).init()
    net8 = MultiLayerNetwork(_mlp_conf()).init()

    net1.fit(x, y, batch_size=16, epochs=2, async_prefetch=False)
    ParallelWrapper(net8, data_parallel_mesh()).fit(
        x, y, batch_size=16, epochs=2, async_prefetch=False
    )

    for p1, p8 in zip(net1.params_list, net8.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p8[k]), rtol=2e-5, atol=2e-6
            )


def test_dp_equivalence_with_batchnorm():
    """Batch statistics under sharding are GLOBAL-batch statistics (GSPMD
    turns the batch mean/var into cross-device collectives), matching
    single-device math — the property the reference could NOT provide
    (each ParallelWrapper replica saw only its own minibatch stats)."""
    x, y = _data(64, seed=3)
    net1 = MultiLayerNetwork(_mlp_conf(with_bn=True)).init()
    net8 = MultiLayerNetwork(_mlp_conf(with_bn=True)).init()

    net1.fit(x, y, batch_size=32, epochs=1, async_prefetch=False)
    ParallelWrapper(net8, data_parallel_mesh()).fit(
        x, y, batch_size=32, epochs=1, async_prefetch=False
    )

    for p1, p8 in zip(net1.params_list, net8.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p8[k]), rtol=5e-5, atol=5e-6
            )
    # running stats also match
    for s1, s8 in zip(net1.state_list, net8.state_list):
        if s1 is None:
            continue
        for k in s1:
            np.testing.assert_allclose(
                np.asarray(s1[k]), np.asarray(s8[k]), rtol=5e-5, atol=5e-6
            )


def test_allreduce_equals_parameter_averaging():
    """Per-step gradient allreduce == ParallelWrapper parameter averaging
    with frequency=1 (reference semantics: ParallelWrapper.java:417-424):
    mean_i(theta - lr*g_i) == theta - lr*mean_i(g_i)."""
    x, y = _data(32, seed=11)
    lr = 0.05
    net = MultiLayerNetwork(_mlp_conf(updater=Updater.SGD)).init()
    theta0 = [dict(p) for p in net.params_list]

    # manual per-"worker" SGD on each shard, then average the params
    n_workers = 8
    shard = 32 // n_workers
    averaged = None
    for w in range(n_workers):
        sl = slice(w * shard, (w + 1) * shard)
        grads = jax.grad(
            lambda p: net._loss(
                p, net.state_list, jnp.asarray(x[sl]), jnp.asarray(y[sl]),
                None, None, rng=jax.random.fold_in(
                    jax.random.PRNGKey(net.net_conf.seed ^ 0x5EED), 0),
            )[0]
        )(theta0)
        stepped = jax.tree_util.tree_map(
            lambda t, g: t - lr * g, theta0, grads
        )
        if averaged is None:
            averaged = stepped
        else:
            averaged = jax.tree_util.tree_map(jnp.add, averaged, stepped)
    averaged = jax.tree_util.tree_map(lambda a: a / n_workers, averaged)

    # allreduce path: one sharded global-batch step
    ParallelWrapper(net, data_parallel_mesh()).fit(
        x, y, batch_size=32, epochs=1, async_prefetch=False
    )

    for pa, pw in zip(averaged, net.params_list):
        for k in pa:
            np.testing.assert_allclose(
                np.asarray(pa[k]), np.asarray(pw[k]), rtol=2e-5, atol=2e-6
            )


def test_workers_stacking_minibatches():
    """workers=k consumes k iterator minibatches per global step (the
    reference's one-minibatch-per-DefaultTrainer dispatch)."""
    x, y = _data(64)
    net_st = MultiLayerNetwork(_mlp_conf()).init()
    net_gl = MultiLayerNetwork(_mlp_conf()).init()

    # stacked: iterator yields per-worker batches of 8, workers=2 -> global 16
    it = ListDataSetIterator(DataSet(x, y), 8)
    ParallelWrapper(net_st, data_parallel_mesh(), workers=2).fit(
        it, epochs=1, async_prefetch=False
    )
    # equivalent: global batches of 16
    ParallelWrapper(net_gl, data_parallel_mesh()).fit(
        x, y, batch_size=16, epochs=1, async_prefetch=False
    )
    for p1, p2 in zip(net_st.params_list, net_gl.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=1e-6)


def test_tail_batch_not_divisible():
    """A tail batch not divisible by the device count trains SHARDED via
    pad-and-mask (wrapped pad rows, zero labels-mask, masked-example
    mean), with numerics exactly equal to single-device training on the
    same examples."""
    x, y = _data(36)  # 36 = 2*16 + tail 4
    net1 = MultiLayerNetwork(_mlp_conf()).init()
    net8 = MultiLayerNetwork(_mlp_conf()).init()
    net1.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    ParallelWrapper(net8, data_parallel_mesh()).fit(
        x, y, batch_size=16, epochs=1, async_prefetch=False
    )
    assert net8.iteration == 3
    assert np.isfinite(float(net8._score))
    for p1, p8 in zip(net1.params_list, net8.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p8[k]), rtol=2e-5, atol=2e-6
            )
    # every batch (incl. the padded tail) was sharded over all 8 devices
    w0 = net8.params_list[0]["W"]
    assert len(w0.sharding.device_set) == 8


def test_tail_batch_single_executable():
    """Pad-and-mask keeps ONE compiled executable across an epoch with a
    non-divisible tail — no tail-shape recompile (round-2 weakness)."""
    x, y = _data(36)
    net = MultiLayerNetwork(_mlp_conf()).init()
    ParallelWrapper(net, data_parallel_mesh()).fit(
        x, y, batch_size=16, epochs=1, async_prefetch=False
    )
    assert net._train_step_fn._cache_size() == 1


def test_tail_smaller_than_device_count():
    """A tail smaller than the mesh (pad > n) wraps cyclically."""
    x, y = _data(19)  # tail of 3 on 8 devices
    net = MultiLayerNetwork(_mlp_conf()).init()
    wrapper = ParallelWrapper(net, data_parallel_mesh())
    wrapper.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    assert np.isfinite(float(net._score))
    # padded inference path: result matches plain forward on the unpadded x
    out = np.asarray(wrapper.output(x[:5]))
    np.testing.assert_allclose(
        out, np.asarray(net.output(x[:5])), rtol=2e-5, atol=1e-6)


def test_parallel_inference_matches_output():
    x, _ = _data(32)
    net = MultiLayerNetwork(_mlp_conf()).init()
    expected = np.asarray(net.output(x))

    pi = ParallelInference(net, data_parallel_mesh(),
                           inference_mode=InferenceMode.BATCHED,
                           max_batch_size=32)
    try:
        results = {}

        def call(i):
            results[i] = np.asarray(pi.output(x[i * 8 : (i + 1) * 8]))

        threads = [threading.Thread(target=call, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        got = np.concatenate([results[i] for i in range(4)], axis=0)
        np.testing.assert_allclose(got, expected, rtol=2e-5, atol=1e-6)
    finally:
        pi.shutdown()


def test_parallel_inference_sequential():
    x, _ = _data(16)
    net = MultiLayerNetwork(_mlp_conf()).init()
    pi = ParallelInference(net, data_parallel_mesh(),
                           inference_mode=InferenceMode.SEQUENTIAL)
    np.testing.assert_allclose(
        np.asarray(pi.output(x)), np.asarray(net.output(x)), rtol=2e-5, atol=1e-6
    )


def test_parallel_inference_validates_shapes():
    """Mismatched trailing dims are rejected at output() — not deep in the
    collector where they would fail the whole fused group; oversized
    requests run alone instead of overshooting a fused batch."""
    x, _ = _data(16)
    net = MultiLayerNetwork(_mlp_conf()).init()
    pi = ParallelInference(net, data_parallel_mesh(), max_batch_size=8)
    try:
        out = np.asarray(pi.output(x[:8]))
        assert out.shape == (8, 4)
        with pytest.raises(ValueError, match="does not match"):
            pi.output(np.zeros((4, 7), np.float32))
        # oversized request (16 > max_batch_size 8) still served
        out = np.asarray(pi.output(x))
        np.testing.assert_allclose(
            out, np.asarray(net.output(x)), rtol=2e-5, atol=1e-6)
    finally:
        pi.shutdown()


def test_dp_tbptt_routes_through_segment_loop():
    """TBPTT-configured nets train segment-wise under the wrapper too (the
    wrapper delegates to the model's fit loop, so BackpropType dispatch is
    preserved), and match single-device TBPTT training."""
    from deeplearning4j_tpu.nn.conf import BackpropType, LSTM, RnnOutputLayer

    def rnn_conf():
        return (
            NeuralNetConfiguration.builder()
            .seed(5)
            .updater(Updater.SGD)
            .learning_rate(0.05)
            .weight_init("xavier")
            .list()
            .layer(LSTM(n_in=6, n_out=8, activation="tanh"))
            .layer(RnnOutputLayer(n_in=8, n_out=3, activation="softmax",
                                  loss="mcxent"))
            .backprop_type(BackpropType.TRUNCATED_BPTT)
            .t_bptt_lengths(4)
            .build()
        )

    rng = np.random.default_rng(2)
    x = rng.standard_normal((16, 12, 6)).astype(np.float32)
    y = np.zeros((16, 12, 3), np.float32)
    y[np.arange(16)[:, None], np.arange(12)[None, :],
      rng.integers(0, 3, (16, 12))] = 1.0

    net1 = MultiLayerNetwork(rnn_conf()).init()
    net8 = MultiLayerNetwork(rnn_conf()).init()
    net1.fit(x, y, batch_size=16, epochs=1, async_prefetch=False)
    ParallelWrapper(net8, data_parallel_mesh()).fit(
        x, y, batch_size=16, epochs=1, async_prefetch=False
    )
    # 12 timesteps / tbptt length 4 = 3 segment steps
    assert net1.iteration == 3 and net8.iteration == 3
    for p1, p8 in zip(net1.params_list, net8.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p8[k]), rtol=5e-5, atol=5e-6
            )


def test_averaging_frequency_gt1_rejected():
    net = MultiLayerNetwork(_mlp_conf()).init()
    with pytest.raises(ValueError):
        ParallelWrapper(net, data_parallel_mesh(), averaging_frequency=4)


def test_tensor_parallel_dense_stack():
    """TP'd dense stack (column/row Megatron split over the "model" axis)
    trains with numerics equal to the unsharded net; weights are actually
    distributed (each device holds a 1/8 shard)."""
    from deeplearning4j_tpu.parallel import shard_params_tp
    from deeplearning4j_tpu.parallel.mesh import mesh_2d

    def build():
        conf = (
            NeuralNetConfiguration.builder().seed(11).updater(Updater.ADAM)
            .learning_rate(0.01).weight_init("xavier").list()
            .layer(DenseLayer(n_in=12, n_out=32, activation="tanh"))
            .layer(DenseLayer(n_in=32, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                               loss="mcxent"))
            .build()
        )
        return MultiLayerNetwork(conf).init()

    x, y = _data(64, seed=9)
    net_ref = build()
    net_tp = build()
    mesh = mesh_2d(1, 8)
    shard_params_tp(net_tp, mesh)
    # first dense column-parallel: local shard is 1/8 of the columns
    w0 = net_tp.params_list[0]["W"]
    assert w0.sharding.shard_shape(w0.shape) == (12, 4)
    # second dense row-parallel
    w1 = net_tp.params_list[1]["W"]
    assert w1.sharding.shard_shape(w1.shape) == (4, 16)

    net_ref.fit(x, y, batch_size=32, epochs=2, async_prefetch=False)
    net_tp.fit(x, y, batch_size=32, epochs=2, async_prefetch=False)
    for p1, p2 in zip(net_ref.params_list, net_tp.params_list):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=2e-5, atol=2e-6,
                err_msg=f"TP diverged on {k}")
    # TP placement survives the train step (GSPMD kept the layout)
    w0b = net_tp.params_list[0]["W"]
    assert w0b.sharding.shard_shape(w0b.shape) == (12, 4)
