"""Benchmark entry point — prints ONE JSON line with the headline metric.

Current flagship: LeNet-MNIST training throughput (images/sec/chip) on the
default backend (TPU under axon; CPU elsewhere). Will switch to ResNet-50
images/sec/chip (BASELINE.md metric of record) once the ComputationGraph
workload lands. The reference publishes no numbers (BASELINE.json
published={}), so vs_baseline is reported as 1.0 by convention.

Protocol (BASELINE.md): synthetic data (BenchmarkDataSetIterator-equivalent)
to remove ETL noise; steady-state steps timed after warmup/compile;
per-chip batch; bf16 compute policy on TPU.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def bench_lenet(batch: int = 512, steps: int = 30, warmup: int = 5) -> dict:
    from deeplearning4j_tpu.models.lenet import lenet_network

    on_tpu = jax.default_backend() not in ("cpu",)
    net = lenet_network(precision="bf16" if on_tpu else "f32")

    rng = np.random.default_rng(0)
    x = rng.random((batch, 784), np.float32)
    y = np.zeros((batch, 10), np.float32)
    y[np.arange(batch), rng.integers(0, 10, batch)] = 1.0

    # warmup (includes compile)
    for _ in range(warmup):
        states, score = net._fit_step(x, y, None, None)
        net.state_list = states
    jax.block_until_ready(net.params_list)

    t0 = time.perf_counter()
    for _ in range(steps):
        states, score = net._fit_step(x, y, None, None)
        net.state_list = states
    jax.block_until_ready(net.params_list)
    dt = time.perf_counter() - t0

    ips = batch * steps / dt
    return {
        "metric": "lenet_mnist_train_images_per_sec_per_chip",
        "value": round(ips, 1),
        "unit": "images/sec/chip",
        "vs_baseline": 1.0,
        "backend": jax.default_backend(),
        "batch": batch,
        "steps": steps,
        "seconds": round(dt, 3),
    }


if __name__ == "__main__":
    result = bench_lenet()
    print(json.dumps(result))
